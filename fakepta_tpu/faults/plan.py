"""Deterministic fault injection: a seeded plan arming named engine sites.

Chaos testing for the engine (docs/RELIABILITY.md): a :class:`FaultPlan`
arms **named sites** threaded through the hot paths — the chunk dispatch
and drain of :meth:`EnsembleSimulator.run`, the pipeline writer thread,
checkpoint appends, the persistent-compile-cache wiring, the serve
dispatcher, the sampler's segment step — and fires scripted faults at
exact, reproducible hit indices. Every fired fault is mirrored into the
crash flight recorder (``obs.flightrec``) and counted
(``faults.injected``), so a chaos run's telemetry shows precisely what was
injected where.

The plan is **deterministic by construction**: each site keeps a per-plan
hit counter, and a :class:`FaultSpec` names the hit indices (``at``) that
fire. Two runs under the same plan inject the same faults at the same
sites in the same order — which is what lets the chaos tests assert the
recovered run's packed streams *bit-identical* to the unfaulted run.

Sites in the engine (the canonical list, docs/RELIABILITY.md):

========================  ====================================================
site                      where it is checked
========================  ====================================================
``mc.dispatch``           montecarlo.run, before each chunk dispatch
``mc.recycle``            montecarlo.run, the donated-scratch recycle check
``pipeline.writer``       the per-chunk/segment drain (writer thread)
``ckpt.append``           EnsembleCheckpoint/SampleCheckpoint ``save``
``cache.load``            pipeline.configure_compile_cache
``serve.dispatch``        ServePool's dispatcher thread, per cohort
``sample.segment``        SamplingRun.run, before each segment dispatch
``fleet.replica``         ServeFleet's router, per dispatch to a replica
``fleet.heartbeat``       the fleet health monitor, per replica probe
``ingest.append``         StreamState.append, at the top of each TOA block
``telemetry.scrape``      the fleet health monitor, before each telemetry
                          scrape riding a successful probe
``gateway.admit``         Gateway.submit, after auth and before any quota
                          or cache state moves
``gateway.cutover``       StreamManager.cutover, twice per operation: at
                          the fence (``stage='restage'``) and again before
                          the atomic swap (``stage='swap'``)
========================  ====================================================

``fleet.heartbeat`` is checked inside the monitor's probe path with
``replica=<id>`` context, so a ``hang`` there is a probe that misses its
deadline (the wedged-replica simulation: consecutive misses open the
circuit breaker, docs/RELIABILITY.md "Fleet lifecycle") and a
``transient`` is one flaky probe.

``telemetry.scrape`` is checked with ``replica=<id>`` context inside the
monitor's scrape step, AFTER the heartbeat verdict for that probe is
already recorded — a raising kind there loses one telemetry snapshot
(counted ``telemetry.scrape_errors``, flight-recorded) but can never
produce a heartbeat miss: the scrape is best-effort by contract
(docs/OBSERVABILITY.md).

``ingest.append`` is checked BEFORE any state mutates, so a raising kind
(``transient``/``fatal``) leaves the stream untouched and a retry of the
same block is deterministic; the ``torn`` kind lets the block land and
then corrupts its checkpoint file before simulated process death
(:class:`KillFault`) — resume must detect the bad CRC and roll back to the
last consistent :class:`~fakepta_tpu.stream.StreamState`
(docs/STREAMING.md).

Fault kinds: ``transient`` / ``fatal`` raise (:class:`TransientFault` /
:class:`FatalFault`); ``degrade`` / ``precision`` raise the ladder triggers
(:class:`DegradeFault` — a Pallas compile/runtime failure stand-in — and
:class:`PrecisionFault` — a bf16 certification failure); ``kill`` raises
:class:`KillFault` (a ``BaseException``: simulated process death, never
caught by recovery); ``hang`` sleeps ``hang_s`` at the site (a stuck drain
the watchdog must catch); ``poison`` / ``torn`` / ``donation`` return the
kind string so the site applies the corruption itself (NaN the dispatched
output, tear the checkpoint write, fake a failed donation).

No plan installed means every site check is one global read and a ``None``
return — the harness costs nothing in production.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Optional, Sequence, Tuple

from ..obs import flightrec

#: fault kinds that raise at the site
_RAISING_KINDS = ("transient", "fatal", "degrade", "precision", "kill")
#: fault kinds returned to the site for in-place corruption
_ACTING_KINDS = ("poison", "torn", "donation", "hang")
KINDS = _RAISING_KINDS + _ACTING_KINDS


class FaultError(RuntimeError):
    """Base class of every injected (raising) fault."""


class TransientFault(FaultError):
    """A retryable failure (the injected stand-in for preemptions, evicted
    executables, transient RPC errors); recovery retries with backoff."""


class FatalFault(FaultError):
    """A non-retryable failure: recovery must fail loudly, never mask it."""


class DegradeFault(FaultError):
    """A Pallas/megakernel compile-or-runtime failure stand-in: recovery
    steps down the statistic-path ladder (mega -> fused -> xla)."""


class PrecisionFault(FaultError):
    """A bf16 certification failure stand-in: recovery re-dispatches the
    chunk at f32."""


class KillFault(BaseException):
    """Simulated process death (SIGKILL analog) — derives from
    ``BaseException`` so no recovery path can swallow it; the kill-resume
    chaos tests raise it mid-checkpoint-write."""


class WatchdogTimeout(RuntimeError):
    """A per-chunk watchdog deadline expired: the oldest in-flight drain
    never completed. The engine dumps the flight recorder and aborts."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One armed site: fire ``kind`` at the site's hit indices ``at``.

    ``at`` is a tuple of 0-based per-site hit counters (the Nth time the
    engine reaches the site under this plan); ``times`` caps total fires
    (default: one per ``at`` entry). ``hang_s`` is the sleep of a ``hang``
    fault — size it against the watchdog deadline under test.

    ``match`` narrows the spec to site visits whose context carries the
    given (key, value) pairs — e.g. ``match=(("replica", "r1"),)`` wedges
    ONE replica's heartbeat probes while its siblings stay healthy. A
    matched spec keeps its own hit counter over *matching* visits only, so
    ``at`` stays deterministic no matter how the fleet interleaves probes.
    """

    site: str
    kind: str = "transient"
    at: Tuple[int, ...] = (0,)
    times: Optional[int] = None
    hang_s: float = 2.0
    match: Tuple[Tuple[str, str], ...] = ()

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"known: {KINDS}")
        object.__setattr__(self, "at", tuple(int(i) for i in self.at))
        object.__setattr__(self, "match",
                           tuple((str(k), str(v)) for k, v in self.match))


class FaultPlan:
    """A deterministic schedule of faults over named sites.

    >>> plan = FaultPlan([FaultSpec("mc.dispatch", "transient", at=(1,))])
    >>> with fakepta_tpu.faults.inject(plan):
    ...     sim.run(...)        # chunk 1's dispatch fails once, is retried

    ``hits``/``fired`` record what actually happened (the chaos tests
    assert on them); both are plain host bookkeeping.
    """

    def __init__(self, specs: Sequence[FaultSpec], seed: int = 0):
        self.specs = tuple(specs)
        self.seed = int(seed)
        self.hits: dict = {}          # site -> times the site was reached
        self.fired: list = []         # (site, kind, hit_index) in fire order
        self._remaining = {id(s): (len(s.at) if s.times is None else s.times)
                           for s in self.specs}
        # matched specs count their own matching visits (FaultSpec.match)
        self._match_hits = {id(s): 0 for s in self.specs if s.match}

    def sites(self) -> Tuple[str, ...]:
        return tuple(sorted({s.site for s in self.specs}))

    def hit(self, site: str, **ctx) -> Optional[str]:
        """One site visit: fire any armed spec whose ``at`` matches.

        Raising kinds raise; acting kinds return the kind string for the
        site to apply. Every fire is flight-recorded and counted.
        """
        idx = self.hits.get(site, 0)
        self.hits[site] = idx + 1
        for spec in self.specs:
            if spec.site != site:
                continue
            if spec.match:
                if any(str(ctx.get(k)) != v for k, v in spec.match):
                    continue
                spec_idx = self._match_hits[id(spec)]
                self._match_hits[id(spec)] = spec_idx + 1
            else:
                spec_idx = idx
            if spec_idx not in spec.at:
                continue
            if self._remaining[id(spec)] <= 0:
                continue
            self._remaining[id(spec)] -= 1
            self.fired.append((site, spec.kind, spec_idx))
            flightrec.note("fault_fired", site=site, kind=spec.kind,
                           hit=spec_idx,
                           **{k: v for k, v in ctx.items()
                              if isinstance(v, (int, float, str))})
            from ..obs import count as _count
            _count("faults.injected")
            if spec.kind == "transient":
                raise TransientFault(f"injected transient fault at {site} "
                                     f"(hit {spec_idx})")
            if spec.kind == "fatal":
                raise FatalFault(f"injected fatal fault at {site} "
                                 f"(hit {spec_idx})")
            if spec.kind == "degrade":
                raise DegradeFault(f"injected pallas failure at {site} "
                                   f"(hit {spec_idx})")
            if spec.kind == "precision":
                raise PrecisionFault(f"injected bf16 certification failure "
                                     f"at {site} (hit {spec_idx})")
            if spec.kind == "kill":
                raise KillFault(f"injected process kill at {site} "
                                f"(hit {spec_idx})")
            if spec.kind == "hang":
                time.sleep(spec.hang_s)
                return "hang"
            return spec.kind          # poison / torn / donation
        return None


# process-wide active plan: a single slot, installed by inject(). Reads are
# unlocked (one global load on the hot path); tests install one plan at a
# time, and the writer/serve threads only ever read it.
_ACTIVE: Optional[FaultPlan] = None


def active() -> Optional[FaultPlan]:
    """The installed plan, if any."""
    return _ACTIVE


def check(site: str, **ctx) -> Optional[str]:
    """Site hook: fire any armed fault at ``site`` (see FaultPlan.hit).

    Returns ``None`` with no plan installed — a single global read, so the
    harness is free when idle.
    """
    plan = _ACTIVE
    if plan is None:
        return None
    return plan.hit(site, **ctx)


@contextlib.contextmanager
def inject(plan: FaultPlan):
    """Install ``plan`` process-wide for the scope of the context."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("a FaultPlan is already installed; nest-injecting "
                           "plans would make the hit counters ambiguous")
    flightrec.note("fault_plan_armed", sites=",".join(plan.sites()),
                   seed=plan.seed)
    _ACTIVE = plan
    try:
        yield plan
    finally:
        _ACTIVE = None
