"""Recovery policy: bounded retry, degradation ladders, failure triage.

One policy object (:class:`RecoveryPolicy`) governs every recovery site in
the engine (docs/RELIABILITY.md):

- **retry**: transient dispatch/drain failures are retried up to
  ``max_retries`` times with exponential backoff (``backoff_s`` doubling by
  ``backoff_mult`` up to ``max_backoff_s``). A retried chunk/segment
  re-dispatches the *same* RNG lanes at the same offsets — per-realization
  keys fold absolute indices, so the retried chunk is bit-identical to the
  unfaulted run at the same executable shape.
- **degradation ladders**: a Pallas/megakernel compile-or-runtime failure
  steps the statistic path down :data:`PATH_LADDER` (``mega -> fused ->
  xla``); a bf16 certification failure re-dispatches at f32; a broken
  donated-buffer recycle turns donation off for the rest of the run (the
  ``pipeline_depth -> 0`` analog: depth bounding stays, the peak-HBM claim
  is withdrawn). Degraded dispatches change the executable shape, so their
  streams certify at the engine's mesh-invariance tolerance instead of
  bit-identity (the shape-dependent-reduction rule, docs/INVARIANTS.md).
- **watchdog**: ``watchdog_s`` arms a per-chunk deadline on the oldest
  in-flight drain; expiry dumps the flight recorder and aborts the run
  with :class:`~fakepta_tpu.faults.WatchdogTimeout` (pipelined runs only —
  the serial loop drains inline on the dispatch thread).

:func:`classify` is the failure triage shared by every site: injected
fault types map directly; real-world exceptions match conservative message
patterns (RPC-ish transients, Pallas/Mosaic compiles). Anything
unrecognized is ``fatal`` — recovery must never retry blindly.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from .plan import DegradeFault, FatalFault, KillFault, PrecisionFault, \
    TransientFault

#: statistic-path degradation ladder: on a Pallas compile/runtime failure
#: the run steps down one rung and re-dispatches (docs/RELIABILITY.md)
PATH_LADDER = {"mega": "fused", "fused": "xla"}

# conservative message fingerprints of retryable runtime failures (RPC /
# allocator transients a re-dispatch can outlive); matched case-insensitive
_TRANSIENT_PATTERNS = ("resource_exhausted", "resource exhausted",
                       "unavailable", "deadline_exceeded", "deadline "
                       "exceeded", "aborted", "connection reset",
                       "socket closed", "preempt")
# fingerprints of a serve-fleet replica dying under a request (connection
# loss, a closed pool, a killed subprocess) — the router's failover class:
# re-dispatching to a SIBLING is the recovery, never retrying the corpse
_REPLICA_DEATH_PATTERNS = ("connection refused", "connection reset",
                           "broken pipe", "pipe closed", "socket closed",
                           "bad file descriptor", "eof",
                           "died mid-flight", "is dead", "pool is closed",
                           "pool closed")
# exception type NAMES (matched without importing the serving layer —
# recovery sits below serve in the import graph) that mean the replica
# itself is gone rather than the request having failed
_REPLICA_DEATH_TYPES = ("ReplicaDead", "ServeClosed", "ConnectionError",
                        "ConnectionResetError", "ConnectionRefusedError",
                        "BrokenPipeError", "EOFError")
# fingerprints of a failing Pallas/Mosaic lowering or kernel
_PALLAS_PATTERNS = ("pallas", "mosaic")


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """Knobs for the engine-wide recovery ladder (module docstring)."""

    max_retries: int = 2
    backoff_s: float = 0.05
    backoff_mult: float = 2.0
    max_backoff_s: float = 2.0
    degrade_paths: bool = True        # mega -> fused -> xla
    degrade_precision: bool = True    # bf16 -> f32
    degrade_pipeline: bool = True     # donation off on a broken recycle
    watchdog_s: Optional[float] = None

    def next_backoff(self, delay: float) -> float:
        return min(delay * self.backoff_mult, self.max_backoff_s)


#: recovery disabled: no retries, no ladders, no watchdog — every failure
#: propagates like the pre-recovery engine (run(recovery=False))
DISABLED = RecoveryPolicy(max_retries=0, backoff_s=0.0,
                          degrade_paths=False, degrade_precision=False,
                          degrade_pipeline=False, watchdog_s=None)


def as_policy(recovery) -> RecoveryPolicy:
    """Normalize the ``run(recovery=...)`` argument: ``None`` -> defaults,
    ``False`` -> :data:`DISABLED`, a policy -> itself."""
    if recovery is None:
        return RecoveryPolicy()
    if recovery is False:
        return DISABLED
    if isinstance(recovery, RecoveryPolicy):
        return recovery
    raise TypeError(f"recovery must be None, False or a RecoveryPolicy, "
                    f"got {type(recovery).__name__}")


def classify(exc: BaseException) -> str:
    """Triage one failure: 'transient' | 'pallas' | 'precision' | 'fatal'."""
    if isinstance(exc, TransientFault):
        return "transient"
    if isinstance(exc, DegradeFault):
        return "pallas"
    if isinstance(exc, PrecisionFault):
        return "precision"
    if isinstance(exc, (FatalFault, KillFault)):
        return "fatal"
    msg = f"{type(exc).__name__}: {exc}".lower()
    if any(p in msg for p in _PALLAS_PATTERNS):
        return "pallas"
    if any(p in msg for p in _TRANSIENT_PATTERNS):
        return "transient"
    return "fatal"


def classify_replica(exc: BaseException) -> str:
    """Fleet-tier failure triage: ``'replica_death'`` when the replica
    serving the request is gone (the router fails over to a ring sibling —
    correctness-safe because per-request RNG lanes make the re-dispatch
    bit-identical per executable shape), else :func:`classify`'s verdict.

    A :class:`KillFault` counts as replica death here: at a fleet site it
    IS the simulated process kill, and failover to a *different* replica
    is exactly the recovery that must never be swallowed in-place (the
    engine-site rule that no recovery catches KillFault still holds — the
    victim replica's own ladder dies; only the router moves the work).
    """
    seen = 0
    cur: Optional[BaseException] = exc
    while cur is not None and seen < 8:     # cause chain, cycle-bounded
        if isinstance(cur, KillFault):
            return "replica_death"
        if any(t.__name__ in _REPLICA_DEATH_TYPES
               for t in type(cur).__mro__):
            return "replica_death"
        msg = f"{type(cur).__name__}: {cur}".lower()
        if any(p in msg for p in _REPLICA_DEATH_PATTERNS):
            return "replica_death"
        cur = cur.__cause__
        seen += 1
    return classify(exc)


def sleep(seconds: float) -> None:
    """Backoff sleep (a hook the chaos tests could stub; bounded by the
    policy's ``max_backoff_s``)."""
    if seconds > 0:
        time.sleep(seconds)
