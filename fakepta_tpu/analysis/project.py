"""One-shot project index for the whole-program analysis pass.

The per-file rules see one module at a time; the concurrency and
collective-discipline rules (analysis/concurrency.py,
analysis/rules/collectives.py) need the *program*: which method a call
resolves to, which functions run on which thread, and which callbacks a
``Future`` resolution can re-enter. This module builds that picture once —
a symbol table, a conservative name-resolved call graph, and thread-entry
/ callback discovery — and every whole-program rule shares it.

Resolution is deliberately conservative (over-approximate) and purely
syntactic, in the same stdlib-``ast`` discipline as the per-file rules:

- ``self.m()`` resolves within the receiver's class (plus any base classes
  present in the index);
- ``self.attr.m()`` resolves through the attribute's inferred class —
  inferred from ``self.attr = ClassName(...)`` constructor assignments, or
  declared in ``policy.ATTR_CLASS_HINTS`` for duck-typed parameters
  (``self.fleet = fleet``);
- ``mod.f()`` resolves through import aliases to an indexed module by
  dotted-suffix match;
- an untyped ``obj.m()`` resolves to EVERY indexed class defining ``m``
  (class-hierarchy style), unless ``m`` is too generic to be meaningful
  (``policy.GENERIC_METHOD_NAMES``);
- ``fut.set_result()`` / ``fut.set_exception()`` resolve to every function
  or lambda the project ever registers via ``add_done_callback`` — the
  edge that makes a completion callback visible to the lock-order pass.

Determinism: modules index in sorted path order, functions in source
order, and every derived table is built from those orderings alone — two
builds over the same sources yield identical graphs and identical finding
order (pinned by tests/test_analysis_project.py).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from . import policy
from .rules.common import NameResolver, last_component

#: qualified-name separator between module path and object path
QSEP = "::"

_THREAD_NAMES = ("threading.Thread", "Thread")


@dataclasses.dataclass
class FunctionInfo:
    """One function/method/lambda scope in the index."""

    qname: str                     # "serve/fleet.py::SocketReplica.submit"
    module: str                    # repo-relative posix path
    cls: Optional[str]             # owning class name, None for functions
    name: str                      # bare name ("<lambda>" for lambdas)
    node: ast.AST
    lineno: int


@dataclasses.dataclass
class ClassInfo:
    module: str
    name: str
    node: ast.ClassDef
    methods: Dict[str, str] = dataclasses.field(default_factory=dict)
    bases: Tuple[str, ...] = ()
    # self.<attr> = threading.Lock()/RLock() sites
    lock_attrs: Dict[str, int] = dataclasses.field(default_factory=dict)
    # self.<attr> = threading.Condition(self.<lock>) -> lock attr name
    cond_aliases: Dict[str, str] = dataclasses.field(default_factory=dict)
    # self.<attr> = ClassName(...) constructor-inferred attribute types
    attr_classes: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModuleInfo:
    path: str
    tree: ast.AST
    resolver: NameResolver
    # module-level `X = threading.Lock()` bindings
    module_locks: Dict[str, int] = dataclasses.field(default_factory=dict)
    functions: Dict[str, str] = dataclasses.field(default_factory=dict)
    classes: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class CallSite:
    node: ast.Call
    callees: Tuple[str, ...]       # resolved callee qnames (may be empty)


@dataclasses.dataclass
class ThreadRoot:
    """One discovered thread entry point: ``Thread(target=X)``."""

    target: str                    # qname of the target function/method
    spawn_module: str
    spawn_line: int


def _is_lock_ctor(resolver: NameResolver, node: ast.AST) -> Optional[str]:
    """'lock' / 'cond' when node constructs a threading primitive."""
    if not isinstance(node, ast.Call):
        return None
    name = resolver.resolve(node.func)
    tail = last_component(name)
    if tail in ("Lock", "RLock"):
        return "lock"
    if tail == "Condition":
        return "cond"
    return None


def _self_attr_path(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """('fleet', '_lock') for ``self.fleet._lock``; None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and parts:
        return tuple(reversed(parts))
    return None


class ProjectIndex:
    """Symbol table + call graph + thread roots over a set of modules.

    ``contexts`` is a sequence of objects with ``path`` (repo-relative
    posix) and ``tree`` (parsed module) — the engine hands it the SAME
    parsed trees the per-file pass used, so the index costs one walk, not
    one parse, per module.
    """

    def __init__(self, contexts: Sequence) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        # bare class name -> ClassInfo list (for class-hierarchy lookups);
        # (module, name) is unique, bare names may repeat across modules
        self.classes: Dict[str, List[ClassInfo]] = {}
        self.class_by_qname: Dict[str, ClassInfo] = {}
        # method name -> qnames of every indexed class method of that name
        self._methods_by_name: Dict[str, List[str]] = {}
        self.calls: Dict[str, List[CallSite]] = {}
        self.thread_roots: List[ThreadRoot] = []
        self.done_callbacks: List[str] = []

        for ctx in sorted(contexts, key=lambda c: c.path):
            self._index_module(ctx.path, ctx.tree)
        for fi in self.functions.values():
            self.calls[fi.qname] = self._resolve_calls(fi)
        self._discover_threads_and_callbacks()

    # -- symbol table -------------------------------------------------------

    def _index_module(self, path: str, tree: ast.AST) -> None:
        resolver = NameResolver(tree)
        mi = ModuleInfo(path=path, tree=tree, resolver=resolver)
        self.modules[path] = mi
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _is_lock_ctor(resolver, node.value):
                mi.module_locks[node.targets[0].id] = node.lineno
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_function(mi, node, cls=None)
            elif isinstance(node, ast.ClassDef):
                self._index_class(mi, node)

    def _index_function(self, mi: ModuleInfo, node: ast.AST,
                        cls: Optional[str]) -> FunctionInfo:
        name = getattr(node, "name", "<lambda>")
        if name == "<lambda>":
            qname = f"{mi.path}{QSEP}" + (f"{cls}." if cls else "") \
                    + f"<lambda@{node.lineno}>"
        elif cls:
            qname = f"{mi.path}{QSEP}{cls}.{name}"
        else:
            qname = f"{mi.path}{QSEP}{name}"
        fi = FunctionInfo(qname=qname, module=mi.path, cls=cls, name=name,
                          node=node, lineno=node.lineno)
        self.functions[qname] = fi
        if cls is None and name != "<lambda>":
            mi.functions.setdefault(name, qname)
        # lambdas anywhere inside this scope index with the same class
        # context (their `self` is the enclosing method's)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Lambda):
                lq = f"{mi.path}{QSEP}" + (f"{cls}." if cls else "") \
                     + f"<lambda@{sub.lineno}>"
                if lq not in self.functions:
                    self.functions[lq] = FunctionInfo(
                        qname=lq, module=mi.path, cls=cls, name="<lambda>",
                        node=sub, lineno=sub.lineno)
        return fi

    def _index_class(self, mi: ModuleInfo, node: ast.ClassDef) -> None:
        ci = ClassInfo(module=mi.path, name=node.name, node=node,
                       bases=tuple(b for b in
                                   (mi.resolver.resolve(base)
                                    for base in node.bases) if b))
        mi.classes[node.name] = f"{mi.path}{QSEP}{node.name}"
        self.classes.setdefault(node.name, []).append(ci)
        self.class_by_qname[f"{mi.path}{QSEP}{node.name}"] = ci
        for item in ast.iter_child_nodes(node):
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = self._index_function(mi, item, cls=node.name)
                ci.methods[item.name] = fi.qname
        # attribute model: lock attrs, condition aliases, constructor types
        for item in ast.walk(node):
            if not isinstance(item, ast.Assign) or len(item.targets) != 1:
                continue
            tgt = item.targets[0]
            ap = _self_attr_path(tgt)
            if ap is None or len(ap) != 1:
                continue
            attr = ap[0]
            kind = _is_lock_ctor(mi.resolver, item.value)
            if kind == "lock":
                ci.lock_attrs[attr] = item.lineno
            elif kind == "cond":
                args = item.value.args
                inner = _self_attr_path(args[0]) if args else None
                if inner and len(inner) == 1:
                    ci.cond_aliases[attr] = inner[0]
                else:
                    # a Condition() with its own hidden lock is still a
                    # lock for ordering purposes
                    ci.lock_attrs[attr] = item.lineno
            elif isinstance(item.value, ast.Call):
                ctor = last_component(mi.resolver.resolve(item.value.func))
                if ctor and ctor in self.classes or ctor and ctor[:1].isupper():
                    ci.attr_classes.setdefault(attr, ctor)
        for attr, cls_name in policy.ATTR_CLASS_HINTS.items():
            if attr[0] == node.name:
                ci.attr_classes[attr[1]] = cls_name

    # -- call resolution ----------------------------------------------------

    def _methods_named(self, name: str) -> List[str]:
        got = self._methods_by_name.get(name)
        if got is None:
            got = []
            for cname in sorted(self.classes):
                for ci in self.classes[cname]:
                    if name in ci.methods:
                        got.append(ci.methods[name])
            self._methods_by_name[name] = got
        return got

    def _class_named(self, name: Optional[str]) -> List[ClassInfo]:
        return self.classes.get(name, []) if name else []

    def _resolve_dotted(self, dotted: str) -> List[str]:
        """'obs.flightrec.note' -> qnames by dotted module-suffix match."""
        if "." not in dotted:
            return []
        mod_dots, leaf = dotted.rsplit(".", 1)
        out = []
        for path in sorted(self.modules):
            dotted_path = path[:-3].replace("/", ".") \
                if path.endswith(".py") else path.replace("/", ".")
            if dotted_path == mod_dots or \
                    dotted_path.endswith("." + mod_dots):
                mi = self.modules[path]
                if leaf in mi.functions:
                    out.append(mi.functions[leaf])
                elif leaf in mi.classes:
                    ci = self.class_by_qname[mi.classes[leaf]]
                    if "__init__" in ci.methods:
                        out.append(ci.methods["__init__"])
        return out

    def constructed_class(self, fi: FunctionInfo,
                          call: ast.Call) -> Optional[str]:
        """Class name when ``call`` constructs an indexed (or hinted)
        class — ``StreamState(...)``, ``spec.ArraySpec(...)``."""
        name = last_component(self.modules[fi.module]
                              .resolver.resolve(call.func))
        if name and (name in self.classes
                     or name in policy.BLOCKING_CONSTRUCTORS):
            return name
        return None

    def attr_class(self, fi: FunctionInfo, attr: str) -> Optional[str]:
        """The inferred/declared class of ``self.<attr>`` inside ``fi``."""
        if fi.cls is None:
            return None
        for ci in self._class_named(fi.cls):
            if ci.module == fi.module and attr in ci.attr_classes:
                return ci.attr_classes[attr]
        return None

    def _resolve_call(self, fi: FunctionInfo,
                      call: ast.Call) -> Tuple[str, ...]:
        mi = self.modules[fi.module]
        func = call.func
        out: List[str] = []
        if isinstance(func, ast.Name):
            if func.id in mi.functions:
                out.append(mi.functions[func.id])
            elif func.id in mi.classes:
                ci = self.class_by_qname[mi.classes[func.id]]
                if "__init__" in ci.methods:
                    out.append(ci.methods["__init__"])
            else:
                dotted = mi.resolver.resolve(func)
                if dotted and dotted != func.id:
                    out.extend(self._resolve_dotted(dotted))
                # bare imported class name: `from ..stream import StreamState`
                tail = last_component(dotted)
                for ci in self._class_named(tail):
                    if "__init__" in ci.methods:
                        out.append(ci.methods["__init__"])
        elif isinstance(func, ast.Attribute):
            meth = func.attr
            ap = _self_attr_path(func)
            if isinstance(func.value, ast.Call) and \
                    isinstance(func.value.func, ast.Name) and \
                    func.value.func.id == "super":
                # super().m() resolves within the index-visible bases only
                for ci in self._class_named(fi.cls):
                    if ci.module != fi.module:
                        continue
                    for base in ci.bases:
                        for bci in self._class_named(
                                last_component(base)):
                            if meth in bci.methods:
                                out.append(bci.methods[meth])
            elif ap is not None and len(ap) == 1 and fi.cls is not None:
                # self.m() -> own class (plus index-resolved bases)
                for ci in self._class_named(fi.cls):
                    if ci.module != fi.module:
                        continue
                    if meth in ci.methods:
                        out.append(ci.methods[meth])
                    else:
                        for base in ci.bases:
                            for bci in self._class_named(
                                    last_component(base)):
                                if meth in bci.methods:
                                    out.append(bci.methods[meth])
            elif ap is not None and len(ap) == 2:
                # self.attr.m() through the attribute's inferred class
                for ci in self._class_named(
                        self.attr_class(fi, ap[0])):
                    if meth in ci.methods:
                        out.append(ci.methods[meth])
            else:
                dotted = mi.resolver.resolve(func)
                if dotted:
                    out.extend(self._resolve_dotted(dotted))
                    # ClassName.m / imported-instance patterns
                    parts = dotted.split(".")
                    if len(parts) >= 2:
                        for ci in self._class_named(parts[-2]):
                            if meth in ci.methods:
                                out.append(ci.methods[meth])
                if not out and meth not in policy.GENERIC_METHOD_NAMES \
                        and not meth.startswith("__"):
                    # untyped receiver: class-hierarchy over-approximation
                    # (dunders excluded — every class has them)
                    out.extend(self._methods_named(meth))
        seen, uniq = set(), []
        for q in out:
            if q not in seen:
                seen.add(q)
                uniq.append(q)
        return tuple(uniq)

    def _resolve_calls(self, fi: FunctionInfo) -> List[CallSite]:
        sites: List[CallSite] = []
        for node in self._walk_own_scope(fi.node):
            if isinstance(node, ast.Call):
                sites.append(CallSite(node=node,
                                      callees=self._resolve_call(fi, node)))
        sites.sort(key=lambda s: (s.node.lineno, s.node.col_offset))
        return sites

    @staticmethod
    def _walk_own_scope(fn: ast.AST):
        """Nodes of ``fn``'s own scope, not descending into nested
        def/lambda bodies (they are indexed as their own functions)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    # -- thread roots + future callbacks ------------------------------------

    def _target_qname(self, fi: FunctionInfo,
                      node: ast.AST) -> Optional[str]:
        """Resolve a Thread(target=X) / add_done_callback(X) argument."""
        mi = self.modules[fi.module]
        if isinstance(node, ast.Lambda):
            lq = f"{fi.module}{QSEP}" + \
                 (f"{fi.cls}." if fi.cls else "") + \
                 f"<lambda@{node.lineno}>"
            return lq if lq in self.functions else None
        ap = _self_attr_path(node)
        if ap is not None and len(ap) == 1 and fi.cls is not None:
            for ci in self._class_named(fi.cls):
                if ci.module == fi.module and ap[0] in ci.methods:
                    return ci.methods[ap[0]]
            return None
        if isinstance(node, ast.Name):
            if node.id in mi.functions:
                return mi.functions[node.id]
            # nested def: find a FunctionInfo with that bare name in module
            q = f"{fi.module}{QSEP}{node.id}"
            if q in self.functions:
                return q
        dotted = mi.resolver.resolve(node) if isinstance(
            node, (ast.Name, ast.Attribute)) else None
        if dotted:
            got = self._resolve_dotted(dotted)
            if got:
                return got[0]
        return None

    def _discover_threads_and_callbacks(self) -> None:
        cb_seen = set()
        for qname in sorted(self.functions):
            fi = self.functions[qname]
            for site in self.calls[qname]:
                call = site.node
                name = self.modules[fi.module].resolver.resolve(call.func)
                if name in _THREAD_NAMES or \
                        (name or "").endswith(".Thread"):
                    for kw in call.keywords:
                        if kw.arg == "target":
                            tq = self._target_qname(fi, kw.value)
                            if tq is not None:
                                self.thread_roots.append(ThreadRoot(
                                    target=tq, spawn_module=fi.module,
                                    spawn_line=call.lineno))
                if isinstance(call.func, ast.Attribute) and \
                        call.func.attr == "add_done_callback" and call.args:
                    tq = self._target_qname(fi, call.args[0])
                    if tq is not None and tq not in cb_seen:
                        cb_seen.add(tq)
                        self.done_callbacks.append(tq)

    # -- queries -------------------------------------------------------------

    def callees_of(self, qname: str) -> Tuple[str, ...]:
        seen, out = set(), []
        for site in self.calls.get(qname, ()):
            for q in site.callees:
                if q not in seen:
                    seen.add(q)
                    out.append(q)
        return tuple(out)

    def future_resolution_targets(self) -> Tuple[str, ...]:
        """Every function a ``set_result``/``set_exception`` can invoke
        synchronously: the project's registered done-callbacks."""
        return tuple(self.done_callbacks)

    def reachable_from(self, roots: Sequence[str]) -> List[str]:
        """Transitive closure over the call graph, deterministic order."""
        seen: List[str] = []
        seen_set = set()
        stack = [q for q in roots if q in self.functions]
        while stack:
            q = stack.pop(0)
            if q in seen_set:
                continue
            seen_set.add(q)
            seen.append(q)
            stack.extend(self.callees_of(q))
        return seen


def build_index(contexts: Sequence) -> ProjectIndex:
    return ProjectIndex(contexts)
