"""Rule engine: file walking, pragma suppression, baseline, reporting.

The analyzer is a correctness tool for the engine's *invariants* — stream
discipline, dtype policy, tracer hygiene, mesh-axis contracts — so it holds
itself to the same standard: pure stdlib, no import of the code under
analysis, deterministic output ordering, and an explicit suppression trail
(every ``# fakepta: allow[rule]`` must carry a one-line justification, and
the committed baseline is versioned data, not tribal knowledge).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import policy

# rule id for the meta-rule enforcing justified pragmas; kept here because
# the engine (pragma parser), not a visitor, detects it
PRAGMA_RULE = "pragma-justification"
UNUSED_PRAGMA_RULE = "pragma-unused"


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at a source location (ordering = report order)."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"


@dataclasses.dataclass
class Pragma:
    line: int            # physical line the comment sits on
    target: int          # line whose findings it suppresses
    rules: Tuple[str, ...]
    justification: str
    used: bool = False


_PRAGMA_RE = re.compile(
    r"fakepta:\s*allow\[([A-Za-z0-9_,\- ]+)\]\s*(.*)$")


@dataclasses.dataclass
class ModuleContext:
    """Everything a rule needs to know about one source file."""

    path: str                 # as reported (repo-relative posix)
    tree: ast.AST
    source: str
    dtype_policy: str         # policy.DTYPE_* value for this module
    is_library: bool

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(self.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0) + 1, rule, message)


def parse_pragmas(source: str) -> List[Pragma]:
    """Extract ``# fakepta: allow[rule-a,rule-b] <justification>`` comments.

    Comments are found with :mod:`tokenize` (never regex over raw lines), so
    a ``#`` inside a string literal cannot fake a pragma. A pragma on a code
    line suppresses that line; a standalone pragma (comment-only line)
    suppresses the next code line — the ergonomic spot above a long
    statement.
    """
    pragmas: List[Pragma] = []
    standalone: List[Pragma] = []
    code_lines = set()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):  # syntax errors surface
        return []                                    # via ast.parse instead
    for tok in tokens:
        if tok.type == tokenize.COMMENT:
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            rules = tuple(r.strip() for r in m.group(1).split(",")
                          if r.strip())
            p = Pragma(line=tok.start[0], target=tok.start[0], rules=rules,
                       justification=m.group(2).strip())
            line_src = source.splitlines()[tok.start[0] - 1]
            if line_src.lstrip().startswith("#"):
                standalone.append(p)
            pragmas.append(p)
        elif tok.type not in (tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
                              tokenize.DEDENT, tokenize.ENCODING,
                              tokenize.ENDMARKER, tokenize.COMMENT):
            code_lines.add(tok.start[0])
    # standalone pragmas retarget to the next code line
    for p in standalone:
        nxt = [ln for ln in code_lines if ln > p.line]
        if nxt:
            p.target = min(nxt)
    return pragmas


def all_rules():
    """The registered rule list: (rule_id, check(ctx) -> findings)."""
    from .rules import ALL_RULES

    return ALL_RULES


def project_rules():
    """The whole-program rule list: (rule_id, check(index) -> findings).

    Imported lazily — the concurrency pass sits on top of the project
    index, which itself reuses the per-file resolver machinery."""
    from .rules import PROJECT_RULES

    return PROJECT_RULES


def _apply_pragmas(rel: str, source: str,
                   findings: Sequence[Finding]) -> List[Finding]:
    """Pragma suppression + the engine's own meta-findings for one file."""
    pragmas = parse_pragmas(source)
    by_target: Dict[int, List[Pragma]] = {}
    for p in pragmas:
        by_target.setdefault(p.target, []).append(p)
        if p.target != p.line:
            by_target.setdefault(p.line, []).append(p)

    kept: List[Finding] = []
    for f in findings:
        suppressed = False
        for p in by_target.get(f.line, ()):
            if f.rule in p.rules:
                p.used = True
                suppressed = True
        if not suppressed:
            kept.append(f)

    for p in pragmas:
        if not p.justification:
            kept.append(Finding(
                rel, p.line, 1, PRAGMA_RULE,
                f"pragma allow[{','.join(p.rules)}] carries no "
                f"justification; append a one-line reason"))
        elif not p.used:
            kept.append(Finding(
                rel, p.line, 1, UNUSED_PRAGMA_RULE,
                f"pragma allow[{','.join(p.rules)}] suppresses nothing on "
                f"line {p.target}; remove it or fix the rule id"))
    return sorted(kept)


def _parse_context(path: str, source: str):
    """(ModuleContext, None) or (None, syntax-error Finding)."""
    rel = path.replace("\\", "/")
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return None, Finding(rel, e.lineno or 1, (e.offset or 0) + 1,
                             "syntax-error",
                             f"file does not parse: {e.msg}")
    return ModuleContext(path=rel, tree=tree, source=source,
                         dtype_policy=policy.dtype_policy_for(rel),
                         is_library=policy.is_library(rel)), None


def check_source(path: str, source: str,
                 rules: Optional[Sequence] = None) -> List[Finding]:
    """Run every per-file rule over one module's source; apply pragma
    suppression.

    Returns the surviving findings (sorted), including the engine's own
    meta-findings: unjustified pragmas (always) — a pragma with no reason is
    tribal knowledge in the making. The whole-program pass does NOT run
    here (see :func:`check_files`) — per-file findings stay byte-identical
    whatever the rest of the project looks like.
    """
    ctx, err = _parse_context(path, source)
    if err is not None:
        return [err]
    findings: List[Finding] = []
    for rule_id, check in (rules if rules is not None else all_rules()):
        findings.extend(check(ctx))
    return _apply_pragmas(ctx.path, source, findings)


def check_files(files: Sequence[Tuple[str, str]],
                rules: Optional[Sequence] = None,
                project: Optional[Sequence] = None,
                run_project: bool = True) -> List[Finding]:
    """The two-pass analysis over ``(path, source)`` pairs.

    Pass 1 runs the per-file rules on each module exactly as
    :func:`check_source` would. Pass 2 builds one
    :class:`~fakepta_tpu.analysis.project.ProjectIndex` over the *library*
    modules (``policy.is_library``) and runs the whole-program rules on
    it. Both passes' findings flow through the same per-file pragma
    machinery — an ``allow[lock-order-inversion]`` on the witness line
    suppresses the interprocedural finding like any other.
    """
    contexts: List[Tuple[ModuleContext, str]] = []
    out: List[Finding] = []
    per_path: Dict[str, List[Finding]] = {}
    for path, source in files:
        ctx, err = _parse_context(path, source)
        if err is not None:
            out.append(err)
            continue
        contexts.append((ctx, source))
        bucket = per_path.setdefault(ctx.path, [])
        for rule_id, check in (rules if rules is not None else all_rules()):
            bucket.extend(check(ctx))

    if run_project:
        lib_ctxs = [ctx for ctx, _ in contexts if ctx.is_library]
        if lib_ctxs:
            from .project import build_index

            index = build_index(lib_ctxs)
            for rule_id, check in (project if project is not None
                                   else project_rules()):
                for f in check(index):
                    if f.path in per_path:
                        per_path[f.path].append(f)
                    else:
                        out.append(f)

    for ctx, source in contexts:
        out.extend(_apply_pragmas(ctx.path, source,
                                  per_path.get(ctx.path, ())))
    return sorted(out)


def check_source_project(path: str, source: str) -> List[Finding]:
    """One file through BOTH passes (fixture corpus entry point)."""
    return check_files([(path, source)])


def iter_python_files(paths: Sequence[str]) -> Iterable[Path]:
    """Expand path arguments: files pass through, directories walk ``*.py``
    minus the default-excluded dir names (fixture corpora, caches)."""
    seen = set()
    for raw in paths:
        p = Path(raw)
        if p.is_file():
            if p not in seen:
                seen.add(p)
                yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if any(part in policy.EXCLUDE_DIR_NAMES
                       for part in f.parts):
                    continue
                if f not in seen:
                    seen.add(f)
                    yield f


def _rel(p: Path, root: Optional[Path]) -> str:
    base = root if root is not None else Path.cwd()
    try:
        return p.resolve().relative_to(base.resolve()).as_posix()
    except ValueError:
        return p.as_posix()


def check_paths(paths: Sequence[str], root: Optional[Path] = None,
                rules: Optional[Sequence] = None,
                run_project: bool = True) -> List[Finding]:
    """Analyze every python file under ``paths``; returns sorted findings.

    Runs both passes: per-file rules on every file, whole-program rules
    over the library modules in the set."""
    files = [(_rel(f, root), f.read_text(encoding="utf-8"))
             for f in iter_python_files(paths)]
    return check_files(files, rules=rules, run_project=run_project)


def build_project_index(paths: Sequence[str],
                        root: Optional[Path] = None):
    """A ProjectIndex over the library modules under ``paths`` (the
    ``graph`` CLI subcommand and tooling entry point)."""
    from .project import build_index

    contexts = []
    for f in iter_python_files(paths):
        ctx, err = _parse_context(_rel(f, root),
                                  f.read_text(encoding="utf-8"))
        if ctx is not None and ctx.is_library:
            contexts.append(ctx)
    return build_index(contexts)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def baseline_key(f: Finding) -> str:
    return f"{f.path}::{f.rule}"


def load_baseline(path: Path) -> Dict[str, int]:
    data = json.loads(path.read_text(encoding="utf-8"))
    if not isinstance(data, dict) or data.get("version") != 1:
        raise ValueError(f"unrecognized baseline format in {path}")
    counts = data.get("findings", {})
    if not all(isinstance(v, int) for v in counts.values()):
        raise ValueError(f"baseline counts must be integers in {path}")
    return dict(counts)


def save_baseline(path: Path, findings: Sequence[Finding]) -> None:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[baseline_key(f)] = counts.get(baseline_key(f), 0) + 1
    path.write_text(json.dumps(
        {"version": 1, "findings": dict(sorted(counts.items()))},
        indent=2) + "\n", encoding="utf-8")


def apply_baseline(findings: Sequence[Finding],
                   baseline: Dict[str, int]) -> List[Finding]:
    """Drop up to ``baseline[key]`` findings per (path, rule) — line numbers
    churn too much to key on, counts don't. New findings always surface."""
    budget = dict(baseline)
    kept: List[Finding] = []
    for f in sorted(findings):
        k = baseline_key(f)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            kept.append(f)
    return kept
