"""Per-module policy tables the rules cross-check against.

This is the one place where the repo's precision/axis contracts are written
down as data rather than prose: which modules are *sanctioned* host-float64
stages (their f64 use is the design, not a leak), which mesh axis names
exist, and what counts as library code (where e.g. literal re-seeding is a
bug rather than a test convenience).

Keep this file boring: plain dicts and tuples, no imports from the rest of
the package, so rules and tests can read it without dragging jax in.
"""

from __future__ import annotations

# Mesh axis names every collective must use — single-sourced in spirit with
# fakepta_tpu/parallel/mesh.py (REAL_AXIS/PSR_AXIS/TOA_AXIS); duplicated as
# literals here because the analyzer must not import the package under
# analysis. test_static_analysis pins the two in sync.
MESH_AXES = ("real", "psr", "toa")

# Module-level constant names that resolve to a declared axis (the idiomatic
# way montecarlo.py spells them).
MESH_AXIS_CONSTANTS = ("REAL_AXIS", "PSR_AXIS", "TOA_AXIS")

# dtype policy: repo-relative posix paths -> "host-f64" for modules whose
# float64 use is sanctioned by design (one-off host staging: ephemeris
# element propagation, CGW phase references, ORF Cholesky factorization,
# pixel geometry, the host facade's f64 phase tables). Everything else under
# the library prefix defaults to "device-f32", where f64 markers are
# findings unless pragma'd with a justification; paths outside the library
# (tests, examples, benchmarks) are exempt — their f64 oracles are the
# point.
DTYPE_POLICY = {
    "fakepta_tpu/ephemeris.py": "host-f64",
    "fakepta_tpu/models/cgw.py": "host-f64",
    "fakepta_tpu/ops/healpix.py": "host-f64",
    "fakepta_tpu/ops/gwb.py": "host-f64",
    "fakepta_tpu/ops/kepler.py": "host-f64",
    "fakepta_tpu/fake_pta.py": "host-f64",
    "fakepta_tpu/utils/io.py": "host-f64",
    # the batch builder IS the sanctioned host-f64 staging layer: absolute
    # TOAs and noisedict variances assemble at f64, device arrays take the
    # batch dtype at materialization
    "fakepta_tpu/batch.py": "host-f64",
    # facade-side statistics layer: host numpy analysis (optimal statistic,
    # ORF fits) around small jitted helpers whose dtype follows the inputs
    "fakepta_tpu/correlated_noises.py": "host-f64",
    # the observability layer is pure host-side telemetry (metrics, reports,
    # CLI): wall-clock floats and JSON serialization are its job, never
    # device arrays — its hooks are trace-time-only by contract
    # (docs/INVARIANTS.md), so f64 host timing there is sanctioned
    "fakepta_tpu/obs/__init__.py": "host-f64",
    "fakepta_tpu/obs/metrics.py": "host-f64",
    "fakepta_tpu/obs/timing.py": "host-f64",
    "fakepta_tpu/obs/report.py": "host-f64",
    "fakepta_tpu/obs/cli.py": "host-f64",
    "fakepta_tpu/obs/__main__.py": "host-f64",
    "fakepta_tpu/obs/trace.py": "host-f64",
    "fakepta_tpu/obs/memwatch.py": "host-f64",
    "fakepta_tpu/obs/flightrec.py": "host-f64",
    "fakepta_tpu/obs/gate.py": "host-f64",
    # the detection-statistics subsystem's host layers: operator precompute
    # (ORF templates, pair counts, noise weighting) is one-off f64 staging
    # like the ORF Cholesky; the facade/CLI reduce packed lanes with host
    # numpy. The device contraction itself lives in parallel/montecarlo.py
    # under the default device-f32 policy.
    "fakepta_tpu/detect/operators.py": "host-f64",
    "fakepta_tpu/detect/run.py": "host-f64",
    "fakepta_tpu/detect/cli.py": "host-f64",
    # the inference subsystem's host layers: the facade/CLI reduce packed
    # likelihood lanes with host numpy (recovery metrics, Fisher means) and
    # stage theta grids at f64. The device pieces live elsewhere under the
    # default device-f32 policy: ops/woodbury.py and infer/model.py's
    # basis/phi/lnl functions are dtype-polymorphic jnp (they run at the
    # batch dtype inside the chunk program), and the engine lane is in
    # parallel/montecarlo.py.
    "fakepta_tpu/infer/run.py": "host-f64",
    "fakepta_tpu/infer/cli.py": "host-f64",
    # the sampling subsystem's host layers: the facade's one-off f64
    # staging (data -> Woodbury moments -> Newton/Laplace warm start runs
    # under enable_x64 on CPU before any chain dispatches) and the host
    # diagnostics finishers (R-hat/ESS from drained accumulators at f64).
    # The device pieces live elsewhere under device-f32: ops/mcmc.py is
    # dtype-polymorphic jnp and the chain program runs at the batch dtype.
    "fakepta_tpu/sample/run.py": "host-f64",
    "fakepta_tpu/sample/model.py": "host-f64",
    "fakepta_tpu/sample/cli.py": "host-f64",
    # the factorized free-spectrum driver: plan derivation, host-side
    # moment restriction (numpy, f64-preserving by contract), the dense
    # f64 additivity oracle, and lane recombination are all host staging
    # around ordinary SamplingRun lanes (the device pieces are unchanged).
    "fakepta_tpu/sample/factorized.py": "host-f64",
    # the serve protocol codec: JSON request lines stage their TOA blocks
    # and theta grids to host f64 arrays (the same staging role the other
    # subsystem CLIs play); the device work happens in the pool/stream
    # layers under their own policies.
    "fakepta_tpu/serve/cli.py": "host-f64",
    # the streaming-ingestion subsystem: append-vs-restage is certified as
    # an f64 oracle (docs/STREAMING.md), so the StreamState kernels, the
    # rolling OS statistic, and the refresher's Laplace warm start all run
    # under enable_x64 when the stream dtype is f64 (the default). The
    # incremental-moment device math itself is dtype-polymorphic jnp
    # (ops/woodbury.py append_parts under the default device-f32 policy).
    "fakepta_tpu/stream/state.py": "host-f64",
    "fakepta_tpu/stream/refresh.py": "host-f64",
    "fakepta_tpu/stream/bench.py": "host-f64",
    "fakepta_tpu/detect/streaming.py": "host-f64",
}
DTYPE_DEFAULT_LIBRARY = "device-f32"
DTYPE_EXEMPT = "exempt"

# bf16-storage policy (the mixed-precision-cast rule): library modules
# sanctioned to down-cast f32 arrays to bfloat16 — the storage-halving /
# f32-accumulate precision modes (pallas kernel operands, the megakernel's
# bf16 base/coefficient storage, the engine's bases/stats casts). An
# implicit f32->bf16 cast anywhere ELSE in the library is a silent
# half-precision leak: it changes realization streams without a policy
# entry or a tolerance certification, so the rule flags it (pragma with the
# certified bound, or add the module here WITH the certification tests).
BF16_STORAGE_MODULES = (
    "fakepta_tpu/ops/pallas_kernels.py",
    "fakepta_tpu/ops/megakernel.py",
    "fakepta_tpu/parallel/montecarlo.py",
)

# timing-discipline allowlist: library modules sanctioned to read raw
# clocks (time.time / time.perf_counter / time.monotonic). obs/timing.py IS
# the sanctioned clock (everything routes through its now()/Timer/span);
# obs/flightrec.py reads perf_counter directly to stay import-cycle-free
# below the metrics core (metrics mirrors events into the flight-recorder
# ring, so flightrec cannot import timing, which imports metrics). A bare
# clock read anywhere else in the library is a measurement the telemetry
# artifacts never see — the rule flags it.
TIMING_MODULES = (
    "fakepta_tpu/obs/timing.py",
    "fakepta_tpu/obs/flightrec.py",
)

# unbounded-queue allowlist: library modules whose unbounded queue/deque
# construction is bounded by an EXTERNAL invariant rather than a maxsize/
# maxlen argument. pipeline.py's writer queue is the one deliberate case:
# the run loop's donated-buffer recycling ring blocks dispatch until the
# oldest in-flight chunk drains, so the queue never holds more than
# depth + 1 entries (ThreadWriter docstring) — a maxsize would just add a
# second, redundant blocking point on the dispatch thread. Everything else
# (the serve scheduler's admission/demux queues, the SLO rings, the flight
# recorder) carries an explicit bound.
UNBOUNDED_QUEUE_MODULES = (
    "fakepta_tpu/parallel/pipeline.py",
)

# unbounded-cache allowlist: library modules whose cache-named dict
# containers are bounded by an EXTERNAL invariant the AST can't see.
# Currently empty: every cache in the repo carries its bound locally —
# the fake_pta phase cache evicts oldest-first against a byte budget, the
# fleet's _recent and the gateway result index are popitem-bounded LRUs,
# and the gateway single-flight table bypasses (never inserts) at cap.
UNBOUNDED_CACHE_MODULES = ()

# unbounded-thread-join allowlist: library modules whose bare ``.join()``
# waits are bounded by an EXTERNAL invariant rather than a timeout
# argument. Currently empty: every shutdown join in the repo carries a
# generous bound and flight-records the leak when it expires
# (serve/scheduler.py ``serve_close_join_timeout``, serve/health.py
# ``health_stop_join_timeout``, serve/loadgen.py
# ``fleet_spawn_join_timeout`` — docs/RELIABILITY.md shutdown discipline).
UNBOUNDED_JOIN_MODULES = ()

# unbounded-socket-io allowlist: library modules whose blocking socket
# reads are bounded by an EXTERNAL invariant rather than a settimeout in
# scope (e.g. an intentionally-blocking accept loop whose lifetime the
# process owner controls). Currently empty: the serve socket server sets a
# per-connection idle timeout in its handler setup and the fleet's socket
# client stamps timeouts at connect (serve/cli.py, serve/fleet.py), so
# every blocking read in the repo carries a deadline in scope.
SOCKET_IO_MODULES = ()

# swallowed-exception allowlist: library modules whose broad silent
# handlers are the DESIGN, not a leak. obs/flightrec.py is the crash
# flight recorder itself: its dump path runs inside another exception's
# handling, and a dump failure must never mask the exception being
# reported — there is no lower layer left to record to. obs/memwatch.py
# probes per-device allocator stats across backends where the probe
# itself raises arbitrarily (missing attr, RPC error, stale device);
# the sampler's contract is "telemetry is best-effort, never a crash",
# and an unstatted device is the recorded outcome (the field is absent).
# Everything else records or re-raises (docs/RELIABILITY.md).
SWALLOWED_EXCEPT_MODULES = (
    "fakepta_tpu/obs/flightrec.py",
    "fakepta_tpu/obs/memwatch.py",
)

# metric-name discipline (analysis/rules/metric_names.py): every library
# call to the obs counter/gauge/timing emitters (obs.count / obs.gauge /
# obs.observe, the Collector methods on a ``collector`` receiver, and
# obs.telemetry.publish) must pass a LITERAL name drawn from this registry
# and matching METRIC_NAME_RE — renaming a metric is a schema change made
# in obs/metrics.py, not a drive-by edit at a call site, which is what
# keeps the Prometheus exposition names stable. Duplicated as literals here
# because the analyzer must not import the package under analysis;
# test_static_analysis pins this tuple == obs.metrics.METRIC_NAMES.
METRIC_NAME_RE = r"^[a-z][a-z0-9_.]*$"
METRIC_NAMES = (
    "faults.degradations", "faults.injected", "faults.retries",
    "faults.rollbacks",
    "fleet.breaker_opens", "fleet.drains", "fleet.heartbeat_misses",
    "fleet.joins", "fleet.scale_events",
    "gateway.auth_failures", "gateway.cache_rejects",
    "gateway.coalesce_bypass", "gateway.coalesced",
    "gateway.cutover_aborts", "gateway.cutovers", "gateway.hits",
    "gateway.requests", "gateway.store_evictions", "gateway.store_puts",
    "gateway.throttles",
    "jax.backend_compile_s", "jax.lowering_s", "jax.trace_s",
    "obs.chunks", "obs.peak_hbm_bytes", "obs.retraces", "obs.traces",
    "pipeline.d2h_async", "pipeline.h2d_prefetch",
    "sample.lane_runs", "sample.segments_done",
    "serve.append_latency_s", "serve.stream_requests",
    "stream.appends", "stream.compiles", "stream.detections",
    "stream.fs_bins_touched", "stream.fs_lanes_refreshed",
    "stream.fs_refreshes",
    "stream.promotions", "stream.rebuckets", "stream.recompiles",
    "stream.refresh_gate_holds", "stream.refresh_gate_opens",
    "stream.refresh_skips", "stream.refreshes", "stream.replays",
    "telemetry.alerts", "telemetry.scrape_errors", "telemetry.scrapes",
)

# metric-name-discipline allowlist: library modules sanctioned to emit
# dynamic (non-literal) metric names. obs/metrics.py defines the emitters —
# its helpers forward caller-supplied names by construction; obs/timing.py
# derives ``timer.<name>`` names from caller-chosen Timer labels (the
# per-timer histogram IS the feature). Everywhere else a computed name
# would silently mint an unregistered exposition series.
METRIC_NAME_MODULES = (
    "fakepta_tpu/obs/metrics.py",
    "fakepta_tpu/obs/timing.py",
)

# hardcoded-dispatch-knob allowlist: the ONE library module where literal
# dispatch-knob values (megakernel rt, pipeline_depth, bucket ladders) may
# live — the hand-set defaults the autotuner A/Bs against
# (fakepta_tpu.tune, docs/TUNING.md). Every other library call site must
# plumb knobs from a caller, a TunedConfig, or tune/defaults.py; tests,
# examples and benchmarks are exempt (their pinned knobs are the
# experimental conditions being measured).
DISPATCH_KNOB_MODULES = (
    "fakepta_tpu/tune/defaults.py",
)

# the only modules where flagship-scale ArraySpec / PulsarBatch.synthetic
# literals may live (the unregistered-scenario rule, docs/SCENARIOS.md):
# the scenario registry is the single source of named array-scale
# configurations, and tune/defaults.py's probe shapes are dispatch-tuning
# inputs, not dataset definitions. Everything else — INCLUDING bench.py
# and benchmarks/, where shadow flagships historically accreted —
# resolves scenarios by name through fakepta_tpu.scenarios.registry.
SCENARIO_SPEC_MODULES = (
    "fakepta_tpu/scenarios/registry.py",
    "fakepta_tpu/tune/defaults.py",
)

# the npsr floor separating "a unit-test fixture" from "a dataset claim":
# at or above this population size an ad-hoc literal is a shadow scenario
SCENARIO_NPSR_FLOOR = 64

# ---------------------------------------------------------------------------
# whole-program concurrency policy (analysis/concurrency.py)
# ---------------------------------------------------------------------------

# Canonical lock names for acquisitions that reach another object's lock
# through a duck-typed attribute (``self.fleet._lock`` from the health
# monitor IS the fleet's lock). Keys are the lock name as observed at the
# acquisition site (``<OwnerClass>.<attr path>``); values are the canonical
# name the lock-order graph uses. Without an alias each spelling would be a
# distinct graph node and cross-object cycles would go unseen.
LOCK_ALIASES = {
    "HealthMonitor.fleet._lock": "ServeFleet._lock",
    "SamplingSession.fleet._lock": "ServeFleet._lock",
    "LocalReplica.pool._lock": "ServePool._lock",
    "LocalReplica.pool._cond": "ServePool._lock",
}

# The canonical lock acquisition order (docs/INVARIANTS.md "Concurrency &
# collective discipline"). A thread may acquire a lock only while holding
# locks that appear EARLIER in this tuple; an observed edge that runs
# backwards is a lock-order-inversion finding even before a full cycle
# exists in the graph. Locks not listed here are constrained only by cycle
# detection.
LOCK_ORDER = (
    "Gateway._lock",           # gateway tier: tenant admission + the
                               # single-flight table (outermost — held for
                               # bookkeeping only, released before any
                               # fleet/store call; futures resolve outside)
    "SocketReplica._lock",     # transport: pending-futures map (leaf-most
                               # holder — completion callbacks run OUTSIDE)
    "ServePool._lock",         # scheduler: admission queues + stats
    "StreamManager._lock",     # stream registry (per-stream locks nest
                               # UNDER nothing — opened outside the registry)
    "ServeFleet._lock",        # router: ring membership + SLO stats
    "HealthMonitor._lock",     # health counters (probes run lock-free)
    "ResultStore._io_lock",    # gateway index-file writes: serializes
                               # write_atomic's fixed staged tmp name;
                               # nests OVER _lock (flush re-snapshots)
    "ResultStore._lock",       # gateway result store: index + payload LRU
                               # (leaf under _io_lock — payload IO happens
                               # outside, index mutation is bookkeeping)
    "obs/flightrec._dump_lock",  # flight-recorder dump serialization
                                 # (leaf; module locks are keyed
                                 # <module-short>.<name>)
)

# Duck-typed attribute -> class hints for call/lock resolution where the
# constructor assigns a bare parameter (``self.fleet = fleet``): the index
# cannot infer the type, so the policy declares it. Keys: (owner class,
# attribute name).
ATTR_CLASS_HINTS = {
    ("HealthMonitor", "fleet"): "ServeFleet",
    ("SamplingSession", "fleet"): "ServeFleet",
    ("Autoscaler", "fleet"): "ServeFleet",
}

# Engine-dispatch method names that block for a device program (compile +
# execute) — reachable under a lock they serialize every sibling behind
# minutes of device work (the blocking-under-lock rule).
BLOCKING_DISPATCH_METHODS = ("run", "warm_start", "prewarm", "ensure_warm")

# Class constructors whose __init__ does heavy device/IO work (checkpoint
# replay, process spawn + banner handshake): constructing one under a lock
# is a blocking-under-lock finding just like an engine dispatch.
BLOCKING_CONSTRUCTORS = ("StreamState", "SocketReplica", "ServePool")

# Per-module exemptions for the whole-program rules (same shape as the
# per-file allowlists above; prefer a line pragma with a justification —
# a module-wide exemption is for modules whose DESIGN is the exception).
BLOCKING_UNDER_LOCK_MODULES = ()
SHARED_STATE_MODULES = ()
COLLECTIVE_DIVERGENCE_MODULES = ()

# Method names too generic for class-hierarchy call resolution: an
# untyped receiver's ``x.get()`` must not resolve to every class in the
# repo that happens to define ``get``. Distinctive names (``submit``,
# ``retry_hint``, ``ping``, ``handle``) still resolve to every indexed
# class that defines them — that over-approximation is what lets the
# lock-order pass see a failover callback re-entering a sibling replica.
GENERIC_METHOD_NAMES = frozenset((
    "append", "extend", "add", "get", "put", "pop", "popleft", "items",
    "keys", "values", "update", "copy", "clear", "close", "join", "wait",
    "result", "set", "is_set", "count", "index", "insert", "remove",
    "sort", "read", "write", "flush", "note", "stats", "start", "stop",
    "run", "send", "recv", "encode", "decode", "format", "split", "strip",
    "exists", "open", "name", "parts", "todict", "acquire", "release",
    "mean", "sum", "std", "min", "max", "reset", "kill", "report",
))

# Library code prefix: rules with a library-only clause (literal re-seeding,
# dtype policy) fire only under it.
LIBRARY_PREFIXES = ("fakepta_tpu/",)

# Directory names skipped when *walking* directories (explicit file
# arguments always win): the analyzer's own fixture corpus is intentionally
# dirty, so `check tests/` must not trip on it.
EXCLUDE_DIR_NAMES = ("__pycache__", "fixtures_analysis", ".git")


def dtype_policy_for(rel: str) -> str:
    """Resolve the dtype policy for a repo-relative posix path."""
    if rel in DTYPE_POLICY:
        return DTYPE_POLICY[rel]
    if is_library(rel):
        return DTYPE_DEFAULT_LIBRARY
    return DTYPE_EXEMPT


def is_library(rel: str) -> bool:
    return any(rel.startswith(p) for p in LIBRARY_PREFIXES)
