"""Static analysis for the engine's correctness invariants.

An stdlib-``ast`` linter enforcing, at review time, the contracts the test
suite can only spot-check at runtime: RNG stream discipline, host-sync and
tracer hygiene inside jitted scopes, the per-module dtype policy, and the
mesh-axis naming contract. See docs/INVARIANTS.md for the catalogue and
``python -m fakepta_tpu.analysis check fakepta_tpu/ tests/ examples/`` for
the CLI the tier-1 suite runs.

Suppression: ``# fakepta: allow[rule-id] <one-line justification>`` on (or
standalone above) the offending line, or the committed baseline
(``fakepta_tpu/analysis/baseline.json``). Unjustified pragmas are findings
themselves.
"""

from .engine import (Finding, apply_baseline, build_project_index,
                     check_files, check_paths, check_source,
                     check_source_project, load_baseline, save_baseline)
from .rules import ALL_RULES, PROJECT_RULE_IDS, PROJECT_RULES, RULE_IDS

__all__ = ["Finding", "ALL_RULES", "RULE_IDS", "PROJECT_RULES",
           "PROJECT_RULE_IDS", "apply_baseline", "build_project_index",
           "check_files", "check_paths", "check_source",
           "check_source_project", "load_baseline", "save_baseline"]
