"""unregistered-scenario: ad-hoc flagship-scale array literals outside the
scenario registry.

``fakepta_tpu.scenarios.registry`` is the single source of named
array-scale configurations (docs/SCENARIOS.md): a flagship-scale
``ArraySpec(npsr=100, ...)`` or ``PulsarBatch.synthetic(npsr=100, ...)``
literal spelled out anywhere else in library or bench code is a shadow
scenario — it drifts from the registered spec silently (different ntoa,
different seed, different noise menu), its rows stop grouping with the
registry's spec hashes, and the golden-run trajectory loses the very
config the literal was meant to measure. Sanctioned homes
(``analysis.policy.SCENARIO_SPEC_MODULES``): the registry itself and
``tune/defaults.py`` (whose probe shapes are dispatch-tuning inputs, not
dataset definitions). Everything else resolves scenarios by name —
``scenarios.get("flagship_100").batch_parts()`` / ``.serve_spec()`` — or
derives variants with ``dataclasses.replace`` on a registered spec.

Flagged at a ``Call`` node: ``ArraySpec(...)`` or ``*.synthetic(...)``
with a literal ``npsr >= policy.SCENARIO_NPSR_FLOOR``. Small arrays
(unit-test scale, reduced stand-ins) stay free-form — the floor is what
separates "a fixture" from "a dataset claim". Unlike most library-only
rules, bench surfaces (``bench.py``, ``benchmarks/``) are IN scope:
they are exactly where shadow flagships accrete.
"""

from __future__ import annotations

import ast
from typing import List

from .. import policy
from ..engine import Finding, ModuleContext

RULE_ID = "unregistered-scenario"


def _int_literal(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _callee_name(func: ast.AST):
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def check(ctx: ModuleContext) -> List[Finding]:
    in_scope = (ctx.is_library or ctx.path == "bench.py"
                or ctx.path.startswith("benchmarks/"))
    if not in_scope or ctx.path in policy.SCENARIO_SPEC_MODULES:
        return []
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _callee_name(node.func)
        if callee not in ("ArraySpec", "synthetic"):
            continue
        for kw in node.keywords:
            if kw.arg != "npsr":
                continue
            npsr = _int_literal(kw.value)
            if npsr is not None and npsr >= policy.SCENARIO_NPSR_FLOOR:
                findings.append(ctx.finding(
                    RULE_ID, kw.value,
                    f"ad-hoc {callee}(npsr={npsr}) literal at flagship "
                    f"scale (>= {policy.SCENARIO_NPSR_FLOOR}): array-"
                    f"scale configs are registered scenarios — resolve "
                    f"by name via fakepta_tpu.scenarios.registry (or "
                    f"dataclasses.replace a registered spec) so the "
                    f"config cannot drift from the golden trajectory"))
    return findings
