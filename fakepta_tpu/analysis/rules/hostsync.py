"""host-sync-in-jit: host materialization inside device programs.

``float(x)``, ``x.item()``, ``x.tolist()`` and ``np.asarray(x)`` on a traced
value force a device->host sync (or a ConcretizationTypeError) inside a
``jax.jit``/``pjit``/``shard_map`` program — on a remote TPU every sync is
~80 ms of flat latency (montecarlo.run's whole chunking strategy exists to
avoid exactly that), and in the best case it silently pins a constant at
trace time. Flags those calls inside functions that are decorated with or
wrapped by a jit-family transform (nested defs included).

Second clause (library code only): a bare ``to_host(...)`` /
``block_until_ready(...)`` inside a ``for``/``while`` loop body — the
chunk-loop shape — serializes fetch behind compute on every iteration,
which is exactly the stall the async pipeline exists to hide
(docs/PERFORMANCE.md). The sanctioned path is structural: drains live in
functions outside the loop (``montecarlo._drain_chunk``) and run on the
pipeline's writer thread; a deliberate in-loop sync takes a pragma with its
justification. Comprehensions are not flagged — a single post-loop gather
(``[to_host(p) for p in out]``) is the intended final fetch.

Third clause (the chain-loop clause): any host sync — ``to_host``/
``block_until_ready``, ``float(...)``-family casts, ``.item()``/
``.tolist()``, ``np.asarray`` — inside a function passed as a
``lax.scan``/``fori_loop``/``while_loop``/``associative_scan`` body.
Those bodies are ALWAYS traced (scan traces its body even without an
enclosing ``jax.jit``), and they are exactly where the on-device sampler's
zero-host-round-trips contract lives (docs/SAMPLING.md): one host
materialization inside the chain loop's transition body re-serializes every
MCMC step behind a device round-trip, the pattern ``fakepta_tpu.sample``
exists to kill. Thinned draws leave through the writer-thread drain at
segment boundaries; there is no sanctioned in-scan sync, so a violation
here takes a pragma or a redesign.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, ModuleContext
from .common import (NameResolver, call_name, jitted_functions,
                     last_component)

RULE_ID = "host-sync-in-jit"

_HOST_CASTS = {"float", "int", "bool", "complex"}
_HOST_METHODS = {"item", "tolist"}
_NUMPY_MATERIALIZERS = {"numpy.asarray", "numpy.array", "numpy.copy"}

# blocking fetch/sync helpers that must not sit in a chunk-loop body:
# the engine's to_host (parallel.mesh) and jax.block_until_ready (matched
# as a bare call or a method on an array)
_LOOP_SYNCS = {"to_host", "block_until_ready"}

# lax loop-control primitives whose callable arguments are traced bodies
# (the chain-loop clause): argument positions holding a traced function.
# while_loop's cond AND body are both traced; fori_loop's body is arg 2.
_TRACED_BODY_ARGS = {
    "lax.scan": (0,),
    "lax.fori_loop": (2,),
    "lax.while_loop": (0, 1),
    "lax.associative_scan": (0,),
}


def _loop_sync_findings(ctx: ModuleContext,
                        resolver: NameResolver) -> List[Finding]:
    findings: List[Finding] = []
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
            continue
        for node in ast.walk(loop):
            if node is loop or not isinstance(node, ast.Call):
                continue
            name = call_name(resolver, node)
            is_sync = last_component(name) in _LOOP_SYNCS if name else False
            if not is_sync and isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "block_until_ready":
                is_sync = True
                name = node.func.attr
            if is_sync:
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"{last_component(name)}() inside a loop body blocks "
                    f"the dispatch loop on a device sync every iteration; "
                    f"route the fetch through the async chunk pipeline's "
                    f"writer (parallel/pipeline.py, copy_to_host_async + "
                    f"drain) or pragma the deliberate sync"))
    return findings


def _traced_body_functions(tree: ast.AST, resolver: NameResolver):
    """(fn node, primitive) for functions passed as lax loop-control bodies.

    Matches a named def (module- or closure-level) or an inline lambda in a
    traced-callable position of scan/fori_loop/while_loop/associative_scan.
    """
    defs_by_name = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
    bodies = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(resolver, node)
        if not name:
            continue
        for prim, positions in _TRACED_BODY_ARGS.items():
            if name != prim and not name.endswith("." + prim):
                continue
            for pos in positions:
                if pos >= len(node.args):
                    continue
                arg = node.args[pos]
                if isinstance(arg, ast.Lambda):
                    bodies.append((arg, last_component(prim)))
                elif isinstance(arg, ast.Name):
                    for d in defs_by_name.get(arg.id, ()):
                        bodies.append((d, last_component(prim)))
    return bodies


def _sync_call_message(resolver: NameResolver, node: ast.Call, where: str):
    """The shared host-sync match: a message when ``node`` is one, else
    None. ``where`` names the traced scope for the message."""
    name = call_name(resolver, node)
    if name and last_component(name) in _LOOP_SYNCS or (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready"):
        kind = (last_component(name) if name
                and last_component(name) in _LOOP_SYNCS
                else "block_until_ready")
        return (f"{kind}() inside {where} is a host round-trip in the "
                f"chain loop — every step serializes behind a device "
                f"sync; accumulate on device and drain thinned output at "
                f"segment boundaries through the writer thread")
    if name in _HOST_CASTS and len(node.args) == 1 and \
            not isinstance(node.args[0], ast.Constant):
        return (f"{name}() on a value inside {where} materializes it on "
                f"host at trace time; use jnp ops or hoist the cast out "
                f"of the traced scope")
    if name in _NUMPY_MATERIALIZERS:
        return (f"{name.replace('numpy', 'np')} inside {where} forces a "
                f"device->host copy (or pins a trace-time constant); use "
                f"jnp.asarray or move it to setup code")
    if isinstance(node.func, ast.Attribute) and \
            node.func.attr in _HOST_METHODS and not node.args:
        return (f".{node.func.attr}() inside {where} is a blocking "
                f"device->host sync; keep the value on device")
    return None


def _chain_loop_findings(ctx: ModuleContext, resolver: NameResolver,
                         seen) -> List[Finding]:
    findings: List[Finding] = []
    for fn, prim in _traced_body_functions(ctx.tree, resolver):
        fname = getattr(fn, "name", "<lambda>")
        where = f"the {prim} body '{fname}'"
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            key = (node.lineno, node.col_offset)
            if key in seen:
                continue
            msg = _sync_call_message(resolver, node, where)
            if msg is not None:
                findings.append(ctx.finding(RULE_ID, node, msg))
                seen.add(key)
    return findings


def check(ctx: ModuleContext) -> List[Finding]:
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []
    seen: set = set()
    if ctx.is_library:
        findings.extend(_loop_sync_findings(ctx, resolver))
    for fn in jitted_functions(ctx.tree, resolver):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(resolver, node)
            if name in _HOST_CASTS and len(node.args) == 1 and \
                    not isinstance(node.args[0], ast.Constant):
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"{name}() on a value inside jitted '{fn.name}' "
                    f"materializes it on host at trace time; use jnp ops or "
                    f"hoist the cast out of the jitted scope"))
            elif name in _NUMPY_MATERIALIZERS:
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"{name.replace('numpy', 'np')} inside jitted "
                    f"'{fn.name}' forces a device->host copy (or pins a "
                    f"trace-time constant); use jnp.asarray or move it to "
                    f"setup code"))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _HOST_METHODS and not node.args:
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f".{node.func.attr}() inside jitted '{fn.name}' is a "
                    f"blocking device->host sync; keep the value on device"))
            else:
                continue
            seen.add((node.lineno, node.col_offset))
    findings.extend(_chain_loop_findings(ctx, resolver, seen))
    # dedupe: nested loops walk the same call once per enclosing loop
    return sorted(set(findings))
