"""host-sync-in-jit: host materialization inside device programs.

``float(x)``, ``x.item()``, ``x.tolist()`` and ``np.asarray(x)`` on a traced
value force a device->host sync (or a ConcretizationTypeError) inside a
``jax.jit``/``pjit``/``shard_map`` program — on a remote TPU every sync is
~80 ms of flat latency (montecarlo.run's whole chunking strategy exists to
avoid exactly that), and in the best case it silently pins a constant at
trace time. Flags those calls inside functions that are decorated with or
wrapped by a jit-family transform (nested defs included).
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, ModuleContext
from .common import NameResolver, call_name, jitted_functions

RULE_ID = "host-sync-in-jit"

_HOST_CASTS = {"float", "int", "bool", "complex"}
_HOST_METHODS = {"item", "tolist"}
_NUMPY_MATERIALIZERS = {"numpy.asarray", "numpy.array", "numpy.copy"}


def check(ctx: ModuleContext) -> List[Finding]:
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []
    for fn in jitted_functions(ctx.tree, resolver):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(resolver, node)
            if name in _HOST_CASTS and len(node.args) == 1 and \
                    not isinstance(node.args[0], ast.Constant):
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"{name}() on a value inside jitted '{fn.name}' "
                    f"materializes it on host at trace time; use jnp ops or "
                    f"hoist the cast out of the jitted scope"))
            elif name in _NUMPY_MATERIALIZERS:
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"{name.replace('numpy', 'np')} inside jitted "
                    f"'{fn.name}' forces a device->host copy (or pins a "
                    f"trace-time constant); use jnp.asarray or move it to "
                    f"setup code"))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _HOST_METHODS and not node.args:
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f".{node.func.attr}() inside jitted '{fn.name}' is a "
                    f"blocking device->host sync; keep the value on device"))
    return findings
