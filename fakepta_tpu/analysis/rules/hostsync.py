"""host-sync-in-jit: host materialization inside device programs.

``float(x)``, ``x.item()``, ``x.tolist()`` and ``np.asarray(x)`` on a traced
value force a device->host sync (or a ConcretizationTypeError) inside a
``jax.jit``/``pjit``/``shard_map`` program — on a remote TPU every sync is
~80 ms of flat latency (montecarlo.run's whole chunking strategy exists to
avoid exactly that), and in the best case it silently pins a constant at
trace time. Flags those calls inside functions that are decorated with or
wrapped by a jit-family transform (nested defs included).

Second clause (library code only): a bare ``to_host(...)`` /
``block_until_ready(...)`` inside a ``for``/``while`` loop body — the
chunk-loop shape — serializes fetch behind compute on every iteration,
which is exactly the stall the async pipeline exists to hide
(docs/PERFORMANCE.md). The sanctioned path is structural: drains live in
functions outside the loop (``montecarlo._drain_chunk``) and run on the
pipeline's writer thread; a deliberate in-loop sync takes a pragma with its
justification. Comprehensions are not flagged — a single post-loop gather
(``[to_host(p) for p in out]``) is the intended final fetch.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, ModuleContext
from .common import (NameResolver, call_name, jitted_functions,
                     last_component)

RULE_ID = "host-sync-in-jit"

_HOST_CASTS = {"float", "int", "bool", "complex"}
_HOST_METHODS = {"item", "tolist"}
_NUMPY_MATERIALIZERS = {"numpy.asarray", "numpy.array", "numpy.copy"}

# blocking fetch/sync helpers that must not sit in a chunk-loop body:
# the engine's to_host (parallel.mesh) and jax.block_until_ready (matched
# as a bare call or a method on an array)
_LOOP_SYNCS = {"to_host", "block_until_ready"}


def _loop_sync_findings(ctx: ModuleContext,
                        resolver: NameResolver) -> List[Finding]:
    findings: List[Finding] = []
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
            continue
        for node in ast.walk(loop):
            if node is loop or not isinstance(node, ast.Call):
                continue
            name = call_name(resolver, node)
            is_sync = last_component(name) in _LOOP_SYNCS if name else False
            if not is_sync and isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "block_until_ready":
                is_sync = True
                name = node.func.attr
            if is_sync:
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"{last_component(name)}() inside a loop body blocks "
                    f"the dispatch loop on a device sync every iteration; "
                    f"route the fetch through the async chunk pipeline's "
                    f"writer (parallel/pipeline.py, copy_to_host_async + "
                    f"drain) or pragma the deliberate sync"))
    return findings


def check(ctx: ModuleContext) -> List[Finding]:
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []
    if ctx.is_library:
        findings.extend(_loop_sync_findings(ctx, resolver))
    for fn in jitted_functions(ctx.tree, resolver):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(resolver, node)
            if name in _HOST_CASTS and len(node.args) == 1 and \
                    not isinstance(node.args[0], ast.Constant):
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"{name}() on a value inside jitted '{fn.name}' "
                    f"materializes it on host at trace time; use jnp ops or "
                    f"hoist the cast out of the jitted scope"))
            elif name in _NUMPY_MATERIALIZERS:
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"{name.replace('numpy', 'np')} inside jitted "
                    f"'{fn.name}' forces a device->host copy (or pins a "
                    f"trace-time constant); use jnp.asarray or move it to "
                    f"setup code"))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _HOST_METHODS and not node.args:
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f".{node.func.attr}() inside jitted '{fn.name}' is a "
                    f"blocking device->host sync; keep the value on device"))
    # dedupe: nested loops walk the same call once per enclosing loop
    return sorted(set(findings))
