"""metric-name-discipline: library metric emissions use registered names.

The Prometheus exposition (``obs/promfmt.py``) and the bench report schema
promise STABLE metric names: dashboards, alert rules, and regression
baselines key on them. That promise only holds if renaming a metric is a
schema change made in the declared registry (``obs/metrics.py``
``METRIC_NAMES``) rather than a drive-by edit at a call site — so every
library call to the counter/gauge/timing emitters (``obs.count`` /
``obs.gauge`` / ``obs.observe``, the ``Collector`` methods on a
``collector`` receiver, ``obs.telemetry.publish``) must pass a literal
name that is (a) a string constant, (b) well-formed per
``METRIC_NAME_RE`` (lowercase dotted words), and (c) present in the
registry. A computed name silently mints an unregistered exposition
series; a typo'd literal mints a series nothing ever reads.

Modules whose job IS dynamic names (the emitter definitions in
``obs/metrics.py``; ``obs/timing.py``'s per-timer ``timer.<label>``
histograms) are allowlisted in ``analysis.policy.METRIC_NAME_MODULES``;
anything else takes a pragma with its justification. The registry is
duplicated as literals in ``analysis/policy.py`` (the analyzer never
imports the package under analysis); ``test_static_analysis`` pins the
copy in sync with ``obs.metrics.METRIC_NAMES``.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from .. import policy
from ..engine import Finding, ModuleContext
from .common import NameResolver, call_name

RULE_ID = "metric-name-discipline"

# resolved dotted-name prefixes that denote the obs metrics module (module
# helpers reached as ``obs.count`` from outside the package, ``metrics.count``
# from inside it, or fully qualified)
_METRICS_PREFIXES = frozenset((
    "obs", "fakepta_tpu.obs", "metrics", "obs.metrics",
    "fakepta_tpu.obs.metrics",
))
_TELEMETRY_PREFIXES = frozenset((
    "telemetry", "obs.telemetry", "fakepta_tpu.obs.telemetry",
))
_COUNTER_METHODS = frozenset(("count", "gauge", "observe"))

_NAME_RE = re.compile(policy.METRIC_NAME_RE)
_REGISTRY = frozenset(policy.METRIC_NAMES)


def _emitter(name: Optional[str]) -> Optional[str]:
    """The matched emitter spelling, or None for a non-emitter call.

    Matches module-helper calls (``obs.count``/``metrics.observe``/
    ``telemetry.publish`` through any import alias) and Collector-method
    calls on a receiver whose terminal name is ``collector`` (the engine's
    idiom for the active collector captured once per run loop).
    """
    if not name or "." not in name:
        return None
    prefix, method = name.rsplit(".", 1)
    if method in _COUNTER_METHODS:
        if prefix in _METRICS_PREFIXES:
            return name
        if prefix.rsplit(".", 1)[-1] == "collector":
            return name
    if method == "publish" and prefix in _TELEMETRY_PREFIXES:
        return name
    return None


def check(ctx: ModuleContext) -> List[Finding]:
    if not ctx.is_library or ctx.path in policy.METRIC_NAME_MODULES:
        return []
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        emitter = _emitter(call_name(resolver, node))
        if emitter is None:
            continue
        arg = node.args[0] if node.args else None
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            findings.append(ctx.finding(
                RULE_ID, node,
                f"{emitter}() with a non-literal metric name: a computed "
                f"name mints an exposition series the declared registry "
                f"(obs/metrics.py METRIC_NAMES) never heard of; pass a "
                f"registered literal (or add the module to "
                f"analysis.policy.METRIC_NAME_MODULES with a reason)"))
            continue
        metric = arg.value
        if not _NAME_RE.match(metric):
            findings.append(ctx.finding(
                RULE_ID, node,
                f"{emitter}({metric!r}): metric name violates "
                f"{policy.METRIC_NAME_RE} (lowercase dotted words) — "
                f"Prometheus exposition names derive from it"))
        elif metric not in _REGISTRY:
            findings.append(ctx.finding(
                RULE_ID, node,
                f"{emitter}({metric!r}): name not in the declared metric "
                f"registry; register it in obs/metrics.py METRIC_NAMES "
                f"(and the analysis.policy.METRIC_NAMES copy) so the "
                f"exposition schema stays deliberate"))
    return findings
