"""tracer-leak: Python control flow / mutation on traced values.

Inside a jitted scope, ``if``/``while``/``assert`` on a traced expression
raises ConcretizationTypeError at best and silently bakes a trace-time
constant at worst (the classic "worked on the example input" bug). The
heuristic is deliberately narrow — the test must *syntactically* involve a
``jnp.*``/``jax.lax.*`` call, so static Python flags like
``_simulate_block``'s ``include_white`` never false-positive.

In-place mutation of a *closed-over* list/array (``outer[i] = ...``,
``outer.append(...)``) inside a jitted scope leaks trace-time Python state
across traces: the mutation happens once at trace time, not per call, and
retraces append again — locally-bound accumulators are fine.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..engine import Finding, ModuleContext
from .common import (NameResolver, call_name, jitted_functions,
                     local_bindings)

RULE_ID = "tracer-leak"

_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
             "update", "setdefault"}
_TRACED_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.random.")


def _mentions_traced_call(resolver: NameResolver, expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = call_name(resolver, node)
            if name and (name.startswith(_TRACED_PREFIXES)
                         or name == "jax.numpy"):
                return True
    return False


def check(ctx: ModuleContext) -> List[Finding]:
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []
    module_bound = local_bindings(ctx.tree)
    for fn in jitted_functions(ctx.tree, resolver):
        findings.extend(_check_scope(ctx, resolver, fn,
                                     outer_bound=module_bound))
    return findings


def _check_scope(ctx: ModuleContext, resolver: NameResolver, fn: ast.AST,
                 outer_bound: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    bound = local_bindings(fn)

    def visit(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            findings.extend(_check_scope(ctx, resolver, node,
                                         outer_bound | bound))
            return
        if isinstance(node, (ast.If, ast.While)):
            if _mentions_traced_call(resolver, node.test):
                kind = "if" if isinstance(node, ast.If) else "while"
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"Python {kind} on a traced expression inside a jitted "
                    f"scope concretizes the tracer; use jnp.where / "
                    f"lax.cond / lax.while_loop"))
        elif isinstance(node, ast.Assert):
            if _mentions_traced_call(resolver, node.test):
                findings.append(ctx.finding(
                    RULE_ID, node,
                    "assert on a traced expression inside a jitted scope "
                    "concretizes the tracer; use checkify or move the check "
                    "to host code"))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name):
                    name = t.value.id
                    if name not in bound and name in outer_bound:
                        findings.append(ctx.finding(
                            RULE_ID, t,
                            f"in-place mutation of closed-over '{name}' "
                            f"inside a jitted scope happens at trace time, "
                            f"not per call; use a local accumulator or "
                            f".at[].set()"))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS and \
                isinstance(node.func.value, ast.Name):
            name = node.func.value.id
            if name not in bound and name in outer_bound:
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f".{node.func.attr}() on closed-over '{name}' inside a "
                    f"jitted scope mutates trace-time Python state; "
                    f"accumulate locally and return the result"))
        for child in ast.iter_child_nodes(node):
            visit(child)

    for child in ast.iter_child_nodes(fn):
        visit(child)
    return findings
