"""Rule registry: one module per rule, registered here in report order.

Adding a per-file rule = add a module with ``RULE_ID`` and ``check(ctx)``,
append it below, give it a fixture pair in ``tests/fixtures_analysis/``
(one seeded true positive, one clean file), and document it in
docs/INVARIANTS.md. Whole-program rules take ``check(index)`` over the
:class:`~fakepta_tpu.analysis.project.ProjectIndex` instead and register
in ``PROJECT_RULES``.
"""

from . import (caches, collectives, donation, dtype, excepts, hostsync,
               joins, knobs, meshaxis, metric_names, precision, queues, rng,
               scenarios, socketio, timing, tracer)

ALL_RULES = tuple((mod.RULE_ID, mod.check)
                  for mod in (rng, hostsync, tracer, dtype, meshaxis,
                              donation, precision, timing, queues, caches,
                              excepts, knobs, socketio, joins, metric_names,
                              scenarios))

RULE_IDS = tuple(rid for rid, _ in ALL_RULES)


def _project_rules():
    from .. import concurrency

    return concurrency.PROJECT_RULES + (
        (collectives.RULE_ID, collectives.check_project),)


PROJECT_RULES = _project_rules()

PROJECT_RULE_IDS = tuple(rid for rid, _ in PROJECT_RULES)
