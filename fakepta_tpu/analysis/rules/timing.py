"""timing-discipline: bare clock reads in library code outside obs.timing.

Library code that calls ``time.time()`` / ``time.perf_counter()`` /
``time.monotonic()`` directly produces measurements that live and die in a
local variable: they never reach the active obs collector, mix wall-clock
and monotonic bases across modules, and — the failure PR 2 was built to
end — turn into hand-carried numbers the telemetry artifacts cannot
reproduce. The sanctioned clock is ``fakepta_tpu.obs.timing``: ``now()``
for timestamps, ``Timer``/``span`` for measurements (device-synced, raised
blocks still recorded, collector-visible). A module that legitimately owns
a raw clock (timing itself; the flight recorder, which must stay
import-cycle-free below metrics) is allowlisted in
``analysis.policy.TIMING_MODULES``; anything else takes a pragma with its
justification. ``time.sleep`` and the ``*_ns`` conversions of *recorded*
values are not clock reads and are never flagged.
"""

from __future__ import annotations

import ast
from typing import List

from .. import policy
from ..engine import Finding, ModuleContext
from .common import NameResolver, call_name

RULE_ID = "timing-discipline"

_CLOCK_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
                "time.process_time", "time.perf_counter_ns",
                "time.monotonic_ns", "time.time_ns"}


def check(ctx: ModuleContext) -> List[Finding]:
    if not ctx.is_library or ctx.path in policy.TIMING_MODULES:
        return []
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(resolver, node)
        if name in _CLOCK_CALLS:
            findings.append(ctx.finding(
                RULE_ID, node,
                f"bare {name}() in library code: measurements outside "
                f"fakepta_tpu.obs.timing never reach the telemetry "
                f"artifacts and mix clock bases; use obs.now() / "
                f"obs.Timer / obs.span (or add the module to "
                f"analysis.policy.TIMING_MODULES with a reason)"))
    return findings
