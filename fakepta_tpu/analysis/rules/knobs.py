"""hardcoded-dispatch-knob: literal dispatch-knob values at library call
sites.

The dispatch knobs — the megakernel realization tile ``rt``, the chunk
pipeline's ``pipeline_depth``, the serve bucket ladder — are exactly what
:mod:`fakepta_tpu.tune` exists to choose per platform (docs/TUNING.md): a
literal value baked into a library call site silently pins one platform's
hand-tuning on every other platform and hides the knob from the tuner's
A/B attribution. The sanctioned homes are ``tune/defaults.py`` (the one
place knob literals may live; ``analysis.policy.DISPATCH_KNOB_MODULES``)
and values *plumbed* from a caller, a TunedConfig, or the defaults module
— all of which reach call sites as names, not literals.

Flagged at a ``Call`` node (never at signature defaults — a default IS a
plumbing point):

- ``rt=<int literal>``;
- ``pipeline_depth=<int literal>`` other than 0 — 0 is the serial-
  fallback OFF switch (a semantic mode, e.g. the loadgen's deliberately
  serial baseline), not a tuned magnitude;
- ``buckets=`` / ``prewarm_buckets=`` bound to a literal tuple/list of
  ints — a hardcoded ladder.

Tests, examples and benchmarks are exempt (library-only rule): their
pinned knobs are the experimental conditions being measured.
"""

from __future__ import annotations

import ast
from typing import List

from .. import policy
from ..engine import Finding, ModuleContext

RULE_ID = "hardcoded-dispatch-knob"

_LADDER_KEYWORDS = ("buckets", "prewarm_buckets")


def _is_int_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return True
    # -1 etc. parse as UnaryOp(USub, Constant)
    return (isinstance(node, ast.UnaryOp)
            and isinstance(node.op, (ast.USub, ast.UAdd))
            and _is_int_literal(node.operand))


def _is_literal_ladder(node: ast.AST) -> bool:
    return (isinstance(node, (ast.Tuple, ast.List)) and node.elts
            and all(_is_int_literal(e) for e in node.elts))


def check(ctx: ModuleContext) -> List[Finding]:
    if not ctx.is_library or ctx.path in policy.DISPATCH_KNOB_MODULES:
        return []
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        for kw in node.keywords:
            if kw.arg == "rt" and _is_int_literal(kw.value):
                findings.append(ctx.finding(
                    RULE_ID, kw.value,
                    "literal rt= at a library call site: the realization "
                    "tile is a tuned dispatch knob — plumb it from the "
                    "caller / tune.defaults (or pragma with the reason "
                    "this site is not tunable)"))
            elif kw.arg == "pipeline_depth" \
                    and _is_int_literal(kw.value) \
                    and getattr(getattr(kw.value, "operand", kw.value),
                                "value", None) != 0:
                findings.append(ctx.finding(
                    RULE_ID, kw.value,
                    "literal pipeline_depth= at a library call site "
                    "(depth 0, the serial-fallback off switch, is "
                    "exempt): plumb the depth from the caller / "
                    "tune.defaults so the autotuner's choice reaches "
                    "this dispatch"))
            elif kw.arg in _LADDER_KEYWORDS \
                    and _is_literal_ladder(kw.value):
                findings.append(ctx.finding(
                    RULE_ID, kw.value,
                    f"literal {kw.arg}= ladder at a library call site: "
                    f"bucket ladders are platform-tuned "
                    f"(tune.defaults.DEFAULT_BUCKETS is the hand-set "
                    f"source; ServePool(tuned=True) the tuned one)"))
    return findings
