"""collective-divergence: collectives must issue identically on every host.

On a multi-host slice, ``psum``/``all_gather``/``ppermute``/``pbroadcast``
are rendezvous points: every participating process must issue the SAME
sequence of collectives or the whole slice hangs (no error — the fast
hosts sit in the collective forever waiting for the host that branched the
other way). The pre-deployment invariant is therefore *syntactic*: inside
jit/``shard_map``-reachable code a collective may not be guarded by a
predicate that can differ across hosts, sit inside an exception handler,
or follow an early return taken on a data-dependent test.

Uniformity heuristic (documented, deliberately syntactic): a branch test
built only from plain names, attributes, constants, comparisons and
boolean operators is **trace-time uniform** — inside traced code such a
predicate is necessarily resolved at trace time from config every host
shares. A test containing a call (other than the trace-time-static
builtins ``len``/``isinstance``/``hasattr``/...) or a subscript can
inspect per-host data (``jax.process_index()``, ``x[0] > 0``) and is
treated as potentially divergent. False positives carry the usual pragma
(``# fakepta: allow[collective-divergence] reason``) or a module entry in
``policy.COLLECTIVE_DIVERGENCE_MODULES``.

This is a whole-program rule: entry points are the per-module
jit/``shard_map`` functions (``rules.common.jitted_functions``) plus every
indexed function reachable from them through the project call graph.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .. import policy
from ..engine import Finding
from .common import NameResolver, jitted_functions, last_component

RULE_ID = "collective-divergence"

#: cross-host rendezvous primitives (jax.lax / jax.lax.parallel)
COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "pbroadcast", "psum_scatter",
})

#: calls that are trace-time static on shared config, hence uniform
_UNIFORM_CALLS = frozenset({
    "len", "isinstance", "issubclass", "hasattr", "getattr", "callable",
    "bool", "int", "float", "str", "tuple", "list", "dict", "set",
    "min", "max", "abs", "round", "sorted", "any", "all",
})


def _test_is_uniform(resolver: NameResolver, test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            # only BARE builtin calls are trace-time static; a method
            # call (x.any(), jax.process_index()) can inspect per-host
            # data, whatever its name
            if not (isinstance(node.func, ast.Name)
                    and node.func.id in _UNIFORM_CALLS):
                return False
        elif isinstance(node, (ast.Subscript, ast.Await, ast.Yield,
                               ast.YieldFrom, ast.GeneratorExp)):
            return False
    return True


def _has_early_exit(if_node: ast.If) -> bool:
    for st in if_node.body:
        for sub in ast.walk(st):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break
            if isinstance(sub, (ast.Return, ast.Raise, ast.Continue,
                                ast.Break)):
                return True
    return False


def _collective_name(resolver: NameResolver,
                     call: ast.Call) -> Optional[str]:
    name = last_component(resolver.resolve(call.func))
    if name in COLLECTIVES:
        return name
    return None


def _scan_function(path: str, resolver: NameResolver, fn: ast.AST,
                   findings: List[Finding],
                   seen: Set[tuple]) -> None:
    """Walk ``fn``'s full subtree (nested defs are traced too), tracking
    the innermost divergence context."""

    def visit_block(stmts, div: Optional[str]) -> None:
        cur = div
        for st in stmts:
            visit(st, cur)
            if isinstance(st, ast.If) and cur is None \
                    and not _test_is_uniform(resolver, st.test) \
                    and _has_early_exit(st):
                cur = (f"code after a data-dependent early exit "
                       f"(line {st.lineno})")

    def visit(node: ast.AST, div: Optional[str]) -> None:
        if isinstance(node, ast.If):
            visit(node.test, div)
            inner = div
            if inner is None and not _test_is_uniform(resolver, node.test):
                inner = f"a data-dependent branch (line {node.lineno})"
            visit_block(node.body, inner)
            visit_block(node.orelse, inner)
            return
        if isinstance(node, ast.IfExp):
            visit(node.test, div)
            inner = div
            if inner is None and not _test_is_uniform(resolver, node.test):
                inner = (f"a data-dependent conditional expression "
                         f"(line {node.lineno})")
            visit(node.body, inner)
            visit(node.orelse, inner)
            return
        if isinstance(node, ast.While):
            visit(node.test, div)
            inner = div
            if inner is None and not _test_is_uniform(resolver, node.test):
                inner = f"a data-dependent loop (line {node.lineno})"
            visit_block(node.body, inner)
            visit_block(node.orelse, div)
            return
        if isinstance(node, ast.Try):
            visit_block(node.body, div)
            for h in node.handlers:
                visit_block(h.body,
                            div or f"an exception handler "
                                   f"(line {h.lineno})")
            visit_block(node.orelse, div)
            visit_block(node.finalbody, div)
            return
        if isinstance(node, ast.Call):
            name = _collective_name(resolver, node)
            if name is not None and div is not None:
                key = (node.lineno, node.col_offset)
                if key not in seen:
                    seen.add(key)
                    findings.append(Finding(
                        path, node.lineno, node.col_offset + 1, RULE_ID,
                        f"collective {name}() issued under {div}: hosts "
                        f"that branch differently deadlock the slice at "
                        f"the rendezvous; issue the collective "
                        f"unconditionally (mask/select the payload "
                        f"instead) or make the predicate trace-time "
                        f"uniform"))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            visit_block(node.body, div)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, div)

    visit_block(getattr(fn, "body", []), None)


def check_project(index) -> List[Finding]:
    """Project-rule entry: scan every jit/shard_map-reachable function."""
    findings: List[Finding] = []
    node_to_qname = {id(fi.node): q for q, fi in index.functions.items()}
    to_scan: List[tuple] = []          # (module path, function node)
    scanned: Set[int] = set()
    entry_qnames: List[str] = []
    for path in sorted(index.modules):
        mi = index.modules[path]
        for fn in jitted_functions(mi.tree, mi.resolver):
            if id(fn) not in scanned:
                scanned.add(id(fn))
                to_scan.append((path, fn))
            q = node_to_qname.get(id(fn))
            if q is not None:
                entry_qnames.append(q)
    # closure: indexed functions reachable from indexed jit entries
    for q in index.reachable_from(entry_qnames):
        fi = index.functions[q]
        if id(fi.node) not in scanned:
            scanned.add(id(fi.node))
            to_scan.append((fi.module, fi.node))
    seen_by_module: dict = {}
    for path, fn in to_scan:
        if not policy.is_library(path) or \
                path in policy.COLLECTIVE_DIVERGENCE_MODULES:
            continue
        _scan_function(path, index.modules[path].resolver, fn, findings,
                       seen_by_module.setdefault(path, set()))
    return findings
