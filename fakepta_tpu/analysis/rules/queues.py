"""unbounded-queue: unbounded queue/deque construction in library code.

The serving and pipeline layers are built on explicit backpressure: every
producer/consumer hand-off is either bounded (``queue.Queue(maxsize=...)``,
``deque(maxlen=...)``) or bounded *by construction* through an external
invariant (the chunk pipeline's donated-buffer ring). An unbounded queue in
library code is a latent OOM under sustained load — exactly the failure a
multi-tenant serving process cannot afford: admission keeps succeeding
while host memory grows until the OOM killer takes out every tenant at
once. The rule flags ``queue.Queue()`` / ``queue.LifoQueue()`` /
``queue.PriorityQueue()`` / ``queue.SimpleQueue()`` /
``collections.deque()`` constructed without a bound (including the
explicitly-unbounded ``maxsize=0`` / ``maxlen=None`` spellings).

Deliberately unbounded cases live in the policy exemption list
(``analysis.policy.UNBOUNDED_QUEUE_MODULES`` — currently the chunk
pipeline's writer queue, whose depth the run loop's recycling ring bounds);
anything else takes a ``# fakepta: allow[unbounded-queue] reason`` pragma
with its justification. A *variable* bound (``Queue(maxsize=depth)``) is
accepted — the rule checks structure, not values.
"""

from __future__ import annotations

import ast
from typing import List

from .. import policy
from ..engine import Finding, ModuleContext
from .common import NameResolver, call_name

RULE_ID = "unbounded-queue"

# constructor -> (bounding parameter name, its positional index)
_QUEUE_CALLS = {
    "queue.Queue": ("maxsize", 0),
    "queue.LifoQueue": ("maxsize", 0),
    "queue.PriorityQueue": ("maxsize", 0),
    "collections.deque": ("maxlen", 1),
}

# no bounded form exists at all for SimpleQueue
_ALWAYS_UNBOUNDED = {"queue.SimpleQueue"}


def _is_unbounded_literal(node) -> bool:
    """True for the explicitly-unbounded spellings: 0/negative maxsize,
    None maxlen."""
    if isinstance(node, ast.Constant):
        if node.value is None:
            return True
        if isinstance(node.value, (int, float)) and node.value <= 0:
            return True
    if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)
            and isinstance(node.operand, ast.Constant)):
        return True
    return False


def check(ctx: ModuleContext) -> List[Finding]:
    if not ctx.is_library or ctx.path in policy.UNBOUNDED_QUEUE_MODULES:
        return []
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(resolver, node)
        if name in _ALWAYS_UNBOUNDED:
            findings.append(ctx.finding(
                RULE_ID, node,
                f"{name}() has no bounded form: a producer can outrun its "
                f"consumer without backpressure; use queue.Queue(maxsize=N)"))
            continue
        if name not in _QUEUE_CALLS:
            continue
        param, pos = _QUEUE_CALLS[name]
        bound = None
        if len(node.args) > pos:
            bound = node.args[pos]
        for kw in node.keywords:
            if kw.arg == param:
                bound = kw.value
        if bound is None or _is_unbounded_literal(bound):
            findings.append(ctx.finding(
                RULE_ID, node,
                f"{name}() without a {param} bound in library code: an "
                f"unbounded buffer is a latent OOM under sustained load — "
                f"pass {param}=N (backpressure), or add the module to "
                f"analysis.policy.UNBOUNDED_QUEUE_MODULES / pragma it with "
                f"the invariant that bounds it externally"))
    return findings
