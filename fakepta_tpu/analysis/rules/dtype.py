"""dtype-policy: float64 leaks into declared device-f32 modules.

The engine's precision contract (BASELINE/VERDICT: host-f64 staging feeds
device-f32 kernels) is encoded as data in ``analysis.policy.DTYPE_POLICY``:
modules like ``ephemeris.py`` and ``models/cgw.py`` are *sanctioned*
host-f64 stages; everything else in the library is device-f32, where an f64
marker is either a real dtype leak (flag it) or an intentional host staging
step (pragma it with the justification — which is exactly the audit trail
the policy wants).

Also flags ``jnp.exp``/``jnp.power`` whose arguments carry no log-space
marker in their names: exponentiating a non-log-space magnitude overflows
f32 at |x| > ~88/ln10, the classic silent-inf in spectral code. Log-space
pipelines (``jnp.exp(ln_psd - jnp.log(f))``) pass by construction.
"""

from __future__ import annotations

import ast
from typing import List

from .. import policy
from ..engine import Finding, ModuleContext
from .common import NameResolver, call_name

RULE_ID = "dtype-policy"

_F64_ATTRS = {"numpy.float64", "jax.numpy.float64", "numpy.complex128",
              "jax.numpy.complex128"}
_F64_STRINGS = {"float64", "f8", ">f8", "<f8", "double", "complex128"}
_EXP_FNS = {"jax.numpy.exp", "jax.numpy.power", "jax.numpy.exp2",
            "jax.numpy.exp10"}
_LOG_MARKERS = ("log", "ln_", "_ln", "lg")


def _has_log_marker(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        ident = None
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        elif isinstance(sub, ast.keyword):
            ident = sub.arg
        if ident and any(m in ident.lower() for m in _LOG_MARKERS):
            return True
    return False


def check(ctx: ModuleContext) -> List[Finding]:
    if ctx.dtype_policy != policy.DTYPE_DEFAULT_LIBRARY:
        return []   # host-f64 sanctioned modules and non-library code
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.Attribute, ast.Name)):
            name = resolver.resolve(node)
            if name in _F64_ATTRS:
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"{name.split('.')[-1]} in a device-f32 module; if this "
                    f"is sanctioned host staging, pragma it with the reason "
                    f"(or add the module to analysis.policy.DTYPE_POLICY)"))
            elif name and name.split(".")[-1] == "enable_x64":
                findings.append(ctx.finding(
                    RULE_ID, node,
                    "enable_x64 in a device-f32 module flips global "
                    "precision; sanction it with a pragma naming the host "
                    "stage it wraps"))
        elif isinstance(node, ast.Call):
            cname = call_name(resolver, node)
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str) and \
                        arg.value in _F64_STRINGS:
                    findings.append(ctx.finding(
                        RULE_ID, arg,
                        f"dtype string {arg.value!r} in a device-f32 "
                        f"module; spell the policy (batch dtype) or pragma "
                        f"the host stage"))
            if cname == "jax.config.update" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value == "jax_enable_x64":
                findings.append(ctx.finding(
                    RULE_ID, node,
                    "jax_enable_x64 toggle in a device-f32 module changes "
                    "process-global precision"))
            if cname in _EXP_FNS and node.args and \
                    not any(_has_log_marker(a) for a in node.args):
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"{cname.replace('jax.numpy', 'jnp')} of a non-log-space "
                    f"magnitude overflows f32 beyond ~1e38; compute in log "
                    f"space (or pragma with the proven bound)"))
    return findings
