"""mesh-axis-contract: collectives must name a declared mesh axis.

The whole SPMD program speaks exactly three axis names — ``('real', 'psr',
'toa')``, declared once in ``parallel/mesh.py`` — and every
``lax.psum``/``all_gather``/``axis_index`` call is a contract against them.
A typo'd or ad-hoc axis name fails only at trace time *on a sharded mesh*,
which single-device CPU tests never exercise; this rule catches it at lint
time. Axis arguments must be statically checkable: a string literal in the
declared set, one of the ``REAL_AXIS``/``PSR_AXIS``/``TOA_AXIS`` constants,
or a tuple of those. Anything else (a runtime variable) is flagged as
unverifiable — thread the constant instead.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .. import policy
from ..engine import Finding, ModuleContext
from .common import NameResolver, call_name, last_component

RULE_ID = "mesh-axis-contract"

# collective -> positional index of the axis-name argument
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "all_to_all": 1, "ppermute": 1,
    "axis_index": 0, "axis_size": 0,
}
# only axis_name: collectives' `axis=` kwarg is the ARRAY axis (all_gather)
_AXIS_KWARGS = ("axis_name",)


def _axis_ok(node: ast.AST, resolver: NameResolver) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in policy.MESH_AXES
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_axis_ok(e, resolver) for e in node.elts)
    name = resolver.resolve(node)
    if name is not None:
        return last_component(name) in policy.MESH_AXIS_CONSTANTS
    return False


def _axis_arg(call: ast.Call, pos: int) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg in _AXIS_KWARGS:
            return kw.value
    if len(call.args) > pos:
        return call.args[pos]
    return None


def check(ctx: ModuleContext) -> List[Finding]:
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(resolver, node)
        if not name:
            continue
        tail = last_component(name)
        if tail not in _COLLECTIVES or ".lax" not in "." + name:
            continue
        axis = _axis_arg(node, _COLLECTIVES[tail])
        if axis is None:
            continue   # defaulted/omitted axis is jax's problem, not ours
        if not _axis_ok(axis, resolver):
            declared = ", ".join(repr(a) for a in policy.MESH_AXES)
            findings.append(ctx.finding(
                RULE_ID, node,
                f"lax.{tail} axis is not statically one of the declared "
                f"mesh axes ({declared} / their *_AXIS constants from "
                f"parallel.mesh); typos here only fail on a sharded mesh"))
    return findings
