"""unbounded-socket-io: blocking socket reads without a timeout in library
code.

The serving layer exposes TCP endpoints to processes it does not control
(``serve/cli.py`` socket/replica, the fleet's socket transport). A socket
``accept``/``recv``/``readline`` with no timeout lets ONE stalled or
hostile peer pin a handler thread forever — the thread-pool analog of the
unbounded-queue OOM: admission keeps succeeding while live threads leak
until the server stops serving everyone. Library sockets must bound every
blocking read (``settimeout``, or ``create_connection(timeout=...)``).

What the rule flags in library code:

- ``socket.create_connection(host)`` with neither a positional nor a
  ``timeout=`` argument;
- ``.accept()`` / ``.recv()`` / ``.recvfrom()`` / ``.recv_into()`` /
  ``.makefile()`` calls whose enclosing scope chain (function, class,
  module) contains no ``.settimeout(x)`` with a non-``None`` argument —
  the structural stand-in for "this connection was given a deadline"
  (a ``socketserver`` handler that calls ``settimeout`` in ``setup()``
  covers the reads in ``handle()`` because both live in the class scope);
- ``.readline()`` on a receiver whose name marks it a socket file
  (``rfile`` / ``sockfile`` / ``sock``), under the same scope rule —
  plain file ``readline`` is not socket I/O and is never flagged.

Deliberately blocking accept loops live in the policy exemption list
(``analysis.policy.SOCKET_IO_MODULES``); anything else takes a
``# fakepta: allow[unbounded-socket-io] reason`` pragma. Like the
unbounded-queue rule, this checks structure, not values: a variable
timeout (``settimeout(cfg.idle_s)``) is accepted.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .. import policy
from ..engine import Finding, ModuleContext
from .common import NameResolver, call_name

RULE_ID = "unbounded-socket-io"

#: socket methods that block indefinitely without a deadline
_BLOCKING_METHODS = ("accept", "recv", "recvfrom", "recv_into", "makefile")

#: receiver-name fingerprints that mark a ``.readline()`` as socket I/O
_SOCKET_FILE_NAMES = ("rfile", "sockfile", "sock")


def _is_none(node) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _scope_has_settimeout(scope) -> bool:
    """True when ``scope`` contains a ``<obj>.settimeout(x)`` call with a
    non-None argument. A function/class scope counts its whole body (a
    handler's ``setup()`` covers its ``handle()``); MODULE scope counts
    only top-level statements — one bounded handler must not launder every
    other connection in the file."""
    if isinstance(scope, ast.Module):
        roots = [n for n in ast.iter_child_nodes(scope)
                 if not isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef, ast.ClassDef))]
    else:
        roots = [scope]
    for root in roots:
        for node in ast.walk(root):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "settimeout" and node.args
                    and not _is_none(node.args[0])):
                return True
    return False


def _receiver_name(node: ast.Call) -> Optional[str]:
    """The attribute/name a method is called on (``self.rfile.readline``
    -> ``rfile``; ``sock.recv`` -> ``sock``)."""
    if not isinstance(node.func, ast.Attribute):
        return None
    recv = node.func.value
    if isinstance(recv, ast.Attribute):
        return recv.attr
    if isinstance(recv, ast.Name):
        return recv.id
    return None


def check(ctx: ModuleContext) -> List[Finding]:
    if not ctx.is_library or ctx.path in policy.SOCKET_IO_MODULES:
        return []
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []

    # scope chain per node: module -> enclosing class -> enclosing function
    parents = {}
    for parent in ast.walk(ctx.tree):
        for child in ast.iter_child_nodes(parent):
            parents[id(child)] = parent

    def chain(node):
        out = [ctx.tree]
        cur = node
        while id(cur) in parents:
            cur = parents[id(cur)]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                out.append(cur)
        return out

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(resolver, node)
        if name == "socket.create_connection":
            has_timeout = len(node.args) >= 2 or any(
                kw.arg == "timeout" and not _is_none(kw.value)
                for kw in node.keywords)
            if not has_timeout:
                findings.append(ctx.finding(
                    RULE_ID, node,
                    "socket.create_connection() without a timeout: a "
                    "black-holed peer blocks the caller forever — pass "
                    "timeout=N"))
            continue
        if not isinstance(node.func, ast.Attribute):
            continue
        attr = node.func.attr
        is_blocking = attr in _BLOCKING_METHODS
        if attr == "readline":
            recv = _receiver_name(node)
            is_blocking = recv is not None and any(
                recv == n or recv.endswith("_" + n) or n in recv
                for n in _SOCKET_FILE_NAMES)
        if not is_blocking:
            continue
        if any(_scope_has_settimeout(s) for s in chain(node)):
            continue
        findings.append(ctx.finding(
            RULE_ID, node,
            f".{attr}() with no timeout in scope: a stalled or hostile "
            f"peer pins this thread forever — settimeout() the socket "
            f"(or create_connection(timeout=...)), add the module to "
            f"analysis.policy.SOCKET_IO_MODULES if the blocking loop is "
            f"the design, or pragma with the bounding invariant"))
    return findings
