"""Shared AST machinery for the rule visitors.

Pure stdlib-``ast`` — the analyzer never imports jax/numpy or the modules
under analysis, so it runs identically on a laptop, in CI, and on machines
without an accelerator stack at all.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple


class NameResolver:
    """Resolve Name/Attribute chains to dotted names through import aliases.

    ``import numpy as np`` makes ``np.random.seed`` resolve to
    ``numpy.random.seed``; ``from jax import lax`` makes ``lax.psum``
    resolve to ``jax.lax.psum``. Relative imports are normalized by
    stripping the leading dots (``from ..utils import rng as rng_utils`` ->
    ``rng_utils`` = ``utils.rng``): rules match on suffixes, so the absolute
    package prefix is never needed.
    """

    def __init__(self, tree: ast.AST):
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                mod = (node.module or "").lstrip(".")
                for a in node.names:
                    if a.name == "*":
                        continue
                    full = f"{mod}.{a.name}" if mod else a.name
                    self.aliases[a.asname or a.name] = full

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name for a Name/Attribute chain, or None for anything else."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(self.aliases.get(node.id, node.id))
        return ".".join(reversed(parts))


def last_component(dotted: Optional[str]) -> Optional[str]:
    return dotted.rsplit(".", 1)[-1] if dotted else None


def call_name(resolver: NameResolver, call: ast.Call) -> Optional[str]:
    return resolver.resolve(call.func)


# ---------------------------------------------------------------------------
# jit-scope detection
# ---------------------------------------------------------------------------

_JIT_WRAPPERS = {"jit", "pjit", "shard_map"}


def _is_jit_transform(resolver: NameResolver, node: ast.AST) -> bool:
    name = resolver.resolve(node)
    return last_component(name) in _JIT_WRAPPERS if name else False


def jitted_functions(tree: ast.AST,
                     resolver: NameResolver) -> List[ast.FunctionDef]:
    """Top-level set of FunctionDefs that become device programs.

    Detected forms:

    - decorated: ``@jax.jit``, ``@jit``, ``@pjit``, ``@jax.jit(...)``,
      ``@partial(jax.jit, ...)`` / ``@functools.partial(jit, ...)``;
    - wrapped: ``jax.jit(f)`` / ``shard_map(f, mesh=...)`` / ``pjit(f)``
      where ``f`` names a function defined in the module.

    Nested defs inside a jitted function are jitted too — callers walk each
    returned def's whole subtree, which covers them; the returned list holds
    only the outermost jitted defs so no node is visited twice.
    """
    defs_by_name: Dict[str, List[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    jitted: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_transform(resolver, dec):
                    jitted.add(node)
                elif isinstance(dec, ast.Call):
                    if _is_jit_transform(resolver, dec.func):
                        jitted.add(node)
                    elif (last_component(resolver.resolve(dec.func))
                          == "partial" and dec.args
                          and _is_jit_transform(resolver, dec.args[0])):
                        jitted.add(node)
        elif isinstance(node, ast.Call) and _is_jit_transform(resolver,
                                                              node.func):
            if node.args and isinstance(node.args[0], ast.Name):
                for d in defs_by_name.get(node.args[0].id, ()):
                    jitted.add(d)

    # keep only outermost jitted defs (inner ones ride the subtree walk)
    inner: Set[ast.AST] = set()
    for d in jitted:
        for sub in ast.walk(d):
            if sub is not d and sub in jitted:
                inner.add(sub)
    return [d for d in jitted if d not in inner]


def local_bindings(fn: ast.AST) -> Set[str]:
    """Names bound in ``fn``'s own scope (params, assignments, for/with
    targets, imports, nested def/class names) — NOT descending into nested
    functions, whose bindings live in their own scope."""
    bound: Set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = fn.args
        for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)):
            bound.add(arg.arg)
        if a.vararg:
            bound.add(a.vararg.arg)
        if a.kwarg:
            bound.add(a.kwarg.arg)

    def collect_target(t: ast.AST) -> None:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx,
                                                        (ast.Store,)):
                bound.add(sub.id)

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(child.name)
                continue
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, ast.ClassDef):
                bound.add(child.name)
                continue
            if isinstance(child, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (child.targets
                           if isinstance(child, ast.Assign)
                           else [child.target])
                for t in targets:
                    if isinstance(t, (ast.Name, ast.Tuple, ast.List,
                                      ast.Starred)):
                        collect_target(t)
            elif isinstance(child, ast.NamedExpr):
                collect_target(child.target)
            elif isinstance(child, ast.For):
                collect_target(child.target)
            elif isinstance(child, ast.withitem) and child.optional_vars:
                collect_target(child.optional_vars)
            elif isinstance(child, (ast.Import, ast.ImportFrom)):
                for al in child.names:
                    bound.add((al.asname or al.name).split(".")[0])
            elif isinstance(child, (ast.comprehension,)):
                collect_target(child.target)
            visit(child)

    visit(fn)
    return bound


def walk_scope(fn: ast.AST) -> Iterable[ast.AST]:
    """Yield nodes of ``fn``'s own scope, not descending into nested
    function/lambda bodies (their own scope analysis handles them)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def function_scopes(tree: ast.AST) -> List[ast.AST]:
    """The module plus every function/lambda node — the scopes rules iterate."""
    scopes: List[ast.AST] = [tree]
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            scopes.append(node)
    return scopes


BranchPath = Tuple[Tuple[int, str], ...]


def branch_paths(scope: ast.AST) -> Dict[int, BranchPath]:
    """Map ``id(node)`` -> branch path for every node in ``scope``'s own scope.

    A branch path records which arm of each enclosing If/IfExp/Try the node
    sits in, so rules can tell mutually-exclusive uses (if/else arms —
    cannot both execute) from sequential ones.
    """
    paths: Dict[int, BranchPath] = {}

    def visit(node: ast.AST, path: BranchPath) -> None:
        paths[id(node)] = path
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested scope: its own branch_paths() call covers it
        if isinstance(node, (ast.If, ast.IfExp)):
            visit(node.test, path)
            visit_many(node.body if isinstance(node, ast.If)
                       else [node.body], path + ((id(node), "body"),))
            visit_many(node.orelse if isinstance(node, ast.If)
                       else [node.orelse], path + ((id(node), "else"),))
        elif isinstance(node, ast.Try):
            visit_many(node.body, path + ((id(node), "try"),))
            for h in node.handlers:
                paths[id(h)] = path
                visit_many(h.body, path + ((id(node), "except"),))
            visit_many(node.orelse, path + ((id(node), "try"),))
            visit_many(node.finalbody, path)
        else:
            for child in ast.iter_child_nodes(node):
                visit(child, path)

    def visit_many(nodes, path):
        for n in nodes:
            visit(n, path)

    for child in ast.iter_child_nodes(scope):
        visit(child, ())
    return paths


def paths_diverge(p1: BranchPath, p2: BranchPath) -> bool:
    """True when the two paths sit in different arms of the same branch —
    i.e. they cannot both execute in one pass through the scope."""
    for a, b in zip(p1, p2):
        if a == b:
            continue
        return a[0] == b[0] and a[1] != b[1]
    return False
