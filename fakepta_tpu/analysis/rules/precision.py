"""mixed-precision-cast: implicit f32->bf16 down-casts outside policy.

The engine's bf16-storage / f32-accumulate precision modes (``run(
precision='bf16')``, the Pallas kernels' bf16 operands, the megakernel's
bf16 base storage) are *certified*: their modules are listed in
``analysis.policy.BF16_STORAGE_MODULES`` and their streams are pinned
against the mesh-invariance tolerances in tests. A bfloat16 cast anywhere
else in the library is a silent half-precision leak — it rounds 24-bit
mantissas to 8 without a policy entry, a documented bound, or a
certification test — so it is a finding. Precision *mode strings*
(``precision='bf16'``) are not casts and never flagged; only dtype markers
(``jnp.bfloat16``, ``ml_dtypes.bfloat16``, the ``'bfloat16'`` dtype
string) are.
"""

from __future__ import annotations

import ast
from typing import List

from .. import policy
from ..engine import Finding, ModuleContext
from .common import NameResolver

RULE_ID = "mixed-precision-cast"

_BF16_ATTRS = {"jax.numpy.bfloat16", "numpy.bfloat16", "ml_dtypes.bfloat16",
               "jax.dtypes.bfloat16"}
_BF16_STRINGS = {"bfloat16"}


def check(ctx: ModuleContext) -> List[Finding]:
    if not ctx.is_library or ctx.path in policy.BF16_STORAGE_MODULES:
        return []
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.Attribute, ast.Name)):
            name = resolver.resolve(node)
            if name in _BF16_ATTRS:
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"{name} cast in a module outside the bf16-storage "
                    f"policy (analysis.policy.BF16_STORAGE_MODULES): an "
                    f"implicit f32->bf16 down-cast changes realization "
                    f"streams silently; route it through the engine's "
                    f"precision mode, or add the module to the policy "
                    f"with certification tests"))
        elif isinstance(node, ast.Call):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str) and \
                        arg.value in _BF16_STRINGS:
                    findings.append(ctx.finding(
                        RULE_ID, arg,
                        "dtype string 'bfloat16' in a module outside the "
                        "bf16-storage policy; use the engine's precision "
                        "mode (run(precision='bf16')) or add the module "
                        "to BF16_STORAGE_MODULES with certification "
                        "tests"))
    return findings
