"""unbounded-cache: cache containers without an eviction bound.

The gateway tier made caching a load-bearing subsystem (docs/GATEWAY.md):
the content-addressed result store, the single-flight table, and the warm
pools are all keyed by *client-controlled* input, which turns an unbounded
cache into a memory-exhaustion vector — a tenant iterating fresh specs
grows the map until the OOM killer takes out every tenant at once
(the multi-tenant version of the unbounded-queue failure). The repo
discipline is that every cache is bounded from day one: an LRU cap
(``OrderedDict`` + ``popitem(last=False)``, the ``ServeFleet._recent``
idiom), a byte budget with oldest-first ``pop`` (the ``fake_pta`` phase
cache), or ``functools.lru_cache(maxsize=N)``.

The rule flags, in library code:

- ``@functools.cache`` (no bounded form exists) and
  ``functools.lru_cache(maxsize=None)`` / ``lru_cache(None)`` — the
  explicitly-unbounded spellings; a literal or variable ``maxsize`` is
  accepted (structure, not values);
- assignments binding a **cache-named** target (a snake_case token of the
  name is ``cache``/``cached``/``memo``/``lru`` or a plural) to a
  ``dict()`` / ``{...}`` / ``collections.OrderedDict()`` when the module
  shows NO eviction evidence for that name — no ``.pop(...)`` /
  ``.popitem(...)`` / ``.clear()`` call and no ``del name[...]`` anywhere
  in the module. Evidence anywhere in the module clears every assignment
  to that name: the rule checks that a bound *exists*, not where.

Deliberately unbounded cases live in the policy exemption list
(``analysis.policy.UNBOUNDED_CACHE_MODULES`` — currently empty); anything
else takes a ``# fakepta: allow[unbounded-cache] reason`` pragma naming
the invariant that bounds it externally.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from .. import policy
from ..engine import Finding, ModuleContext
from .common import NameResolver, call_name

RULE_ID = "unbounded-cache"

#: snake_case tokens that mark a binding as a cache (exact-token match, so
#: ``memory`` / ``recent`` never false-positive on a substring)
_CACHE_TOKENS = {"cache", "caches", "cached", "memo", "memos", "memoized",
                 "lru"}

#: container constructors the rule treats as a cache backing store
_DICT_CALLS = {"dict", "collections.OrderedDict", "OrderedDict",
               "collections.defaultdict", "defaultdict"}

#: methods that count as eviction evidence on a name
_EVICT_METHODS = {"pop", "popitem", "clear"}


def _is_cache_name(name: Optional[str]) -> bool:
    if not name:
        return False
    tokens = [t for t in re.split(r"[_\W]+", name.lower()) if t]
    return any(t in _CACHE_TOKENS for t in tokens)


def _target_name(node) -> Optional[str]:
    """Last component of an assignment target (``self._spec_cache`` ->
    ``_spec_cache``), or None for tuple/subscript targets."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _evicted_names(tree: ast.AST) -> Set[str]:
    """Names the module shows eviction evidence for."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _EVICT_METHODS):
            name = _target_name(node.func.value)
            if name:
                out.add(name)
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    name = _target_name(tgt.value)
                    if name:
                        out.add(name)
    return out


def _is_dict_value(resolver: NameResolver, node) -> bool:
    if isinstance(node, ast.Dict):
        return True
    if isinstance(node, ast.Call):
        return call_name(resolver, node) in _DICT_CALLS
    return False


def _lru_unbounded(call: ast.Call) -> bool:
    """True for ``lru_cache(None)`` / ``lru_cache(maxsize=None)``."""
    bound = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "maxsize":
            bound = kw.value
    return isinstance(bound, ast.Constant) and bound.value is None


def check(ctx: ModuleContext) -> List[Finding]:
    if not ctx.is_library or ctx.path in policy.UNBOUNDED_CACHE_MODULES:
        return []
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []

    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            name = resolver.resolve(dec.func if isinstance(dec, ast.Call)
                                    else dec)
            if name == "functools.cache":
                findings.append(ctx.finding(
                    RULE_ID, dec,
                    "functools.cache has no bound: every distinct argument "
                    "tuple is retained for the process lifetime — use "
                    "functools.lru_cache(maxsize=N)"))
            elif (name == "functools.lru_cache" and isinstance(dec, ast.Call)
                    and _lru_unbounded(dec)):
                findings.append(ctx.finding(
                    RULE_ID, dec,
                    "lru_cache(maxsize=None) is the unbounded spelling — "
                    "pass a finite maxsize so client-controlled keys can't "
                    "grow the table without limit"))

    evicted = _evicted_names(ctx.tree)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if not _is_dict_value(resolver, value):
            continue
        names = [n for n in (_target_name(t) for t in targets) if n]
        cacheish = [n for n in names if _is_cache_name(n)]
        if not cacheish:
            continue
        if any(n in evicted for n in names):
            continue
        findings.append(ctx.finding(
            RULE_ID, node,
            f"cache {cacheish[0]!r} is a dict with no eviction anywhere in "
            f"the module (no .pop/.popitem/.clear/del): an unbounded cache "
            f"keyed by request input is a memory-exhaustion vector — bound "
            f"it (OrderedDict LRU with popitem, a byte budget with pop), "
            f"add the module to analysis.policy.UNBOUNDED_CACHE_MODULES, "
            f"or pragma it with the invariant that bounds it externally"))
    return findings
