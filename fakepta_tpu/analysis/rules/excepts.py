"""swallowed-exception: broad except handlers that silently eat failures.

The reliability layer's premise (docs/RELIABILITY.md) is that every failure
either *recovers* or *fails loudly* — a ``except Exception: pass`` in
library code is the third, forbidden outcome: the failure vanishes, the run
"succeeds", and the corruption (a missing checkpoint append, a swallowed
poisoned output, a dead thread) surfaces days later with no evidence. The
rule flags a **broad** handler — bare ``except:``, ``except Exception``,
``except BaseException`` (alone or in a tuple) — in library code whose body
does none of:

- **re-raise**: any ``raise`` statement in the handler body;
- **forward**: reference the bound exception name (``except ... as exc`` +
  any use of ``exc`` — storing it, wrapping it, ``set_exception(exc)``,
  triaging it with ``isinstance``);
- **record**: call a recording function — ``obs.flightrec.note``,
  ``obs.event``/``count``, ``warnings.warn``, ``logging``'s
  ``warning``/``error``/``exception``/``critical``.

Handlers narrowed to specific exception types are never flagged (catching
``FileNotFoundError`` and moving on is a decision, not a swallow).
Deliberately-silent broad handlers live in the policy exemption list
(``analysis.policy.SWALLOWED_EXCEPT_MODULES`` — currently the flight
recorder itself, whose dump path must never mask the exception being
handled) or carry a ``# fakepta: allow[swallowed-exception] reason``
pragma.
"""

from __future__ import annotations

import ast
from typing import List

from .. import policy
from ..engine import Finding, ModuleContext
from .common import NameResolver, last_component

RULE_ID = "swallowed-exception"

#: broad exception type names (resolved through import aliases)
_BROAD = {"Exception", "BaseException", "builtins.Exception",
          "builtins.BaseException"}

#: call name tails that count as recording the failure
_RECORDING_CALLS = {"note", "warn", "warning", "error", "exception",
                    "critical", "event", "count", "fail",
                    "set_exception", "print_exc"}


def _is_broad(resolver: NameResolver, type_node) -> bool:
    """Bare except, Exception/BaseException, or a tuple containing one."""
    if type_node is None:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(resolver, el) for el in type_node.elts)
    name = resolver.resolve(type_node)
    return name in _BROAD if name else False


def _handles(handler: ast.ExceptHandler, resolver: NameResolver) -> bool:
    """True when the body re-raises, forwards the bound name, or records."""
    bound = handler.name
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if (bound and isinstance(node, ast.Name) and node.id == bound
                and isinstance(node.ctx, ast.Load)):
            return True
        if isinstance(node, ast.Call):
            name = resolver.resolve(node.func)
            tail = (last_component(name) if name else
                    node.func.attr if isinstance(node.func, ast.Attribute)
                    else None)   # logger-style chains: getLogger(...).error
            if tail in _RECORDING_CALLS:
                return True
    return False


def check(ctx: ModuleContext) -> List[Finding]:
    if not ctx.is_library or ctx.path in policy.SWALLOWED_EXCEPT_MODULES:
        return []
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(resolver, node.type):
            continue
        if _handles(node, resolver):
            continue
        shape = ("bare except" if node.type is None else
                 f"except {ast.unparse(node.type)}")
        findings.append(ctx.finding(
            RULE_ID, node,
            f"{shape} swallows the failure silently: the body neither "
            f"re-raises, forwards the bound exception, nor records it "
            f"(flightrec.note / warnings.warn / logging). Narrow the "
            f"type, record the failure, or exempt it in "
            f"analysis.policy.SWALLOWED_EXCEPT_MODULES / pragma it with "
            f"the reason silence is correct here"))
    return findings
