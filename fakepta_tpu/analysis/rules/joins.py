"""unbounded-thread-join: bare ``.join()`` on a thread in library code.

A bare ``t.join()`` blocks forever. In library code the joined thread is
usually draining a queue, a socket, or a subprocess pipe — exactly the
things the fault plan can wedge — so an unbounded join turns one stuck
worker into a stuck *caller*: ``close()`` never returns, the process hangs
at shutdown with no telemetry, and the operator's only tool is SIGKILL
(losing the flight recorder it would have dumped). The repo's shutdown
discipline (docs/RELIABILITY.md) is: join with a generous bound, then
flight-record the leak (``serve_close_join_timeout`` and friends) and move
on — a leaked daemon thread is observable, a hung shutdown is not.

The rule flags ``x.join()`` calls with **no arguments at all** (and the
explicit ``timeout=None`` spelling). Zero args is what makes the match
precise: every non-thread ``join`` in practice takes one
(``", ".join(parts)``, ``os.path.join(a, b)``), so a bare no-arg ``.join()``
is a thread/process join by construction. Bounded joins
(``t.join(5.0)`` / ``t.join(timeout=s)``) pass — the rule checks
structure, not values.

Deliberately unbounded joins go in
``analysis.policy.UNBOUNDED_JOIN_MODULES`` (currently empty) or take a
``# fakepta: allow[unbounded-thread-join] reason`` pragma with the
invariant that bounds the wait externally.
"""

from __future__ import annotations

import ast
from typing import List

from .. import policy
from ..engine import Finding, ModuleContext

RULE_ID = "unbounded-thread-join"


def check(ctx: ModuleContext) -> List[Finding]:
    if not ctx.is_library or ctx.path in policy.UNBOUNDED_JOIN_MODULES:
        return []
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "join"):
            continue
        if node.args:
            continue  # positional timeout (or a str/path join) — bounded
        timeout = None
        for kw in node.keywords:
            if kw.arg == "timeout":
                timeout = kw.value
        if timeout is not None and not (isinstance(timeout, ast.Constant)
                                        and timeout.value is None):
            continue  # keyword timeout with a real bound
        findings.append(ctx.finding(
            RULE_ID, node,
            "bare .join() in library code blocks forever if the thread "
            "wedges: join with a bound and flight-record the leak "
            "(t.join(timeout_s); if t.is_alive(): flightrec.note(...)), or "
            "add the module to analysis.policy.UNBOUNDED_JOIN_MODULES / "
            "pragma it with the invariant that bounds the wait"))
    return findings
