"""donated-buffer-reuse: reading an array after it was donated to a jit.

``jax.jit(..., donate_argnums=...)`` hands the argument's buffer to XLA for
in-place reuse (the chunk pipeline recycles each drained chunk's packed
output as the next dispatch's scratch this way, docs/PERFORMANCE.md). The
caller's array is dead the moment the call dispatches: reading it afterwards
raises ``RuntimeError: Array has been deleted`` on backends that honor the
donation — and silently *works* on backends that don't, which is how the bug
ships. Flags, in library code, any later read of a name that was passed at a
donated positional slot of a function known (module-locally) to donate it,
unless the name is re-bound first or the read sits in a diverging branch arm.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..engine import Finding, ModuleContext
from .common import (NameResolver, branch_paths, call_name, function_scopes,
                     last_component, paths_diverge, walk_scope)

RULE_ID = "donated-buffer-reuse"

_JIT_NAMES = {"jit", "pjit"}


def _literal_argnums(node: ast.AST):
    """Resolve a donate_argnums literal (int or tuple of ints), else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _donating_functions(tree: ast.AST,
                        resolver: NameResolver) -> Dict[str, Tuple[int, ...]]:
    """Map local callable names to their donated positional indices.

    Detected forms: ``g = jax.jit(f, donate_argnums=...)`` (the bound name
    ``g`` donates) and ``@jax.jit(donate_argnums=...)`` /
    ``@partial(jax.jit, donate_argnums=...)`` decorators (the decorated
    function's own name donates).
    """

    def donate_spec(call: ast.Call):
        fn = resolver.resolve(call.func)
        inner = call
        if last_component(fn) == "partial" and call.args:
            if last_component(resolver.resolve(call.args[0])) \
                    not in _JIT_NAMES:
                return None
        elif last_component(fn) not in _JIT_NAMES:
            return None
        for kw in inner.keywords:
            if kw.arg == "donate_argnums":
                return _literal_argnums(kw.value)
        return None

    donors: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            spec = donate_spec(node.value)
            if spec:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donors[t.id] = spec
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    spec = donate_spec(dec)
                    if spec:
                        donors[node.name] = spec
    return donors


def check(ctx: ModuleContext) -> List[Finding]:
    if not ctx.is_library:
        return []   # tests deliberately poke deleted buffers to prove safety
    resolver = NameResolver(ctx.tree)
    donors = _donating_functions(ctx.tree, resolver)
    if not donors:
        return []
    findings: List[Finding] = []
    for scope in function_scopes(ctx.tree):
        paths = branch_paths(scope)
        # names stored anywhere in the scope, by line — a re-bind between
        # the donating call and a later read stages a fresh buffer
        stores: Dict[str, List[int]] = {}
        loads: Dict[str, List[ast.Name]] = {}
        for node in walk_scope(scope):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Store):
                    stores.setdefault(node.id, []).append(node.lineno)
                elif isinstance(node.ctx, ast.Load):
                    loads.setdefault(node.id, []).append(node)
        for node in walk_scope(scope):
            if not isinstance(node, ast.Call):
                continue
            spec = donors.get(call_name(resolver, node))
            if not spec:
                continue
            donated: Set[str] = set()
            for idx in spec:
                if idx < len(node.args) and \
                        isinstance(node.args[idx], ast.Name):
                    donated.add(node.args[idx].id)
            for name in donated:
                rebinds = [ln for ln in stores.get(name, [])
                           if ln > node.lineno]
                for use in loads.get(name, []):
                    if use.lineno <= node.lineno:
                        continue
                    if any(ln <= use.lineno for ln in rebinds):
                        continue   # re-bound first: a fresh buffer
                    if paths_diverge(paths.get(id(node), ()),
                                     paths.get(id(use), ())):
                        continue   # mutually-exclusive branch arms
                    findings.append(ctx.finding(
                        RULE_ID, use,
                        f"'{name}' was donated to "
                        f"'{call_name(resolver, node)}' on line "
                        f"{node.lineno} (donate_argnums) and its buffer may "
                        f"already be reused in place; copy before the call "
                        f"or re-stage a fresh array"))
                    break   # one finding per (call, name): the first reuse
    return sorted(set(findings))
