"""rng-discipline: the stream contracts behind bit-identical realizations.

The engine's reproducibility story (montecarlo.py module docstring; VERDICT
coverage rows 2/29) rests on every draw flowing through explicitly threaded
``jax.random`` keys with per-(psr, signal, realization) folding. Three ways
that discipline erodes:

1. **global-state numpy RNG** — ``np.random.normal()`` etc. draw from hidden
   process state the way the reference does at 20+ sites; results then
   depend on import order and call history, never on the seed contract.
2. **key reuse** — the same PRNG key passed to two consuming samplers
   without an intervening ``split``/``fold_in`` makes the two draws
   *identical*, which silently correlates signals.
3. **literal re-seeding in library code** — ``PRNGKey(0)`` inside the
   package pins a stream the caller cannot thread, so two call sites
   collide (tests/examples may pin seeds freely).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..engine import Finding, ModuleContext
from .common import (NameResolver, branch_paths, call_name, last_component,
                     paths_diverge, function_scopes, walk_scope)

RULE_ID = "rng-discipline"

# numpy.random attributes that are NOT the hidden global state
_NP_RANDOM_OK = {"default_rng", "Generator", "SeedSequence", "BitGenerator",
                 "PCG64", "PCG64DXSM", "Philox", "MT19937", "SFC64"}

# jax.random functions that CONSUME a key (same key to two of these = the
# same bits twice); split/fold_in/key constructors derive instead
_CONSUMERS = {
    "normal", "uniform", "bernoulli", "randint", "choice", "permutation",
    "gamma", "beta", "exponential", "poisson", "truncated_normal",
    "multivariate_normal", "categorical", "laplace", "logistic", "gumbel",
    "rademacher", "bits", "ball", "cauchy", "dirichlet", "loggamma",
    "maxwell", "pareto", "rayleigh", "t", "weibull_min", "orthogonal",
}

_SEED_CONSTRUCTORS = {"jax.random.PRNGKey", "jax.random.key",
                      "numpy.random.default_rng"}


def check(ctx: ModuleContext) -> List[Finding]:
    resolver = NameResolver(ctx.tree)
    findings: List[Finding] = []

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(resolver, node)
        if not name:
            continue
        # (1) global-state numpy RNG
        if name.startswith("numpy.random.") and \
                name.split(".")[2] not in _NP_RANDOM_OK:
            findings.append(ctx.finding(
                RULE_ID, node,
                f"{last_component(name)} draws from numpy's hidden global "
                f"state; thread an explicit np.random.default_rng(seed) or "
                f"a jax.random key instead"))
        # (3) literal integer re-seeding inside library code
        if ctx.is_library and name in _SEED_CONSTRUCTORS and node.args:
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, int):
                findings.append(ctx.finding(
                    RULE_ID, node,
                    f"literal seed {a0.value} in library code pins a stream "
                    f"callers cannot thread; accept a seed/key argument "
                    f"(utils.rng.as_key) instead"))

    findings.extend(_key_reuse(ctx, resolver))
    return findings


def _key_reuse(ctx: ModuleContext, resolver: NameResolver) -> List[Finding]:
    """(2) same key Name consumed twice with no rebinding between.

    Per scope: record consuming uses (a bare Name as the key argument of a
    jax.random sampler) and rebindings, ordered by position, each tagged
    with its branch path. A second use flags unless it sits in the opposite
    arm of the same branch as the first (mutually exclusive), or the name
    was rebound between the two.
    """
    findings: List[Finding] = []
    for scope in function_scopes(ctx.tree):
        paths = branch_paths(scope)
        # (name -> list of (pos, kind, node, path)) in source order
        events: Dict[str, List[Tuple[Tuple[int, int], str, ast.AST,
                                     tuple]]] = {}

        def record(name: str, kind: str, node: ast.AST) -> None:
            pos = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))
            events.setdefault(name, []).append(
                (pos, kind, node, paths.get(id(node), ())))

        for node in walk_scope(scope):
            if isinstance(node, ast.Call):
                fname = call_name(resolver, node)
                if fname and fname.startswith("jax.random.") and \
                        fname.split(".")[2] in _CONSUMERS:
                    key_arg = None
                    if node.args:
                        key_arg = node.args[0]
                    else:
                        for kw in node.keywords:
                            if kw.arg == "key":
                                key_arg = kw.value
                    if isinstance(key_arg, ast.Name):
                        record(key_arg.id, "use", node)
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                   ast.NamedExpr, ast.For)):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.NamedExpr):
                    targets = [node.target]
                elif isinstance(node, ast.For):
                    targets = [node.target]
                else:
                    targets = [node.target]
                for t in targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name) and \
                                isinstance(sub.ctx, ast.Store):
                            record(sub.id, "rebind", sub)

        for name, evs in events.items():
            evs.sort(key=lambda e: e[0])
            active: List[Tuple[tuple, ast.AST]] = []
            for pos, kind, node, path in evs:
                if kind == "rebind":
                    active.clear()
                    continue
                clash = next((n for p, n in active
                              if not paths_diverge(p, path)), None)
                if clash is not None:
                    findings.append(ctx.finding(
                        RULE_ID, node,
                        f"key '{name}' already consumed on line "
                        f"{clash.lineno}; reusing it yields identical bits "
                        f"— split/fold_in a fresh subkey first"))
                active.append((path, node))
    return findings
