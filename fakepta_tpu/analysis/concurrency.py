"""Whole-program concurrency rules over the project index.

Three rules, all driven by one lock model extracted from the
:class:`~fakepta_tpu.analysis.project.ProjectIndex`:

- **lock-order-inversion**: per-class lock discovery (``self._lock =
  threading.Lock()``, conditions aliasing their lock, module-level locks)
  feeds a lock-order graph — an edge A→B whenever a path acquires B while
  holding A, transitively closed over the call graph *including* the
  future-callback edges (``set_result``/``set_exception`` synchronously
  run every ``add_done_callback`` the project registers — the exact path
  a failover callback re-enters a sibling replica through). Any cycle is
  an ABBA finding; an edge running backwards against the canonical
  ``policy.LOCK_ORDER`` is an inversion finding even before the closing
  edge lands in the repo.
- **blocking-under-lock**: socket ``recv``/``accept``, ``queue.get/put``
  and ``.join()``/``.wait()``/``.result()`` without a timeout, subprocess
  waits, engine dispatch (``run``/``warm_start``/``prewarm``) and heavy
  constructors (``policy.BLOCKING_CONSTRUCTORS``) reachable — directly or
  through the call graph — while a lock is held. ``Condition.wait`` on
  the held lock's own condition is exempt (it *releases* the lock).
- **thread-shared-state**: instance attributes written from two or more
  distinct thread roots (``Thread(target=...)`` entry points plus the
  external-caller root seeded at every public method) with no lock held
  in common across every write path. ``__init__`` writes are
  construction-time and exempt.

Lock names: ``ClassName.attr`` for instance locks (a Condition built from
a lock IS that lock), ``<module>.name`` for module-level locks, with
``policy.LOCK_ALIASES``/``policy.ATTR_CLASS_HINTS`` resolving duck-typed
cross-object acquisitions (``self.fleet._lock`` → ``ServeFleet._lock``).
The same conservative static model that finds real inversions can be
wrong about exotic dynamic dispatch — suppression is the usual pragma
(``# fakepta: allow[rule] reason``) or the per-module policy exemptions.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from . import policy
from .engine import Finding
from .project import ProjectIndex, FunctionInfo, _self_attr_path, QSEP

LOCK_ORDER_RULE = "lock-order-inversion"
BLOCKING_RULE = "blocking-under-lock"
SHARED_STATE_RULE = "thread-shared-state"

EXTERNAL_ROOT = "<external>"

_SOCKET_BLOCKING = ("accept", "recv", "recvfrom", "recv_into")
_SUBPROCESS_FNS = ("run", "call", "check_call", "check_output")


def _short(path: str) -> str:
    p = path
    for prefix in policy.LIBRARY_PREFIXES:
        if p.startswith(prefix):
            p = p[len(prefix):]
    return p[:-3] if p.endswith(".py") else p


@dataclasses.dataclass(frozen=True)
class Event:
    held: Tuple[str, ...]
    kind: str                  # 'acquire' | 'call' | 'blocking' | 'write'
    payload: object            # lock key | callee qnames | desc | attr name
    node: ast.AST


@dataclasses.dataclass(frozen=True)
class Edge:
    """First witness of 'acquires ``dst`` while holding ``src``'."""

    src: str
    dst: str
    module: str
    line: int
    via: str                   # '' for an intra-function nesting


class LockModel:
    """Per-function event streams + the interprocedural lock-order graph.

    Built once per index (``LockModel.of(index)`` memoizes on the index
    object) and shared by all three rules.
    """

    def __init__(self, index: ProjectIndex) -> None:
        self.index = index
        self.events: Dict[str, List[Event]] = {}
        self._local_locks: Dict[str, Dict[str, int]] = {}
        self._kw_timeout_cache: Dict[int, bool] = {}  # fakepta: allow[unbounded-cache] one entry per AST call node of one analysis pass, freed with the pass
        for qname in sorted(index.functions):
            self.events[qname] = self._function_events(
                index.functions[qname])
        # transitive lock-acquisition and blocking closures
        self.acquires: Dict[str, Tuple[str, ...]] = {}
        self.blocks: Dict[str, Tuple[Tuple[str, int, str], ...]] = {}
        self._close_over_callgraph()
        self.edges: List[Edge] = self._build_edges()

    @staticmethod
    def of(index: ProjectIndex) -> "LockModel":
        model = getattr(index, "_lock_model", None)
        if model is None:
            model = LockModel(index)
            index._lock_model = model
        return model

    # -- lock naming ---------------------------------------------------------

    def _class_info(self, fi: FunctionInfo):
        for ci in self.index.classes.get(fi.cls or "", []):
            if ci.module == fi.module:
                return ci
        return None

    def _locals_of(self, fi: FunctionInfo) -> Dict[str, int]:
        got = self._local_locks.get(fi.qname)
        if got is None:
            got = {}
            from .project import _is_lock_ctor
            resolver = self.index.modules[fi.module].resolver
            for node in ProjectIndex._walk_own_scope(fi.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and _is_lock_ctor(resolver, node.value):
                    got[node.targets[0].id] = node.lineno
            self._local_locks[fi.qname] = got
        return got

    def lock_key(self, fi: FunctionInfo, expr: ast.AST) -> Optional[str]:
        ci = self._class_info(fi)
        ap = _self_attr_path(expr)
        if ap is not None and ci is not None:
            if len(ap) == 1:
                a = ap[0]
                if a in ci.cond_aliases:
                    return f"{ci.name}.{ci.cond_aliases[a]}"
                if a in ci.lock_attrs:
                    return f"{ci.name}.{a}"
                return None
            observed = f"{ci.name}." + ".".join(ap)
            if observed in policy.LOCK_ALIASES:
                return policy.LOCK_ALIASES[observed]
            acls = ci.attr_classes.get(ap[0])
            if acls is not None and len(ap) == 2:
                for tci in self.index.classes.get(acls, []):
                    a = ap[1]
                    if a in tci.cond_aliases:
                        return f"{tci.name}.{tci.cond_aliases[a]}"
                    if a in tci.lock_attrs:
                        return f"{tci.name}.{a}"
            return None
        if isinstance(expr, ast.Name):
            mi = self.index.modules[fi.module]
            if expr.id in mi.module_locks:
                return f"{_short(fi.module)}.{expr.id}"
            if expr.id in self._locals_of(fi):
                return f"{_short(fi.module)}:{fi.name}.{expr.id}"
            return None
        if isinstance(expr, ast.Attribute):
            dotted = self.index.modules[fi.module].resolver.resolve(expr)
            if dotted and "." in dotted:
                mod_dots, leaf = dotted.rsplit(".", 1)
                for path in sorted(self.index.modules):
                    dp = path[:-3].replace("/", ".")
                    if dp.endswith(".__init__"):
                        dp = dp[: -len(".__init__")]
                    if (dp == mod_dots or dp.endswith("." + mod_dots)) \
                            and leaf in self.index.modules[path] \
                            .module_locks:
                        return f"{_short(path)}.{leaf}"
        return None

    # -- blocking-call classification ---------------------------------------

    def _has_real_timeout(self, call: ast.Call, names=("timeout",)) -> bool:
        for kw in call.keywords:
            if kw.arg in names:
                return not (isinstance(kw.value, ast.Constant)
                            and kw.value.value is None)
        return False

    def _has_block_false(self, call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return True
        return False

    def _blocking_desc(self, fi: FunctionInfo,
                       call: ast.Call) -> Optional[str]:
        ctor = self.index.constructed_class(fi, call)
        if ctor is not None and ctor in policy.BLOCKING_CONSTRUCTORS:
            return f"constructing {ctor} (device/IO-heavy __init__)"
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        dotted = self.index.modules[fi.module].resolver.resolve(func) or ""
        if dotted.startswith("subprocess."):
            if attr in _SUBPROCESS_FNS + ("communicate", "wait") \
                    and not self._has_real_timeout(call):
                return f"subprocess.{attr}() with no timeout"
            return None
        if attr in _SOCKET_BLOCKING:
            return f"socket .{attr}() (network wait)"
        if attr == "get" and not call.args \
                and not self._has_real_timeout(call) \
                and not self._has_block_false(call):
            return "queue .get() with no timeout"
        if attr == "put" and len(call.args) == 1 \
                and not self._has_real_timeout(call) \
                and not self._has_block_false(call):
            return "queue .put() with no timeout"
        if attr == "join" and not call.args \
                and not self._has_real_timeout(call):
            return ".join() with no timeout"
        if attr == "communicate" and not self._has_real_timeout(call):
            return ".communicate() with no timeout"
        if attr == "wait" and not call.args \
                and not self._has_real_timeout(call):
            # Condition.wait on the held lock's own condition RELEASES the
            # lock — the sanctioned blocking-wait design, not a finding
            ap = _self_attr_path(func.value)
            ci = self._class_info(fi)
            if ap is not None and len(ap) == 1 and ci is not None \
                    and ap[0] in ci.cond_aliases:
                return None
            return ".wait() with no timeout"
        if attr == "result" and not call.args \
                and not self._has_real_timeout(call):
            return "Future.result() with no timeout"
        if attr in policy.BLOCKING_DISPATCH_METHODS:
            return f"engine dispatch .{attr}()"
        return None

    # -- per-function event streams -----------------------------------------

    def _function_events(self, fi: FunctionInfo) -> List[Event]:
        callees_at: Dict[int, Tuple[str, ...]] = {
            id(site.node): site.callees
            for site in self.index.calls.get(fi.qname, ())}
        future_targets = self.index.future_resolution_targets()
        events: List[Event] = []

        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held
                for item in node.items:
                    visit(item.context_expr, held)
                    key = self.lock_key(fi, item.context_expr)
                    if key is not None:
                        events.append(Event(inner, "acquire", key,
                                            item.context_expr))
                        inner = inner + (key,)
                for st in node.body:
                    visit(st, inner)
                return
            if isinstance(node, ast.Call):
                callees = callees_at.get(id(node), ())
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("set_result", "set_exception"):
                    callees = tuple(dict.fromkeys(
                        callees + future_targets))
                if callees:
                    events.append(Event(held, "call", callees, node))
                desc = self._blocking_desc(fi, node)
                if desc is not None and not (
                        desc.startswith("Future.result")
                        and fi.qname in self.index.done_callbacks):
                    # .result() inside a done-callback runs on an
                    # already-resolved future — never blocks
                    events.append(Event(held, "blocking", desc, node))
            elif isinstance(node, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    ap = _self_attr_path(t)
                    if ap is not None and len(ap) == 1 \
                            and isinstance(t, ast.Attribute) \
                            and isinstance(t.ctx, ast.Store):
                        events.append(Event(held, "write", ap[0], t))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for child in ast.iter_child_nodes(fi.node):
            visit(child, ())
        events.sort(key=lambda e: (getattr(e.node, "lineno", 0),
                                   getattr(e.node, "col_offset", 0)))
        return events

    # -- interprocedural closures -------------------------------------------

    def _close_over_callgraph(self) -> None:
        order = sorted(self.events)
        acq: Dict[str, set] = {q: set() for q in order}
        blk: Dict[str, dict] = {q: {} for q in order}
        callees: Dict[str, List[str]] = {}
        for q in order:
            outs: List[str] = []
            for ev in self.events[q]:
                if ev.kind == "acquire":
                    acq[q].add(ev.payload)
                elif ev.kind == "blocking":
                    fi = self.index.functions[q]
                    blk[q].setdefault(
                        ev.payload,
                        (fi.module, ev.node.lineno, ""))
                elif ev.kind == "call":
                    outs.extend(ev.payload)
            callees[q] = [c for c in dict.fromkeys(outs) if c in acq]
        changed = True
        while changed:
            changed = False
            for q in order:
                fi = self.index.functions[q]
                for c in callees[q]:
                    if not acq[c] <= acq[q]:
                        acq[q] |= acq[c]
                        changed = True
                    for desc, wit in blk[c].items():
                        tagged = f"{desc} [via {_qdisplay(c)}]" \
                            if not desc.endswith("]") else desc
                        if tagged not in blk[q]:
                            blk[q][tagged] = wit
                            changed = True
        self.acquires = {q: tuple(sorted(acq[q])) for q in order}
        self.blocks = {q: tuple(sorted((d, w[1], w[0]) for d, w in
                                       blk[q].items()))
                       for q in order}

    def _build_edges(self) -> List[Edge]:
        seen: Dict[Tuple[str, str], Edge] = {}

        def add(src: str, dst: str, module: str, line: int,
                via: str) -> None:
            if src == dst and not via:
                # re-acquiring the SAME lock with no call in between is
                # the non-reentrant self-deadlock; with a call chain it is
                # the sibling-instance ABBA — both are cycles, keep them
                pass
            key = (src, dst)
            if key not in seen:
                seen[key] = Edge(src, dst, module, line, via)

        for q in sorted(self.events):
            fi = self.index.functions[q]
            for ev in self.events[q]:
                if not ev.held:
                    continue
                if ev.kind == "acquire":
                    for h in ev.held:
                        add(h, ev.payload, fi.module,
                            ev.node.lineno, "")
                elif ev.kind == "call":
                    for c in ev.payload:
                        for dst in self.acquires.get(c, ()):
                            for h in ev.held:
                                add(h, dst, fi.module, ev.node.lineno,
                                    _qdisplay(c))
        return sorted(seen.values(),
                      key=lambda e: (e.src, e.dst))

    # -- cycles --------------------------------------------------------------

    def cycles(self) -> List[List[Edge]]:
        """Deterministic list of lock-order cycles (as edge lists)."""
        adj: Dict[str, List[Edge]] = {}
        for e in self.edges:
            adj.setdefault(e.src, []).append(e)
        sccs = _tarjan_sccs(sorted({e.src for e in self.edges}
                                   | {e.dst for e in self.edges}), adj)
        out: List[List[Edge]] = []
        for comp in sccs:
            comp_set = set(comp)
            internal = [e for e in self.edges
                        if e.src in comp_set and e.dst in comp_set]
            if len(comp) > 1 or any(e.src == e.dst for e in internal):
                out.append(internal)
        out.sort(key=lambda edges: (edges[0].module, edges[0].line))
        return out

    def to_dot(self) -> str:
        """The lock-order graph in DOT (``graph --dot``); cycle edges red."""
        in_cycle = {(e.src, e.dst) for cyc in self.cycles() for e in cyc}
        lines = ["digraph lock_order {", "  rankdir=LR;",
                 '  node [shape=box, fontname="monospace"];']
        nodes = sorted({e.src for e in self.edges}
                       | {e.dst for e in self.edges})
        for n in nodes:
            lines.append(f'  "{n}";')
        for e in self.edges:
            attrs = [f'label="{e.module}:{e.line}"']
            if (e.src, e.dst) in in_cycle:
                attrs.append('color=red')
            lines.append(f'  "{e.src}" -> "{e.dst}" '
                         f'[{", ".join(attrs)}];')
        lines.append("}")
        return "\n".join(lines) + "\n"


def _qdisplay(qname: str) -> str:
    return qname.split(QSEP, 1)[-1]


def _tarjan_sccs(nodes: Sequence[str],
                 adj: Dict[str, List[Edge]]) -> List[List[str]]:
    index_of: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Dict[str, bool] = {}
    stack: List[str] = []
    counter = [0]
    out: List[List[str]] = []

    def strongconnect(v: str) -> None:
        work = [(v, 0)]
        while work:
            node, pi = work.pop()
            if pi == 0:
                index_of[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack[node] = True
            recurse = False
            edges = adj.get(node, ())
            for i in range(pi, len(edges)):
                w = edges[i].dst
                if w not in index_of:
                    work.append((node, i + 1))
                    work.append((w, 0))
                    recurse = True
                    break
                if on_stack.get(w):
                    low[node] = min(low[node], index_of[w])
            if recurse:
                continue
            if low[node] == index_of[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == node:
                        break
                out.append(sorted(comp))
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for v in nodes:
        if v not in index_of:
            strongconnect(v)
    return out


# ---------------------------------------------------------------------------
# the three rules
# ---------------------------------------------------------------------------

def _finding(path: str, line: int, col: int, rule: str,
             message: str) -> Finding:
    return Finding(path, line, col, rule, message)


def _is_checked(path: str, exempt: Sequence[str]) -> bool:
    return policy.is_library(path) and path not in exempt


def check_lock_order(index: ProjectIndex) -> List[Finding]:
    model = LockModel.of(index)
    findings: List[Finding] = []
    cycle_edges = set()
    for cyc in model.cycles():
        cycle_edges.update((e.src, e.dst) for e in cyc)
        witness = min(cyc, key=lambda e: (e.module, e.line))
        if not _is_checked(witness.module, ()):
            continue
        chain = "; ".join(
            f"{e.src} -> {e.dst} at {e.module}:{e.line}"
            + (f" (via {e.via})" if e.via else "") for e in cyc)
        findings.append(_finding(
            witness.module, witness.line, 1, LOCK_ORDER_RULE,
            f"lock-order cycle (ABBA deadlock): {chain}; break the cycle "
            f"by releasing the first lock before the nested acquisition "
            f"or follow the canonical order (policy.LOCK_ORDER, "
            f"docs/INVARIANTS.md)"))
    rank = {name: i for i, name in enumerate(policy.LOCK_ORDER)}
    for e in model.edges:
        if (e.src, e.dst) in cycle_edges:
            continue
        if e.src in rank and e.dst in rank and rank[e.src] > rank[e.dst]:
            if not _is_checked(e.module, ()):
                continue
            findings.append(_finding(
                e.module, e.line, 1, LOCK_ORDER_RULE,
                f"acquires {e.dst} while holding {e.src}"
                + (f" (via {e.via})" if e.via else "")
                + f", against the canonical lock order "
                  f"({e.dst} before {e.src} — policy.LOCK_ORDER); "
                  f"reorder or release first"))
    return findings


def check_blocking_under_lock(index: ProjectIndex) -> List[Finding]:
    model = LockModel.of(index)
    findings: List[Finding] = []
    for q in sorted(model.events):
        fi = index.functions[q]
        if not _is_checked(fi.module, policy.BLOCKING_UNDER_LOCK_MODULES):
            continue
        for ev in model.events[q]:
            if not ev.held:
                continue
            locks = ", ".join(dict.fromkeys(ev.held))
            if ev.kind == "blocking":
                findings.append(_finding(
                    fi.module, ev.node.lineno, ev.node.col_offset + 1,
                    BLOCKING_RULE,
                    f"{ev.payload} while holding {locks}: every sibling "
                    f"of the lock stalls for the full wait; move the "
                    f"blocking call outside the lock or bound it"))
            elif ev.kind == "call":
                for c in ev.payload:
                    for desc, line, module in model.blocks.get(c, ()):
                        findings.append(_finding(
                            fi.module, ev.node.lineno,
                            ev.node.col_offset + 1, BLOCKING_RULE,
                            f"calls {_qdisplay(c)} while holding {locks}, "
                            f"which reaches {desc} ({module}:{line}); "
                            f"release the lock before the call or bound "
                            f"the wait"))
                        break          # one finding per callee chain
    return findings


def check_thread_shared_state(index: ProjectIndex) -> List[Finding]:
    model = LockModel.of(index)
    roots: List[Tuple[str, List[str]]] = []
    seen_targets = []
    for tr in index.thread_roots:
        if tr.target not in seen_targets:
            seen_targets.append(tr.target)
            roots.append((tr.target, [tr.target]))
    external_seeds: List[str] = []
    for qname in sorted(index.functions):
        fi = index.functions[qname]
        if fi.name.startswith("_") or fi.name == "<lambda>":
            continue
        if qname in seen_targets:
            continue
        external_seeds.append(qname)
    roots.append((EXTERNAL_ROOT, external_seeds))

    # meet-over-paths held-lock propagation per root
    entry_held: Dict[Tuple[str, str], frozenset] = {}
    for root_id, seeds in roots:
        work = [(q, frozenset()) for q in seeds]
        while work:
            q, held = work.pop(0)
            if q not in model.events:
                continue
            key = (root_id, q)
            old = entry_held.get(key)
            new = held if old is None else (old & held)
            if old is not None and new == old:
                continue
            entry_held[key] = new
            for ev in model.events[q]:
                if ev.kind == "call":
                    at = new | frozenset(ev.held)
                    for c in ev.payload:
                        work.append((c, at))

    # collect writes per (module, class, attr)
    writes: Dict[Tuple[str, str, str],
                 List[Tuple[str, frozenset, int]]] = {}
    for (root_id, q), held in sorted(entry_held.items()):
        fi = index.functions[q]
        if fi.cls is None or fi.name == "__init__":
            continue
        for ev in model.events[q]:
            if ev.kind != "write":
                continue
            guard = held | frozenset(ev.held)
            writes.setdefault((fi.module, fi.cls, ev.payload), []) \
                .append((root_id, guard, ev.node.lineno))

    # only classes that OPT INTO concurrency — own a lock/condition or
    # have a method spawned as a thread target — are judged; everything
    # else is confined by its owner's lock by convention and the
    # over-approximate call graph would otherwise drown the signal
    concurrent: set = set()
    for cname in index.classes:
        for ci in index.classes[cname]:
            if ci.lock_attrs or ci.cond_aliases:
                concurrent.add((ci.module, ci.name))
    for tr in index.thread_roots:
        fi = index.functions.get(tr.target)
        if fi is not None and fi.cls is not None:
            concurrent.add((fi.module, fi.cls))

    findings: List[Finding] = []
    for (module, cls, attr) in sorted(writes):
        if not _is_checked(module, policy.SHARED_STATE_MODULES):
            continue
        if (module, cls) not in concurrent:
            continue
        sites = writes[(module, cls, attr)]
        root_ids = sorted({r for r, _, _ in sites})
        if len(root_ids) < 2:
            continue
        common = None
        for _, guard, _ in sites:
            common = guard if common is None else (common & guard)
        if common:
            continue
        unguarded = sorted(line for _, guard, line in sites
                           if not guard)
        anchor = unguarded[0] if unguarded else min(
            line for _, _, line in sites)
        pretty_roots = ", ".join(_qdisplay(r) if r != EXTERNAL_ROOT
                                 else "external callers"
                                 for r in root_ids)
        findings.append(_finding(
            module, anchor, 1, SHARED_STATE_RULE,
            f"{cls}.{attr} is written from {len(root_ids)} thread roots "
            f"({pretty_roots}) with no common lock on every write path; "
            f"guard every write with one lock or confine the attribute "
            f"to a single thread"))
    return findings


PROJECT_RULES = (
    (LOCK_ORDER_RULE, check_lock_order),
    (BLOCKING_RULE, check_blocking_under_lock),
    (SHARED_STATE_RULE, check_thread_shared_state),
)
