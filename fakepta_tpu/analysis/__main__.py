"""CLI: ``python -m fakepta_tpu.analysis check <paths...>``.

Exit codes: 0 clean, 1 findings, 2 usage error — so the tier-1 test (and
any CI job) can gate on it directly. ``--write-baseline`` snapshots the
current findings into the committed baseline; the intended steady state is
an *empty* baseline with every sanctioned exception pragma'd in place,
because a pragma carries its justification next to the code and a baseline
entry does not.

``--format json`` emits the stable machine schema (CI annotations,
editors)::

    {"schema": "fakepta_tpu.analysis/1",
     "count": 2,
     "findings": [{"path": ..., "line": ..., "col": ...,
                   "rule": ..., "message": ...}, ...]}

Findings are sorted (path, line, col, rule); the exit code is the same as
text mode. ``graph <paths...> --dot`` prints the whole-program lock-order
graph in DOT (cycle edges red) for docs and deadlock review.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import engine
from .rules import PROJECT_RULE_IDS, RULE_IDS

#: bump only with a documented migration; consumers pin on this
JSON_SCHEMA = "fakepta_tpu.analysis/1"

DEFAULT_BASELINE = Path(__file__).parent / "baseline.json"


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m fakepta_tpu.analysis",
        description="AST linter for the engine's correctness invariants "
                    "(RNG discipline, host-sync/tracer hygiene in jit, "
                    "dtype policy, mesh-axis contracts)")
    sub = parser.add_subparsers(dest="command", required=True)
    check = sub.add_parser("check", help="analyze files/directories")
    check.add_argument("paths", nargs="+",
                       help="python files or directories to analyze")
    check.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                       help="baseline JSON of accepted findings "
                            "(default: the committed package baseline)")
    check.add_argument("--no-baseline", action="store_true",
                       help="report every finding, baseline ignored")
    check.add_argument("--write-baseline", action="store_true",
                       help="snapshot current findings into --baseline and "
                            "exit 0")
    check.add_argument("--format", choices=("text", "json"), default="text")
    check.add_argument("--root", type=Path, default=None,
                       help="directory paths are reported relative to "
                            "(default: cwd; baseline keys use these paths)")
    sub.add_parser("rules", help="list registered rule ids")
    graph = sub.add_parser(
        "graph", help="export the whole-program lock-order graph")
    graph.add_argument("paths", nargs="+",
                       help="python files or directories to index")
    graph.add_argument("--dot", action="store_true",
                       help="emit graphviz DOT (default: edge list)")
    graph.add_argument("--root", type=Path, default=None)
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "rules":
        for rid in (RULE_IDS + PROJECT_RULE_IDS
                    + (engine.PRAGMA_RULE, engine.UNUSED_PRAGMA_RULE)):
            print(rid)
        return 0
    if args.command == "graph":
        from .concurrency import LockModel

        index = engine.build_project_index(args.paths, root=args.root)
        model = LockModel.of(index)
        if args.dot:
            sys.stdout.write(model.to_dot())
        else:
            for e in model.edges:
                via = f" via {e.via}" if e.via else ""
                print(f"{e.src} -> {e.dst}  [{e.module}:{e.line}{via}]")
        return 0

    findings = engine.check_paths(args.paths, root=args.root)
    if args.write_baseline:
        engine.save_baseline(args.baseline, findings)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0
    if not args.no_baseline and args.baseline.exists():
        findings = engine.apply_baseline(
            findings, engine.load_baseline(args.baseline))

    if args.format == "json":
        print(json.dumps(
            {"schema": JSON_SCHEMA, "count": len(findings),
             "findings": [{"path": f.path, "line": f.line, "col": f.col,
                           "rule": f.rule, "message": f.message}
                          for f in findings]}, indent=2))
    else:
        for f in findings:
            print(f.format())
        n = len(findings)
        print(f"{n} finding(s)" if n else "clean: 0 findings")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
