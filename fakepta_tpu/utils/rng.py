"""Explicit PRNG key threading.

The reference draws from the *global* ``np.random`` state at 20+ sites with no seed
control anywhere (e.g. ``fake_pta.py:45,206-230,374``, ``correlated_noises.py:154-155``),
so its runs are unreproducible by design. Here every stochastic kernel takes a
``jax.random`` key, and keys are derived deterministically from (seed, label, counter)
so that per-(pulsar, signal, realization) streams are independent and reproducible.
"""

from __future__ import annotations

import zlib
from typing import Union

import jax
import numpy as np

KeyLike = Union[int, jax.Array, None]

_DEFAULT_SEED = 0


def set_default_seed(seed: int) -> None:
    """Set the package-level seed used when an API call gets no explicit seed/key."""
    global _DEFAULT_SEED
    _DEFAULT_SEED = int(seed)


def get_default_seed() -> int:
    return _DEFAULT_SEED


def as_key(seed_or_key: KeyLike) -> jax.Array:
    """Coerce an int seed / key / None (-> package default seed) into a PRNG key."""
    if seed_or_key is None:
        return jax.random.key(_DEFAULT_SEED)
    if isinstance(seed_or_key, (int, np.integer)):
        return jax.random.key(int(seed_or_key))
    return seed_or_key


def _label_to_int(label) -> int:
    if isinstance(label, str):
        return zlib.crc32(label.encode("utf-8"))
    return int(label)


def fold(key: jax.Array, *labels) -> jax.Array:
    """Derive a subkey by folding in string/int labels (stable across runs)."""
    for label in labels:
        key = jax.random.fold_in(key, _label_to_int(label))
    return key


_auto_streams = 0


class KeyStream:
    """A mutable counter-based key stream for the stateful host facade.

    Each ``next(label)`` call returns ``fold(base, label, counter)`` and bumps the
    counter, so successive injector calls on a ``Pulsar`` consume distinct streams
    while staying reproducible from the constructor seed.

    With ``seed_or_key=None`` the base key is additionally folded with a
    process-wide instance counter: unseeded objects get *distinct* (but still
    run-to-run deterministic) streams instead of bit-identical draws — two unseeded
    pulsars must not share their noise realizations.
    """

    def __init__(self, seed_or_key: KeyLike, *labels):
        global _auto_streams
        base = as_key(seed_or_key)
        if seed_or_key is None:
            base = fold(base, "auto_stream", _auto_streams)
            _auto_streams += 1
        self._base = fold(base, *labels) if labels else base
        self._count = 0

    def next(self, *labels) -> jax.Array:
        key = fold(self._base, self._count, *labels)
        self._count += 1
        return key

    def host_rng(self, *labels) -> np.random.Generator:
        """A numpy Generator seeded from this stream, for host-side config sampling."""
        key = self.next(*labels)
        data = jax.random.key_data(key)
        return np.random.default_rng(np.asarray(data, dtype=np.uint32).ravel().tolist())
