"""Explicit PRNG key threading.

The reference draws from the *global* ``np.random`` state at 20+ sites with no seed
control anywhere (e.g. ``fake_pta.py:45,206-230,374``, ``correlated_noises.py:154-155``),
so its runs are unreproducible by design. Here every stochastic kernel takes a
``jax.random`` key, and keys are derived deterministically from (seed, label, counter)
so that per-(pulsar, signal, realization) streams are independent and reproducible.
"""

from __future__ import annotations

import functools
import zlib
from typing import Union

import jax
import numpy as np

KeyLike = Union[int, jax.Array, None]

_DEFAULT_SEED = 0


@functools.lru_cache(maxsize=4096)
def _int_key_data(seed: int) -> np.ndarray:
    """Cached *host* key data for integer seeds.

    The cache stores host uint32 key data, not device keys: a cached device
    key would pin whichever backend was live at first call, and the
    dead-tunnel fallback switches ``jax_platforms`` to cpu mid-process —
    stale-backend keys must not survive that. Threefry key data is
    platform-independent, so rewrapping is exact. Computed on the local CPU
    backend when one exists so seeding never pays an accelerator round-trip.
    """
    try:
        # local_devices, not devices: in a multi-process program the global
        # list starts with process 0's devices, which other processes cannot
        # fetch key data from
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        cpu = None
    if cpu is None:
        return np.asarray(jax.random.key_data(jax.random.key(seed)))
    with jax.default_device(cpu):
        return np.asarray(jax.random.key_data(jax.random.key(seed)))


@functools.lru_cache(maxsize=4096)
def _wrapped_key(seed: int, backend: str) -> jax.Array:
    # keyed on the live default backend: a platform switch MISSES the cache
    # (fresh wrap on the new backend) instead of serving a stale device key,
    # while repeated seeds on a stable backend stay a dict lookup — seeding
    # is otherwise an eager device op of ~ms dispatch latency on a remote TPU
    return jax.random.wrap_key_data(_int_key_data(seed))


def _int_key(seed: int) -> jax.Array:
    return _wrapped_key(seed, jax.default_backend())


def set_default_seed(seed: int) -> None:
    """Set the package-level seed used when an API call gets no explicit seed/key."""
    global _DEFAULT_SEED
    _DEFAULT_SEED = int(seed)


def get_default_seed() -> int:
    return _DEFAULT_SEED


def as_key(seed_or_key: KeyLike) -> jax.Array:
    """Coerce an int seed / key / None (-> package default seed) into a PRNG key."""
    if seed_or_key is None:
        return _int_key(_DEFAULT_SEED)
    if isinstance(seed_or_key, (int, np.integer)):
        return _int_key(int(seed_or_key))
    return seed_or_key


def _label_to_int(label) -> int:
    if isinstance(label, str):
        return zlib.crc32(label.encode("utf-8"))
    return int(label)


def fold(key: jax.Array, *labels) -> jax.Array:
    """Derive a subkey by folding in string/int labels (stable across runs)."""
    for label in labels:
        key = jax.random.fold_in(key, _label_to_int(label))
    return key


_auto_streams = 0


class KeyStream:
    """A mutable counter-based key stream for the stateful host facade.

    Each ``next(label)`` call returns ``fold(base, label, counter)`` and bumps the
    counter, so successive injector calls on a ``Pulsar`` consume distinct streams
    while staying reproducible from the constructor seed.

    With ``seed_or_key=None`` the base key is additionally folded with a
    process-wide instance counter: unseeded objects get *distinct* (but still
    run-to-run deterministic) streams instead of bit-identical draws — two unseeded
    pulsars must not share their noise realizations.
    """

    def __init__(self, seed_or_key: KeyLike, *labels):
        global _auto_streams
        base = as_key(seed_or_key)
        if seed_or_key is None:
            base = fold(base, "auto_stream", _auto_streams)
            _auto_streams += 1
        self._base = fold(base, *labels) if labels else base
        self._count = 0

    def next(self, *labels) -> jax.Array:
        key = fold(self._base, self._count, *labels)
        self._count += 1
        return key

    def next_spec(self, *labels):
        """(base key, uint32 fold labels) for key derivation INSIDE a jitted
        kernel instead of eagerly.

        Each eager ``fold_in`` is a device dispatch — milliseconds of latency
        per call on a remote TPU — while folding inside the consuming kernel is
        free. Applying ``jax.random.fold_in`` left-to-right over the returned
        labels yields the exact key :meth:`next` would have returned (same
        counter bump, same fold order, same 32-bit label values).
        """
        folds = np.array([self._count] + [_label_to_int(l) for l in labels],
                         dtype=np.uint32)
        self._count += 1
        return self._base, folds

    def host_rng(self, *labels) -> np.random.Generator:
        """A numpy Generator seeded from this stream, for host-side config sampling."""
        key = self.next(*labels)
        data = jax.random.key_data(key)
        return np.random.default_rng(np.asarray(data, dtype=np.uint32).ravel().tolist())


NO_FOLDS = np.zeros((0,), dtype=np.uint32)


def fold_key_in_kernel(key, folds):
    """Apply a :meth:`KeyStream.next_spec` fold-label array inside a kernel.

    The loop length is static (folds is a fixed-shape argument), so this traces
    to a chain of fold_ins with no data-dependent control flow.
    """
    for i in range(folds.shape[0]):
        key = jax.random.fold_in(key, folds[i])
    return key
