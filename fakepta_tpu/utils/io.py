"""Persistence: ENTERPRISE-layout pickles, config JSONs, ensemble checkpoints.

The reference's entire persistence story is ``pickle.dump``/``load`` of the pulsar
list plus two JSON config files (SURVEY.md §5, ``examples/make_fake_array.py:31,65``).
These helpers make that contract explicit, and add what the reference lacks: a
resumable checkpoint format for long Monte-Carlo runs (the closest thing the
reference has is re-derivability of a realization from ``signal_model``).
"""

from __future__ import annotations

import io
import json
import os
import pickle
import zipfile
import zlib
from pathlib import Path
from typing import Optional

import numpy as np


def write_atomic(path, data: bytes) -> int:
    """Crash-safe file write: tmp + fsync + rename + directory fsync.

    The rename is atomic on POSIX, so a reader never sees a half-written
    file under the final name; the two fsyncs (file data before the
    rename, the directory entry after) close the crash window where the
    rename survives a power loss but the data pages do not — the classic
    torn-write. Returns the CRC32 of ``data`` (the checksum the checkpoint
    manifests record, so resume can *detect* the torn writes that fsync
    cannot prevent on failing storage). See docs/RELIABILITY.md.
    """
    path = Path(path)
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    dirfd = os.open(path.parent, os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)
    return zlib.crc32(data)


def npz_bytes(**arrays) -> bytes:
    """Serialize arrays to npz *bytes* (for :func:`write_atomic`)."""
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def save_array(psrs, path):
    """Pickle a pulsar list in the ENTERPRISE-compatible layout (ref
    ``examples/make_fake_array.py:65``)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "wb") as fh:
        pickle.dump(list(psrs), fh)
    return path


def load_array(path):
    """Load a pulsar list pickle (fakepta_tpu or ENTERPRISE objects)."""
    with open(path, "rb") as fh:
        return pickle.load(fh)


def load_noisedict(path) -> dict:
    """Flat ``{parameter_name: float}`` JSON, ENTERPRISE naming (SURVEY.md §2.4)."""
    nd = json.loads(Path(path).read_text())
    bad = {k: v for k, v in nd.items() if not isinstance(v, (int, float))}
    if bad:
        raise ValueError(f"noisedict values must be numbers; offending keys: "
                         f"{sorted(bad)[:5]}")
    return nd


def load_custom_models(path) -> dict:
    """``{psrname: {'RN': n|None, 'DM': n|None, 'Sv': n|None}}`` JSON."""
    models = json.loads(Path(path).read_text())
    for name, entry in models.items():
        missing = {"RN", "DM", "Sv"} - set(entry)
        if missing:
            raise ValueError(f"custom_models[{name!r}] missing {sorted(missing)}")
    return models


class EnsembleCheckpoint:
    """Chunk-granular checkpoint/resume for :meth:`EnsembleSimulator.run`.

    Append-only: each completed chunk is written once to its own ``.c<k>.npz``
    file and a small manifest records how far the run got, so checkpoint I/O per
    chunk is O(chunk), not O(done) (rewriting the accumulated history made each
    save grow quadratically over the run). Because each chunk's RNG keys derive
    from ``fold_in(base_key, absolute_index)``, a resumed run continues the
    *identical* realization stream — the result equals the uninterrupted run,
    which the tests assert.

    **Hardened** (docs/RELIABILITY.md): every file lands via
    :func:`write_atomic` (tmp + fsync + rename + dir fsync), the manifest
    records a CRC32 per chunk file, and :meth:`load` verifies them — a torn
    or corrupt chunk file **rolls the checkpoint back to the last good
    chunk** (bad files dropped, manifest rewritten, the rollback
    flight-recorded) instead of resuming from garbage or crashing. The
    resumed stream is still bit-identical to the uninterrupted run: rolled-
    back chunks simply recompute from their absolute-index keys.
    """

    def __init__(self, path):
        self.path = Path(path)
        self._sums: dict = {}      # chunk index -> CRC32 (manifest-backed)

    def _chunk_path(self, k: int) -> Path:
        return self.path.with_name(self.path.name + f".c{k:06d}.npz")

    def _write_manifest(self, seed, nreal: int, chunk: int, done: int,
                        n_extra: int) -> None:
        n_chunks = done // chunk
        manifest = dict(seed=np.int64(seed), nreal=np.int64(nreal),
                        chunk=np.int64(chunk), done=np.int64(done),
                        n_extra=np.int64(n_extra),
                        sums=np.asarray([self._sums.get(k, 0)
                                         for k in range(n_chunks)],
                                        dtype=np.int64))
        write_atomic(self.path, npz_bytes(**manifest))

    def _rollback(self, seed, nreal: int, chunk: int, good: int,
                  total: int, n_extra: int) -> None:
        """Drop chunks ``good..total-1`` and rewrite the manifest — the
        torn-write recovery path (resume recomputes the dropped chunks
        from their absolute-index keys, bit-identically)."""
        from ..obs import flightrec
        for k in range(good, total):
            self._chunk_path(k).unlink(missing_ok=True)
            self._sums.pop(k, None)
        flightrec.note("ckpt_rollback", path=str(self.path), good=good,
                       dropped=total - good)
        if good == 0:
            self.delete()
        else:
            self._write_manifest(seed, nreal, chunk, good * chunk, n_extra)

    def load(self, seed, nreal: int, chunk: int, keep_corr: bool = True,
             n_extra: int = 0) -> Optional[dict]:
        """Return accumulated saved state if it matches this run's configuration.

        ``keep_corr=False`` skips reading the (large) per-chunk correlation
        tensors that a ``keep_corr=False`` resume would discard anyway.
        ``n_extra`` is the expected extra packed-lane count (the OS lanes of
        a ``run(os=...)``); a mismatch means the checkpoint was written by a
        run with a different detection configuration and must not resume.

        Torn-write detection: each chunk file's bytes are checked against
        the manifest's CRC32 before use; the first bad chunk triggers a
        rollback to the last good one (``state["rolled_back"]`` counts the
        dropped chunks — the engine's ``faults.rollbacks`` counter). An
        unreadable manifest is flight-recorded and treated as no
        checkpoint: the restarted run reproduces the stream from scratch.
        """
        if not self.path.exists():
            return None
        try:
            with np.load(self.path, allow_pickle=False) as z:
                manifest = {k: z[k] for k in z.files}
        except (OSError, ValueError, zipfile.BadZipFile) as exc:
            from ..obs import flightrec
            flightrec.note("ckpt_manifest_corrupt", path=str(self.path),
                           error=repr(exc)[:200])
            self.delete()
            return None
        if (int(manifest["seed"]) != int(seed) or int(manifest["nreal"]) != nreal
                or int(manifest["chunk"]) != chunk):
            raise ValueError(
                f"checkpoint {self.path} was written by a different run "
                f"(seed/nreal/chunk = {int(manifest['seed'])}/"
                f"{int(manifest['nreal'])}/{int(manifest['chunk'])}, requested "
                f"{seed}/{nreal}/{chunk}); delete it or use a different path")
        saved_extra = int(manifest.get("n_extra", 0))
        if saved_extra != int(n_extra):
            raise ValueError(
                f"checkpoint {self.path} carries {saved_extra} extra "
                f"statistic lane(s) but this run expects {n_extra} (a "
                f"different os= configuration); delete it or use a "
                f"different path")
        done = int(manifest["done"])
        if done and not self._chunk_path(0).exists():
            raise ValueError(
                f"checkpoint {self.path} has no chunk files (written by an "
                f"older single-file format, or the .c*.npz files were removed); "
                f"delete it and restart the run")
        sums = manifest.get("sums")   # absent on pre-hardening checkpoints
        total = done // chunk
        parts = []
        good = total
        self._sums = {}
        for k in range(total):
            try:
                data = self._chunk_path(k).read_bytes()
                crc = zlib.crc32(data)
                if sums is not None and k < len(sums) and crc != int(sums[k]):
                    raise ValueError(
                        f"chunk {k} checksum mismatch (torn write)")
                with np.load(io.BytesIO(data), allow_pickle=False) as z:
                    keys = [key for key in z.files
                            if keep_corr or key != "corr"]
                    parts.append({key: z[key] for key in keys})
                self._sums[k] = crc
            except (OSError, ValueError, KeyError,
                    zipfile.BadZipFile) as exc:
                from ..obs import flightrec
                flightrec.note("ckpt_chunk_corrupt", chunk=k,
                               error=repr(exc)[:200])
                good = k
                parts = parts[:good]
                break
        if good < total:
            self._rollback(seed, nreal, chunk, good, total, saved_extra)
            done = good * chunk
            if good == 0:
                return None
        state = {
            "done": done,
            "rolled_back": total - good,
            "curves": np.concatenate([p["curves"] for p in parts]),
            "autos": np.concatenate([p["autos"] for p in parts]),
        }
        if parts and all("corr" in p for p in parts):
            state["corr"] = np.concatenate([p["corr"] for p in parts])
        if parts and all("extra" in p for p in parts):
            state["extra"] = np.concatenate([p["extra"] for p in parts])
        return state

    def save(self, seed, nreal: int, chunk: int, done: int, curves, autos,
             corr=None, extra=None):
        """Record one completed chunk (its arrays only, not the accumulation).

        ``extra`` holds any additional packed statistic lanes (the OS lanes
        of a ``run(os=...)``) so a resumed detection run keeps them too.
        Both writes are atomic (:func:`write_atomic`) and the manifest —
        written last, so a crash between the two leaves an unreferenced
        chunk file the next save overwrites — carries the chunk CRCs.
        """
        from .. import faults
        self.path.parent.mkdir(parents=True, exist_ok=True)
        act = faults.check("ckpt.append", done=int(done))
        payload = dict(curves=curves, autos=autos)
        if corr is not None:
            payload["corr"] = corr
        if extra is not None:
            payload["extra"] = extra
        k = done // chunk - 1
        cpath = self._chunk_path(k)
        self._sums[k] = write_atomic(cpath, npz_bytes(**payload))
        self._write_manifest(seed, nreal, chunk, done,
                             0 if extra is None else np.shape(extra)[1])
        if act == "torn":
            # chaos harness: simulate the torn write fsync cannot prevent
            # (failing storage drops the data pages AFTER the rename became
            # durable) and the process dying with it — resume must detect
            # the bad CRC and roll back to the last good chunk
            data = cpath.read_bytes()
            cpath.write_bytes(data[:max(len(data) // 2, 1)])
            raise faults.KillFault(
                f"injected torn checkpoint write at chunk {k}")

    def delete(self):
        for p in self.path.parent.glob(self.path.name + ".c*.npz"):
            p.unlink(missing_ok=True)
        self.path.unlink(missing_ok=True)
        self._sums = {}
