"""Persistence: ENTERPRISE-layout pickles, config JSONs, ensemble checkpoints.

The reference's entire persistence story is ``pickle.dump``/``load`` of the pulsar
list plus two JSON config files (SURVEY.md §5, ``examples/make_fake_array.py:31,65``).
These helpers make that contract explicit, and add what the reference lacks: a
resumable checkpoint format for long Monte-Carlo runs (the closest thing the
reference has is re-derivability of a realization from ``signal_model``).
"""

from __future__ import annotations

import json
import pickle
from pathlib import Path
from typing import Optional

import numpy as np


def save_array(psrs, path):
    """Pickle a pulsar list in the ENTERPRISE-compatible layout (ref
    ``examples/make_fake_array.py:65``)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "wb") as fh:
        pickle.dump(list(psrs), fh)
    return path


def load_array(path):
    """Load a pulsar list pickle (fakepta_tpu or ENTERPRISE objects)."""
    with open(path, "rb") as fh:
        return pickle.load(fh)


def load_noisedict(path) -> dict:
    """Flat ``{parameter_name: float}`` JSON, ENTERPRISE naming (SURVEY.md §2.4)."""
    nd = json.loads(Path(path).read_text())
    bad = {k: v for k, v in nd.items() if not isinstance(v, (int, float))}
    if bad:
        raise ValueError(f"noisedict values must be numbers; offending keys: "
                         f"{sorted(bad)[:5]}")
    return nd


def load_custom_models(path) -> dict:
    """``{psrname: {'RN': n|None, 'DM': n|None, 'Sv': n|None}}`` JSON."""
    models = json.loads(Path(path).read_text())
    for name, entry in models.items():
        missing = {"RN", "DM", "Sv"} - set(entry)
        if missing:
            raise ValueError(f"custom_models[{name!r}] missing {sorted(missing)}")
    return models


class EnsembleCheckpoint:
    """Chunk-granular checkpoint/resume for :meth:`EnsembleSimulator.run`.

    Append-only: each completed chunk is written once to its own ``.c<k>.npz``
    file and a small manifest records how far the run got, so checkpoint I/O per
    chunk is O(chunk), not O(done) (rewriting the accumulated history made each
    save grow quadratically over the run). Because each chunk's RNG keys derive
    from ``fold_in(base_key, absolute_index)``, a resumed run continues the
    *identical* realization stream — the result equals the uninterrupted run,
    which the tests assert.
    """

    def __init__(self, path):
        self.path = Path(path)

    def _chunk_path(self, k: int) -> Path:
        return self.path.with_name(self.path.name + f".c{k:06d}.npz")

    def load(self, seed, nreal: int, chunk: int, keep_corr: bool = True,
             n_extra: int = 0) -> Optional[dict]:
        """Return accumulated saved state if it matches this run's configuration.

        ``keep_corr=False`` skips reading the (large) per-chunk correlation
        tensors that a ``keep_corr=False`` resume would discard anyway.
        ``n_extra`` is the expected extra packed-lane count (the OS lanes of
        a ``run(os=...)``); a mismatch means the checkpoint was written by a
        run with a different detection configuration and must not resume.
        """
        if not self.path.exists():
            return None
        with np.load(self.path, allow_pickle=False) as z:
            manifest = {k: z[k] for k in z.files}
        if (int(manifest["seed"]) != int(seed) or int(manifest["nreal"]) != nreal
                or int(manifest["chunk"]) != chunk):
            raise ValueError(
                f"checkpoint {self.path} was written by a different run "
                f"(seed/nreal/chunk = {int(manifest['seed'])}/"
                f"{int(manifest['nreal'])}/{int(manifest['chunk'])}, requested "
                f"{seed}/{nreal}/{chunk}); delete it or use a different path")
        saved_extra = int(manifest.get("n_extra", 0))
        if saved_extra != int(n_extra):
            raise ValueError(
                f"checkpoint {self.path} carries {saved_extra} extra "
                f"statistic lane(s) but this run expects {n_extra} (a "
                f"different os= configuration); delete it or use a "
                f"different path")
        done = int(manifest["done"])
        if done and not self._chunk_path(0).exists():
            raise ValueError(
                f"checkpoint {self.path} has no chunk files (written by an "
                f"older single-file format, or the .c*.npz files were removed); "
                f"delete it and restart the run")
        parts = []
        for k in range(done // chunk):
            with np.load(self._chunk_path(k), allow_pickle=False) as z:
                keys = [key for key in z.files if keep_corr or key != "corr"]
                parts.append({key: z[key] for key in keys})
        state = {
            "done": done,
            "curves": np.concatenate([p["curves"] for p in parts]),
            "autos": np.concatenate([p["autos"] for p in parts]),
        }
        if parts and all("corr" in p for p in parts):
            state["corr"] = np.concatenate([p["corr"] for p in parts])
        if parts and all("extra" in p for p in parts):
            state["extra"] = np.concatenate([p["extra"] for p in parts])
        return state

    def save(self, seed, nreal: int, chunk: int, done: int, curves, autos,
             corr=None, extra=None):
        """Record one completed chunk (its arrays only, not the accumulation).

        ``extra`` holds any additional packed statistic lanes (the OS lanes
        of a ``run(os=...)``) so a resumed detection run keeps them too.
        """
        self.path.parent.mkdir(parents=True, exist_ok=True)
        payload = dict(curves=curves, autos=autos)
        if corr is not None:
            payload["corr"] = corr
        if extra is not None:
            payload["extra"] = extra
        cpath = self._chunk_path(done // chunk - 1)
        tmp = cpath.with_suffix(".tmp.npz")
        np.savez(tmp, **payload)
        tmp.replace(cpath)
        # manifest last: a crash between the two writes leaves an unreferenced
        # chunk file that the next save simply overwrites
        manifest = dict(seed=np.int64(seed), nreal=np.int64(nreal),
                        chunk=np.int64(chunk), done=np.int64(done),
                        n_extra=np.int64(0 if extra is None
                                         else np.shape(extra)[1]))
        tmp = self.path.with_suffix(".tmp.npz")
        np.savez(tmp, **manifest)
        tmp.replace(self.path)

    def delete(self):
        for p in self.path.parent.glob(self.path.name + ".c*.npz"):
            p.unlink(missing_ok=True)
        self.path.unlink(missing_ok=True)
