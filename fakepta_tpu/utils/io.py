"""Persistence: ENTERPRISE-layout pickles, config JSONs, ensemble checkpoints.

The reference's entire persistence story is ``pickle.dump``/``load`` of the pulsar
list plus two JSON config files (SURVEY.md §5, ``examples/make_fake_array.py:31,65``).
These helpers make that contract explicit, and add what the reference lacks: a
resumable checkpoint format for long Monte-Carlo runs (the closest thing the
reference has is re-derivability of a realization from ``signal_model``).
"""

from __future__ import annotations

import json
import pickle
from pathlib import Path
from typing import Optional

import numpy as np


def save_array(psrs, path):
    """Pickle a pulsar list in the ENTERPRISE-compatible layout (ref
    ``examples/make_fake_array.py:65``)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "wb") as fh:
        pickle.dump(list(psrs), fh)
    return path


def load_array(path):
    """Load a pulsar list pickle (fakepta_tpu or ENTERPRISE objects)."""
    with open(path, "rb") as fh:
        return pickle.load(fh)


def load_noisedict(path) -> dict:
    """Flat ``{parameter_name: float}`` JSON, ENTERPRISE naming (SURVEY.md §2.4)."""
    nd = json.loads(Path(path).read_text())
    bad = {k: v for k, v in nd.items() if not isinstance(v, (int, float))}
    if bad:
        raise ValueError(f"noisedict values must be numbers; offending keys: "
                         f"{sorted(bad)[:5]}")
    return nd


def load_custom_models(path) -> dict:
    """``{psrname: {'RN': n|None, 'DM': n|None, 'Sv': n|None}}`` JSON."""
    models = json.loads(Path(path).read_text())
    for name, entry in models.items():
        missing = {"RN", "DM", "Sv"} - set(entry)
        if missing:
            raise ValueError(f"custom_models[{name!r}] missing {sorted(missing)}")
    return models


class EnsembleCheckpoint:
    """Chunk-granular checkpoint/resume for :meth:`EnsembleSimulator.run`.

    One ``.npz`` per run, rewritten atomically after every chunk: because each
    chunk's RNG keys derive from ``fold_in(base_key, absolute_index)``, a resumed
    run continues the *identical* realization stream — the result equals the
    uninterrupted run, which the tests assert.
    """

    def __init__(self, path):
        self.path = Path(path)

    def load(self, seed, nreal: int, chunk: int) -> Optional[dict]:
        """Return saved state if it matches this run's configuration."""
        if not self.path.exists():
            return None
        with np.load(self.path, allow_pickle=False) as z:
            state = {k: z[k] for k in z.files}
        if (int(state["seed"]) != int(seed) or int(state["nreal"]) != nreal
                or int(state["chunk"]) != chunk):
            raise ValueError(
                f"checkpoint {self.path} was written by a different run "
                f"(seed/nreal/chunk = {int(state['seed'])}/{int(state['nreal'])}"
                f"/{int(state['chunk'])}, requested {seed}/{nreal}/{chunk}); "
                f"delete it or use a different path")
        return state

    def save(self, seed, nreal: int, chunk: int, done: int, curves, autos,
             corr=None):
        self.path.parent.mkdir(parents=True, exist_ok=True)
        payload = dict(seed=np.int64(seed), nreal=np.int64(nreal),
                       chunk=np.int64(chunk), done=np.int64(done),
                       curves=curves, autos=autos)
        if corr is not None:
            payload["corr"] = corr
        tmp = self.path.with_suffix(".tmp.npz")
        np.savez(tmp, **payload)
        tmp.replace(self.path)

    def delete(self):
        self.path.unlink(missing_ok=True)
