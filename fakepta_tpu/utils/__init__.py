from . import masks, rng  # noqa: F401
