"""Padding / masking helpers.

Ragged per-pulsar TOA counts (the reference draws them per pulsar,
``fake_pta.py:596,608-610``) become padded ``(npsr, max_toa)`` arrays plus boolean
masks on device. Shapes are bucketed to multiples of the TPU lane width so the
jit cache stays small and tiles map cleanly onto the VPU/MXU.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

LANE = 128


def bucket_size(n: int, bucket: int = LANE) -> int:
    """Smallest multiple of ``bucket`` >= n (minimum one bucket)."""
    return max(bucket, int(-(-n // bucket)) * bucket)


def pad_1d(x: np.ndarray, size: int, fill=0.0) -> np.ndarray:
    """Pad a 1-D array to ``size`` with ``fill``."""
    x = np.asarray(x)
    out = np.full((size,), fill, dtype=x.dtype)
    out[: len(x)] = x
    return out


def stack_ragged(arrays: Sequence[np.ndarray], size: int | None = None, fill=0.0):
    """Stack ragged 1-D arrays into a padded 2-D array + boolean validity mask."""
    lengths = np.array([len(a) for a in arrays])
    size = size if size is not None else bucket_size(int(lengths.max()))
    out = np.stack([pad_1d(a, size, fill) for a in arrays])
    mask = np.arange(size)[None, :] < lengths[:, None]
    return out, mask
