"""Tracing and timing: the TPU-idiomatic observability layer.

The reference has no profiling at all (SURVEY.md §5). On TPU the idiomatic
equivalents are ``jax.profiler`` device traces (viewable in TensorBoard /
Perfetto) and wall-clock timing that accounts for async dispatch — a naive
``time.time()`` around a jitted call measures dispatch, not execution, so
:func:`timed` blocks on the returned arrays.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Dict, List

import jax


@contextlib.contextmanager
def trace(logdir: str, annotate: str = ""):
    """Capture a device trace under ``logdir`` (open with TensorBoard/Perfetto).

    >>> with trace("/tmp/pta_trace"):
    ...     sim.run(1000, seed=0)
    """
    with jax.profiler.trace(str(logdir)):
        if annotate:
            with jax.profiler.TraceAnnotation(annotate):
                yield
        else:
            yield


annotation = jax.profiler.TraceAnnotation    # named spans inside a trace


@dataclass
class Timer:
    """Accumulating wall-clock timer with device-sync semantics.

    ``block_until_ready`` is applied to whatever the timed block returns through
    ``set_result``, so the recorded time includes device execution, not just
    Python dispatch.
    """

    times: Dict[str, List[float]] = field(default_factory=dict)

    @contextlib.contextmanager
    def section(self, name: str):
        holder = {}

        def set_result(x):
            holder["out"] = x
            return x

        t0 = time.perf_counter()
        yield set_result
        if "out" in holder:
            jax.block_until_ready(holder["out"])
        self.times.setdefault(name, []).append(time.perf_counter() - t0)

    def summary(self) -> Dict[str, dict]:
        return {name: {"n": len(ts), "total_s": sum(ts),
                       "mean_s": sum(ts) / len(ts)}
                for name, ts in self.times.items() if ts}
