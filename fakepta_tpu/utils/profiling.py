"""Deprecated: absorbed into :mod:`fakepta_tpu.obs` (PR 2).

This module is a thin back-compat re-export. ``Timer``/``trace``/
``annotation`` now live in :mod:`fakepta_tpu.obs.timing`, alongside the
metrics core and the :class:`~fakepta_tpu.obs.RunReport` artifact — and the
``obs`` Timer fixes this module's old bug where a raising timed block lost
its measurement entirely (the elapsed time is now recorded in ``finally``).
"""

from __future__ import annotations

import warnings

from ..obs.timing import Timer, annotation, trace  # noqa: F401

warnings.warn(
    "fakepta_tpu.utils.profiling is deprecated; import Timer/trace/annotation "
    "from fakepta_tpu.obs instead (docs/OBSERVABILITY.md)",
    DeprecationWarning, stacklevel=2)

__all__ = ["Timer", "annotation", "trace"]
