"""Version tolerance for the handful of jax APIs that moved out of experimental.

The engine targets the modern public names (``jax.shard_map``,
``jax.enable_x64``) but must also run on jaxlib builds where those still live
under ``jax.experimental`` — the virtual-CPU test mesh in CI is one such
build. Everything here resolves the preferred name first and falls back, so
call sites import from this module and never branch on versions themselves.
"""

from __future__ import annotations

import jax

try:
    from jax import enable_x64  # noqa: F401  (re-export)
except ImportError:  # pragma: no cover - depends on the installed jax
    from jax.experimental import enable_x64  # noqa: F401


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the pre-0.5 experimental fallback.

    ``check_vma`` is the modern name of the replication-checking switch; on
    older jax it maps onto ``check_rep``, which gates the same validation.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)
