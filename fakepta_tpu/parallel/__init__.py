from . import mesh, montecarlo  # noqa: F401
