"""Asynchronous bounded-depth chunk pipeline (docs/PERFORMANCE.md).

The chunk loop of :meth:`EnsembleSimulator.run` is memory/latency-bound, not
FLOP-bound (BASELINE round 5: 7.1 FLOP/B against a v5e ridge of 240), so the
throughput win left on the table is hiding everything that is *not* the chunk
program: host precompute of the next chunk's staged inputs, checkpoint I/O,
progress syncs, and device->host fetches. This module holds the host-side
machinery the run loop pipelines through:

- a **single background writer thread** draining a FIFO of per-chunk drain
  thunks (materialize outputs via the already-started ``copy_to_host_async``,
  append the checkpoint chunk, invoke the progress callback) in the serial
  loop's exact order — checkpoint semantics are unchanged: append-only,
  process-0-only, resume-compatible with the existing manifest;
- an **inline writer** with the same interface for the serial fallback
  (``run(pipeline_depth=0)``) and for multi-process runs, where a background
  thread issuing ``process_allgather`` collectives could reorder collective
  launches across processes and deadlock the pod;
- the **persistent compile cache** wiring (``FAKEPTA_TPU_COMPILE_CACHE`` env
  var / ``EnsembleSimulator(compile_cache_dir=...)``) so the obs-measured
  ``compile_s`` amortizes across processes and rounds instead of being paid
  per process.

Exceptions raised by a drain (a checkpoint write failing, a progress callback
aborting the run) propagate to the ``run()`` caller exactly as in the serial
loop: the writer records the first exception, skips the remaining queued
drains (matching the serial loop's abort-at-failure semantics), and re-raises
it at the next ``submit``/``close``. Depth bounding and donated-buffer
recycling live in the run loop itself (see montecarlo.run), which hands each
chunk's previous packed output back to the jitted step as a donated scratch
buffer once its drain has materialized it.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, Optional

import jax
import numpy as np

from ..faults import check as faults_check
from ..faults import classify as faults_classify
from ..faults import sleep as faults_sleep
from ..obs import flightrec
from ..obs.timing import now as _now

# opt-in env var for the persistent XLA compile cache; the kwarg
# EnsembleSimulator(compile_cache_dir=...) takes precedence
COMPILE_CACHE_ENV = "FAKEPTA_TPU_COMPILE_CACHE"

_STOP = object()


def configure_compile_cache(path=None) -> Optional[str]:
    """Wire jax's persistent compilation cache (opt-in, idempotent).

    ``path`` wins; otherwise the ``FAKEPTA_TPU_COMPILE_CACHE`` env var is
    honored; with neither set this is a no-op (returns None). The thresholds
    are dropped to zero so even the fast CPU-mesh compiles of tests and
    small runs persist — the flagship chunk program's multi-second compile
    then loads from disk on every later process/round instead of recompiling
    (the AOT warm-start :meth:`EnsembleSimulator.warm_start` populates the
    same cache ahead of the first run).

    A cache that cannot be wired — unwritable directory, a jax build
    without the knobs, an injected ``cache.load`` fault — **degrades, never
    aborts**: the failure is flight-recorded and the run proceeds without a
    persistent cache (it recompiles; it does not die). Returns the wired
    path, or None when no cache is active.
    """
    if path is None:
        path = os.environ.get(COMPILE_CACHE_ENV)
    if not path:
        return None
    path = str(path)
    try:
        faults_check("cache.load", path=path)
        jax.config.update("jax_compilation_cache_dir", path)
        for opt, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                         ("jax_persistent_cache_min_entry_size_bytes", 0)):
            try:
                jax.config.update(opt, val)
            # fakepta: allow[swallowed-exception] knob missing in this jax
            # version; the cache still works without it
            except Exception:
                pass
        try:
            # jax memoizes the cache-used decision at the FIRST compile of
            # the process; a sim constructed after any compile would
            # silently get no cache without this re-evaluation
            from jax.experimental.compilation_cache import compilation_cache
            compilation_cache.reset_cache()
        # fakepta: allow[swallowed-exception] optional API surface; older
        # jax versions arm the cache at first compile anyway
        except Exception:
            pass
    except Exception as exc:   # noqa: BLE001 — recorded + degraded below
        # graceful degradation (docs/RELIABILITY.md): a broken cache dir
        # must cost recompiles, not the run
        flightrec.note("cache_load_failed", path=path,
                       error=repr(exc)[:200])
        try:
            jax.config.update("jax_compilation_cache_dir", None)
        # fakepta: allow[swallowed-exception] best-effort un-wiring after a
        # cache failure that is already flight-recorded above
        except Exception:
            pass
        return None
    return path


def run_drain_with_retry(drain: Callable[[], None], retries: int,
                         backoff_s: float, backoff_mult: float = 2.0,
                         max_backoff_s: float = 2.0,
                         on_retry: Optional[Callable[[int], None]] = None
                         ) -> None:
    """Run one drain thunk, retrying *transient* failures with backoff.

    Drains are idempotent by construction — materialize into a fixed slot,
    overwrite the same checkpoint chunk file, re-invoke the progress
    callback with the same counts — so a transient failure (an injected
    ``pipeline.writer`` fault, a flaky filesystem) costs a bounded retry
    instead of aborting the run. Non-transient failures propagate
    unchanged; :class:`~fakepta_tpu.faults.KillFault` (simulated process
    death) is BaseException and never enters the except clause.
    """
    delay = backoff_s
    for attempt in range(retries + 1):
        try:
            drain()
            return
        except Exception as exc:   # noqa: BLE001 — triaged + bounded below
            if faults_classify(exc) != "transient" or attempt >= retries:
                raise
            flightrec.note("drain_retry", attempt=attempt + 1,
                           error=repr(exc)[:200])
            if on_retry is not None:
                on_retry(attempt + 1)
            faults_sleep(delay)
            delay = min(delay * backoff_mult, max_backoff_s)


class InlineWriter:
    """Degenerate writer: drains run synchronously at submit time.

    The serial fallback (``pipeline_depth=0``) and the multi-process path —
    a background thread issuing collectives (``process_allgather`` inside
    ``to_host``) could interleave with the main thread's chunk dispatches in
    a different order on different processes, which deadlocks multi-host
    collectives; inline drains keep the per-process launch order identical.
    Transient drain failures retry like the threaded writer's.
    """

    pipelined = False

    def __init__(self, retries: int = 0, backoff_s: float = 0.05,
                 on_retry: Optional[Callable[[int], None]] = None):
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.on_retry = on_retry

    def submit(self, drain: Callable[[], None]) -> float:
        run_drain_with_retry(drain, self.retries, self.backoff_s,
                             on_retry=self.on_retry)
        return 0.0

    def close(self, timeout: Optional[float] = None) -> None:
        pass

    def abort(self) -> None:
        pass


class ThreadWriter:
    """One background thread draining per-chunk thunks in FIFO order.

    The queue is unbounded — in-flight depth is bounded by the run loop's
    donated-buffer ring (the dispatch of chunk ``i`` waits for chunk
    ``i - depth``'s drain before reusing its output buffer), so the queue
    never grows past ``depth + 1`` entries in practice. A *transient* drain
    failure retries in place with bounded backoff
    (:func:`run_drain_with_retry`); the first non-recovered exception is
    recorded, the remaining queued drains are *cancelled* (their completion
    events still fire so the dispatch loop cannot deadlock), and the
    exception re-raises at the next ``submit``/``close`` — the pipelined
    analog of the serial loop aborting mid-run.
    """

    pipelined = True

    def __init__(self, retries: int = 0, backoff_s: float = 0.05,
                 on_retry: Optional[Callable[[int], None]] = None):
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.on_retry = on_retry
        self._q: "queue.Queue" = queue.Queue()
        # _exc crosses threads (writer sets it, dispatch thread reads and
        # clears it); the lock makes the handoff a clean publish instead
        # of a data race (thread-shared-state invariant)
        self._exc_lock = threading.Lock()
        self._exc: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._loop, name="fakepta-chunk-writer", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is _STOP:
                return
            drain, cancel = item
            with self._exc_lock:
                failed = self._exc is not None
            if not failed:
                try:
                    run_drain_with_retry(drain, self.retries,
                                         self.backoff_s,
                                         on_retry=self.on_retry)
                except BaseException as exc:   # noqa: BLE001 — re-raised
                    with self._exc_lock:       # in the dispatch thread
                        self._exc = exc
                    cancel()
            else:
                cancel()

    def submit(self, drain: Callable[[], None],
               cancel: Callable[[], None] = lambda: None) -> float:
        """Enqueue a drain; returns seconds blocked (0 — unbounded queue).

        Raises the writer's pending exception instead of enqueueing more
        work, so the dispatch loop stops at most one chunk after a failure.
        """
        self._raise_pending()
        t0 = _now()
        self._q.put((drain, cancel))
        return _now() - t0

    def _raise_pending(self) -> None:
        with self._exc_lock:
            exc, self._exc = self._exc, None
        if exc is not None:
            raise exc

    def close(self, timeout: Optional[float] = None) -> None:
        """Flush the queue, join the thread, re-raise any drain exception.

        ``timeout`` arms the watchdog variant: a writer thread that does
        not finish within it (a hung drain — a stuck device fetch, an
        injected hang) raises :class:`~fakepta_tpu.faults.WatchdogTimeout`
        instead of blocking forever; the caller dumps the flight recorder.
        """
        self._q.put(_STOP)
        self._thread.join(timeout)
        if self._thread.is_alive():
            from ..faults import WatchdogTimeout
            flightrec.note("watchdog_close_timeout", timeout_s=timeout)
            raise WatchdogTimeout(
                f"writer thread still draining after {timeout}s at close "
                f"(hung drain); aborting — see the flight-recorder dump")
        self._raise_pending()

    def abort(self) -> None:
        """Stop the thread without re-raising (error-path cleanup)."""
        self._q.put(_STOP)
        self._thread.join(timeout=60.0)
        with self._exc_lock:
            self._exc = None


def donation_unsafe(mesh) -> bool:
    """True when donated-scratch recycling must be disabled for this run.

    XLA:CPU executables loaded from the **persistent compile cache** carry
    input-output aliasing metadata that can disagree with jax's runtime
    donation bookkeeping: the async execution then writes into a buffer
    jax already released, and — after malloc reuse — a later chunk's
    output lands inside another chunk's already-drained host copy. The
    observed symptom is a whole chunk of one run's packed stream equal to
    a *different* chunk's values (a silent stream swap), reproduced only
    on CPU with a warm on-disk cache (tests/test_faults.py pins the
    degradation; docs/RELIABILITY.md the analysis). Donation never changes
    values — only peak memory — so the safe engine response is to run the
    pipeline without it on that configuration. TPU keeps donation + cache.
    """
    if mesh.devices.flat[0].platform != "cpu":
        return False
    cache_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
    return bool(cache_dir)


def make_writer(pipelined: bool, retries: int = 0, backoff_s: float = 0.05,
                on_retry: Optional[Callable[[int], None]] = None):
    """The writer the run loop drains through: threaded iff pipelined.

    ``retries``/``backoff_s`` wire the recovery policy's transient-drain
    retry into either writer; ``on_retry`` is the engine's counter hook
    (``faults.retries``), called with the attempt number.
    """
    if pipelined:
        return ThreadWriter(retries=retries, backoff_s=backoff_s,
                            on_retry=on_retry)
    return InlineWriter(retries=retries, backoff_s=backoff_s,
                        on_retry=on_retry)


def materialize_copy(x):
    """Forced host copy of a device array that leaves the buffer DONATABLE.

    ``np.array(np.asarray(x))`` — the obvious materialization — makes jax
    cache a host view on the array (``_npy_value``); on backends where that
    view is zero-copy (XLA:CPU) the cache holds a live external reference
    to the device buffer, and XLA then *silently declines the donation*
    when the pipelined loop recycles the buffer as a later dispatch's
    scratch: the claimed in-place aliasing quietly became
    dispatch-time copies (found by obs.memwatch's runtime donation check —
    the recycled buffer was never marked deleted). Copying shard-by-shard
    (``shard.data`` is a fresh per-shard view whose host view dies with
    this scope) leaves no reference behind, so donation consumes the
    buffer as designed. Single-process only (addressable shards ARE the
    array) — exactly the pipelined loop's precondition; callers on the
    multi-process path keep using ``to_host`` (process_allgather).
    """
    if not hasattr(x, "addressable_shards"):     # pragma: no cover
        return np.array(np.asarray(x))           # old jax: plain copy
    jax.block_until_ready(x)
    out = np.empty(x.shape, x.dtype)
    for s in x.addressable_shards:
        out[s.index] = np.asarray(s.data)
    return out


def start_d2h(*arrays) -> int:
    """Start non-blocking device->host copies; returns how many were issued.

    ``jax.Array.copy_to_host_async`` overlaps the transfer with subsequent
    device work; the later ``to_host``/``np.asarray`` then only waits for
    completion instead of serializing fetch behind compute. Host/numpy
    inputs (and jax builds without the method) are skipped.
    """
    n = 0
    for x in arrays:
        if x is not None and hasattr(x, "copy_to_host_async"):
            x.copy_to_host_async()
            n += 1
    return n
