"""Device-mesh helpers: the framework's distributed-communication layer.

The reference has no parallelism or communication backend at all (SURVEY.md §5);
scaling here is pure SPMD: a 3-D ``jax.sharding.Mesh`` with a ``'real'`` axis for
Monte-Carlo realizations (embarrassingly parallel, the data-parallel analog), a
``'psr'`` axis for pulsars (the model-parallel analog — cross-pulsar statistics
ride XLA collectives: ``all_gather`` over 'psr', ``psum`` reductions over 'real'),
and a ``'toa'`` axis for the time dimension — the sequence-parallel analog for
long datasets: per-TOA state shards over 'toa', and the correlation statistic
(a reduction over TOAs) closes with one ``psum`` over the axis, the
reduction-shaped counterpart of ring/all-to-all sequence parallelism.
Collectives are inserted by shard_map/GSPMD over ICI on real hardware; the same
program runs unchanged on the virtual CPU mesh used in tests.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

REAL_AXIS = "real"
PSR_AXIS = "psr"
TOA_AXIS = "toa"


def make_mesh(devices: Optional[Sequence] = None, psr_shards: int = 1,
              toa_shards: int = 1) -> Mesh:
    """Build the (real, psr, toa) mesh over the given (default: all) devices.

    ``psr_shards * toa_shards`` must divide the device count; the remaining
    devices go to the realization axis. One device -> a 1x1x1 mesh, so every
    code path is identical on a laptop CPU, one TPU chip, or a pod slice. In a
    multi-host program ``jax.devices()`` already spans every process (after
    :func:`initialize_multihost`), so the same call builds the global pod mesh.
    """
    devices = list(devices if devices is not None else jax.devices())
    model = psr_shards * toa_shards
    if len(devices) % model != 0:
        raise ValueError(f"psr_shards*toa_shards={model} must divide "
                         f"{len(devices)} devices")
    grid = np.array(devices).reshape(len(devices) // model, psr_shards,
                                     toa_shards)
    return Mesh(grid, (REAL_AXIS, PSR_AXIS, TOA_AXIS))


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> Mesh:
    """Join JAX's distributed runtime and return the global pod mesh.

    The multi-host analog of the reference's (nonexistent) communication
    backend: one SPMD program per host, XLA collectives over ICI within a
    slice and DCN across slices — no NCCL/MPI code to port. On Cloud TPU
    pods every argument is discovered from the environment, so
    ``initialize_multihost()`` with no arguments is the whole setup; other
    clusters pass the coordinator explicitly (`jax.distributed.initialize`
    semantics).

    After this call ``jax.devices()`` spans all processes and
    :func:`make_mesh` builds the global mesh. Per-host result gathering is
    handled inside :meth:`EnsembleSimulator.run` (non-addressable outputs go
    through ``process_allgather``), so the single-host user code runs
    unchanged on a pod.
    """
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return make_mesh(jax.devices())


def to_host(x) -> np.ndarray:
    """Materialize a (possibly multi-host-sharded) device array on every host.

    Single-process arrays are fully addressable and copy directly; in a
    multi-host program the 'real'-sharded outputs live partly on other
    processes, where ``np.asarray`` would raise — ``process_allgather``
    assembles the global value on every host instead.
    """
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x, tiled=True)
