"""Device-mesh helpers: the framework's distributed-communication layer.

The reference has no parallelism or communication backend at all (SURVEY.md §5);
scaling here is pure SPMD: a 2-D ``jax.sharding.Mesh`` with a ``'real'`` axis for
Monte-Carlo realizations (embarrassingly parallel, the data-parallel analog) and a
``'psr'`` axis for pulsars (the model-parallel analog — cross-pulsar statistics
ride XLA collectives: ``all_gather`` over 'psr', ``psum`` reductions over 'real').
Collectives are inserted by shard_map/GSPMD over ICI on real hardware; the same
program runs unchanged on the virtual CPU mesh used in tests.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

REAL_AXIS = "real"
PSR_AXIS = "psr"


def make_mesh(devices: Optional[Sequence] = None, psr_shards: int = 1) -> Mesh:
    """Build the (real, psr) mesh over the given (default: all) devices.

    ``psr_shards`` must divide the device count; the remaining devices go to the
    realization axis. One device -> a 1x1 mesh, so every code path is identical on
    a laptop CPU, one TPU chip, or a pod slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) % psr_shards != 0:
        raise ValueError(f"psr_shards={psr_shards} must divide {len(devices)} devices")
    grid = np.array(devices).reshape(len(devices) // psr_shards, psr_shards)
    return Mesh(grid, (REAL_AXIS, PSR_AXIS))
