"""Sharded Monte-Carlo ensemble engine — the north-star workload (BASELINE.md).

Simulates thousands of independent PTA realizations (white + red + DM noise +
HD-correlated GWB) entirely on device and reduces them to cross-correlation
statistics. The reference has no ensemble machinery at all — config 5 of
BASELINE.md ("10k-realization Monte Carlo of 100-psr HD GWB") exists only here.

SPMD layout (see :mod:`fakepta_tpu.parallel.mesh`):

- realizations shard over the ``'real'`` mesh axis (independent streams, zero
  communication — the data-parallel axis);
- pulsars shard over the ``'psr'`` axis; the GWB's cross-pulsar coupling is the
  tiny (npsr x npsr) Cholesky matmul, which every psr-shard recomputes redundantly
  from an identical per-realization key ("replicate the small, shard the large"),
  so the *only* collective in the program is one ``all_gather`` of residual blocks
  over 'psr' to form cross-correlation rows;
- per-pulsar noise keys fold the realization key with the *global* pulsar index
  (``axis_index('psr') * p_local + local index``), so the realization stream is
  bit-identical on every mesh shape — resharding changes how draws are
  distributed, never what they are.

Everything is a single jitted program per chunk; chunking bounds device memory at
a few hundred MB regardless of the total realization count.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from functools import partial
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from .. import faults as faults_mod
from .. import obs
from ..batch import PulsarBatch, fourier_basis_norm
from ..ops import gwb as gwb_ops
from ..tune import defaults as tune_defaults
from ..utils import rng as rng_utils
from ..utils.compat import enable_x64, shard_map
from . import pipeline as pipeline_mod
from .mesh import PSR_AXIS, REAL_AXIS, TOA_AXIS, make_mesh, to_host

# PulsarBatch fields whose LAST axis is the TOA dimension (shard over 'toa');
# sys_mask carries it behind the band axis
_BATCH_TOA_FIELDS = ("t_own", "t_common", "mask", "freqs", "sigma2",
                     "epoch_idx", "ecorr_amp")


@dataclasses.dataclass(frozen=True)
class GWBConfig:
    """Common-signal configuration for the ensemble simulator.

    Pass a sequence of configs to ``EnsembleSimulator(gwb=[...])`` to inject
    several simultaneous correlated signals (HD background + clock monopole +
    ephemeris dipole, ...) in one program — the engine analog of layering
    facade ``add_common_correlated_noise`` calls (ref
    ``correlated_noises.py:111-160`` run repeatedly). Config 0 keeps the
    single-signal key stream, so adding more signals never changes existing
    realizations; a ``NoiseSampling('gwb')`` prior applies to config 0.
    """

    psd: np.ndarray                 # (C,) PSD on the common grid n/Tspan_array
    orf: str = "hd"
    h_map: Optional[np.ndarray] = None
    idx: float = 0.0
    freqf: float = 1400.0


@dataclasses.dataclass(frozen=True)
class CGWConfig:
    """A deterministic continuous-wave source for the ensemble.

    Same parameterization as the facade's ``Pulsar.add_cgw`` (reference
    ``fake_pta.py:422-442``); evaluated once at simulator construction with
    :func:`fakepta_tpu.models.cgw.cw_delay` vmapped over the pulsar batch.
    """

    costheta: float
    phi: float
    cosinc: float
    log10_mc: float
    log10_fgw: float
    log10_h: Optional[float] = None
    log10_dist: Optional[float] = None
    phase0: float = 0.0
    psi: float = 0.0
    psrterm: bool = False


@dataclasses.dataclass(frozen=True)
class RoemerConfig:
    """A BayesEphem-style ephemeris perturbation for the ensemble.

    Same parameterization and units as the facade's
    ``correlated_noises.add_roemer_delay`` (reference ``ephemeris.py:118-144``);
    evaluated on device with the float32-stable delta kernel
    (:func:`fakepta_tpu.models.roemer.roemer_delay_dev`).
    """

    planet: str
    d_mass: float = 0.0
    d_Om: float = 0.0
    d_omega: float = 0.0
    d_inc: float = 0.0
    d_a: float = 0.0
    d_e: float = 0.0
    d_l0: float = 0.0


@dataclasses.dataclass(frozen=True)
class NoiseSampling:
    """Per-realization spectrum hyperparameter sampling for a GP stage.

    The parameters PTA population studies actually marginalize — noise
    amplitudes, spectral slopes, turnover frequencies, per-bin free-spectrum
    powers — drawn fresh for every realization *inside* the device program:

    - ``target='red' | 'dm' | 'chrom'``: each pulsar draws independent
      hyperparameters per realization (population marginalization over
      per-pulsar noise uncertainty); the sampled PSD replaces the batch's
      fixed ``<target>_psd`` for that stage.
    - ``target='sys'``: each (pulsar, backend band) draws independent
      hyperparameters per realization — the per-system population prior
      completing the per-pulsar surface; the sampled PSD replaces the
      batch's ``sys_psd`` while the band TOA membership (``sys_mask``)
      stays the batch's. Keys fold the GLOBAL pulsar index then the band
      index, so streams are mesh-shape independent like every other stage.
    - ``target='gwb'``: ONE global draw per realization (the background is
      common); replaces ``GWBConfig.psd``. The ORF and chromatic index still
      come from ``GWBConfig``.

    ``spectrum`` names any registered PSD model (the same registry every
    facade injector resolves, honoring the reference's plugin contract
    ``fake_pta.py:272-277`` per realization); ``params`` maps hyperparameter
    names to ``(a, b)`` ranges. Parameters not sampled keep the model's
    defaults. ``log10_A`` / ``gamma`` remain as convenience kwargs for the
    power-law case (merged into ``params``). Per-frequency parameters
    (``log10_rho``, ``alphas``) draw one independent value per bin.

    Ranges follow the ``(a, b)`` convention: ``dist='uniform'`` draws
    ``U(a, b)`` (the reference's population convention — ``make_fake_array``
    draws log10_A ~ U(-17, -13), gamma ~ U(1, 5), ``fake_pta.py:653-667`` —
    but per *array construction*, never per realization; the reference cannot
    vary anything inside a loop); ``dist='normal'`` draws ``N(mean=a, std=b)``.
    Zero-width ranges pin the parameter. ``dist`` may also be a mapping
    ``{param: 'uniform'|'normal'}`` (unlisted params default to uniform).

    Stream discipline matches every other stage: draws fold the realization
    key with a dedicated domain tag and (for per-pulsar targets) the *global*
    pulsar index, so realizations are bit-identical on any mesh shape and the
    coefficient/white/GWB streams are untouched — a run with a zero-width
    sampling range reproduces the fixed-PSD run's statistics exactly. The
    all-uniform power-law case keeps the original (log10_A, gamma) draw
    layout, so existing realizations never move.
    """

    target: str
    log10_A: Optional[Tuple[float, float]] = None
    gamma: Optional[Tuple[float, float]] = None
    dist: Union[str, dict] = "uniform"
    spectrum: str = "powerlaw"
    params: Optional[dict] = None


# domain tag for hyperparameter sampling keys (cf. 0x51 noise / 0x6B gwb /
# 0x77 roemer-sampling); per-target subtags keep multi-target draws independent
_HYPER_TAG = 0x9C
_HYPER_SUBTAG = {"red": 0, "dm": 1, "chrom": 2, "gwb": 3, "sys": 4}

# domain tag for per-realization CGW source sampling
_CGW_TAG = 0xC6

# domain tag for per-realization white-noise/ECORR hyperparameter sampling
_WHITE_TAG = 0xE1

# domain tag for the OS lane's paired noise-only stream (detect null
# calibration): null keys are fold_in(realization key, 0xD7), so the null
# realizations are independent of — and as reproducible as — the signal ones
_NULL_TAG = 0xD7


@dataclasses.dataclass(frozen=True)
class WhiteSampling:
    """Per-realization white-noise/ECORR hyperparameter sampling.

    Each realization draws an independent ``(efac, log10_tnequad[,
    log10_ecorr])`` triple per (pulsar, backend) *inside* the device program
    and rebuilds the white variance ``sigma^2 = efac^2 toaerr^2 +
    10^(2 log10_tnequad)`` from the raw TOA errors — the population prior the
    reference's ``randomize=True`` draws once per *injection call* on the host
    (``fake_pta.py:203-210``: efac ~ U(0.5, 2.5), log10_tnequad ~ U(-8, -5),
    log10_ecorr ~ U(-10, -7) — the defaults here), never per realization.

    ``(a, b)`` ranges follow :class:`NoiseSampling`'s convention:
    ``dist='uniform'`` draws U(a, b), ``dist='normal'`` draws N(mean=a,
    std=b); zero-width pins the parameter. A range of ``None`` pins the
    parameter at its neutral value instead: efac=1, no EQUAD contribution,
    and (for ecorr) the batch's fixed ``ecorr_amp``. When ``log10_ecorr`` is
    sampled, the drawn per-backend amplitude replaces ``ecorr_amp`` wherever
    the batch has ECORR active (padding TOAs and single-TOA epochs stay
    excluded, matching the facade and reference ``fake_pta.py:223-224``).

    The sampled variance replaces the batch's fixed ``sigma2`` for the white
    stage; the raw squared TOA errors and the (pulsar, backend) partition come
    from ``EnsembleSimulator(toaerr2=..., backend_id=...)`` (see
    :func:`fakepta_tpu.batch.padded_toaerr2` /
    :func:`~fakepta_tpu.batch.padded_backend_ids`).

    Stream discipline matches every other sampled stage: draws fold the
    realization key with the 0xE1 domain tag and the *global* pulsar index, so
    realizations are mesh-shape independent and the white/ECORR coefficient
    streams (``kw``/``ke``) are untouched — zero-width ranges matching the
    batch's fixed values reproduce the fixed run exactly.
    """

    efac: Optional[Tuple[float, float]] = (0.5, 2.5)
    log10_tnequad: Optional[Tuple[float, float]] = (-8.0, -5.0)
    log10_ecorr: Optional[Tuple[float, float]] = None
    dist: str = "uniform"


@dataclasses.dataclass(frozen=True)
class CGWSampling:
    """Per-realization CGW source sampling inside the device program.

    Each realization draws one circular-SMBHB source with every parameter
    ~ U(a, b) from its ``(a, b)`` range (zero-width pins it) and evaluates the
    full evolving waveform on device — a continuous-wave *population* search
    prior, Monte-Carlo-marginalized at ensemble speed. The reference evaluates
    one fixed source per ``add_cgw`` call through an external package
    (``fake_pta.py:422-442``) and cannot vary it in any loop.

    Draws are global nuisances (one source common to the array): keys fold the
    realization key with the 0xC6 domain tag and the per-config index only —
    never the pulsar-shard index — so streams are mesh-shape independent.

    Precision: the waveform is evaluated at float32 from epochs relative to
    ``tref`` (host-float64 subtraction). With ``tref=0`` and MJD-second epochs
    ~4.6e9 s the f32 quantization is ~550 s => ~2e-5 rad of GW phase at
    f_gw ~ 1e-8 Hz — negligible against the waveform, and irrelevant in the
    usual population setup where ``phase0`` is itself sampled over (0, 2 pi).
    Pass ``tref`` near the data span's midpoint to shrink it further (~1e-6
    rad); ``phase0`` is then referenced at ``tref``.

    Amplitude modes: ``log10_h`` samples the strain directly (the default);
    giving a ``log10_dist`` range instead samples the luminosity distance in
    log10(Mpc) — the physical population prior. ``log10_dist`` takes
    precedence here (``log10_h`` carries a default range, so its mere
    presence cannot signal intent — the opposite of the fixed
    ``CGWConfig``/``cw_delay`` contract, where both default to None and an
    explicit ``log10_h`` wins). Pass ``log10_h=None`` to make the choice
    explicit.

    ``dist`` selects the draw family per parameter: one string for all, or a
    mapping ``{param: 'uniform'|'normal'}`` (unlisted default to uniform).
    ``'uniform'`` reads the ``(a, b)`` range as U(a, b); ``'normal'`` as
    N(mean=a, std=b). The all-uniform case keeps the original draw layout,
    so existing realizations never move.

    ``psrterm=True`` uses the simulator's ``pdist`` means; with
    ``sample_pdist=True`` each pulsar additionally draws its distance
    nuisance ``p_dist ~ N(0, 1)`` (in units of its ``pdist`` sigma, the
    convention the pulsar term's ``pdist=(mean, sigma)`` contract implies,
    ref ``fake_pta.py:436-441``) per realization — keys fold the global
    pulsar index, so streams stay mesh-shape independent. The pulsar term's
    retarded phase is ~omega L/c ~ 1e3-1e4 rad — far beyond f32 — so its
    bulk ``dph(-tau)`` is precomputed per (realization, pulsar) at host
    float64 from the replicated draw chain and fed to the kernel mod 2pi
    (``EnsembleSimulator._host_cgw_bulks`` /
    :func:`fakepta_tpu.models.cgw.psrterm_phase_bulk`); the f32 kernel only
    evaluates the O(10 rad) residual via the exact split
    ``dph(t - tau) = dph(-tau) + dph(t; omega0 (1 + k tau)^{-3/8})``.
    Realizations therefore reproduce across mesh shapes at the engine's
    common tolerance (~1e-7 measured, vs ~1e-3 pre-split).
    """

    # field order: the original fields keep their round-4 positions (appending
    # the new ones at the end) so positional construction cannot silently
    # rebind — e.g. an old call's phase0 range landing in log10_dist
    costheta: Tuple[float, float] = (-1.0, 1.0)
    phi: Tuple[float, float] = (0.0, 2.0 * np.pi)
    cosinc: Tuple[float, float] = (-1.0, 1.0)
    log10_mc: Tuple[float, float] = (8.5, 9.5)
    log10_fgw: Tuple[float, float] = (-8.5, -7.5)
    log10_h: Optional[Tuple[float, float]] = (-14.5, -13.5)
    phase0: Tuple[float, float] = (0.0, 2.0 * np.pi)
    psi: Tuple[float, float] = (0.0, np.pi)
    psrterm: bool = False
    tref: float = 0.0
    log10_dist: Optional[Tuple[float, float]] = None
    sample_pdist: bool = False
    dist: Union[str, dict] = "uniform"


@dataclasses.dataclass(frozen=True)
class RoemerSampling:
    """Per-realization BayesEphem nuisance sampling inside the device program.

    Each realization draws independent Gaussian perturbations
    ``d_<param> ~ N(0, s_<param>)`` (same units as :class:`RoemerConfig`) and
    runs them through the float32-stable delta kernel — ephemeris uncertainty
    marginalized by Monte Carlo, entirely on device. The reference cannot vary
    its ephemeris inside any loop at all (its ``roemer_delay`` mutates the
    stored orbital elements in place, ``ephemeris.py:131-136``).

    The draws are global nuisance parameters: they fold the realization key
    only (never the pulsar-shard index), so every psr shard perturbs the same
    solar system and the stream is mesh-shape independent like every other
    stage. Pass a sequence of configs to ``EnsembleSimulator(roemer_sample=...)``
    to sample several bodies per realization (draws are independent per body).
    """

    planet: str
    s_mass: float = 0.0
    s_Om: float = 0.0
    s_omega: float = 0.0
    s_inc: float = 0.0
    s_a: float = 0.0
    s_e: float = 0.0
    s_l0: float = 0.0


def _simulate_block(keys, batch: PulsarBatch, chols, gwb_ws, gwb_idxs,
                    gwb_freqfs,
                    include_white, include_ecorr, include_red, include_dm,
                    include_chrom, include_sys, include_gwb,
                    samp_static=(), samp_params=(), bases_bf16=False,
                    white_static=None, white_params=None, white_toaerr2=None,
                    white_bid=None, white_nb=1, toa_shards=1, split_gp=False):
    """Simulate residual blocks for a chunk of realizations (shard_map body).

    keys: (R_local,) per-realization keys (identical across psr shards).
    batch: the *local* pulsar shard. Returns (R_local, P_local, T).
    chols/gwb_ws: tuples, one (P, P) Cholesky + (C_j,) weight vector per
    common correlated signal (several GWBConfigs — e.g. an HD background
    plus a clock monopole — ride one program; config 0 keeps the original
    key stream, so single-signal realizations are bit-identical to before).
    gwb_idxs/gwb_freqfs: matching static tuples.
    samp_static: static tuple of resolved NoiseSampling descriptors
    ``(target, spectrum, names, per_bin flags, dist per param)`` (see
    :func:`_resolve_noise_sampling`); samp_params the matching traced
    (n_params, 2) range arrays in draw order.
    white_static: static (sample_efac, sample_equad, sample_ecorr, dist) for
    per-realization white sampling (:class:`WhiteSampling`); white_params the
    traced (3, 2) range array, white_toaerr2/white_bid the local (P, T) raw
    squared TOA errors and int32 backend partition, white_nb the static
    backend count.
    toa_shards: static size of the 'toa' mesh axis. Per-TOA draws (white,
    ECORR epoch normals) generate at the FULL TOA width from the same
    per-pulsar keys and slice locally, so realization streams are
    bit-identical to the unsharded program on any time sharding; every other
    draw (GP/GWB coefficients, hyperparameters, sources) is T-independent and
    identical on every time shard by key construction.

    ``split_gp=True`` is the megakernel contract (:mod:`fakepta_tpu.ops
    .megakernel`): the GP stages' coefficient DRAWS run unchanged (same
    keys, same order — streams are byte-identical to the projected
    program's), but the dense-basis projection is skipped and the function
    returns ``(base, coeffs, gp_basis_all)`` — the masked white/ECORR/
    system residual base (R, P, T), the concatenated per-realization GP
    coefficients (R, P, K) in stage order, and the dense basis (P, T, K)
    for callers that still need an XLA-side projection (the lnlike lane's
    Woodbury moments; XLA dead-code-eliminates it when unused).
    """
    from .. import spectrum as spectrum_lib
    p_local = batch.t_own.shape[0]
    pidx = lax.axis_index(PSR_AXIS)
    dtype = batch.t_own.dtype

    n_red = batch.red_psd.shape[1]
    n_dm = batch.dm_psd.shape[1]
    n_gwbs = tuple(w.shape[0] for w in gwb_ws)

    red_basis = fourier_basis_norm(batch.t_own, n_red)                 # (P,T,2,NR)
    dm_scale = (1400.0 / batch.freqs) ** 2
    dm_basis = fourier_basis_norm(batch.t_own, n_dm, scale=dm_scale)   # (P,T,2,ND)
    if include_chrom:
        n_chrom = batch.chrom_psd.shape[1]
        chrom_basis = fourier_basis_norm(batch.t_own, n_chrom,
                                         scale=(1400.0 / batch.freqs) ** 4)
        chrom_w = jnp.sqrt(batch.chrom_psd * batch.df_own[:, None])    # (P,NC)
    if include_sys:
        n_sys = batch.sys_psd.shape[2]
        sys_basis = fourier_basis_norm(batch.t_own, n_sys)             # (P,T,2,NS)
        sys_w = jnp.sqrt(batch.sys_psd * batch.df_own[:, None, None])  # (P,B,NS)
        n_bands = batch.sys_psd.shape[1]
    # configs sharing (idx, freqf, ncomp) share ONE basis block: the GP
    # projection is linear in the coefficients, so their correlated draws sum
    # per group instead of widening the (HBM-bound) fused einsum with
    # duplicate identical bases. Draws stay per-config — streams unchanged.
    gwb_bases, gwb_group = [], []
    if include_gwb:
        seen = {}
        for idx_j, freqf_j, n_j in zip(gwb_idxs, gwb_freqfs, n_gwbs):
            sig = (idx_j, freqf_j, n_j)
            if sig not in seen:
                seen[sig] = len(gwb_bases)
                scale = None
                if idx_j:
                    scale = (freqf_j / batch.freqs) ** idx_j
                gwb_bases.append(fourier_basis_norm(batch.t_common, n_j,
                                                    scale=scale))
            gwb_group.append(seen[sig])

    red_w = jnp.sqrt(batch.red_psd * batch.df_own[:, None])            # (P,NR)
    dm_w = jnp.sqrt(batch.dm_psd * batch.df_own[:, None])              # (P,ND)
    p_total = chols[0].shape[0]

    T = batch.t_own.shape[1]

    # All GP signals project through ONE concatenated (P, T, K_total) basis and
    # one einsum per realization. The projections are HBM-bound, not FLOP-bound,
    # under the realization vmap: separate einsums each materialize an
    # (R_local, P, T)-sized temporary (3.1 GB at the flagship chunk), and
    # merging them collapsed ~30 ms/chunk of traffic. Coefficient DRAWS stay
    # per-signal with unchanged keys/shapes, so realization streams are
    # bit-identical to the unmerged program. System noise stays separate: its
    # per-band mask applies after projection.
    gp_bases = []
    if include_red:
        gp_bases.append(red_basis.reshape(p_local, T, -1))
    if include_dm:
        gp_bases.append(dm_basis.reshape(p_local, T, -1))
    if include_chrom:
        gp_bases.append(chrom_basis.reshape(p_local, T, -1))
    if include_gwb:
        for gb in gwb_bases:             # one block per (idx, freqf, n) group
            gp_bases.append(gb.reshape(p_local, T, -1))
    gp_basis_all = jnp.concatenate(gp_bases, axis=-1) if gp_bases else None
    if bases_bf16 and gp_basis_all is not None:
        # bf16 basis storage halves the projection's HBM reads. On TPU this
        # costs ~nothing numerically: XLA's DEFAULT matmul precision already
        # rounds f32 operands to bf16 for the MXU, so the kernel consumes the
        # same bits either way (accumulation stays f32 via
        # preferred_element_type). ~4e-3 relative operand rounding, same
        # bound as the corr contraction tolerates.
        gp_basis_all = gp_basis_all.astype(jnp.bfloat16)

    def one(key):
        # noise keys fold by GLOBAL pulsar index, so realization streams are
        # bit-identical on any mesh shape (1 device or a pod slice shard the
        # same draws differently, they don't change them)
        gidx = pidx * p_local + jnp.arange(p_local)
        # the 0x51 domain tag is folded BEFORE the pulsar index so no global
        # index can alias another key domain (fold_in(key, 107) would otherwise
        # collide with the GWB key fold_in(key, 0x6B) at npsr >= 108)
        noise_root = jax.random.fold_in(key, 0x51)

        def psr_keys(g):
            return jax.random.split(jax.random.fold_in(noise_root, g), 6)

        kw, kr, kd, kc, ke, ks = jnp.moveaxis(jax.vmap(psr_keys)(gidx), 1, 0)

        def draw(keys_p, *shape):
            """(P, *shape) normals, one independent stream per pulsar key."""
            return jax.vmap(
                lambda k: jax.random.normal(k, shape, dtype))(keys_p)

        # per-realization hyperparameter sampling (NoiseSampling): sampled
        # spectrum weights replace the fixed precomputed ones for their
        # stage. Keys live in their own 0x9C domain + per-target subtag, so
        # the coefficient/white/GWB streams above are byte-identical whether
        # or not sampling is on. Per-pulsar targets fold the GLOBAL index
        # (mesh-shape independent); 'gwb' draws are global (the background is
        # common), identical on every psr shard. The all-uniform scalar draw
        # rides ONE uniform vector in declaration order (the legacy
        # (log10_A, gamma) layout), normal scalars a sibling subkey, per-bin
        # parameters (free-spectrum rho, t-process alphas) their own per-bin
        # subkeys — so the power-law stream is unchanged from before the
        # generalization.
        w_samp = {}
        if samp_static:
            hyper_root = jax.random.fold_in(key, _HYPER_TAG)
            for (target, spectrum, names, per_bin, dists), params in zip(
                    samp_static, samp_params):
                kt = jax.random.fold_in(hyper_root, _HYPER_SUBTAG[target])
                per_psr = target != "gwb"
                if target == "gwb":
                    nbin = n_gwbs[0]
                elif target == "sys":
                    nbin = batch.sys_psd.shape[2]
                else:
                    nbin = {"red": n_red, "dm": n_dm}.get(target)
                    if nbin is None:
                        nbin = batch.chrom_psd.shape[1]
                n_scalar = sum(1 for pb in per_bin if not pb)
                any_norm = any(d == "normal" for pb, d in zip(per_bin, dists)
                               if not pb)

                def draw_cfg(k, nbin=nbin, names=names, per_bin=per_bin,
                             dists=dists, params=params, n_scalar=n_scalar,
                             any_norm=any_norm):
                    """name -> sampled value for ONE key: scalars (), bins (N,)."""
                    u = (jax.random.uniform(k, (n_scalar,), dtype)
                         if n_scalar else None)
                    g = (jax.random.normal(jax.random.fold_in(k, 1),
                                           (n_scalar,), dtype)
                         if any_norm else None)
                    out = {}
                    zi = 0
                    for i, (name, pb) in enumerate(zip(names, per_bin)):
                        a, b = params[i, 0], params[i, 1]
                        if pb:
                            kb = jax.random.fold_in(k, 16 + i)
                            z = (jax.random.uniform(kb, (nbin,), dtype)
                                 if dists[i] == "uniform"
                                 else jax.random.normal(kb, (nbin,), dtype))
                        else:
                            z = u[zi] if dists[i] == "uniform" else g[zi]
                            zi += 1
                        out[name] = a + z * ((b - a) if dists[i] == "uniform"
                                             else b)
                    return out

                if target == "sys":
                    # per-(pulsar, band) draws: fold the GLOBAL pulsar index
                    # (mesh-shape independence), then the band index — each
                    # backend band is an independent population nuisance
                    kts = jax.vmap(
                        lambda g, k=kt: jax.random.fold_in(k, g))(gidx)
                    kpb = jax.vmap(lambda kp: jax.vmap(
                        lambda b, kp=kp: jax.random.fold_in(kp, b))(
                            jnp.arange(n_bands)))(kts)          # (P, B) keys
                    vals = jax.vmap(jax.vmap(draw_cfg))(kpb)
                    df = batch.df_own[:, None, None]                # (P,1,1)
                    kwargs = {n: (vals[n] if pb else vals[n][..., None])
                              for n, pb in zip(names, per_bin)}
                elif per_psr:
                    kts = jax.vmap(
                        lambda g, k=kt: jax.random.fold_in(k, g))(gidx)
                    vals = jax.vmap(draw_cfg)(kts)  # (P,) scalars, (P,N) bins
                    df = batch.df_own[:, None]                          # (P,1)
                    kwargs = {n: (vals[n] if pb else vals[n][:, None])
                              for n, pb in zip(names, per_bin)}
                else:
                    vals = draw_cfg(kt)
                    df = 1.0 / batch.tspan_common
                    kwargs = vals
                if spectrum == "free_spectrum":
                    # psd * df = 10^(2 rho) by definition: the weights are
                    # 10^rho directly — no Tspan inference (whose f[0] probe
                    # would read the wrong axis on the (P, N) grid here).
                    # log10_rho is per-bin, so shapes are already (.., N)
                    w_samp[target] = 10.0 ** kwargs["log10_rho"]
                else:
                    f = jnp.arange(1, nbin + 1, dtype=dtype) * df
                    psd = spectrum_lib.evaluate(spectrum, f, **kwargs)
                    w_samp[target] = jnp.sqrt(psd * df)

        # per-realization white/ECORR hyperparameter sampling (WhiteSampling):
        # the drawn per-(pulsar, backend) values rebuild sigma2/ecorr_amp from
        # the raw TOA errors, replacing the batch's fixed arrays. Keys live in
        # their own 0xE1 domain folded with the GLOBAL pulsar index, so the
        # white/ECORR coefficient streams (kw/ke) below are byte-identical
        # whether or not sampling is on, and streams are mesh-shape invariant.
        sigma2_eff = batch.sigma2
        ecorr_eff = batch.ecorr_amp
        if white_static is not None and (include_white or include_ecorr):
            s_efac, s_equad, s_ecorr, wdist = white_static
            wroot = jax.random.fold_in(key, _WHITE_TAG)
            kp = jax.vmap(lambda g: jax.random.fold_in(wroot, g))(gidx)
            zw = jax.vmap(lambda k: (
                jax.random.uniform(k, (white_nb, 3), dtype)
                if wdist == "uniform"
                else jax.random.normal(k, (white_nb, 3), dtype)))(kp)  # (P,B,3)
            # eager (P, B, 3) values, not a closure over the draw (a closure
            # here once invited silent capture of later same-named arrays)
            wscale = (white_params[:, 1] - white_params[:, 0]
                      if wdist == "uniform" else white_params[:, 1])
            wvals = white_params[:, 0] + zw * wscale

            def wgather(i):
                return jnp.take_along_axis(wvals[..., i], white_bid,
                                           axis=1)                     # (P,T)

            if include_white and (s_efac or s_equad):
                # the raw toaerr^2 only replaces the batch's sigma2 when an
                # efac/equad is actually drawn: ecorr-only sampling must keep
                # the (possibly noisedict-derived) fixed white variance, not
                # silently reset it to neutral toaerr^2 (ADVICE r5 finding 1)
                sigma2_eff = white_toaerr2
                if s_efac:
                    sigma2_eff = wgather(0) ** 2 * sigma2_eff
                if s_equad:
                    sigma2_eff = sigma2_eff + 10.0 ** (2.0 * wgather(1))
            if s_ecorr:
                # the where-gate keeps padding TOAs and single-TOA epochs
                # excluded exactly as the fixed path resolved them
                ecorr_eff = jnp.where(batch.ecorr_amp > 0.0,
                                      10.0 ** wgather(2), 0.0)

        # per-TOA draws under time sharding: generate at the FULL width from
        # the same keys and slice this shard's window — values per global TOA
        # are bit-identical to the unsharded program (XLA computes only the
        # sliced elements: the RNG is an elementwise map over iota, and the
        # slice fuses into it)
        if toa_shards > 1:
            full_T = T * toa_shards
            t0 = lax.axis_index(TOA_AXIS) * T

            def draw_toa(keys_p):
                return lax.dynamic_slice_in_dim(draw(keys_p, full_T), t0, T,
                                                axis=1)
        else:
            full_T = T

            def draw_toa(keys_p):
                return draw(keys_p, T)

        res = jnp.zeros((p_local, T), dtype)
        if include_white:
            with obs.span("white"):
                res = res + jnp.sqrt(sigma2_eff) * draw_toa(kw)
        if include_ecorr:
            # sigma^2 I + c^2 11^T per epoch block == diagonal white (above) plus
            # ONE shared normal per epoch: no per-block Cholesky (the reference
            # draws a dense MVN per block, fake_pta.py:219-228). Epoch ids are
            # GLOBAL, so the epoch normals index the full-width draw — epochs
            # straddling a time-shard boundary see the same shared normal on
            # both shards
            with obs.span("ecorr"):
                shared = jnp.take_along_axis(draw(ke, full_T),
                                             batch.epoch_idx, axis=1)
                res = res + ecorr_eff * shared
        coeffs = []
        if include_red:
            with obs.span("red"):
                c = draw(kr, 2, n_red) * w_samp.get("red", red_w)[:, None, :]
            coeffs.append(c.reshape(p_local, -1))
        if include_dm:
            with obs.span("dm"):
                c = draw(kd, 2, n_dm) * w_samp.get("dm", dm_w)[:, None, :]
            coeffs.append(c.reshape(p_local, -1))
        if include_chrom:
            with obs.span("chrom"):
                c = draw(kc, 2, n_chrom) * w_samp.get("chrom",
                                                      chrom_w)[:, None, :]
            coeffs.append(c.reshape(p_local, -1))
        if include_sys:
            # per-(pulsar, backend-band) GP on the shared basis, masked to the
            # band's TOAs (shell equivalent: fake_pta.py:333-355 via the masked
            # injector; bands share the basis, draws are independent). Static
            # loop over the (small) band count so no (R, P, B, T) intermediate
            # is ever materialized under the realization vmap.
            with obs.span("sys"):
                c = draw(ks, n_bands, 2, n_sys) * w_samp.get(
                    "sys", sys_w)[:, :, None, :]
                for b in range(n_bands):
                    contrib = jnp.einsum("ptkn,pkn->pt", sys_basis, c[:, b])
                    res = res + jnp.where(batch.sys_mask[:, b], contrib, 0.0)
        if include_gwb:
            # identical z on every psr shard (key NOT folded with pidx): the
            # (npsr x npsr) correlation matmul is replicated, then sliced
            # locally. Config 0 keeps the bare 0x6B key (legacy stream);
            # further configs fold their index on top. Coefficients of
            # configs sharing a basis group sum (projection is linear).
            with obs.span("gwb"):
                tag = jax.random.fold_in(key, 0x6B)
                gwb_c = [None] * len(gwb_bases)
                for j, (chol_j, w_j) in enumerate(zip(chols, gwb_ws)):
                    kg = tag if j == 0 else jax.random.fold_in(tag, j)
                    zg = jax.random.normal(kg, (2, n_gwbs[j], p_total), dtype)
                    corr = zg @ chol_j.T
                    corr_local = lax.dynamic_slice_in_dim(
                        corr, pidx * p_local, p_local, axis=2)
                    w_eff = w_samp.get("gwb", w_j) if j == 0 else w_j
                    c = corr_local * w_eff[None, :, None]              # (2,C,P_loc)
                    c = jnp.transpose(c, (2, 0, 1)).reshape(p_local, -1)
                    g = gwb_group[j]
                    gwb_c[g] = c if gwb_c[g] is None else gwb_c[g] + c
                coeffs.extend(gwb_c)
        if split_gp:
            c_all = (jnp.concatenate(coeffs, axis=-1) if coeffs
                     else jnp.zeros((p_local, 0), dtype))
            return jnp.where(batch.mask, res, 0.0), c_all
        if coeffs:
            with obs.span("gp_project"):
                c_all = jnp.concatenate(coeffs, axis=-1)
                if bases_bf16:
                    c_all = c_all.astype(jnp.bfloat16)
                res = res + jnp.einsum("ptk,pk->pt", gp_basis_all, c_all,
                                       preferred_element_type=dtype)
        return jnp.where(batch.mask, res, 0.0)

    if split_gp:
        base, c_all = jax.vmap(one)(keys)
        return base, c_all, gp_basis_all
    return jax.vmap(one)(keys)


def _sampled_roemer(keys, state, scales, pos_local, tag):
    """(R_local, P_local, T) per-realization BayesEphem delays (shard_map body).

    ``state`` is this shard's slice of the nominal
    :class:`~fakepta_tpu.models.roemer.OrbitState` (its per-TOA leaves shard
    over 'psr' exactly like the batch); the f32-stable delta kernel runs on
    per-realization Gaussian draws. The draw key folds the 0x77 domain tag and
    the per-planet index ``tag`` but never the shard index: each perturbed
    solar-system body is one global nuisance per realization.
    """
    from ..models.roemer import roemer_delay_dev

    dtype = scales.dtype

    def one(key):
        with obs.span("roemer"):
            kz = jax.random.fold_in(jax.random.fold_in(key, 0x77), tag)
            z = jax.random.normal(kz, (7,), dtype)
            d = z * scales
            return roemer_delay_dev(state, pos_local, d_mass=d[0], d_Om=d[1],
                                    d_omega=d[2], d_inc=d[3], d_a=d[4],
                                    d_e=d[5], d_l0=d[6])

    return jax.vmap(one)(keys)


def _as_config_list(x):
    """Coerce a single config / sequence of configs / None into a list."""
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


# spectrum hyperparameters that are per-frequency-bin vectors, not scalars;
# NoiseSampling draws one independent value per bin for these
_PER_BIN_PARAMS = ("log10_rho", "alphas", "alphas_adapt")


def _resolve_dists(dist, names, label):
    """Normalize a str-or-mapping ``dist`` spec to one value per name.

    Shared by :class:`NoiseSampling` and :class:`CGWSampling` so the two
    cannot drift (same expansion, unknown-name check, family check).
    """
    if isinstance(dist, str):
        dmap = {n: dist for n in names}
    else:
        bad = [k for k in dist if k not in names]
        if bad:
            raise ValueError(f"{label} dist mapping names {bad} are not "
                             f"sampled parameters {list(names)}")
        dmap = {n: dist.get(n, "uniform") for n in names}
    for d in dmap.values():
        if d not in ("uniform", "normal"):
            raise ValueError(f"{label} dist must be 'uniform' or 'normal', "
                             f"got {d!r}")
    return tuple(dmap[n] for n in names)


def _resolve_noise_sampling(cfg: NoiseSampling):
    """Validate one NoiseSampling config against the spectrum registry.

    Returns ``(static, ranges)``: the static kernel descriptor
    ``(target, spectrum, names, per_bin flags, dist per param)`` plus the
    ``(n_params, 2)`` host range rows in draw order.
    """
    from .. import spectrum as spectrum_lib

    if cfg.spectrum not in spectrum_lib.SPECTRA:
        raise ValueError(f"NoiseSampling spectrum {cfg.spectrum!r} is not "
                         f"registered; known: {sorted(spectrum_lib.SPECTRA)}")
    reg = spectrum_lib.SPECTRA[cfg.spectrum]
    ranges = {}
    if cfg.log10_A is not None:
        ranges["log10_A"] = tuple(cfg.log10_A)
    if cfg.gamma is not None:
        ranges["gamma"] = tuple(cfg.gamma)
    if cfg.params:
        ranges.update({k: tuple(v) for k, v in cfg.params.items()})
    if not ranges:
        raise ValueError(f"NoiseSampling({cfg.target!r}) has no parameters "
                         f"to sample: give log10_A/gamma or params ranges")
    unknown = [k for k in ranges if k not in reg.params]
    if unknown:
        raise ValueError(f"params {unknown} are not hyperparameters of "
                         f"{cfg.spectrum!r} (has {list(reg.params)})")
    if "nfreq" in ranges:
        # t_process_adapt's nfreq is a bin INDEX selecting where alphas_adapt
        # applies, not a continuous hyperparameter: a drawn nfreq either
        # breaks broadcasting against the per-bin alphas_adapt draw or (alone)
        # is silently ignored by the model. Pin it via functools.partial on a
        # re-registered spectrum instead.
        raise ValueError("'nfreq' (a bin index) cannot be sampled; register "
                         "a partial spectrum with nfreq bound instead")
    names = tuple(ranges)
    per_bin = tuple(n in _PER_BIN_PARAMS for n in names)
    static = (cfg.target, cfg.spectrum, names, per_bin,
              _resolve_dists(cfg.dist, names, "NoiseSampling"))
    return static, [list(ranges[n]) for n in names]


def _sampled_cgw(keys, t_rel, pos_local, pdist_local, ranges, static, tag,
                 bulk=None):
    """(R_local, P_local, T) per-realization CGW delays (shard_map body).

    ``t_rel`` is this shard's (P_local, T) epochs relative to the config's
    ``tref`` (precomputed host-f64, stored f32); ``ranges`` the (8, 2)
    parameter bounds in CGWSampling field order (row 5 = the amplitude,
    ``log10_h`` or ``log10_dist`` per the mode); ``static`` the resolved
    ``(psrterm, mode, dists, sample_pdist)`` descriptor. Source draws fold
    the 0xC6 domain tag and the per-config index ``tag`` but never the shard
    index: one sampled source is a global nuisance per realization. The
    per-pulsar ``p_dist`` nuisance (subkey 2) folds the GLOBAL pulsar index,
    so streams stay mesh-shape independent.

    ``bulk`` (psrterm configs only) is this shard's (R_local, P_local) slice
    of the host-f64 retarded-phase bulk (``EnsembleSimulator._host_cgw_bulks``
    replicates the same key chain on the host CPU backend — threefry is
    backend-bit-exact — and evaluates the ~1e4-rad pulsar-term phase offset
    at float64, mod 2pi). The kernel then only computes O(10 rad) residual
    phases, which is what makes psrterm realization streams mesh-shape
    reproducible at the common tolerance (models/cgw.py:psrterm_phase_bulk).
    """
    from ..models.cgw import cw_delay, cw_delay_psrterm_split

    psrterm, mode, dists, sample_pdist = static
    dtype = t_rel.dtype
    p_local = t_rel.shape[0]
    norm_mask = np.array([d == "normal" for d in dists])
    gidx = lax.axis_index(PSR_AXIS) * p_local + jnp.arange(p_local)

    def one(key, bulk_r):
        kz = jax.random.fold_in(jax.random.fold_in(key, _CGW_TAG), tag)
        u = jax.random.uniform(kz, (8,), dtype)
        v = ranges[:, 0] + u * (ranges[:, 1] - ranges[:, 0])
        if norm_mask.any():
            g = jax.random.normal(jax.random.fold_in(kz, 1), (8,), dtype)
            v = jnp.where(jnp.asarray(norm_mask),
                          ranges[:, 0] + g * ranges[:, 1], v)
        if sample_pdist:
            kpd = jax.random.fold_in(kz, 2)
            pd = jax.vmap(lambda gi: jax.random.normal(
                jax.random.fold_in(kpd, gi), (), dtype))(gidx)
        else:
            pd = jnp.zeros((p_local,), dtype)
        amp_kw = {("log10_h" if mode == "h" else "log10_dist"): v[5]}
        with obs.span("cgw"):
            if bulk_r is not None:
                return jax.vmap(lambda t, p, pdm, pz, br: cw_delay_psrterm_split(
                    t, p, (pdm[0], pdm[1]), br, cos_gwtheta=v[0], gwphi=v[1],
                    cos_inc=v[2], log10_mc=v[3], log10_fgw=v[4], phase0=v[6],
                    psi=v[7], p_dist=pz,
                    **amp_kw))(t_rel, pos_local, pdist_local, pd, bulk_r)
            return jax.vmap(lambda t, p, pdm, pz: cw_delay(
                t, p, (pdm[0], pdm[1]), cos_gwtheta=v[0], gwphi=v[1],
                cos_inc=v[2], log10_mc=v[3], log10_fgw=v[4], phase0=v[6],
                psi=v[7], psrTerm=psrterm, evolve=True, p_dist=pz,
                **amp_kw))(t_rel, pos_local, pdist_local, pd)

    if bulk is not None:
        return jax.vmap(one)(keys, bulk)
    return jax.vmap(lambda k: one(k, None))(keys)


def _validated_toas_abs(batch, toas_abs, what: str) -> np.ndarray:
    """Shared validation for features that need absolute host-f64 epochs."""
    if toas_abs is None:
        raise ValueError(
            f"{what} needs toas_abs: the padded (npsr, max_toa) absolute "
            f"MJD-second TOAs (float64 host array; build one from a pulsar "
            f"list with fakepta_tpu.batch.padded_abs_toas(psrs))")
    # fakepta: allow[dtype-policy] absolute MJD-second epochs need host f64
    toas_abs = np.asarray(toas_abs, dtype=np.float64)
    if toas_abs.shape != batch.t_own.shape:
        raise ValueError(f"toas_abs shape {toas_abs.shape} != batch "
                         f"{batch.t_own.shape}")
    return toas_abs


def _orbit_state_specs(has_toa=False):
    """PartitionSpecs for an OrbitState: per-TOA leaves shard over 'psr' (and
    'toa' when the mesh has the axis — every leaf's TOA dim is axis 1), the
    scalar masses replicate (mirrors :func:`_batch_specs`)."""
    from ..models.roemer import OrbitState

    leaf = P(PSR_AXIS, TOA_AXIS) if has_toa else P(PSR_AXIS)
    specs = {f.name: leaf for f in dataclasses.fields(OrbitState)}
    specs["mass"] = P()
    specs["mass_ss"] = P()
    return OrbitState(**specs)


def _build_deterministic(batch, cgw, roemer, ephem, toas_abs, pdist, dtype,
                         waveform=None):
    """(P, T) summed deterministic delay block, or None if nothing configured.

    ``cgw``/``roemer`` accept a single config or a sequence. CGW waveforms are
    vmapped over pulsars on device (f32 phases are fine: the ~1e-6 rad error
    from 28 s TOA quantization is far below the waveform scale); Roemer deltas
    go through the f32-stable difference kernel with the nominal orbit
    propagated host-side in float64.

    ``waveform`` is the engine counterpart of the facade's generic
    ``add_deterministic`` hook (reference ``fake_pta.py:444-455``): either a
    precomputed padded (P, T) delay array, or a callable with the FACADE'S
    contract — invoked ``fn(toas=...)`` on ONE pulsar's real (unpadded)
    absolute epochs, the exact keyword convention ``Pulsar.add_deterministic``
    uses — evaluated per pulsar here at host float64, so the same callable
    (keyword-only ``toas`` included) injects identically through the facade
    and the engine (zero padding never leaks into min/max/span-sensitive
    waveforms). Extra parameters the facade would forward as ``**kwargs``
    must be pre-bound with ``functools.partial`` here: the engine passes
    ``toas`` alone. A sequence mixes both forms; contributions sum.
    ``toas_abs`` is only required when a callable (or a cgw/roemer config)
    needs epochs.
    """
    cgw_list = _as_config_list(cgw)
    roe_list = _as_config_list(roemer)
    wf_list = _as_config_list(waveform)
    if not cgw_list and not roe_list and not wf_list:
        return None
    if cgw_list or roe_list or any(callable(w) for w in wf_list):
        toas_abs = _validated_toas_abs(
            batch, toas_abs, "cgw/roemer/waveform deterministic signals")

    det = jnp.zeros(batch.t_own.shape, dtype)
    mask_np = np.asarray(batch.mask)
    for wf in wf_list:
        if callable(wf):
            arr = np.zeros(batch.t_own.shape)
            for i in range(batch.npsr):
                n = int(mask_np[i].sum())
                # fakepta: allow[dtype-policy] facade-parity host evaluation
                row = np.asarray(wf(toas=toas_abs[i, :n]), dtype=np.float64)
                if row.shape != (n,):
                    raise ValueError(
                        f"deterministic waveform returned shape {row.shape} "
                        f"for pulsar {i} ({n} epochs); the callable contract "
                        f"is fn(toas=...) -> delays per pulsar, as in the "
                        f"facade's add_deterministic (pre-bind extra kwargs "
                        f"with functools.partial)")
                arr[i, :n] = row
        else:
            # fakepta: allow[dtype-policy] precomputed host array, cast below
            arr = np.asarray(wf, dtype=np.float64)
            if arr.shape != batch.t_own.shape:
                raise ValueError(
                    f"deterministic waveform array has shape {arr.shape}; "
                    f"expected the padded batch shape {batch.t_own.shape}")
        det = det + jnp.asarray(arr, dtype)
    if cgw_list:
        from ..models import cgw as cgw_model

        if pdist is None:
            pdist = np.zeros((batch.npsr, 2))
        # fakepta: allow[dtype-policy] one-off host-f64 CGW staging (below)
        pdist = np.asarray(pdist, dtype=np.float64).reshape(batch.npsr, 2)
        # fakepta: allow[dtype-policy] one-off host-f64 CGW staging (below)
        pos64 = np.asarray(batch.pos, dtype=np.float64)
        # construction-time, once: evaluate at float64 on the host CPU backend
        # (absolute MJD-second epochs ~4.6e9 s quantize at ~550 s in f32 —
        # ~2e-5 rad of phase error the one-off f64 evaluation avoids for free).
        # Sources sharing a (psrterm, amplitude-mode) signature evaluate as ONE
        # vmapped parameter batch (cw_delay_batched) instead of a Python loop.
        groups = {}
        for cfg in cgw_list:
            mode = "h" if cfg.log10_h is not None else "dist"
            groups.setdefault((bool(cfg.psrterm), mode), []).append(cfg)
        # fakepta: allow[dtype-policy] sanctioned host-f64 stage: CGW phases
        # from ~4.6e9 s epochs lose ~550 s at f32 (module docstring bound)
        with enable_x64(), jax.default_device(jax.devices("cpu")[0]):
            for (psrterm, mode), cfgs in groups.items():
                amp = np.array([c.log10_h if mode == "h" else c.log10_dist
                                for c in cfgs])
                kw = {("log10_h" if mode == "h" else "log10_dist"): amp}
                delay = cgw_model.cw_delay_batched(
                    jnp.asarray(toas_abs), jnp.asarray(pos64),
                    jnp.asarray(pdist),
                    cos_gwtheta=np.array([c.costheta for c in cfgs]),
                    gwphi=np.array([c.phi for c in cfgs]),
                    cos_inc=np.array([c.cosinc for c in cfgs]),
                    log10_mc=np.array([c.log10_mc for c in cfgs]),
                    log10_fgw=np.array([c.log10_fgw for c in cfgs]),
                    phase0=np.array([c.phase0 for c in cfgs]),
                    psi=np.array([c.psi for c in cfgs]),
                    psrTerm=psrterm, evolve=True, **kw)
                det = det + jnp.asarray(np.asarray(delay), dtype)
    if roe_list:
        from ..models import roemer as roemer_dev

        if ephem is None:
            from ..ephemeris import Ephemeris
            ephem = Ephemeris()
        for cfg in roe_list:
            state = roemer_dev.nominal_state(ephem, cfg.planet, toas_abs,
                                             dtype=dtype)
            delay = jax.jit(roemer_dev.roemer_delay_dev)(
                state, batch.pos, d_mass=cfg.d_mass, d_Om=cfg.d_Om,
                d_omega=cfg.d_omega, d_inc=cfg.d_inc, d_a=cfg.d_a,
                d_e=cfg.d_e, d_l0=cfg.d_l0)
            det = det + delay.astype(dtype)
    return jnp.where(batch.mask, det, 0.0)


def _lane_mode(offset) -> bool:
    """True when a dispatch carries serve RNG lanes (vector offset)."""
    return bool(getattr(offset, "ndim", 0))


def _chunk_keys(base_key, offset, nreal):
    """Per-realization keys for one chunk dispatch — both key modes.

    Batch mode (scalar ``offset``): ``fold_in(base_key, offset + i)``, the
    engine's absolute-index stream (checkpoint resume identity).

    Lane mode (the :mod:`fakepta_tpu.serve` layer): ``base_key`` is an
    (nreal,) int32 vector of per-slot *request seeds* and ``offset`` the
    matching (nreal,) int32 vector of within-request indices; slot i draws
    ``fold_in(key(seed_i), within_i)`` — exactly the key ``run(n,
    seed=seed_i)`` gives its realization ``within_i``, so a served request's
    stream is bit-identical to its own solo run regardless of which cohort,
    bucket pad, or mesh shape served it. Key values are an elementwise map
    of (seed, index), so lane streams are mesh-shape independent like every
    other stage.
    """
    if _lane_mode(offset):
        return jax.vmap(lambda s, w: jax.random.fold_in(
            jax.random.key(s), w))(base_key, offset)
    return jax.vmap(lambda i: jax.random.fold_in(base_key, i))(
        offset + jnp.arange(nreal))


def _lane_arrays(lanes, nreal):
    """Per-slot (request seed, within-request index) vectors for a lane run.

    ``lanes`` is a sequence of ``(seed, n)`` pairs in slot order (the serve
    scheduler's coalesced cohort); slots past the last lane are bucket
    padding (seed 0, continuing indices) whose results callers discard.
    """
    seeds = np.zeros(nreal, dtype=np.int32)
    within = np.arange(nreal, dtype=np.int32)
    pos = 0
    for s, n in lanes:
        s, n = int(s), int(n)
        if n <= 0:
            raise ValueError(f"lane realization count must be > 0, got {n}")
        if not 0 <= s < 2 ** 31:
            # int32 seeds ride the device program; jax.random.key(int32 s)
            # equals key(python s) on this range, which is what makes lane
            # streams bit-identical to run(n, seed=s)
            raise ValueError(f"lane seed must be in [0, 2**31), got {s}")
        if pos + n > nreal:
            raise ValueError(f"lanes need {pos + n} slots but the run has "
                             f"nreal={nreal}")
        seeds[pos:pos + n] = s
        within[pos:pos + n] = np.arange(n, dtype=np.int32)
        pos += n
    return seeds, within


def pack_stats(curves, autos, *extras):
    """Pack per-realization statistic lanes into one (n, nbins+1+...) array.

    The single source of truth for the packed statistic layout: lane
    ``n < nbins`` is curve bin n, lane ``nbins`` is the mean autocorrelation,
    and any ``extras`` (each (n, K)) follow in order — the OS lane packs its
    per-ORF amp2 values (and, under null calibration, the paired noise-only
    amp2 values) here. Curves, autos and detection statistics ride one array
    so a chunk's outputs are ONE device->host fetch (a round-trip through a
    remote-TPU tunnel costs ~80 ms flat regardless of size). Works on device
    and host arrays alike.
    """
    lib = np if isinstance(curves, np.ndarray) else jnp
    return lib.concatenate([curves, autos[:, None], *extras], axis=1)


def unpack_stats(packed, nbins: int):
    """Inverse of :func:`pack_stats`: (curves (n, nbins), autos (n,))."""
    return packed[:, :nbins], packed[:, nbins]


def _batch_specs(has_toa=False):
    """PartitionSpecs for a PulsarBatch: every (npsr, ...) leaf shards over the
    psr axis, per-TOA trailing axes additionally over 'toa' (when the mesh has
    the axis), scalars replicate. Derived from the dataclass fields so adding
    a field to PulsarBatch cannot silently miss a spec."""
    specs = {f.name: P(PSR_AXIS) for f in dataclasses.fields(PulsarBatch)}
    if has_toa:
        for name in _BATCH_TOA_FIELDS:
            specs[name] = P(PSR_AXIS, TOA_AXIS)
        specs["sys_mask"] = P(PSR_AXIS, None, TOA_AXIS)
    specs["tspan_common"] = P()
    return PulsarBatch(**specs)


def _correlation_rows(res_local, stats_bf16=False, toa_psum=False):
    """Raw cross-correlation rows via the program's one collective.

    all_gathers the residual blocks over 'psr' and contracts local rows against
    the full array: returns (R_local, P_local, P_total) pair-product sums. The
    1/valid-pair-TOA-count normalization (ref ``correlated_noises.py:14-19``
    divides by the full TOA count; identical on uniform grids, correct under
    padding here) is NOT applied — the counts are static (mask-derived), so
    callers fold them into precomputed binning weights. That keeps the mask
    all_gather + counts einsum out of the shard_map body and single-sources
    the normalization with the fused Pallas path (the division itself was
    measured perf-neutral: XLA fused it).

    ``stats_bf16`` casts the residual blocks to bfloat16 at this statistic
    boundary — the signal accumulation stays f32; only the (R, P, T) tensors
    feeding the collective + contraction (the program's dominant HBM/ICI
    traffic per the roofline: intensity 7 vs ridge 240) halve their bytes.
    Numerically this is the SAME operand rounding XLA's default TPU matmul
    precision already applies inside the contraction (~4e-3 relative on pair
    correlations); the explicit cast additionally halves the HBM reads and
    the all_gather payload, which default-precision f32 storage does not.
    Accumulation stays f32 via preferred_element_type.
    """
    if stats_bf16:
        res_local = res_local.astype(jnp.bfloat16)
    with obs.span("all_gather"):
        res_full = lax.all_gather(res_local, PSR_AXIS, axis=1, tiled=True)
    with obs.span("correlate"):
        corr = jnp.einsum("rpt,rqt->rpq", res_local, res_full,
                          preferred_element_type=jnp.float32)
    if toa_psum:
        # sequence parallelism's closing collective: the pair products are a
        # reduction over TOAs, so time shards contribute partial sums and one
        # psum over 'toa' completes them (replicating corr over the axis)
        corr = lax.psum(corr, TOA_AXIS)
    return corr


class EnsembleSimulator:
    """Compiled Monte-Carlo engine over a (real, psr) device mesh.

    Produces per-realization pair-correlation matrices and angular-binned
    correlation curves (the Hellings-Downs statistic) fully on device.
    """

    def __init__(self, batch: PulsarBatch,
                 gwb: Optional[Union[GWBConfig, Sequence[GWBConfig]]] = None,
                 mesh=None, include=("white", "ecorr", "red", "dm", "chrom",
                                     "sys", "gwb", "det"),
                 nbins: int = 15, use_pallas: Optional[bool] = None,
                 pallas_precision: str = "bf16", pallas_mxu_binning: bool = True,
                 bases_dtype: str = "f32", stats_dtype: str = "f32",
                 cgw=None, roemer=None, roemer_sample=None, ephem=None,
                 toas_abs=None, pdist=None, noise_sample=None,
                 cgw_sample=None, white_sample=None, toaerr2=None,
                 backend_id=None, waveform=None, compile_cache_dir=None):
        """``noise_sample`` takes :class:`NoiseSampling` config(s) — per-
        realization (log10_A, gamma) draws replacing the fixed PSD of the
        red/dm/chrom/gwb stages. ``use_pallas`` selects the statistic path:
        ``True`` enables the fused binned-correlation kernel
        (:mod:`fakepta_tpu.ops.pallas_kernels`); ``'mega'`` enables the
        whole-chunk megakernel (:mod:`fakepta_tpu.ops.megakernel`) — GP
        projection, correlation and binning fused in VMEM with the Fourier
        bases recomputed in-kernel, the HBM-roofline path; its default
        statistic precision is full f32 (stream-compatible with the XLA
        path) and ``run(precision='bf16')`` opts into the bf16-storage /
        f32-accumulate mode per run. ``pallas_precision`` is
        ``'bf16'`` (default: bf16 matmul operands with f32 accumulation —
        ~4e-3 relative rounding on individual pair correlations, 2x the MXU
        rate) or ``'f32'`` (full-precision matmul at half rate). The XLA path
        (default) accumulates in f32 but its big correlation contraction also
        runs XLA's default TPU matmul precision (f32 operands rounded to bf16
        — the same ~4e-3 pair-correlation bound); the angular-binning einsums
        are pinned to full f32 precision. Wrap construction AND the ``run``
        call in ``jax.default_matmul_precision('highest')`` for a full-f32
        program at roughly half the matmul rate.

        ``compile_cache_dir`` wires jax's persistent compilation cache so
        the chunk-program compile amortizes across processes and rounds
        (the ``FAKEPTA_TPU_COMPILE_CACHE`` env var is the opt-in default;
        see :func:`fakepta_tpu.parallel.pipeline.configure_compile_cache`
        and :meth:`warm_start` for the AOT warm path, docs/PERFORMANCE.md).
        """
        pipeline_mod.configure_compile_cache(compile_cache_dir)
        self.mesh = mesh if mesh is not None else make_mesh(jax.devices()[:1])
        n_real_shards = self.mesh.shape[REAL_AXIS]
        n_psr_shards = self.mesh.shape[PSR_AXIS]
        if batch.npsr % n_psr_shards != 0:
            raise ValueError(
                f"npsr={batch.npsr} must be divisible by the psr mesh axis "
                f"({n_psr_shards}); pad the batch")
        # the 'toa' axis (sequence parallelism for long datasets) is optional
        # so externally-built 2-D (real, psr) meshes keep working
        self._has_toa = TOA_AXIS in self.mesh.shape
        self._n_toa_shards = (self.mesh.shape[TOA_AXIS]
                              if self._has_toa else 1)
        if batch.max_toa % self._n_toa_shards != 0:
            raise ValueError(
                f"max_toa={batch.max_toa} must be divisible by the toa mesh "
                f"axis ({self._n_toa_shards}); pad the batch")
        if self._n_toa_shards > 1:
            # restore _batch_specs' cannot-silently-miss guarantee for the
            # 'toa' dimension: any batch leaf whose trailing axis is the TOA
            # width must be in the shard list, else it would enter the
            # shard_map body at full width beside local-width siblings
            known = set(_BATCH_TOA_FIELDS) | {"sys_mask"}
            for fld in dataclasses.fields(PulsarBatch):
                arr = getattr(batch, fld.name)
                if (getattr(arr, "ndim", 0) >= 2
                        and arr.shape[-1] == batch.max_toa
                        and fld.name not in known):
                    raise AssertionError(
                        f"PulsarBatch.{fld.name} has a TOA-width trailing "
                        f"axis but is not listed in _BATCH_TOA_FIELDS; add "
                        f"it (or, if the width match is coincidental — e.g. "
                        f"a bin count equal to max_toa — rename this check's "
                        f"exemptions)")
        if self._n_toa_shards > 1 and use_pallas:
            raise ValueError(
                "use_pallas is incompatible with toa sharding (the fused "
                "kernel assumes each shard holds the full TOA axis); drop "
                "one of the two")
        self.batch = batch
        self.nbins = nbins
        self._n_real_shards = n_real_shards
        dtype = batch.t_own.dtype

        # ``gwb`` accepts one GWBConfig or a sequence: several simultaneous
        # common correlated signals (e.g. HD background + clock monopole +
        # ephemeris dipole — the facade/reference layers them with repeated
        # add_common_correlated_noise calls) ride the same program, each with
        # its own ORF Cholesky, PSD weights and chromatic index
        gwb_cfgs = _as_config_list(gwb)
        if gwb_cfgs and "gwb" in include:
            df_common = 1.0 / batch.tspan_common
            chols, ws, idxs, freqfs = [], [], [], []
            for cfg in gwb_cfgs:
                orf = gwb_ops.build_orf(cfg.orf, batch.pos, cfg.h_map)
                # orf_cholesky factorizes in host float64 (singular ORFs NaN
                # at f32)
                chols.append(gwb_ops.orf_cholesky(orf).astype(dtype))
                # the common frequency grid n/Tspan is implicit in the
                # normalized-time basis; only the bin width enters the weights
                ws.append(jnp.sqrt(jnp.asarray(cfg.psd, dtype) * df_common))
                idxs.append(cfg.idx)
                freqfs.append(cfg.freqf)
            self._chol = tuple(chols)
            self._gwb_w = tuple(ws)
            self._gwb_idx = tuple(idxs)
            self._gwb_freqf = tuple(freqfs)
        else:
            self._chol = (jnp.eye(batch.npsr, dtype=dtype),)
            self._gwb_w = (jnp.zeros((1,), dtype),)
            self._gwb_idx = (0.0,)
            self._gwb_freqf = (1400.0,)
        include = tuple(include)

        # per-realization hyperparameter sampling (NoiseSampling, single or
        # sequence): static (target, dist) structure + tiny traced (2, 2)
        # range arrays, validated against the stages actually in the program
        samp_list = _as_config_list(noise_sample)
        seen = set()
        samp_static, samp_params = [], []
        for cfg in samp_list:
            if cfg.target not in _HYPER_SUBTAG:
                raise ValueError(f"NoiseSampling target {cfg.target!r} not in "
                                 f"{sorted(_HYPER_SUBTAG)}")
            if cfg.target in seen:
                raise ValueError(f"duplicate NoiseSampling target "
                                 f"{cfg.target!r}")
            seen.add(cfg.target)
            if cfg.target not in include:
                raise ValueError(f"NoiseSampling target {cfg.target!r} needs "
                                 f"stage {cfg.target!r} in include")
            if cfg.target == "sys" and not bool(
                    np.any(np.asarray(batch.sys_mask))):
                raise ValueError(
                    "NoiseSampling('sys') needs system-noise bands: build "
                    "the batch from pulsars with system_noise entries (the "
                    "band TOA membership comes from sys_mask; only the PSD "
                    "is replaced by the draws)")
            if cfg.target == "gwb" and not gwb_cfgs:
                raise ValueError("NoiseSampling('gwb') needs a GWBConfig (its "
                                 "orf/idx and psd length set the program; the "
                                 "psd values are replaced by the draws)")
            static, rows = _resolve_noise_sampling(cfg)
            samp_static.append(static)
            samp_params.append(jnp.asarray(rows, dtype))
        self._samp_static = tuple(samp_static)
        self._samp_params = tuple(samp_params)
        sampled = {cfg.target for cfg in samp_list}

        # per-realization white/ECORR hyperparameter sampling (WhiteSampling):
        # static sample flags + a tiny traced (3, 2) range array; the raw
        # squared TOA errors and (pulsar, backend) partition ride the program
        # as (P, T) arrays sharded like the batch
        self._white_static = None
        if white_sample is not None:
            ws = white_sample
            if not isinstance(ws, WhiteSampling):
                raise TypeError(f"white_sample must be a WhiteSampling, got "
                                f"{type(ws).__name__}")
            if ws.dist not in ("uniform", "normal"):
                raise ValueError(f"WhiteSampling dist must be 'uniform' or "
                                 f"'normal', got {ws.dist!r}")
            if (ws.efac is None and ws.log10_tnequad is None
                    and ws.log10_ecorr is None):
                # all-None would sample nothing yet still swap the batch's
                # noisedict-derived sigma2 for raw toaerr^2 — silent statistics
                # change with zero randomization
                raise ValueError("WhiteSampling has no parameters to sample: "
                                 "give an efac/log10_tnequad/log10_ecorr range")
            if "white" not in include:
                raise ValueError("WhiteSampling needs stage 'white' in include")
            if ws.log10_ecorr is not None and not (
                    "ecorr" in include
                    and bool(np.any(np.asarray(batch.ecorr_amp) > 0.0))):
                raise ValueError(
                    "WhiteSampling.log10_ecorr needs a live ECORR stage: build "
                    "the batch with ecorr=True (epochs + nonzero ecorr_amp) "
                    "and keep 'ecorr' in include")
            if toaerr2 is None:
                # the synthetic/default case: the batch's fixed white variance
                # IS the raw toaerr^2 (efac=1, no EQUAD baked in). A
                # from_pulsars batch with noisedict efac/equad baked into
                # sigma2 would silently double-apply them here — the batch
                # carries no provenance to detect that, so warn and point at
                # the explicit path (batch.padded_toaerr2). Ecorr-only
                # sampling never reads toaerr2 (the fixed sigma2 stays in
                # place), so the provenance warning would be noise there.
                if ws.efac is not None or ws.log10_tnequad is not None:
                    import warnings
                    warnings.warn(
                        "WhiteSampling with no explicit toaerr2: treating "
                        "batch.sigma2 as the raw toaerr^2 (exact for synthetic "
                        "batches; WRONG if the batch baked noisedict efac/equad "
                        "into sigma2 — pass toaerr2=padded_toaerr2(psrs))",
                        stacklevel=2)
                toaerr2 = np.asarray(batch.sigma2)
            # fakepta: allow[dtype-policy] host validation; device cast below
            toaerr2 = np.asarray(toaerr2, dtype=np.float64)
            if toaerr2.shape != batch.t_own.shape:
                raise ValueError(f"toaerr2 shape {toaerr2.shape} != batch "
                                 f"{batch.t_own.shape}")
            if backend_id is None:
                backend_id = np.zeros(batch.t_own.shape, dtype=np.int32)
            backend_id = np.asarray(backend_id, dtype=np.int32)
            if backend_id.shape != batch.t_own.shape:
                raise ValueError(f"backend_id shape {backend_id.shape} != "
                                 f"batch {batch.t_own.shape}")
            self._white_nb = int(backend_id.max()) + 1
            self._white_static = (ws.efac is not None,
                                  ws.log10_tnequad is not None,
                                  ws.log10_ecorr is not None, ws.dist)
            rows = [list(ws.efac or (1.0, 1.0)),
                    list(ws.log10_tnequad or (-8.0, -8.0)),
                    list(ws.log10_ecorr or (-8.0, -8.0))]
            self._white_params = jnp.asarray(rows, dtype)
        else:
            self._white_nb = 1
            self._white_params = jnp.zeros((3, 2), dtype)
            # never read when white_static is None: (P, 1) broadcast-shaped
            # dummies keep the shard_map argument list static without parking
            # two full (P, T) arrays in device memory
            toaerr2 = np.zeros((batch.npsr, 1))
            backend_id = np.zeros((batch.npsr, 1), dtype=np.int32)
        self._white_toaerr2 = jnp.asarray(toaerr2, dtype)
        self._white_bid = jnp.asarray(backend_id)

        # optional stages only enter the program if their parameters are anywhere
        # nonzero — the default synthetic batch has chrom/ecorr off, so nothing
        # is traced for them. A sampled stage is always live: its PSD comes
        # from the per-realization draws, not the batch arrays.
        has_chrom = bool(np.any(np.asarray(batch.chrom_psd) > 0.0)) \
            or "chrom" in sampled
        has_ecorr = bool(np.any(np.asarray(batch.ecorr_amp) > 0.0))
        has_sys = bool(np.any(np.asarray(batch.sys_psd) > 0.0)) \
            or "sys" in sampled
        self._include = (("white" in include),
                         ("ecorr" in include and has_ecorr),
                         ("red" in include),
                         ("dm" in include), ("chrom" in include and has_chrom),
                         ("sys" in include and has_sys),
                         ("gwb" in include and bool(gwb_cfgs)))

        # deterministic signals (CGW sources + BayesEphem Roemer perturbations):
        # evaluated ONCE here into a (P, T) delay block that the kernel adds to
        # every realization — BASELINE config 4 (GWB + DM + BayesEphem at 100
        # psr) as a single device program. ``toas_abs`` are the padded absolute
        # MJD-second TOAs (host float64: the ephemeris element propagation and
        # CGW epoch both need more than f32 gives on ~1e9 s). Only built when
        # the 'det' stage is actually enabled.
        self._det = _build_deterministic(
            batch, cgw, roemer, ephem, toas_abs, pdist, dtype,
            waveform=waveform) \
            if "det" in include else None
        self._has_det = self._det is not None
        if self._det is None:
            self._det = jnp.zeros_like(batch.t_own)

        # per-realization BayesEphem sampling (RoemerSampling, single config or
        # a sequence — one per sampled body): nominal orbit states propagated
        # once on host f64, perturbations drawn and evaluated per realization
        # inside the kernel. Enabled by passing the config(s) — NOT gated on
        # `include` — with all-zero-scale entries skipped entirely (nothing to
        # sample), matching the skip-zero-stage convention.
        sample_list = _as_config_list(roemer_sample)
        self._roe_states: Tuple = ()
        self._roe_scales: Tuple = ()
        active = [(cfg, [cfg.s_mass, cfg.s_Om, cfg.s_omega, cfg.s_inc,
                         cfg.s_a, cfg.s_e, cfg.s_l0])
                  for cfg in sample_list]
        active = [(cfg, sc) for cfg, sc in active if any(s != 0.0 for s in sc)]
        if active:
            toas64 = _validated_toas_abs(batch, toas_abs, "roemer_sample")
            from ..models import roemer as roemer_dev
            if ephem is None:
                from ..ephemeris import Ephemeris
                ephem = Ephemeris()
            self._roe_states = tuple(
                roemer_dev.nominal_state(ephem, cfg.planet, toas64,
                                         dtype=dtype) for cfg, _ in active)
            self._roe_scales = tuple(
                jnp.asarray(sc, dtype) for _, sc in active)

        # per-realization CGW source sampling (CGWSampling, single or a
        # sequence — one sampled source per config): epochs relative to each
        # config's tref precomputed host-f64 and stored f32 (see the class
        # docstring for the phase-precision bound), parameter ranges as tiny
        # replicated (8, 2) arrays, waveforms evaluated inside the kernel
        cgw_s_list = _as_config_list(cgw_sample)
        cgw_static, cgw_ranges = [], []
        for c in cgw_s_list:
            mode = "dist" if c.log10_dist is not None else "h"
            amp = c.log10_dist if mode == "dist" else c.log10_h
            if amp is None:
                raise ValueError("CGWSampling needs a log10_h or log10_dist "
                                 "amplitude range")
            names = ("costheta", "phi", "cosinc", "log10_mc", "log10_fgw",
                     "log10_dist" if mode == "dist" else "log10_h",
                     "phase0", "psi")
            dists = _resolve_dists(c.dist, names, "CGWSampling")
            if c.sample_pdist and not c.psrterm:
                raise ValueError("CGWSampling(sample_pdist=True) needs "
                                 "psrterm=True (the distance nuisance only "
                                 "enters through the pulsar term)")
            if c.sample_pdist and (pdist is None
                                   or not np.any(np.asarray(pdist)[..., -1])):
                import warnings
                warnings.warn("CGWSampling(sample_pdist=True) with all-zero "
                              "pdist sigmas draws a nuisance that cannot move "
                              "anything; pass pdist=(mean, sigma) pairs",
                              stacklevel=2)
            cgw_static.append((bool(c.psrterm), mode, dists,
                               bool(c.sample_pdist)))
            cgw_ranges.append(jnp.asarray(
                [list(c.costheta), list(c.phi), list(c.cosinc),
                 list(c.log10_mc), list(c.log10_fgw), list(amp),
                 list(c.phase0), list(c.psi)], dtype))
        self._cgw_static = tuple(cgw_static)
        self._cgw_ranges = tuple(cgw_ranges)
        # psrterm configs get a host-f64 retarded-phase bulk input per chunk
        # (see _host_cgw_bulks): record which config indices need one
        self._cgw_psrterm = tuple(j for j, stat in enumerate(cgw_static)
                                  if stat[0])
        if cgw_s_list:
            toas64 = _validated_toas_abs(batch, toas_abs, "cgw_sample")
            self._cgw_trel = tuple(
                jnp.asarray(toas64 - c.tref, dtype) for c in cgw_s_list)
        else:
            self._cgw_trel = ()
        if pdist is None:
            pdist = np.zeros((batch.npsr, 2))
        # fakepta: allow[dtype-policy] host staging; jnp cast to dtype below,
        # f64 copy kept for the psrterm retarded-phase bulk precompute
        self._pdist_host = np.asarray(pdist, dtype=np.float64).reshape(
            batch.npsr, 2)
        self._pdist = jnp.asarray(self._pdist_host, dtype)

        # angular bins for the correlation curve (static, from positions)
        # fakepta: allow[dtype-policy] host-f64 angle/bin setup, done once
        pos = np.asarray(batch.pos, dtype=np.float64)
        # host-f64 positions, shared by the OS-lane operator build and the
        # psrterm bulk precompute
        self._pos64 = pos
        ang = np.arccos(np.clip(pos @ pos.T, -1, 1))
        edges = np.linspace(0.0, np.pi, nbins + 1)
        bin_idx = np.clip(np.digitize(ang, edges) - 1, 0, nbins - 1)
        offdiag = ~np.eye(batch.npsr, dtype=bool)
        onehot = np.zeros((batch.npsr, batch.npsr, nbins))
        onehot[np.arange(batch.npsr)[:, None], np.arange(batch.npsr)[None, :],
               bin_idx] = 1.0
        onehot *= offdiag[:, :, None]
        self.bin_centers = edges[:-1] + 0.5 * (edges[1] - edges[0])

        # Pair-count normalization folded into static statistic weights (the
        # counts depend only on the TOA masks). corr stays raw pair sums inside
        # the program and the pre-divided weights produce identical
        # curves/autos; this also removes the mask all_gather + counts einsum
        # from the shard_map body and matches how the fused Pallas path already
        # normalizes (measured perf-neutral: XLA was fusing the division).
        # fakepta: allow[dtype-policy] exact integer pair counts at host f64
        mask_np = np.asarray(batch.mask, dtype=np.float64)
        raw_counts = mask_np @ mask_np.T
        # public: the RAW valid-pair TOA counts optimal_statistic wants as its
        # `counts` argument (ADVICE r3: single-source them with the engine).
        # Unclamped on purpose — a zero count is how the statistic knows to
        # zero-weight an empty pair; the clamp below exists only so the
        # internal weight normalization never divides by zero.
        self.pair_counts = raw_counts
        counts_full = np.maximum(raw_counts, 1.0)
        bc = np.maximum(onehot.sum((0, 1)), 1.0)
        self._w_bins = jnp.asarray(
            onehot / counts_full[:, :, None] / bc, dtype)
        self._w_auto = jnp.asarray(
            np.eye(batch.npsr) / counts_full / batch.npsr, dtype)
        self._counts_dev = jnp.asarray(counts_full, dtype)

        # fused pallas statistic path (curves+autos without materializing the
        # (R, P, P) correlation tensor in HBM). Opt-in: the XLA path is already
        # near MXU roofline; the fused kernel trades the (R,P,P) HBM round-trip
        # for per-chunk Mosaic compiles, which pays off for repeated runs at a
        # fixed chunk size. On non-TPU platforms it runs in interpret mode
        # (tests); on TPU it is a real Mosaic kernel.
        platform = self.mesh.devices.flat[0].platform
        if use_pallas not in (None, False, True, "mega"):
            raise ValueError(f"use_pallas must be False, True or 'mega', "
                             f"got {use_pallas!r}")
        # statistic path: 'xla' (two-stage einsums), 'fused' (the binned-
        # correlation Pallas kernel) or 'mega' (the whole-chunk megakernel,
        # fakepta_tpu.ops.megakernel — GP projection + correlation + binning
        # in VMEM, bases recomputed in-kernel)
        self._stat_path = ("mega" if use_pallas == "mega"
                           else "fused" if use_pallas else "xla")
        self._use_pallas = self._stat_path != "xla"
        self._pallas_interpret = platform != "tpu"
        if pallas_precision not in ("bf16", "f32"):
            raise ValueError(f"pallas_precision must be 'bf16' or 'f32', "
                             f"got {pallas_precision!r}")
        self._pallas_precision = pallas_precision
        self._pallas_mxu_binning = bool(pallas_mxu_binning)
        if bases_dtype not in ("f32", "bf16"):
            raise ValueError(f"bases_dtype must be 'f32' or 'bf16', got "
                             f"{bases_dtype!r}")
        # 'bf16' stores the concatenated GP projection basis (and the
        # coefficient operand) in bfloat16 — half the HBM traffic of the
        # projection einsum at the same effective MXU operand precision as
        # XLA's TPU default (accumulation stays f32); realizations shift by
        # the ~4e-3 operand rounding
        self._bases_bf16 = bases_dtype == "bf16"
        if self._bases_bf16 and self._stat_path == "mega":
            raise ValueError(
                "bases_dtype='bf16' is inert under use_pallas='mega' (the "
                "megakernel recomputes bases in VMEM and never reads the "
                "dense one); use run(precision='bf16') for the bf16-storage "
                "mode instead")
        if stats_dtype not in ("f32", "bf16"):
            raise ValueError(f"stats_dtype must be 'f32' or 'bf16', got "
                             f"{stats_dtype!r}")
        # 'bf16' halves the (R, P, T) residual traffic through the all_gather
        # + correlation contraction — the program's dominant HBM bytes per the
        # roofline (BASELINE.md round 5). Signal accumulation stays f32; the
        # cast adds only the operand rounding the TPU matmul already applies
        # (~4e-3 relative on pair correlations). XLA path only: the fused
        # Pallas path keeps residuals in VMEM and has its own
        # pallas_precision knob, so the combination would be silently inert —
        # reject it instead.
        self._stats_bf16 = stats_dtype == "bf16"
        if self._stats_bf16 and self._use_pallas:
            raise ValueError(
                "stats_dtype='bf16' applies to the XLA statistic path only "
                "(a no-op under use_pallas, whose precision is "
                "pallas_precision); drop one of the two")

        # --- observability state (fakepta_tpu.obs, docs/OBSERVABILITY.md) ---
        # span registry filled at trace time (persists so reports from
        # already-compiled runs still list the program's stages), the retrace
        # guard's per-signature trace counts, and the one-time cost-analysis
        # capture cache. last_report is the most recent run()'s RunReport.
        self._obs_spans: set = set()
        self._obs_trace_counts: dict = {}
        self._obs_retraces = 0
        self._obs_cost: dict = {}
        self._obs_in_capture = False
        self.last_report = None

        # empty OS-weight stack for the plain fused step (the fused builders
        # share one signature so the n_os=0 path stays byte-compatible)
        self._w_os_empty = jnp.zeros((0, batch.npsr, batch.npsr), dtype)
        self._step_os_cache: dict = {}  # fakepta: allow[unbounded-cache] keyed by the bf16 flag, 2 entries max
        self._step_fused_os_cache: dict = {}  # fakepta: allow[unbounded-cache] keyed by (bf16, n_os) over the fixed OS-weight set
        # lnlike lane (fakepta_tpu.infer): compiled models and step variants,
        # keyed by the (hashable) LikelihoodSpec + mode + path
        self._lnlike_compiled_cache: dict = {}  # fakepta: allow[unbounded-cache] one entry per LikelihoodSpec this simulator serves — caller-enumerated, not request-keyed
        self._step_lnlike_cache: dict = {}  # fakepta: allow[unbounded-cache] keyed by (bf16, LikelihoodSpec) over the same enumerated set
        self._step_xla_cache: dict = {}  # fakepta: allow[unbounded-cache] keyed by the bf16 flag, 2 entries max
        self._step_mega_cache: dict = {}  # fakepta: allow[unbounded-cache] keyed by the bf16 flag, 2 entries max
        self._mega_tables = None
        self._step = self._build_step(self._stats_bf16)
        self._step_xla_cache[self._stats_bf16] = self._step
        self._step_fused = (self._build_step_fused()
                            if self._stat_path == "fused" else None)
        # build the default megakernel step eagerly so configuration errors
        # surface at construction, like the fused path
        self._step_mega = (self._get_step_mega(0, False, "f32")
                           if self._stat_path == "mega" else None)

    def _obs_note_trace(self, signature) -> None:
        """Retrace guard: called from INSIDE the jitted steps, so it executes
        only when jax (re)traces the program — a cached call never reaches
        Python. The first trace per static signature is the expected compile;
        any further trace of the same signature is an unexpected
        recompilation, counted into ``RunReport.retraces``."""
        if self._obs_in_capture:
            return   # the AOT cost-analysis lower() is not a user retrace
        n = self._obs_trace_counts.get(signature, 0) + 1
        self._obs_trace_counts[signature] = n
        obs.count("obs.traces")
        if n > 1:
            self._obs_retraces += 1
            obs.count("obs.retraces")
            obs.event("retrace", value=list(map(str, signature)),
                      count=n)

    def _obs_capture_cost(self, base_key, chunk: int, path: str,
                          precision: str = "f32", w_os=None,
                          with_null: bool = False, lnl=None) -> dict:
        """One-time XLA cost/memory analysis of the chunk program (cached per
        simulator and step variant — plain/fused/megakernel/OS/OS+null
        programs and the f32/bf16 precision modes have genuinely different
        FLOPs/bytes, and per-mode bytes-per-chunk is a recorded benchmark
        metric). Uses the AOT path, which compiles a second executable —
        that one extra compile is the documented price of making the
        roofline's FLOPs/bytes a recorded artifact; events it emits are
        sunk into a throwaway collector so they never pollute run
        metrics."""
        cache_key = (int(chunk), str(path), str(precision),
                     None if w_os is None else int(w_os.shape[0]),
                     bool(with_null),
                     None if lnl is None else lnl[2])
        if cache_key in self._obs_cost:
            return self._obs_cost[cache_key]
        cost: dict = {}
        self._obs_in_capture = True
        try:
            with obs.collect():     # sink capture-compile monitoring events
                bulks = tuple(jnp.zeros((chunk, self.batch.npsr),
                                        self.batch.t_own.dtype)
                              for _ in self._cgw_psrterm)
                # scratch=None: the cost capture measures the program's
                # FLOPs/bytes, which donation aliasing does not change
                stats_bf16 = precision == "bf16"
                if lnl is not None:
                    lnl_step, lnl_theta, _ = lnl
                    if path != "xla":
                        lowered = lnl_step.lower(base_key, 0, chunk,
                                                 lnl_theta, bulks, None)
                    else:
                        lowered = lnl_step.lower(base_key, 0, chunk,
                                                 lnl_theta, bulks, None,
                                                 False)
                elif w_os is not None and path == "mega":
                    lowered = self._get_step_mega(
                        int(w_os.shape[0]), with_null, precision).lower(
                            base_key, 0, chunk, w_os, bulks, None)
                elif w_os is not None and path == "fused":
                    lowered = self._get_step_fused_os(
                        int(w_os.shape[0]), with_null, precision).lower(
                            base_key, 0, chunk, w_os, bulks, None)
                elif w_os is not None:
                    lowered = self._get_step_os(with_null, stats_bf16).lower(
                        base_key, 0, chunk, w_os, bulks, None, False)
                elif path == "mega":
                    lowered = self._get_step_mega(0, False, precision).lower(
                        base_key, 0, chunk, self._w_os_empty, bulks, None)
                elif path == "fused":
                    lowered = self._get_step_fused_os(
                        0, False, precision).lower(
                            base_key, 0, chunk, self._w_os_empty, bulks,
                            None)
                else:
                    lowered = self._get_step_xla(stats_bf16).lower(
                        base_key, 0, chunk, bulks, None, False)
                compiled = lowered.compile()
                ca = compiled.cost_analysis()
                ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
                flops = float(ca.get("flops", 0.0))
                nbytes = float(ca.get("bytes accessed", 0.0))
                if flops > 0:
                    cost["flops_per_chunk"] = flops
                if nbytes > 0:
                    cost["bytes_per_chunk"] = nbytes
                try:
                    ma = compiled.memory_analysis()
                    cost["static_reservation_bytes"] = int(
                        ma.temp_size_in_bytes + ma.argument_size_in_bytes
                        + ma.output_size_in_bytes
                        + ma.generated_code_size_in_bytes)
                except (AttributeError, TypeError, ValueError):
                    pass    # memory_analysis absent/shape-different on
                    #         this jax build; the cost dict just omits it
        except Exception as exc:   # noqa: BLE001 — recorded, not swallowed
            # best-effort capture (cost model absent on some backends/jax
            # builds), but never SILENT: the flight recorder keeps the
            # reason the roofline fields are missing from this run
            obs.flightrec.note("cost_capture_failed", path=str(path),
                               error=repr(exc)[:200])
        finally:
            self._obs_in_capture = False
        try:
            # the analytic HBM model beside the measured number: on TPU the
            # two agree to fusion detail; on the CPU stand-in the measured
            # one is polluted by XLA:CPU's unfused draw chain and the
            # interpret-mode loop accounting, so the model is the recorded
            # roofline source of truth there (ops/megakernel.py docstring)
            from ..ops.megakernel import chunk_bytes_model, stage_k
            if self._mega_tables is None:
                self._mega_tables = self._build_mega_tables()
            mode = {"xla": "xla", "fused": "fused"}.get(
                path, "mega_bf16" if precision == "bf16" else "mega")
            cost["model_bytes_per_chunk"] = chunk_bytes_model(
                chunk, self.batch.npsr, self.batch.max_toa,
                stage_k(self._mega_tables[0]), mode=mode,
                psr_shards=self.mesh.shape[PSR_AXIS],
                dtype_bytes=np.dtype(self.batch.t_own.dtype).itemsize)
        except Exception as exc:   # noqa: BLE001 — recorded, not swallowed
            obs.flightrec.note("cost_model_failed", path=str(path),
                               error=repr(exc)[:200])
        self._obs_cost[cache_key] = cost
        return cost

    def _obs_memory_stats(self) -> dict:
        """Allocator stats, MAX-aggregated over this host's mesh devices
        (empty on backends without them, e.g. XLA:CPU). Sampling only one
        device — what this did before obs.memwatch — underreports a
        multi-chip mesh's peak HBM whenever sharding is uneven or one chip
        carries the replicated extras."""
        from ..obs import memwatch as obs_memwatch

        return obs_memwatch.local_device_stats(self.mesh.devices.flat)

    def _host_cgw_bulks(self, base_key, offset: int, nreal: int):
        """Per-chunk host-f64 retarded-phase bulks for psrterm CGW sampling.

        Replicates the device draw chain (0xC6 domain tag, per-config index,
        per-pulsar global-index folds) on the host CPU backend — threefry key
        streams are backend-bit-exact, so the host sees the same f32 sampled
        sky, frequency and distance nuisances the kernel will draw — then
        evaluates each realization's pulsar-term orbital-phase bulk
        ``dph(-tau)`` at float64 from the host-staged pdist/positions, mod
        2pi (:func:`fakepta_tpu.models.cgw.psrterm_phase_bulk`). The f32
        kernel is left only the O(10 rad) residual phase, which is what makes
        psrterm realization streams mesh-shape reproducible at the engine's
        common tolerance. Returns one (nreal, npsr) batch-dtype array per
        psrterm config (empty tuple when none): ordinary (real, psr)-sharded
        step inputs, ~1e6 host flops per flagship chunk — noise against the
        chunk's device work.
        """
        if not self._cgw_psrterm:
            return ()
        from .. import constants as const
        from ..models.cgw import psrterm_phase_bulk

        npsr = self.batch.npsr
        ddt = self.batch.t_own.dtype
        cpu = jax.local_devices(backend="cpu")[0]
        key_data = np.asarray(jax.random.key_data(base_key))
        out = []
        with jax.default_device(cpu):
            base = jax.random.wrap_key_data(jnp.asarray(key_data))
            keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
                offset + jnp.arange(nreal))
            for j in self._cgw_psrterm:
                _, _, dists, sample_pdist = self._cgw_static[j]
                ranges = jnp.asarray(np.asarray(self._cgw_ranges[j]), ddt)
                norm_mask = np.array([d == "normal" for d in dists])

                def draw(key, j=j, ranges=ranges, norm_mask=norm_mask,
                         sample_pdist=sample_pdist):
                    # mirrors _sampled_cgw's draw chain op for op
                    kz = jax.random.fold_in(
                        jax.random.fold_in(key, _CGW_TAG), j)
                    u = jax.random.uniform(kz, (8,), ddt)
                    v = ranges[:, 0] + u * (ranges[:, 1] - ranges[:, 0])
                    if norm_mask.any():
                        g = jax.random.normal(jax.random.fold_in(kz, 1),
                                              (8,), ddt)
                        v = jnp.where(jnp.asarray(norm_mask),
                                      ranges[:, 0] + g * ranges[:, 1], v)
                    if sample_pdist:
                        kpd = jax.random.fold_in(kz, 2)
                        pd = jax.vmap(lambda gi: jax.random.normal(
                            jax.random.fold_in(kpd, gi), (),
                            ddt))(jnp.arange(npsr))
                    else:
                        pd = jnp.zeros((npsr,), ddt)
                    return v, pd

                v, pd = jax.jit(jax.vmap(draw))(keys)
                # fakepta: allow[dtype-policy] sanctioned host-f64 stage: the
                # ~1e4 rad retarded phase loses ~2e-4 rad/ulp at f32
                v = np.asarray(v, np.float64)
                # fakepta: allow[dtype-policy] same host-f64 bulk stage
                pd = np.asarray(pd, np.float64)
                # cos(mu) at f64 from the f32-exact sampled sky (same antenna
                # geometry as models.cgw.antenna_pattern)
                sin_t = np.sqrt(np.maximum(1.0 - v[:, 0] ** 2, 0.0))
                cosmu = (sin_t[:, None] * np.cos(v[:, 1])[:, None]
                         * self._pos64[None, :, 0]
                         + sin_t[:, None] * np.sin(v[:, 1])[:, None]
                         * self._pos64[None, :, 1]
                         + v[:, 0][:, None] * self._pos64[None, :, 2])
                dist_sec = ((self._pdist_host[None, :, 0]
                             + self._pdist_host[None, :, 1] * pd)
                            * const.kpc / const.c)
                tau = dist_sec * (1.0 - cosmu)
                bulk = psrterm_phase_bulk(tau, v[:, 3][:, None],
                                          v[:, 4][:, None])
                out.append(np.asarray(bulk, ddt))
        return tuple(out)

    def _residuals(self, keys, batch, chols, gwb_ws, det, samp_params,
                   white_params, white_toaerr2, white_bid, cgw_trel,
                   cgw_pdist, cgw_bulks, roe, *, toa_shards, null=False,
                   split_gp=False):
        """(R_local, P_local, T) residual blocks inside a shard_map body.

        The single signal-assembly path every step variant (XLA, fused
        Pallas, megakernel, OS, OS+null) shares, so adding a stage cannot
        fork the program. Term order is frozen (noise block, deterministic
        block, sampled Roemer, sampled CGW): f32 addition order is part of
        the realization-stream contract. ``null=True`` is the OS lane's
        paired noise-only stream — same noise stages and sampled noise
        nuisances under the caller's (derived) keys, but no common
        correlated signal, no deterministic block and no sampled CGW
        sources.

        ``split_gp=True`` (the megakernel contract) returns ``(base,
        coeffs, gp_basis_all)``: the residual WITHOUT the GP projection —
        but with the deterministic/sampled delay terms added, so the base
        is everything the kernel does not recompute — plus the coefficient
        tensor and the dense basis (see :func:`_simulate_block`). The GP
        projection then lands *last* in the addition order (inside the
        kernel), vs. before the deterministic terms on the projected path:
        with no det/roemer/cgw terms the two orders are identical ops, and
        with them the difference is one f32 reassociation (bounded by the
        engine's common mesh-invariance tolerance, pinned in
        tests/test_megakernel.py).
        """
        inc = self._include if not null else self._include[:6] + (False,)
        out = _simulate_block(keys, batch, chols, gwb_ws, self._gwb_idx,
                              self._gwb_freqf, *inc,
                              samp_static=self._samp_static,
                              samp_params=samp_params,
                              bases_bf16=self._bases_bf16,
                              white_static=self._white_static,
                              white_params=white_params,
                              white_toaerr2=white_toaerr2,
                              white_bid=white_bid, white_nb=self._white_nb,
                              toa_shards=toa_shards, split_gp=split_gp)
        if split_gp:
            res, coeffs, basis = out
        else:
            res = out
        if self._has_det and not null:
            res = res + det[None]
        for j in range(len(self._roe_states)):
            term = _sampled_roemer(keys, roe[j], self._roe_scales[j],
                                   batch.pos, tag=j)
            res = res + jnp.where(batch.mask, term, 0.0)
        if not null:
            bulks = dict(zip(self._cgw_psrterm, cgw_bulks))
            for j, stat in enumerate(self._cgw_static):
                term = _sampled_cgw(keys, cgw_trel[j], batch.pos, cgw_pdist,
                                    self._cgw_ranges[j], stat, tag=j,
                                    bulk=bulks.get(j))
                res = res + jnp.where(batch.mask, term, 0.0)
        if split_gp:
            return res, coeffs, basis
        return res

    def _step_in_specs(self, has_toa):
        """shard_map in_specs shared by every step variant (after the keys).

        (P, T) side inputs shard over 'toa' like the batch's per-TOA leaves;
        the no-sampling white dummies are (P, 1) broadcast shapes and stay
        replicated over 'toa'; psrterm CGW bulk inputs shard (real, psr).
        """
        pt_spec = P(PSR_AXIS, TOA_AXIS) if has_toa else P(PSR_AXIS)
        white_spec = pt_spec if self._white_static is not None else P(PSR_AXIS)
        return (_batch_specs(has_toa),
                tuple(P() for _ in self._chol),
                tuple(P() for _ in self._gwb_w), pt_spec,
                tuple(P() for _ in self._samp_params), P(),
                white_spec, white_spec,
                tuple(pt_spec for _ in self._cgw_trel), P(PSR_AXIS),
                tuple(P(REAL_AXIS, PSR_AXIS) for _ in self._cgw_psrterm),
                *(tuple(_orbit_state_specs(has_toa)
                        for _ in self._roe_states)))

    def _resolve_precision(self, path: str, precision) -> str:
        """Effective statistic precision for a run: the run-level override
        (``run(precision=...)``) or the path's constructor default — the
        XLA path's ``stats_dtype``, the fused kernel's ``pallas_precision``,
        and full f32 for the megakernel (which is stream-compatible with
        the XLA path by default; bf16 storage is the explicit opt-in)."""
        if precision is None:
            if path == "xla":
                return "bf16" if self._stats_bf16 else "f32"
            if path == "fused":
                return self._pallas_precision
            return "f32"
        if precision not in ("f32", "bf16"):
            raise ValueError(f"precision must be 'f32' or 'bf16', got "
                             f"{precision!r}")
        return precision

    def _make_corr_sharded(self, with_null, stats_bf16):
        """shard_map'd raw-pair-sum program behind the XLA step variants.

        Yields corr (R, P, P) sharded over (real, psr) — plus the paired
        noise-only stream's corr when ``with_null`` (the OS lane's on-device
        null calibration; per-realization keys derive via the 0xD7 tag, so
        the null stream is as reproducible as the signal one and never names
        a mesh axis beyond the declared (real, psr, toa)).
        """
        has_toa = self._has_toa
        toa_shards = self._n_toa_shards

        def sharded(keys, batch, chol, gwb_w, det, samp_params, white_params,
                    white_toaerr2, white_bid, cgw_trel, cgw_pdist, cgw_bulks,
                    *roe):
            res = self._residuals(keys, batch, chol, gwb_w, det, samp_params,
                                  white_params, white_toaerr2, white_bid,
                                  cgw_trel, cgw_pdist, cgw_bulks, roe,
                                  toa_shards=toa_shards)
            corr = _correlation_rows(res, stats_bf16=stats_bf16,
                                     toa_psum=has_toa)
            if not with_null:
                return corr
            with obs.span("null"):
                nkeys = jax.vmap(
                    lambda k: jax.random.fold_in(k, _NULL_TAG))(keys)
                res0 = self._residuals(nkeys, batch, chol, gwb_w, det,
                                       samp_params, white_params,
                                       white_toaerr2, white_bid, cgw_trel,
                                       cgw_pdist, cgw_bulks, roe,
                                       toa_shards=toa_shards, null=True)
                corr0 = _correlation_rows(res0, stats_bf16=stats_bf16,
                                          toa_psum=has_toa)
            return corr, corr0

        out_spec = P(REAL_AXIS, PSR_AXIS)
        return shard_map(
            sharded, mesh=self.mesh,
            in_specs=(P(REAL_AXIS), *self._step_in_specs(has_toa)),
            out_specs=(out_spec, out_spec) if with_null else out_spec,
        )

    def _stat_lanes(self, corr):
        """Curve + auto lanes from a (R, P, P) raw pair-sum tensor.

        HIGHEST: these einsums lower to matmuls, and XLA's default TPU
        matmul rounds f32 operands to bf16 — a free-to-avoid ~4e-3
        relative error here (the binning is a trivial fraction of the
        program's FLOPs; the big corr contraction keeps the fast default).
        """
        hi = jax.lax.Precision.HIGHEST
        curves = jnp.einsum("rpq,pqn->rn", corr, self._w_bins, precision=hi)
        # mean autocorrelation (count-normalized trace / P)
        autos = jnp.einsum("rpq,pq->r", corr, self._w_auto, precision=hi)
        return curves, autos

    def _build_step(self, stats_bf16=False):
        shmapped = self._make_corr_sharded(False, stats_bf16)

        # ``scratch`` is the donated output-recycling buffer (the pipelined
        # run loop hands back a drained chunk's packed array): same shape,
        # dtype and sharding as the packed output, so XLA aliases the two and
        # the executable writes in place — one packed buffer per in-flight
        # chunk instead of one per dispatch. keep_unused keeps the (otherwise
        # dataflow-dead) parameter alive so the aliasing can attach; None
        # disables donation (the serial path and direct step calls).
        @partial(jax.jit, static_argnums=(2, 5), donate_argnums=(4,),
                 keep_unused=True)
        def step(base_key, offset, nreal, cgw_bulks, scratch,
                 with_corr=False):
            # trace-time only: the retrace guard (see _obs_note_trace)
            self._obs_note_trace(("step", nreal, with_corr, stats_bf16,
                                  scratch is not None,
                                  _lane_mode(offset)))
            # per-realization keys derived on device: one tiny transfer per chunk
            keys = _chunk_keys(base_key, offset, nreal)
            corr = shmapped(keys, self.batch, self._chol, self._gwb_w,
                            self._det, self._samp_params, self._white_params,
                            self._white_toaerr2, self._white_bid,
                            self._cgw_trel, self._pdist, cgw_bulks,
                            *self._roe_states)
            curves, autos = self._stat_lanes(corr)
            # with_corr=False drops the (nreal, P, P) tensor from the program
            # outputs entirely: it stays a fusible intermediate instead of a
            # forced 400 MB HBM output buffer at the flagship size
            packed = pack_stats(curves, autos)
            if with_corr:
                return packed, corr / self._counts_dev
            return packed

        return step

    def _get_step_xla(self, stats_bf16):
        step = self._step_xla_cache.get(bool(stats_bf16))
        if step is None:
            step = self._build_step(bool(stats_bf16))
            self._step_xla_cache[bool(stats_bf16)] = step
        return step

    def _build_step_os(self, with_null, stats_bf16=False):
        """XLA step with the OS lane: per-ORF amp2 packed beside curves/autos.

        ``w_os`` is the (K, P, P) stack of ``fakepta_tpu.detect`` operator
        weight matrices (host-f64 precompute cast to the batch dtype); each
        realization's optimal statistic is ONE extra einsum against the raw
        pair sums, so the (R, P, P) tensor stays a fusible intermediate — the
        detection workload inherits the engine's packed single-fetch contract
        instead of forcing ``keep_corr=True``. ``with_null`` adds the paired
        noise-only stream's lanes for on-device null calibration.
        """
        shmapped = self._make_corr_sharded(with_null, stats_bf16)

        # scratch: donated packed-output recycling buffer (see _build_step)
        @partial(jax.jit, static_argnums=(2, 6), donate_argnums=(5,),
                 keep_unused=True)
        def step(base_key, offset, nreal, w_os, cgw_bulks, scratch,
                 with_corr=False):
            # trace-time only: the retrace guard (see _obs_note_trace)
            # w_os.shape[0] is a static Python int at trace time
            self._obs_note_trace(("step_os", nreal, w_os.shape[0],
                                  with_null, with_corr, stats_bf16,
                                  scratch is not None,
                                  _lane_mode(offset)))
            keys = _chunk_keys(base_key, offset, nreal)
            out = shmapped(keys, self.batch, self._chol, self._gwb_w,
                           self._det, self._samp_params, self._white_params,
                           self._white_toaerr2, self._white_bid,
                           self._cgw_trel, self._pdist, cgw_bulks,
                           *self._roe_states)
            corr, corr0 = out if with_null else (out, None)
            curves, autos = self._stat_lanes(corr)
            hi = jax.lax.Precision.HIGHEST
            with obs.span("os"):
                extras = [jnp.einsum("rpq,kpq->rk", corr, w_os, precision=hi)]
                if with_null:
                    extras.append(jnp.einsum("rpq,kpq->rk", corr0, w_os,
                                             precision=hi))
            packed = pack_stats(curves, autos, *extras)
            if with_corr:
                return packed, corr / self._counts_dev
            return packed

        return step

    def _get_step_os(self, with_null, stats_bf16=False):
        key = (bool(with_null), bool(stats_bf16))
        step = self._step_os_cache.get(key)
        if step is None:
            step = self._build_step_os(*key)
            self._step_os_cache[key] = step
        return step

    def _build_step_fused(self):
        """The plain fused statistic path — the n_os=0 case of
        :meth:`_build_step_fused_os` (one builder, so the OS lanes cannot
        fork the kernel program)."""
        return self._build_step_fused_os(0, False, self._pallas_precision)

    def _build_step_fused_os(self, n_os, with_null, kernel_prec=None):
        if kernel_prec is None:
            kernel_prec = self._pallas_precision
        """Pallas statistic path: one kernel computes curves+autos (and any
        OS lanes) from residuals with the per-realization correlation block
        kept in VMEM (see :mod:`fakepta_tpu.ops.pallas_kernels`).

        The OS lanes ride the SAME kernel as ``n_os`` extra weight slots
        between the angular bins and the auto trace — the kernel contract is
        a plain weighted reduction per slot, so detection statistics are free
        once the correlation block is in VMEM. Under ``with_null`` the paired
        noise-only stream runs a second kernel invocation over its own
        residual blocks with the OS-only weight stack (plus a zero auto slot
        to keep the (n+1, P, P) weights contract).
        """
        from ..ops.pallas_kernels import binned_correlation, pick_rt

        if not hasattr(self, "_stat_weights"):
            # combined statistic weights, single-sourced from the XLA path's
            # normalization: slot n < nbins is onehot/(pair counts * bin
            # count); slot nbins is the normalized auto trace. (nbins+1, P, P)
            self._stat_weights = jnp.concatenate(
                [jnp.moveaxis(self._w_bins, 2, 0), self._w_auto[None]],
                axis=0)

        has_toa = self._has_toa   # size-1 only: toa_shards > 1 raises at init
        nbins = self.nbins
        nb_eff = nbins + n_os
        interpret = self._pallas_interpret

        def sharded(keys, batch, chol, gwb_w, weights, w_null, det,
                    samp_params, white_params, white_toaerr2, white_bid,
                    cgw_trel, cgw_pdist, cgw_bulks, *roe):
            res = self._residuals(keys, batch, chol, gwb_w, det, samp_params,
                                  white_params, white_toaerr2, white_bid,
                                  cgw_trel, cgw_pdist, cgw_bulks, roe,
                                  toa_shards=1)
            with obs.span("all_gather"):
                res_full = lax.all_gather(res, PSR_AXIS, axis=1, tiled=True)
            r_local = res.shape[0]
            # realization tile capped by the kernel's VMEM working set
            rt = pick_rt(r_local, res.shape[1], res_full.shape[1],
                         res.shape[2], nb_eff,
                         mxu_binning=self._pallas_mxu_binning)
            with obs.span("correlate"):
                curves_p, autos_p = binned_correlation(
                    res, res_full, weights, nbins=nb_eff, rt=rt,
                    interpret=interpret, precision=kernel_prec,
                    mxu_binning=self._pallas_mxu_binning)
                # the only other collective: reduce partial bin sums over
                # psr shards
                outs = [lax.psum(curves_p, PSR_AXIS),
                        lax.psum(autos_p, PSR_AXIS)]
            if with_null:
                with obs.span("null"):
                    nkeys = jax.vmap(
                        lambda k: jax.random.fold_in(k, _NULL_TAG))(keys)
                    res0 = self._residuals(nkeys, batch, chol, gwb_w, det,
                                           samp_params, white_params,
                                           white_toaerr2, white_bid,
                                           cgw_trel, cgw_pdist, cgw_bulks,
                                           roe, toa_shards=1, null=True)
                    res0_full = lax.all_gather(res0, PSR_AXIS, axis=1,
                                               tiled=True)
                    rt0 = pick_rt(r_local, res0.shape[1],
                                  res0_full.shape[1], res0.shape[2], n_os,
                                  mxu_binning=self._pallas_mxu_binning)
                    null_p, _ = binned_correlation(
                        res0, res0_full, w_null, nbins=n_os, rt=rt0,
                        interpret=interpret,
                        precision=kernel_prec,
                        mxu_binning=self._pallas_mxu_binning)
                    outs.append(lax.psum(null_p, PSR_AXIS))
            return tuple(outs)

        shmapped = shard_map(
            sharded, mesh=self.mesh,
            in_specs=(P(REAL_AXIS), *self._step_in_specs(has_toa)[:3],
                      P(None, PSR_AXIS, None), P(None, PSR_AXIS, None),
                      *self._step_in_specs(has_toa)[3:]),
            out_specs=tuple(P(REAL_AXIS)
                            for _ in range(2 + int(with_null))),
            # pallas_call does not annotate vma on its outputs; the psum above
            # makes the outputs replicated over 'psr' by construction
            check_vma=False,
        )

        # scratch: donated packed-output recycling buffer (see _build_step)
        @partial(jax.jit, static_argnums=(2,), donate_argnums=(5,),
                 keep_unused=True)
        def step(base_key, offset, nreal, w_os, cgw_bulks, scratch):
            # trace-time only: the retrace guard (see _obs_note_trace)
            self._obs_note_trace(("step_fused", nreal, n_os, with_null,
                                  kernel_prec, scratch is not None,
                                  _lane_mode(offset)))
            keys = _chunk_keys(base_key, offset, nreal)
            if n_os:
                weights = jnp.concatenate(
                    [self._stat_weights[:nbins], w_os,
                     self._stat_weights[nbins:]], axis=0)
                w_null = jnp.concatenate(
                    [w_os, jnp.zeros_like(w_os[:1])], axis=0)
            else:
                weights, w_null = self._stat_weights, w_os
            out = shmapped(keys, self.batch, self._chol, self._gwb_w,
                           weights, w_null, self._det, self._samp_params,
                           self._white_params, self._white_toaerr2,
                           self._white_bid, self._cgw_trel, self._pdist,
                           cgw_bulks, *self._roe_states)
            curves_ext, autos = out[0], out[1]
            extras = []
            if n_os:
                extras.append(curves_ext[:, nbins:])
            if with_null:
                extras.append(out[2])
            # same packed single-transfer contract as the XLA step
            return pack_stats(curves_ext[:, :nbins], autos, *extras)

        return step

    def _get_step_fused_os(self, n_os, with_null, kernel_prec=None):
        if kernel_prec is None:
            kernel_prec = self._pallas_precision
        key = (int(n_os), bool(with_null), str(kernel_prec))
        step = self._step_fused_os_cache.get(key)
        if step is None:
            step = (self._step_fused
                    if key == (0, False, self._pallas_precision)
                    and self._step_fused is not None
                    else self._build_step_fused_os(*key))
            self._step_fused_os_cache[key] = step
        return step

    def _build_mega_tables(self):
        """Static stage descriptors + staged time/scale tables for the
        whole-chunk megakernel (:mod:`fakepta_tpu.ops.megakernel`).

        Mirrors ``_simulate_block``'s GP stage order and basis-group
        dedup EXACTLY (red, dm, chrom, then one stage per distinct
        ``(idx, freqf, ncomp)`` GWB signature), so the kernel's
        recomputed bases line up element-for-element with the dense ones
        and the concatenated coefficient layout. Scale rows are the same
        dtype expressions the XLA path evaluates, masked to the valid
        TOAs (where the XLA path masks after projection, the kernel's
        bases vanish at the source — identical values either way).
        Returns ``(stages, stages_null, times (2, P, T), scales
        (S, P, T))``; the null stream's stages drop the GWB entries (its
        residuals carry no common signal, so its coefficient tensor is
        correspondingly narrower).
        """
        from ..ops.megakernel import T_COMMON, T_OWN, MegaStage

        batch = self.batch
        dtype = batch.t_own.dtype
        rows, row_idx = [], {}

        def scale_row(key, build):
            if key not in row_idx:
                row_idx[key] = len(rows)
                rows.append(jnp.where(batch.mask, build(), 0.0)
                            .astype(dtype))
            return row_idx[key]

        plain = scale_row(("plain",), lambda: jnp.ones((), dtype))
        stages = []
        (_, _, inc_red, inc_dm, inc_chrom, _, inc_gwb) = self._include
        if inc_red:
            stages.append(MegaStage(batch.red_psd.shape[1], T_OWN, plain))
        if inc_dm:
            stages.append(MegaStage(
                batch.dm_psd.shape[1], T_OWN,
                scale_row(("chrom", 2.0),
                          lambda: (1400.0 / batch.freqs) ** 2)))
        if inc_chrom:
            stages.append(MegaStage(
                batch.chrom_psd.shape[1], T_OWN,
                scale_row(("chrom", 4.0),
                          lambda: (1400.0 / batch.freqs) ** 4)))
        stages_null = tuple(stages)     # the 0xD7 stream has no GWB stage
        if inc_gwb:
            seen = set()
            for idx_j, freqf_j, w_j in zip(self._gwb_idx, self._gwb_freqf,
                                           self._gwb_w):
                sig = (idx_j, freqf_j, int(w_j.shape[0]))
                if sig in seen:
                    continue
                seen.add(sig)
                scol = plain if not idx_j else scale_row(
                    ("gwb", idx_j, freqf_j),
                    lambda f=freqf_j, i=idx_j: (f / batch.freqs) ** i)
                stages.append(MegaStage(sig[2], T_COMMON, scol))
        times = jnp.stack([batch.t_own, batch.t_common])
        return tuple(stages), stages_null, times, jnp.stack(rows)

    def _mega_stats(self, base, coefs, times_l, scales_l, weights,
                    stages_k, nb_k, store_bf16, shared):
        """One megakernel invocation inside a shard_map body (shared by the
        plain/OS/null and lnlike megakernel steps): optional bf16 base
        storage, the base/coefficient/table all_gathers when pulsars are
        sharded, the VMEM-model tile pick, and the kernel call itself.
        Returns batch-dtype (curves_p, autos_p) partial sums."""
        from ..ops.megakernel import chunk_stats, pick_rt_mega, stage_k

        dtype = self.batch.t_own.dtype
        base_bytes = 2 if store_bf16 else np.dtype(dtype).itemsize
        comp_bytes = max(4, np.dtype(dtype).itemsize) if store_bf16 \
            else np.dtype(dtype).itemsize
        kprec = "bf16" if store_bf16 else "f32"
        if store_bf16:
            # the bf16-STORAGE mode: the (R, P, T) base and the (R, P, K)
            # coefficients — the kernel's HBM reads — live in bfloat16;
            # everything downstream accumulates in f32 (policy:
            # analysis/policy.py BF16_STORAGE_MODULES)
            base = base.astype(jnp.bfloat16)
            coefs = coefs.astype(jnp.bfloat16)
        if shared:
            base_f, coef_f = base, coefs
            times_f, scales_f = times_l, scales_l
            base_l = coef_l = times_ll = scales_ll = None
        else:
            with obs.span("all_gather"):
                base_f = lax.all_gather(base, PSR_AXIS, axis=1, tiled=True)
                coef_f = lax.all_gather(coefs, PSR_AXIS, axis=1, tiled=True)
                times_f = lax.all_gather(times_l, PSR_AXIS, axis=1,
                                         tiled=True)
                scales_f = lax.all_gather(scales_l, PSR_AXIS, axis=1,
                                          tiled=True)
            base_l, coef_l = base, coefs
            times_ll, scales_ll = times_l, scales_l
        rt = pick_rt_mega(base.shape[0], base.shape[1], base_f.shape[1],
                          base.shape[2], stage_k(stages_k), nb_k, n_times=2,
                          n_scales=int(scales_l.shape[0]), shared=shared,
                          base_bytes=base_bytes, compute_bytes=comp_bytes)
        with obs.span("megakernel"):
            curves_p, autos_p = chunk_stats(
                base_l, base_f, coef_l, coef_f, times_ll, times_f,
                scales_ll, scales_f, weights, stages=stages_k, nbins=nb_k,
                rt=rt, interpret=self._pallas_interpret, precision=kprec)
        return curves_p.astype(dtype), autos_p.astype(dtype)

    def _build_step_mega(self, n_os, with_null, precision="f32"):
        """Whole-chunk megakernel step: residual assembly + correlation +
        binning fused into one Pallas program per chunk.

        XLA retains the draws, the hyperparameter sampling and the GP
        coefficient assembly (``_residuals(split_gp=True)``) — streams are
        byte-identical to every other path's — while the kernel recomputes
        the Fourier bases in VMEM and keeps the projected residuals and
        the correlation block on-chip (module docstring of
        :mod:`fakepta_tpu.ops.megakernel` has the byte accounting). OS
        lanes ride the same extra weight slots as the fused path; under
        ``with_null`` the paired noise-only stream runs a second kernel
        invocation with the GWB stage dropped from its descriptor.
        ``precision='bf16'`` stores the residual base (the kernel's
        dominant HBM read) in bfloat16 and runs bf16 correlation operands
        with f32 accumulation — the run-level bf16-storage mode.
        """
        if not hasattr(self, "_stat_weights"):
            self._stat_weights = jnp.concatenate(
                [jnp.moveaxis(self._w_bins, 2, 0), self._w_auto[None]],
                axis=0)
        if self._mega_tables is None:
            self._mega_tables = self._build_mega_tables()
        stages, stages_null, times, scales = self._mega_tables
        store_bf16 = precision == "bf16"
        shared = self.mesh.shape[PSR_AXIS] == 1
        has_toa = self._has_toa   # size-1 only: toa_shards > 1 raises at init
        nbins = self.nbins
        nb_eff = nbins + n_os

        def kernel_call(base, coefs, times_l, scales_l, weights, stages_k,
                        nb_k):
            return self._mega_stats(base, coefs, times_l, scales_l, weights,
                                    stages_k, nb_k, store_bf16, shared)

        def sharded(keys, batch, chol, gwb_w, times_l, scales_l, weights,
                    w_null, det, samp_params, white_params, white_toaerr2,
                    white_bid, cgw_trel, cgw_pdist, cgw_bulks, *roe):
            base, coefs, _ = self._residuals(
                keys, batch, chol, gwb_w, det, samp_params, white_params,
                white_toaerr2, white_bid, cgw_trel, cgw_pdist, cgw_bulks,
                roe, toa_shards=1, split_gp=True)
            curves_p, autos_p = kernel_call(base, coefs, times_l, scales_l,
                                            weights, stages, nb_eff)
            with obs.span("correlate"):
                outs = [lax.psum(curves_p, PSR_AXIS),
                        lax.psum(autos_p, PSR_AXIS)]
            if with_null:
                with obs.span("null"):
                    nkeys = jax.vmap(
                        lambda k: jax.random.fold_in(k, _NULL_TAG))(keys)
                    base0, coefs0, _ = self._residuals(
                        nkeys, batch, chol, gwb_w, det, samp_params,
                        white_params, white_toaerr2, white_bid, cgw_trel,
                        cgw_pdist, cgw_bulks, roe, toa_shards=1, null=True,
                        split_gp=True)
                    null_p, _ = kernel_call(base0, coefs0, times_l,
                                            scales_l, w_null, stages_null,
                                            n_os)
                    outs.append(lax.psum(null_p, PSR_AXIS))
            return tuple(outs)

        specs = self._step_in_specs(has_toa)
        table_spec = P(None, PSR_AXIS, None)
        shmapped = shard_map(
            sharded, mesh=self.mesh,
            in_specs=(P(REAL_AXIS), specs[0], specs[1], specs[2],
                      table_spec, table_spec, table_spec, table_spec,
                      *specs[3:]),
            out_specs=tuple(P(REAL_AXIS)
                            for _ in range(2 + int(with_null))),
            # pallas_call does not annotate vma on its outputs; the psum
            # above makes them replicated over 'psr' by construction
            check_vma=False,
        )

        # scratch: donated packed-output recycling buffer (see _build_step)
        @partial(jax.jit, static_argnums=(2,), donate_argnums=(5,),
                 keep_unused=True)
        def step(base_key, offset, nreal, w_os, cgw_bulks, scratch):
            # trace-time only: the retrace guard (see _obs_note_trace)
            self._obs_note_trace(("step_mega", nreal, n_os, with_null,
                                  precision, scratch is not None,
                                  _lane_mode(offset)))
            keys = _chunk_keys(base_key, offset, nreal)
            if n_os:
                weights = jnp.concatenate(
                    [self._stat_weights[:nbins], w_os,
                     self._stat_weights[nbins:]], axis=0)
                w_null = jnp.concatenate(
                    [w_os, jnp.zeros_like(w_os[:1])], axis=0)
            else:
                weights, w_null = self._stat_weights, w_os
            out = shmapped(keys, self.batch, self._chol, self._gwb_w,
                           times, scales, weights, w_null, self._det,
                           self._samp_params, self._white_params,
                           self._white_toaerr2, self._white_bid,
                           self._cgw_trel, self._pdist, cgw_bulks,
                           *self._roe_states)
            curves_ext, autos = out[0], out[1]
            extras = []
            if n_os:
                extras.append(curves_ext[:, nbins:])
            if with_null:
                extras.append(out[2])
            # same packed single-transfer contract as the XLA step
            return pack_stats(curves_ext[:, :nbins], autos, *extras)

        return step

    def _get_step_mega(self, n_os, with_null, precision="f32"):
        key = (int(n_os), bool(with_null), str(precision))
        step = self._step_mega_cache.get(key)
        if step is None:
            step = self._build_step_mega(*key)
            self._step_mega_cache[key] = step
        return step

    def _lnlike_lanes(self, res, batch, theta, compiled, mode):
        """(R_local, K*L) GP-marginalized likelihood lanes (shard_map body).

        The ``fakepta_tpu.infer`` lane: per-pulsar Woodbury moments are
        assembled from the residual blocks (``ops/woodbury.py``) — the
        residual-independent half (``T^T N^-1 T``, ``ln det N``) once per
        chunk program, the per-realization half (``T^T N^-1 r``, ``r^T N^-1
        r``) once per realization — then every theta point costs only a
        rank-2M Cholesky per pulsar plus batched triangular solves. All
        moment parts are plain TOA sums, so under time sharding they psum
        over 'toa' BEFORE the nonlinear ECORR corrections and the
        factorization — the lane is bit-meaningful on any (real, psr, toa)
        mesh. Local pulsar partial lnLs close with one psum over 'psr'.
        ``mode`` adds exact-gradient (jacrev) and Hessian (jacfwd∘jacrev)
        lanes; theta enters only through the prior diagonal ``phi``, so the
        data-side moments are shared by value, grad and Fisher lanes alike.
        """
        from ..ops import woodbury

        ecorr_on = self._include[1]
        num_ep = self.batch.max_toa if ecorr_on else 0
        pidx = lax.axis_index(PSR_AXIS)
        p_local = batch.t_own.shape[0]
        off = pidx * p_local
        with obs.span("lnlike_moments"):
            tmat = compiled.basis(batch)

            def fparts(t, s2, m, e, a):
                return woodbury.fixed_parts(t, s2, m, e, a,
                                            num_epochs=num_ep)

            def rparts(r, t, s2, m, e, a):
                return woodbury.res_parts(r, t, s2, m, e, a,
                                          num_epochs=num_ep)

            fixed = jax.vmap(fparts)(tmat, batch.sigma2, batch.mask,
                                     batch.epoch_idx, batch.ecorr_amp)
            resp = jax.vmap(lambda rr: jax.vmap(rparts)(
                rr, tmat, batch.sigma2, batch.mask, batch.epoch_idx,
                batch.ecorr_amp))(res)
            if self._has_toa:
                # every part is a plain sum over TOAs: close the time axis
                # here, then the (nonlinear) ECORR corrections and the
                # Cholesky run on replicated full-width moments
                fixed = jax.tree_util.tree_map(
                    lambda x: lax.psum(x, TOA_AXIS), fixed)
                resp = jax.tree_util.tree_map(
                    lambda x: lax.psum(x, TOA_AXIS), resp)
            M, lndetN, nv, corr = jax.vmap(woodbury.finish_fixed)(fixed)
            d0, dT = jax.vmap(lambda rp: jax.vmap(woodbury.finish_res)(
                rp, corr))(resp)
        moments = (M, lndetN, nv, d0, dT)
        with obs.span("lnlike"):
            def one_theta(th):
                if mode == "lnlike":
                    return compiled.lnl_local(th, moments, batch, off)[:, None]

                def f(t):
                    return compiled.lnl_local(t, moments, batch, off)

                val = f(th)
                grad = jax.jacrev(f)(th)                        # (R, D)
                if mode == "grad":
                    return jnp.concatenate([val[:, None], grad], axis=1)
                hess = jax.jacfwd(jax.jacrev(f))(th)            # (R, D, D)
                return jnp.concatenate(
                    [val[:, None], grad,
                     hess.reshape(val.shape[0], -1)], axis=1)

            lanes = jax.vmap(one_theta)(theta)                  # (K, R, L)
            lanes = jnp.moveaxis(lanes, 0, 1).reshape(res.shape[0], -1)
            lanes = lax.psum(lanes, PSR_AXIS)
        return lanes

    def _build_step_lnlike(self, compiled, mode, path, precision=None):
        """Step with the lnlike lane packed beside curves/autos.

        The XLA variant mirrors :meth:`_build_step_os` (the lanes are extra
        ``pack_stats`` slots, so checkpointing/resume carry them via the
        ``n_extra`` manifest unchanged); the fused variant runs the Pallas
        statistic kernel for curves/autos while the likelihood lanes are
        computed from the same residual blocks in the same program; the
        megakernel variant feeds the whole-chunk kernel from the split
        base/coefficient tensors while the Woodbury moments read an
        XLA-projected residual from the very same draws (one trace, no
        duplicate draw ops). ``precision`` is the per-run statistic
        precision: it moves the curves/autos contraction only — the
        likelihood moments always run at the batch dtype (the infer
        module is not on the bf16 storage policy, analysis/policy.py).
        """
        has_toa = self._has_toa
        toa_shards = self._n_toa_shards
        specs = self._step_in_specs(has_toa)
        precision = self._resolve_precision(path, precision)

        if path == "xla":
            stats_bf16 = precision == "bf16"
            def sharded(keys, batch, chol, gwb_w, theta, det, samp_params,
                        white_params, white_toaerr2, white_bid, cgw_trel,
                        cgw_pdist, cgw_bulks, *roe):
                res = self._residuals(keys, batch, chol, gwb_w, det,
                                      samp_params, white_params,
                                      white_toaerr2, white_bid, cgw_trel,
                                      cgw_pdist, cgw_bulks, roe,
                                      toa_shards=toa_shards)
                corr = _correlation_rows(res, stats_bf16=stats_bf16,
                                         toa_psum=has_toa)
                lanes = self._lnlike_lanes(res, batch, theta, compiled, mode)
                return corr, lanes

            shmapped = shard_map(
                sharded, mesh=self.mesh,
                in_specs=(P(REAL_AXIS), specs[0], specs[1], specs[2], P(),
                          *specs[3:]),
                out_specs=(P(REAL_AXIS, PSR_AXIS), P(REAL_AXIS)),
            )

            # scratch: donated packed-output recycling (see _build_step)
            @partial(jax.jit, static_argnums=(2, 6), donate_argnums=(5,),
                     keep_unused=True)
            def step(base_key, offset, nreal, theta, cgw_bulks, scratch,
                     with_corr=False):
                # trace-time only: the retrace guard (see _obs_note_trace)
                self._obs_note_trace(("step_lnlike", nreal, theta.shape,
                                      mode, with_corr, stats_bf16,
                                      scratch is not None,
                                      _lane_mode(offset)))
                keys = _chunk_keys(base_key, offset, nreal)
                corr, lanes = shmapped(
                    keys, self.batch, self._chol, self._gwb_w, theta,
                    self._det, self._samp_params, self._white_params,
                    self._white_toaerr2, self._white_bid, self._cgw_trel,
                    self._pdist, cgw_bulks, *self._roe_states)
                curves, autos = self._stat_lanes(corr)
                packed = pack_stats(curves, autos, lanes)
                if with_corr:
                    return packed, corr / self._counts_dev
                return packed

            return step

        if not hasattr(self, "_stat_weights"):
            self._stat_weights = jnp.concatenate(
                [jnp.moveaxis(self._w_bins, 2, 0), self._w_auto[None]],
                axis=0)
        nbins = self.nbins
        dtype = self.batch.t_own.dtype

        if path == "mega":
            if self._mega_tables is None:
                self._mega_tables = self._build_mega_tables()
            stages, _, times, scales = self._mega_tables
            store_bf16 = precision == "bf16"
            shared = self.mesh.shape[PSR_AXIS] == 1

            def sharded(keys, batch, chol, gwb_w, theta, times_l, scales_l,
                        weights, det, samp_params, white_params,
                        white_toaerr2, white_bid, cgw_trel, cgw_pdist,
                        cgw_bulks, *roe):
                base, coefs, basis = self._residuals(
                    keys, batch, chol, gwb_w, det, samp_params,
                    white_params, white_toaerr2, white_bid, cgw_trel,
                    cgw_pdist, cgw_bulks, roe, toa_shards=1, split_gp=True)
                # the Woodbury moments read a full residual: project the
                # SAME coefficients through the dense basis XLA-side (one
                # trace — base/coefs are shared with the kernel operands,
                # so no draw is ever duplicated); the statistic rides the
                # megakernel from the split tensors
                if basis is not None:
                    with obs.span("gp_project"):
                        proj = jnp.einsum("ptk,rpk->rpt", basis, coefs,
                                          preferred_element_type=dtype)
                    res = base + jnp.where(batch.mask, proj, 0.0)
                else:
                    res = base
                curves_p, autos_p = self._mega_stats(
                    base, coefs, times_l, scales_l, weights, stages, nbins,
                    store_bf16, shared)
                with obs.span("correlate"):
                    curves = lax.psum(curves_p, PSR_AXIS)
                    autos = lax.psum(autos_p, PSR_AXIS)
                lanes = self._lnlike_lanes(res, batch, theta, compiled,
                                           mode)
                return curves, autos, lanes

            table_spec = P(None, PSR_AXIS, None)
            shmapped = shard_map(
                sharded, mesh=self.mesh,
                in_specs=(P(REAL_AXIS), specs[0], specs[1], specs[2], P(),
                          table_spec, table_spec, table_spec, *specs[3:]),
                out_specs=(P(REAL_AXIS), P(REAL_AXIS), P(REAL_AXIS)),
                # pallas_call does not annotate vma on its outputs; the
                # psums above make them replicated over 'psr'
                check_vma=False,
            )

            # scratch: donated packed-output recycling (see _build_step)
            @partial(jax.jit, static_argnums=(2,), donate_argnums=(5,),
                     keep_unused=True)
            def step(base_key, offset, nreal, theta, cgw_bulks, scratch):
                # trace-time only: the retrace guard (see _obs_note_trace)
                self._obs_note_trace(("step_mega_lnlike", nreal,
                                      theta.shape, mode, precision,
                                      scratch is not None,
                                      _lane_mode(offset)))
                keys = _chunk_keys(base_key, offset, nreal)
                curves, autos, lanes = shmapped(
                    keys, self.batch, self._chol, self._gwb_w, theta,
                    times, scales, self._stat_weights, self._det,
                    self._samp_params, self._white_params,
                    self._white_toaerr2, self._white_bid, self._cgw_trel,
                    self._pdist, cgw_bulks, *self._roe_states)
                return pack_stats(curves, autos, lanes)

            return step

        from ..ops.pallas_kernels import binned_correlation, pick_rt

        kernel_prec = precision
        interpret = self._pallas_interpret

        def sharded(keys, batch, chol, gwb_w, theta, weights, det,
                    samp_params, white_params, white_toaerr2, white_bid,
                    cgw_trel, cgw_pdist, cgw_bulks, *roe):
            res = self._residuals(keys, batch, chol, gwb_w, det, samp_params,
                                  white_params, white_toaerr2, white_bid,
                                  cgw_trel, cgw_pdist, cgw_bulks, roe,
                                  toa_shards=1)
            with obs.span("all_gather"):
                res_full = lax.all_gather(res, PSR_AXIS, axis=1, tiled=True)
            rt = pick_rt(res.shape[0], res.shape[1], res_full.shape[1],
                         res.shape[2], nbins,
                         mxu_binning=self._pallas_mxu_binning)
            with obs.span("correlate"):
                curves_p, autos_p = binned_correlation(
                    res, res_full, weights, nbins=nbins, rt=rt,
                    interpret=interpret, precision=kernel_prec,
                    mxu_binning=self._pallas_mxu_binning)
                curves = lax.psum(curves_p, PSR_AXIS)
                autos = lax.psum(autos_p, PSR_AXIS)
            lanes = self._lnlike_lanes(res, batch, theta, compiled, mode)
            return curves, autos, lanes

        shmapped = shard_map(
            sharded, mesh=self.mesh,
            in_specs=(P(REAL_AXIS), specs[0], specs[1], specs[2], P(),
                      P(None, PSR_AXIS, None), *specs[3:]),
            out_specs=(P(REAL_AXIS), P(REAL_AXIS), P(REAL_AXIS)),
            # pallas_call does not annotate vma on its outputs; the psums
            # above make them replicated over 'psr' by construction
            check_vma=False,
        )

        # scratch: donated packed-output recycling buffer (see _build_step)
        @partial(jax.jit, static_argnums=(2,), donate_argnums=(5,),
                 keep_unused=True)
        def step(base_key, offset, nreal, theta, cgw_bulks, scratch):
            # trace-time only: the retrace guard (see _obs_note_trace)
            self._obs_note_trace(("step_fused_lnlike", nreal, theta.shape,
                                  mode, kernel_prec,
                                  scratch is not None,
                                  _lane_mode(offset)))
            keys = _chunk_keys(base_key, offset, nreal)
            curves, autos, lanes = shmapped(
                keys, self.batch, self._chol, self._gwb_w, theta,
                self._stat_weights, self._det, self._samp_params,
                self._white_params, self._white_toaerr2, self._white_bid,
                self._cgw_trel, self._pdist, cgw_bulks, *self._roe_states)
            return pack_stats(curves, autos, lanes)

        return step

    def _get_step_lnlike(self, model, mode, path, compiled, precision=None):
        resolved = self._resolve_precision(path, precision)
        key = (model, str(mode), str(path), resolved)
        step = self._step_lnlike_cache.get(key)
        if step is None:
            step = self._build_step_lnlike(compiled, mode, path, resolved)
            self._step_lnlike_cache[key] = step
        return step

    def _prepare_lanes(self, os, lnlike) -> dict:
        """Resolve the optional packed statistic lanes a run carries.

        The OS lane's host-f64 operator precompute (:mod:`fakepta_tpu
        .detect.operators`) and the lnlike lane's compiled model
        (:mod:`fakepta_tpu.infer.model`) — shared by :meth:`run` and
        :meth:`warm_start` so the two select the identical step executable.
        """
        lanes = dict(os_spec=None, os_ops=None, w_os=None, n_os=0,
                     lnl_spec=None, lnl_compiled=None, lnl_theta=None,
                     lnl_k=0, lnl_l=0, n_extra=0)
        if lnlike is not None:
            if os is not None:
                raise ValueError(
                    "run(os=..., lnlike=...) cannot combine the detection "
                    "and likelihood lanes in one run (one packed-extras "
                    "layout per run); run them separately")
            from ..infer import model as infer_model
            lnl_spec = infer_model.as_spec(lnlike)
            lnl_compiled = self._lnlike_compiled_cache.get(lnl_spec.model)
            if lnl_compiled is None:
                lnl_compiled = infer_model.build(lnl_spec.model, self.batch)
                self._lnlike_compiled_cache[lnl_spec.model] = lnl_compiled
            theta_host = lnl_compiled.validate_theta(lnl_spec.theta)
            lanes["lnl_spec"] = lnl_spec
            lanes["lnl_compiled"] = lnl_compiled
            lanes["lnl_theta"] = jnp.asarray(theta_host,
                                             self.batch.t_own.dtype)
            lanes["lnl_k"] = theta_host.shape[0]
            lanes["lnl_l"] = infer_model.lanes_per_point(lnl_spec.mode,
                                                         lnl_compiled.D)
            lanes["n_extra"] = lanes["lnl_k"] * lanes["lnl_l"]
        if os is not None:
            from ..detect import operators as detect_ops
            os_spec = detect_ops.as_spec(os)
            os_ops = detect_ops.build_operators(
                os_spec, self._pos64, np.asarray(self.batch.mask),
                np.asarray(self.batch.sigma2), pair_counts=self.pair_counts)
            lanes["os_spec"] = os_spec
            lanes["os_ops"] = os_ops
            lanes["w_os"] = jnp.asarray(
                np.stack([op.weights for op in os_ops]),
                self.batch.t_own.dtype)
            lanes["n_os"] = len(os_ops)
            lanes["n_extra"] = lanes["n_os"] * (2 if os_spec.null else 1)
        return lanes

    def _exec_plan(self, lane_cfg: dict, path: str, prec: str, precision,
                   keep_corr: bool):
        """Bind ONE chunk dispatch's step executable and argument layout.

        The single source of the step-selection ladder, shared by
        :meth:`run`'s dispatch loop, :meth:`warm_start`, and the serve warm
        pool (:mod:`fakepta_tpu.serve`) — all three MUST select the
        identical executable, so an AOT warm start (or a pool bucket
        prewarm) populates the exact persistent-compile-cache entry the
        later dispatch loads instead of recompiling. Returns ``(invoke,
        lower, sig)``: ``invoke(base, offset, nreal, bulks, scratch) ->
        (packed, corr_or_None)``; ``lower`` the matching ``Lowered``
        factory for AOT compilation; ``sig`` a stable hashable signature of
        the selected executable (the warm pool's bookkeeping key).
        """
        stats_bf16 = prec == "bf16"
        if lane_cfg["lnl_compiled"] is not None:
            spec = lane_cfg["lnl_spec"]
            step = self._get_step_lnlike(spec.model, spec.mode, path,
                                         lane_cfg["lnl_compiled"], precision)
            theta = lane_cfg["lnl_theta"]
            if path != "xla":
                def args(b, o, n, bulks, scratch):
                    return (b, o, n, theta, bulks, scratch)
                paired = False
            else:
                def args(b, o, n, bulks, scratch):
                    return (b, o, n, theta, bulks, scratch, keep_corr)
                paired = keep_corr
            sig = ("lnlike", spec.mode, lane_cfg["lnl_k"], lane_cfg["lnl_l"],
                   path, prec, keep_corr)
        elif lane_cfg["os_ops"] is not None:
            null = lane_cfg["os_spec"].null
            w_os = lane_cfg["w_os"]
            if path == "mega":
                step = self._get_step_mega(lane_cfg["n_os"], null, prec)
            elif path == "fused":
                step = self._get_step_fused_os(lane_cfg["n_os"], null, prec)
            else:
                step = self._get_step_os(null, stats_bf16)
            if path == "xla":
                def args(b, o, n, bulks, scratch):
                    return (b, o, n, w_os, bulks, scratch, keep_corr)
                paired = keep_corr
            else:
                def args(b, o, n, bulks, scratch):
                    return (b, o, n, w_os, bulks, scratch)
                paired = False
            sig = ("os", tuple(lane_cfg["os_spec"].orfs), bool(null), path,
                   prec, keep_corr)
        else:
            if path == "mega":
                step = self._get_step_mega(0, False, prec)
            elif path == "fused":
                step = self._get_step_fused_os(0, False, prec)
            else:
                step = self._get_step_xla(stats_bf16)
            if path == "xla":
                def args(b, o, n, bulks, scratch):
                    return (b, o, n, bulks, scratch, keep_corr)
                paired = keep_corr
            else:
                w_os = self._w_os_empty

                def args(b, o, n, bulks, scratch):
                    return (b, o, n, w_os, bulks, scratch)
                paired = False
            sig = ("plain", path, prec, keep_corr)

        def invoke(b, o, n, bulks, scratch):
            out = step(*args(b, o, n, bulks, scratch))
            return out if paired else (out, None)

        def lower(b, o, n, bulks, scratch):
            return step.lower(*args(b, o, n, bulks, scratch))

        return invoke, lower, sig

    def _normalize_chunk(self, chunk: int, nreal: int) -> int:
        """Clamp the chunk size to the realization-shard contract."""
        chunk = int(min(chunk, nreal))
        chunk -= chunk % self._n_real_shards
        return max(chunk, self._n_real_shards)

    def _drain_chunk(self, packed, corr, rec, packed_out, slot, corr_out,
                     ckpt, seed, nreal, chunk, done, progress, nb, n_extra,
                     materialize, ev=None, t_run0=None, timeline=None,
                     retries=0, backoff_s=0.05, on_retry=None):
        """Host-side completion work for ONE dispatched chunk.

        Runs on the pipeline's writer thread (pipelined runs) or inline at
        submit (the serial fallback), in the serial loop's exact order:
        materialize outputs -> append the checkpoint chunk (process 0 only)
        -> invoke the progress callback. ``materialize`` forces the packed
        lanes onto the host: ``"donatable"`` (the pipelined loop) copies
        shard-by-shard via :func:`pipeline.materialize_copy` so the device
        buffer stays consumable by donation when it is recycled as a later
        dispatch's scratch (a plain ``np.asarray`` leaves jax's cached
        zero-copy host view pinning the buffer on the CPU backend — the
        donation then silently degrades to a copy); truthy-but-not-
        ``"donatable"`` (the serial checkpoint path, which never donates)
        keeps the ``np.array(to_host(...))`` copy that is also
        multi-process-safe. ``rec['ckpt_wait_s']`` records
        the checkpoint append (inline in the chunk wall on the serial path;
        overlapped with device compute when pipelined). ``ev`` (pipelined
        only) signals the dispatch loop that this chunk's buffers are free
        to recycle — set even on failure so the loop cannot deadlock.

        ``t_run0``/``timeline`` feed the run-timeline trace (obs.tracefmt):
        the drain span (writer lane) with its nested checkpoint append, and
        the chunk's *execute* span — dispatch start to outputs
        materialized, the device-side residency the Perfetto view shows
        overlapping the next chunk's dispatch. List appends and float
        subtraction only: microseconds per chunk against multi-ms drains.
        """
        idx = rec.get("idx", slot)
        t_d0 = obs.now()
        t_ready = None

        def body():
            # transient failures in here retry IN PLACE (bounded backoff,
            # run_drain_with_retry below) — crucially BEFORE the finally
            # sets ``ev``, so the dispatch loop can never donate this
            # chunk's buffer out from under a retrying materialize. Drains
            # are idempotent: fixed slot, same checkpoint chunk file, same
            # progress counts.
            nonlocal t_ready
            # chaos site: the writer-thread drain (docs/RELIABILITY.md);
            # a 'hang' here sleeps long enough for the dispatch loop's
            # watchdog to catch it
            faults_mod.check("pipeline.writer", idx=idx)
            if materialize == "donatable":
                # pipelined path: the device buffer is recycled as a later
                # dispatch's donated scratch, so the copy must not leave
                # jax's cached host view pinning it (materialize_copy;
                # found by the memwatch donation check)
                arr = pipeline_mod.materialize_copy(packed)
                packed_out[slot] = arr
                t_ready = obs.now()
            elif materialize:
                arr = np.array(to_host(packed))
                packed_out[slot] = arr
                t_ready = obs.now()
            else:
                arr = None
                packed_out[slot] = packed
            if corr_out is not None:
                corr_out[slot] = to_host(corr)
                t_ready = obs.now()
            if arr is not None and not np.isfinite(arr[:, :nb + 1]).all():
                # poisoned output (an injected NaN, a genuinely non-finite
                # kernel): fail LOUDLY before the checkpoint can absorb it
                # — the run aborts with a flight-recorder dump, never a
                # silently corrupt statistic (docs/RELIABILITY.md)
                obs.flightrec.note("poisoned_chunk", idx=idx)
                raise FloatingPointError(
                    f"chunk {idx} produced non-finite packed statistics "
                    f"(poisoned output); aborting — see the flight-"
                    f"recorder dump")
            if ckpt is not None and jax.process_index() == 0:
                # append-only: each save writes this chunk's arrays,
                # O(chunk) I/O. Only process 0 writes — to_host replicates
                # outputs to every host, and concurrent renames of the same
                # checkpoint files from N processes would race on shared
                # storage.
                if arr is None:
                    arr = to_host(packed)
                    packed_out[slot] = arr
                    t_ready = obs.now()
                t_ck = obs.now()
                c_chunk, a_chunk = unpack_stats(arr, nb)
                ckpt.save(seed, nreal, chunk, done, c_chunk, a_chunk,
                          corr_out[slot] if corr_out is not None else None,
                          extra=(arr[:, nb + 1:] if n_extra else None))
                t_now = obs.now()
                rec["ckpt_wait_s"] = t_now - t_ck
                if timeline is not None:
                    timeline.append({"name": "ckpt_append", "tid": "writer",
                                     "t0": t_ck - t_run0,
                                     "dur": t_now - t_ck, "chunk": idx})
            if progress is not None:
                if arr is None:
                    jax.block_until_ready(packed)  # completion, not dispatch
                    t_ready = obs.now()
                progress(min(done, nreal), nreal)
            obs.flightrec.note("chunk_drained", idx=idx)

        try:
            pipeline_mod.run_drain_with_retry(body, retries, backoff_s,
                                              on_retry=on_retry)
        finally:
            if timeline is not None:
                t_end = obs.now()
                if t_ready is not None and "t0_s" in rec:
                    # outputs-materialized is the completion evidence for
                    # the chunk's device execution (the materialize blocks
                    # on the async d2h copy, which blocks on compute)
                    rec["t_ready_s"] = t_ready - t_run0
                    timeline.append(
                        {"name": "execute", "tid": "device",
                         "t0": rec["t0_s"],
                         "dur": max(t_ready - t_run0 - rec["t0_s"], 0.0),
                         "chunk": idx})
                timeline.append({"name": "drain", "tid": "writer",
                                 "t0": t_d0 - t_run0,
                                 "dur": t_end - t_d0, "chunk": idx})
            if ev is not None:
                ev.set()

    def dispatch_surface(self) -> dict:
        """The problem-shaped identity and model inputs of this
        simulator's chunk programs — what the autotuner keys on and feeds
        its analytic models (:mod:`fakepta_tpu.tune`, docs/TUNING.md).

        Deliberately knob-free: pulsar/TOA/bin counts, the concatenated GP
        coefficient width (``k_coef`` — the megakernel stage table's
        ``stage_k``, the same width :func:`~fakepta_tpu.ops.megakernel
        .chunk_bytes_model` prices), and the batch dtype. Two simulators
        with equal surfaces share one ``TunedConfig`` family regardless of
        mesh, path or precision.
        """
        from ..ops.megakernel import stage_k

        if self._mega_tables is None:
            self._mega_tables = self._build_mega_tables()
        dt = np.dtype(self.batch.t_own.dtype)
        return {"npsr": int(self.batch.npsr),
                "max_toa": int(self.batch.max_toa),
                "nbins": int(self.nbins),
                "k_coef": int(stage_k(self._mega_tables[0])),
                "dtype": dt.name,
                "dtype_bytes": int(dt.itemsize)}

    def model_bytes_per_chunk(self, chunk: int, path=None,
                              precision=None) -> int:
        """Analytic HBM bytes of one chunk program, WITHOUT any lowering
        or compile — the model-first half of :meth:`chunk_cost` (whose AOT
        capture also measures; the autotuner prunes candidates with this
        before paying any compile). Single-sourced with the cost capture
        through :func:`~fakepta_tpu.ops.megakernel.chunk_bytes_model`."""
        from ..ops.megakernel import chunk_bytes_model

        surf = self.dispatch_surface()
        path = path or self._stat_path
        prec = self._resolve_precision(path, precision)
        mode = {"xla": "xla", "fused": "fused"}.get(
            path, "mega_bf16" if prec == "bf16" else "mega")
        return chunk_bytes_model(
            self._normalize_chunk(chunk, chunk), surf["npsr"],
            surf["max_toa"], surf["k_coef"], mode=mode,
            psr_shards=int(self.mesh.shape[PSR_AXIS]),
            dtype_bytes=surf["dtype_bytes"])

    def chunk_cost(self, chunk: int, *, os=None, lnlike=None,
                   keep_corr: bool = False, precision=None) -> dict:
        """XLA cost analysis of ONE chunk program, without executing it.

        Returns the ``{flops_per_chunk, bytes_per_chunk,
        static_reservation_bytes}`` dict the RunReport's one-time capture
        records (empty where the backend exposes no cost model). This is
        the public handle the benchmarks use to record per-mode
        (xla / fused / fused_bf16) bytes-per-chunk rows without paying a
        measured run per mode — the roofline acceptance is a compile-time
        artifact. Cached per (chunk, path, precision, lane) signature like
        the in-run capture.
        """
        chunk = self._normalize_chunk(chunk, chunk)
        lanes = self._prepare_lanes(os, lnlike)
        path = "xla" if keep_corr else self._stat_path
        prec = self._resolve_precision(path, precision)
        base = rng_utils.as_key(0)
        lnl = None
        if lanes["lnl_compiled"] is not None:
            step = self._get_step_lnlike(
                lanes["lnl_spec"].model, lanes["lnl_spec"].mode, path,
                lanes["lnl_compiled"], precision)
            lnl = (step, lanes["lnl_theta"],
                   (lanes["lnl_k"], lanes["lnl_l"], lanes["lnl_spec"].mode))
        return dict(self._obs_capture_cost(
            base, chunk, path, prec, w_os=lanes["w_os"],
            with_null=bool(lanes["os_spec"].null) if lanes["os_spec"]
            else False, lnl=lnl))

    def warm_start(self, chunk: int, *, keep_corr: bool = False, os=None,
                   lnlike=None, precision=None, lane_keys: bool = False,
                   ) -> float:
        """AOT-compile the chunk program ahead of the first :meth:`run`.

        Lowers and compiles the exact step executable ``run(chunk=chunk,
        ...)`` would dispatch for this lane configuration (same shapes,
        same donated-scratch aliasing), without executing it. With the
        persistent compile cache wired (``compile_cache_dir=`` /
        ``FAKEPTA_TPU_COMPILE_CACHE``), the executable lands in the on-disk
        cache, so the first run() chunk — in this process and in every other
        process or later round sharing the cache dir — loads it instead of
        recompiling, and the obs-measured ``compile_s`` amortizes instead of
        being paid per process. Returns the wall seconds spent.

        ``lane_keys=True`` compiles the *serve-key* variant of the same
        program — per-slot ``(request seed, within-request index)`` vectors
        instead of one ``(base key, offset)`` pair (see :func:`_chunk_keys`
        and ``run(lanes=...)``). The serve warm pool prewarms its bucket
        ladder through exactly this call, so a pool bucket and a manual
        ``warm_start(bucket, lane_keys=True)`` of the same spec hit the
        same compile-cache entry by construction (the step selection is
        single-sourced in :meth:`_exec_plan`).
        """
        t0 = obs.now()
        chunk = self._normalize_chunk(chunk, chunk)
        lane_cfg = self._prepare_lanes(os, lnlike)
        path = "xla" if keep_corr else self._stat_path
        prec = self._resolve_precision(path, precision)
        dtype = self.batch.t_own.dtype
        n_lanes = self.nbins + 1 + lane_cfg["n_extra"]
        bulks = tuple(jax.ShapeDtypeStruct((chunk, self.batch.npsr), dtype)
                      for _ in self._cgw_psrterm)
        scratch = jax.ShapeDtypeStruct(
            (chunk, n_lanes), dtype,
            sharding=NamedSharding(self.mesh, P(REAL_AXIS)))
        if lane_keys:
            base = jnp.zeros((chunk,), jnp.int32)
            offset = jnp.zeros((chunk,), jnp.int32)
        else:
            base = rng_utils.as_key(0)
            offset = 0
        prev = self._obs_in_capture
        self._obs_in_capture = True     # an AOT lower is not a user retrace
        try:
            _, lower, _ = self._exec_plan(lane_cfg, path, prec, precision,
                                          keep_corr)
            lower(base, offset, chunk, bulks, scratch).compile()
        finally:
            self._obs_in_capture = prev
        return obs.now() - t0

    def clear_executables(self) -> None:
        """Drop every compiled/traced step executable (and the cost-capture
        cache) and rebuild the defaults.

        The recovery hook for a *poisoned executable* (docs/RELIABILITY.md):
        the serve warm pool calls this when a dispatch returns non-finite
        statistics from a simulator it cannot evict wholesale (registered
        multi-tenant entries own their simulator's lifecycle) — the next
        dispatch re-traces and recompiles from clean state. Host-staged
        data (batch arrays, operators, deterministic delays) is untouched:
        it is input, not executable state.
        """
        for cache in (self._step_xla_cache, self._step_os_cache,
                      self._step_fused_os_cache, self._step_lnlike_cache,
                      self._step_mega_cache, self._obs_cost,
                      self._obs_trace_counts):
            cache.clear()
        self._step = self._build_step(self._stats_bf16)
        self._step_xla_cache[self._stats_bf16] = self._step
        self._step_fused = (self._build_step_fused()
                            if self._stat_path == "fused" else None)
        self._step_mega = (self._get_step_mega(0, False, "f32")
                           if self._stat_path == "mega" else None)
        obs.flightrec.note("executables_cleared")

    def run(self, nreal: int, seed=0, chunk=None, keep_corr: bool = False,
            checkpoint=None, progress=None, os=None, lnlike=None,
            pipeline_depth=None, precision=None, eventlog=None,
            lanes=None, recovery=None, tuned=None):
        """Run the ensemble in device-memory-bounded chunks.

        ``chunk`` and ``pipeline_depth`` default to the hand-set knob
        values in :mod:`fakepta_tpu.tune.defaults` (1024 / 2); ``None``
        means "not set by the caller", which is what lets a tuned run
        distinguish an explicit override from a default to replace.

        ``tuned``: consume the platform-aware autotuner
        (:mod:`fakepta_tpu.tune`, docs/TUNING.md). ``True`` resolves the
        persisted :class:`~fakepta_tpu.tune.TunedConfig` for this
        platform fingerprint x spec family (one store read — zero probes,
        zero extra compiles); a :class:`~fakepta_tpu.tune.TunedConfig` or
        a plain knob dict applies directly (the tuner's own probes run
        through exactly this path). Tuned knobs fill only the knobs the
        caller left unset (``chunk`` / ``pipeline_depth`` /
        ``precision``) plus the statistic path where legal (never under
        ``keep_corr`` or TOA sharding; a mesh-split knob cannot apply to
        an already-built simulator and is noted, not forced). The applied
        knobs are recorded in ``RunReport.meta["tuned"]`` so ``obs
        compare``/``gate`` can attribute wins to the tuner.

        ``lanes``: per-request RNG lanes (the :mod:`fakepta_tpu.serve`
        coalescing contract) — a sequence of ``(seed, n)`` pairs laid out in
        slot order. Slot ``i`` of lane ``(s, n)`` draws from
        ``fold_in(key(s), i)``, the exact key ``run(n, seed=s)`` gives its
        realization ``i``, so each lane's results are bit-identical to its
        own solo run regardless of which batchmates, bucket padding, or
        mesh shape it was coalesced with. Slots past the last lane are
        padding (discarded by the caller). ``seed`` is ignored for key
        derivation on a lane run; checkpointing and psrterm CGW sampling
        (whose host-f64 bulk staging replays the scalar base-key chain) are
        unsupported with lanes.

        Returns a dict with per-realization binned curves ``(nreal, nbins)``,
        mean autocorrelations ``(nreal,)``, bin centers and (optionally) the raw
        pair-correlation matrices.

        ``os``: enable the on-device optimal-statistic lane — an ORF name
        (``'hd'``/``'monopole'``/``'dipole'``), a sequence of them, or a
        :class:`fakepta_tpu.detect.OSSpec` (noise weighting, per-pulsar
        sigma2 override, paired null-stream calibration). Each realization's
        noise-weighted amp2 is computed INSIDE the jitted chunk program from
        the raw pair sums and packed beside curves/autos, so detection
        studies no longer need ``keep_corr=True`` or any (R, P, P) fetch.
        Results land under ``out["os"]`` (schema ``fakepta_tpu.detect/1``):
        per ORF ``amp2`` (nreal,), ``sigma`` (empirical from the paired null
        stream when ``OSSpec(null=True)``, else the analytic white-noise
        value), ``snr``, and — under null calibration — ``null_amp2``, null
        quantiles and per-realization ``p_value``. Legal alongside the fused
        Pallas path (the OS lanes ride the kernel's weight slots) and under
        any (real, psr, toa) sharding; see docs/DETECTION.md.

        ``lnlike``: enable the on-device GP-marginalized likelihood lane —
        an :class:`fakepta_tpu.infer.InferSpec` (a declarative
        :class:`~fakepta_tpu.infer.LikelihoodSpec` plus a (K, D)
        hyperparameter batch and a mode). Each realization's Woodbury lnL
        (and, per mode, exact gradient / Hessian lanes) is evaluated at
        every theta point INSIDE the jitted chunk program and packed beside
        curves/autos — no residual fetch, no host sampler. Results land
        under ``out["lnlike"]`` (schema ``fakepta_tpu.infer/1``): ``lnl``
        (nreal, K) and per mode ``grad`` (nreal, K, D) / ``fisher``
        (nreal, K, D, D). Legal under any (real, psr, toa) sharding and
        beside the fused Pallas statistic path; mutually exclusive with
        ``os`` (one packed-extras layout per run); see docs/INFERENCE.md.

        ``checkpoint``: a path — after every chunk the run appends that chunk's
        outputs to a sibling ``<path>.c<k>.npz`` file and updates a small
        manifest at ``<path>`` (move/copy the whole family to relocate a
        checkpoint). If a matching manifest for the same (seed, nreal, chunk)
        exists, the run resumes after the last completed chunk. Because
        per-realization keys are ``fold_in(base_key, absolute_index)``, the
        resumed stream is identical to an uninterrupted run. All files are
        removed on successful completion.

        ``progress``: callable ``(done, nreal) -> None`` invoked after each chunk
        (the reference's observability is print statements; this is the hook for
        logging/metrics without baking a sink in).

        ``pipeline_depth``: how many dispatched chunks may be in flight
        before the loop waits for the oldest one's host drain (default 2 —
        one chunk computing while the previous drains). Under the pipeline
        the per-chunk host work overlaps device compute: the next chunk's
        CGW bulks precompute while this one runs, checkpoint appends and
        progress callbacks drain on a single background writer thread
        (order and append-only/process-0 semantics unchanged), packed
        outputs stream back via ``copy_to_host_async``, and each drained
        chunk's packed buffer is recycled as the donated scratch of a later
        dispatch (``donate_argnums``), so peak HBM holds ``depth`` packed
        buffers regardless of the chunk count. ``pipeline_depth=0`` is the
        serial fallback (the pre-pipeline loop, one sync per chunk when
        checkpointing); multi-process runs always take it, because a
        background thread issuing ``process_allgather`` collectives could
        reorder collective launches across processes. Realization streams
        are bit-identical at every depth. See docs/PERFORMANCE.md.

        ``precision``: the per-run statistic precision mode — ``None``
        (each path's constructor default), ``'f32'``, or ``'bf16'``. Under
        ``'bf16'`` the statistic contraction runs bf16 *operands* with f32
        accumulation on every path, and the megakernel path additionally
        stores its (R, P, T) residual base in bfloat16 — the bf16-STORAGE
        mode that halves the chunk program's dominant HBM read
        (docs/PERFORMANCE.md has the per-mode byte table). Realization
        draws and the likelihood lane's Woodbury moments always stay at
        the batch dtype: which modules may down-cast is governed by the
        ``analysis`` dtype policy (``BF16_STORAGE_MODULES``,
        docs/INVARIANTS.md), and bf16 streams are certified against the
        engine's mesh-invariance tolerances in tests/test_megakernel.py.

        Every run attaches a :class:`fakepta_tpu.obs.RunReport` under
        ``out["report"]`` (also ``self.last_report``): stage spans, per-chunk
        wall times (``synced`` marks chunks whose wall time included a device
        sync — serial checkpoint/progress runs; pipelined chunk walls are
        dispatch times and ``total_s`` is the device-synced end-to-end
        figure), per-chunk ``stall_s`` (dispatch waited on host work:
        first-chunk staging, depth-bound waits) and ``ckpt_wait_s`` (the
        checkpoint append — inside the chunk wall on the serial path,
        overlapped on the writer thread when pipelined), the
        compile-vs-steady split from the ``jax.monitoring`` bridge, the
        retrace-guard count, one-time XLA cost analysis of the chunk program,
        and device-memory stats where the backend exposes them. All hooks are
        zero-overhead in steady state: nothing is read inside the jitted
        program, only at the chunk boundaries the engine already touches.

        The report also carries the run **timeline** (per-chunk dispatch /
        execute / drain spans across the dispatch and writer threads —
        export with ``python -m fakepta_tpu.obs trace``, view in Perfetto)
        and the HBM watermark (``memory["peak_hbm_bytes"]``: allocator peak
        max-aggregated over local devices via a low-rate background
        sampler where the backend exposes stats, else the packed-buffer
        model). On pipelined runs the engine *asserts* the donated-ring
        memory bound at runtime — at most ``depth`` live packed buffers,
        every recycled scratch consumed by donation — and raises if the
        evidence ever disagrees (obs.memwatch, docs/PERFORMANCE.md). A run
        that dies records its tail in the always-on crash flight recorder
        and dumps it beside the checkpoint (``flightrec-<ts>-p*.json``,
        readable by ``obs summarize``; obs.flightrec).

        ``eventlog``: a directory — after the run each process writes its
        report there as ``events-p<process_index>.jsonl``. On a
        multi-process run this yields one per-host shard per process;
        merge them into a single Perfetto timeline with
        ``python -m fakepta_tpu.obs trace <dir>/events-p*.jsonl -o
        trace.json`` (one pid lane per host).

        ``recovery``: the engine-wide recovery policy
        (:class:`fakepta_tpu.faults.RecoveryPolicy`; ``None`` = defaults,
        ``False`` = disabled — every failure propagates unchanged).
        Transient chunk dispatch/drain failures retry with bounded
        exponential backoff, re-dispatching the same RNG lanes — the
        retried chunk is bit-identical to the unfaulted run. A Pallas
        compile/runtime failure degrades the statistic path (``mega ->
        fused -> xla``), a bf16 certification failure degrades to f32, and
        a broken donated-buffer recycle turns donation off for the rest of
        the run — each degradation counted (``faults.degradations``),
        flight-recorded and visible in the timeline; degraded chunks
        certify at the engine's mesh-invariance tolerance because the
        executable shape changed. ``RecoveryPolicy(watchdog_s=...)`` arms
        a per-chunk deadline on the oldest in-flight drain (pipelined runs)
        that dumps the flight recorder and aborts instead of hanging
        forever. Non-finite packed statistics abort loudly before any
        checkpoint write; torn checkpoint files detected at resume roll
        back to the last good chunk (``faults.rollbacks``). See
        docs/RELIABILITY.md.
        """
        t_run0 = obs.now()
        obs.subscribe_jax_monitoring()
        collector = obs.Collector()
        retraces_before = self._obs_retraces
        chunk_records = []
        base = rng_utils.as_key(seed)

        # tuned-knob resolution (fakepta_tpu.tune, docs/TUNING.md): fill
        # the knobs the caller left unset from the store / given config,
        # then fall back to the hand-set defaults — all before anything
        # reads them
        tuned_applied = None
        tuned_path = None
        if tuned:
            knobs = None
            if isinstance(tuned, dict):
                knobs = dict(tuned)
            elif hasattr(tuned, "knobs"):
                knobs = dict(tuned.knobs)
            else:
                from .. import tune as tune_mod
                cfg_t = tune_mod.resolve_for_sim(self)
                if cfg_t is not None:
                    knobs = dict(cfg_t.knobs)
                else:
                    # a miss is information, not an error: the run
                    # proceeds on hand-set defaults, diagnosably
                    obs.flightrec.note("tune_miss",
                                       npsr=int(self.batch.npsr))
            if knobs:
                tuned_applied = {}
                if chunk is None and knobs.get("chunk"):
                    chunk = int(knobs["chunk"])
                    tuned_applied["chunk"] = chunk
                if pipeline_depth is None \
                        and knobs.get("pipeline_depth") is not None:
                    pipeline_depth = int(knobs["pipeline_depth"])
                    tuned_applied["pipeline_depth"] = pipeline_depth
                if precision is None and knobs.get("precision"):
                    precision = knobs["precision"]
                    tuned_applied["precision"] = precision
                p_t = knobs.get("path")
                if p_t in ("xla", "fused", "mega") and not keep_corr:
                    if p_t != "xla" and self._n_toa_shards > 1:
                        # mega/fused assume each shard holds the full TOA
                        # axis; a tuned path from another mesh regime is
                        # ignored loudly rather than crashing the run
                        obs.flightrec.note("tune_path_illegal", path=p_t)
                    else:
                        tuned_path = p_t
                        tuned_applied["path"] = p_t
                shards_t = knobs.get("psr_shards")
                if shards_t and int(shards_t) != \
                        int(self.mesh.shape[PSR_AXIS]):
                    # the mesh split is a construction-time knob; consume
                    # it where simulators are built (search/suite), note
                    # it here
                    obs.flightrec.note(
                        "tune_mesh_mismatch", want=int(shards_t),
                        have=int(self.mesh.shape[PSR_AXIS]))
        if chunk is None:
            chunk = tune_defaults.DEFAULT_CHUNK
        if pipeline_depth is None:
            pipeline_depth = tune_defaults.DEFAULT_PIPELINE_DEPTH
        chunk = self._normalize_chunk(chunk, nreal)
        packed_out, corr_out = [], []
        nb = self.nbins
        done = 0
        policy = faults_mod.as_policy(recovery)

        # the OS lane's host-f64 operator precompute / the lnlike lane's
        # compiled model + staged theta (shared with warm_start)
        lane_cfg = self._prepare_lanes(os, lnlike)
        os_spec, os_ops, w_os, n_os = (lane_cfg["os_spec"],
                                       lane_cfg["os_ops"],
                                       lane_cfg["w_os"], lane_cfg["n_os"])
        lnl_spec, lnl_compiled, lnl_theta = (lane_cfg["lnl_spec"],
                                             lane_cfg["lnl_compiled"],
                                             lane_cfg["lnl_theta"])
        lnl_k, lnl_l, n_extra = lane_cfg["lnl_k"], lane_cfg["lnl_l"], \
            lane_cfg["n_extra"]

        lane_seeds = lane_within = None
        if lanes is not None:
            if checkpoint is not None:
                raise ValueError(
                    "run(lanes=...) cannot checkpoint: the resume identity "
                    "is keyed on one (seed, nreal, chunk) triple, not a "
                    "cohort; serve requests are short-lived by design")
            if self._cgw_psrterm:
                raise ValueError(
                    "run(lanes=...) is incompatible with psrterm CGW "
                    "sampling (its host-f64 bulk staging replays the scalar "
                    "base-key chain; lane keys have no single base key)")
            lane_seeds, lane_within = _lane_arrays(lanes, nreal)

        ckpt = None
        if checkpoint is not None:
            from ..utils.io import EnsembleCheckpoint
            if not isinstance(seed, (int, np.integer)):
                raise TypeError("checkpointing requires an integer seed (the "
                                "checkpoint stores it to validate a resume)")
            ckpt = EnsembleCheckpoint(checkpoint)
            state = ckpt.load(seed, nreal, chunk, keep_corr=keep_corr,
                              n_extra=n_extra)
            if state is not None:
                done = int(state["done"])
                if state.get("rolled_back"):
                    # torn chunk file(s) detected and dropped by the
                    # checkpoint's checksum verification (utils.io)
                    collector.count("faults.rollbacks",
                                    int(state["rolled_back"]))
                extra = ([state["extra"]] if n_extra else [])
                packed_out.append(pack_stats(state["curves"], state["autos"],
                                             *extra))
                if keep_corr:
                    if "corr" not in state:
                        raise ValueError("checkpoint was written without "
                                         "keep_corr; cannot resume with it")
                    corr_out.append(state["corr"])

        path = "xla" if keep_corr else (tuned_path or self._stat_path)
        prec = self._resolve_precision(path, precision)
        stats_bf16 = prec == "bf16"
        fused = path != "xla"
        # The chunk executor (fakepta_tpu.parallel.pipeline): dispatches are
        # async either way; the *pipelined* loop additionally (a) precomputes
        # the NEXT chunk's CGW bulks while this one computes, (b) drains all
        # per-chunk host work (materialize / checkpoint append / progress) on
        # one background writer thread in FIFO order, and (c) recycles each
        # drained chunk's packed buffer as the donated scratch of a later
        # dispatch — the drained-event wait on the recycling ring IS the
        # depth bound. The serial fallback (depth 0 / multi-process) keeps
        # the pre-pipeline semantics: one blocking sync per chunk when a
        # checkpoint or progress consumer needs host data, device->host
        # round-trips otherwise deferred to the single final fetch (~80 ms
        # flat each through a remote-TPU tunnel).
        depth = max(int(pipeline_depth), 0)
        pipelined = depth > 0 and jax.process_count() == 1
        ring_size = max(depth, 1)
        # (packed, drained ev) per in-flight chunk; maxlen pins the depth
        # bound structurally (the loop popleft-waits before every append at
        # capacity, so the cap is never exercised — it is the invariant)
        ring: collections.deque = collections.deque(maxlen=ring_size)
        sync_each = ckpt is not None and not pipelined
        n_lanes = nb + 1 + n_extra
        dtype = self.batch.t_own.dtype
        scratch_sharding = NamedSharding(self.mesh, P(REAL_AXIS))

        # run identity, built BEFORE the loop so the crash flight recorder
        # can dump it for a run that never finishes (the RunReport reuses it)
        meta = {
            "nreal": int(nreal), "chunk": int(chunk),
            "keep_corr": bool(keep_corr), "fused": bool(fused),
            # which statistic implementation the run executed ('xla' /
            # 'fused' / 'mega') and its effective precision mode — run-shape
            # facts the per-mode bench rows key on
            "statistic_path": path, "precision": prec,
            "platform": self.mesh.devices.flat[0].platform,
            "n_devices": int(self.mesh.devices.size),
            "mesh_shape": {k: int(v) for k, v in self.mesh.shape.items()},
            "npsr": int(self.batch.npsr),
            "max_toa": int(self.batch.max_toa),
            # the depth the run actually executed at (0 = serial fallback,
            # forced for multi-process runs regardless of the kwarg)
            "pipeline_depth": int(depth if pipelined else 0),
            # the obs layer's multi-host identity: which host this report /
            # event-log shard came from (pid lanes in the merged trace)
            "process_index": int(jax.process_index()),
            "process_count": int(jax.process_count()),
        }
        if isinstance(seed, (int, np.integer)):
            meta["seed"] = int(seed)
        if tuned_applied is not None:
            # which knobs the autotuner actually set (fakepta_tpu.tune):
            # `obs compare` attributes wins to the tuner through this, and
            # the bench rows' `tuned` flag sources from it
            meta["tuned"] = {"knobs": dict(tuned_applied)}
        if lanes is not None:
            # a serve-coalesced dispatch: how many request lanes rode this
            # run (slots beyond their sum are bucket padding)
            meta["serve_lanes"] = len(list(lanes))
        if os_spec is not None:
            meta["os"] = {"orfs": list(os_spec.orfs),
                          "weighting": os_spec.weighting,
                          "null": bool(os_spec.null)}
        if lnl_spec is not None:
            meta["lnlike"] = {"k": int(lnl_k), "d": int(lnl_compiled.D),
                              "mode": lnl_spec.mode,
                              "params": list(lnl_compiled.param_names)}

        # observability (docs/OBSERVABILITY.md): the run timeline (dispatch /
        # execute / drain spans, both threads — `obs trace` renders it), the
        # HBM watermark sampler (no-op thread-free on stat-less backends),
        # the packed-buffer ledger asserting the pipeline's depth-bounded
        # peak-HBM claim at runtime, and the always-on crash flight recorder
        timeline: list = []
        ledger = obs.memwatch.PackedLedger(
            int(chunk) * n_lanes * np.dtype(dtype).itemsize, ring_size,
            pipelined, self._n_real_shards)
        sampler = obs.memwatch.HbmSampler(self.mesh.devices.flat)
        sampler.start()
        obs.flightrec.note(
            "run_start", spec_hash=obs.flightrec.spec_hash(meta),
            nreal=int(nreal), chunk=int(chunk), path=path,
            depth=int(depth if pipelined else 0), resume_done=int(done))

        # ONE step-selection ladder for run/warm_start/the serve warm pool
        # (_exec_plan): the dispatch below and an AOT warm start select the
        # identical executable by construction. The selection is held in a
        # mutable cell because the degradation ladder (docs/RELIABILITY.md)
        # may re-select mid-run: mega -> fused -> xla on a Pallas failure,
        # bf16 -> f32 on a certification failure.
        invoke, _, _ = self._exec_plan(lane_cfg, path, prec, precision,
                                       keep_corr)
        exec_sel = {"path": path, "prec": prec, "precision": precision,
                    "invoke": invoke}

        def dispatch(offset, bulks, scratch):
            """One async chunk dispatch -> (packed, corr-or-None)."""
            if lane_seeds is not None:
                # serve lane keys: per-slot (request seed, within-request
                # index) vectors replace the (base key, offset) pair
                return exec_sel["invoke"](
                    jnp.asarray(lane_seeds[offset:offset + chunk]),
                    jnp.asarray(lane_within[offset:offset + chunk]),
                    chunk, bulks, scratch)
            return exec_sel["invoke"](base, offset, chunk, bulks, scratch)

        def degrade_to(new_path, new_prec, new_precision, rec, why):
            """Step the executable selection down one ladder rung."""
            frm = f"{exec_sel['path']}/{exec_sel['prec']}"
            exec_sel["invoke"], _, _ = self._exec_plan(
                lane_cfg, new_path, new_prec, new_precision, keep_corr)
            exec_sel.update(path=new_path, prec=new_prec,
                            precision=new_precision)
            collector.count("faults.degradations")
            obs.flightrec.note("degrade", idx=rec["idx"], frm=frm,
                               to=f"{new_path}/{new_prec}",
                               error=why[:200])
            timeline.append({"name": "degrade", "tid": "main",
                             "t0": obs.now() - t_run0, "dur": None,
                             "chunk": rec["idx"], "from": frm,
                             "to": f"{new_path}/{new_prec}"})
            meta["degraded_path"] = new_path
            meta["degraded_precision"] = new_prec

        def dispatch_recover(offset, bulks, scratch, rec):
            """Dispatch one chunk under the recovery policy: bounded
            exponential-backoff retry of transient failures (same offsets,
            same RNG lanes — the retried chunk is bit-identical to the
            unfaulted run), the degradation ladders on Pallas/precision
            failures, and NaN poisoning of the packed output when the
            chaos harness asks for it (caught loudly by the drain guard).
            """
            attempts, delay = 0, policy.backoff_s
            while True:
                try:
                    act = faults_mod.check("mc.dispatch", idx=rec["idx"],
                                           offset=int(offset))
                    if scratch is not None and scratch.is_deleted():
                        # an earlier attempt's donation consumed the
                        # recycled buffer before failing: replace it (the
                        # old one is dead, so the live count is unchanged)
                        ledger.alloc_replacement()
                        scratch = jax.device_put(
                            np.zeros((chunk, n_lanes), dtype),
                            scratch_sharding)
                    packed, corr = dispatch(offset, bulks, scratch)
                    if act == "poison":
                        packed = packed * jnp.asarray(float("nan"),
                                                      packed.dtype)
                    return packed, corr
                except Exception as exc:   # noqa: BLE001 — triaged below;
                    # unrecognized failures re-raise unchanged (KillFault
                    # is BaseException and never enters this clause)
                    kind = faults_mod.classify(exc)
                    if (kind == "transient"
                            and attempts < policy.max_retries):
                        attempts += 1
                        collector.count("faults.retries")
                        obs.flightrec.note(
                            "chunk_retry", idx=rec["idx"], attempt=attempts,
                            error=repr(exc)[:200])
                        timeline.append(
                            {"name": "retry", "tid": "main",
                             "t0": obs.now() - t_run0, "dur": delay,
                             "chunk": rec["idx"], "attempt": attempts})
                        faults_mod.sleep(delay)
                        delay = policy.next_backoff(delay)
                        continue
                    if (kind == "pallas" and policy.degrade_paths
                            and exec_sel["path"] in faults_mod.PATH_LADDER):
                        # step down the ladder at the SAME effective
                        # precision — degrading the path must not silently
                        # change the precision mode too
                        new_path = faults_mod.PATH_LADDER[exec_sel["path"]]
                        degrade_to(new_path, exec_sel["prec"],
                                   exec_sel["prec"], rec, repr(exc))
                        continue
                    if (kind == "precision" and policy.degrade_precision
                            and exec_sel["prec"] == "bf16"):
                        degrade_to(exec_sel["path"], "f32", "f32", rec,
                                   repr(exc))
                        continue
                    raise

        # chunk 0's staged host inputs are the one precompute the first
        # dispatch genuinely waits on (recorded as its stall_s); every later
        # chunk's bulks precompute below, overlapped with device execution
        t_pre0 = obs.now()
        bulks = self._host_cgw_bulks(base, done, chunk)
        pre_stall = obs.now() - t_pre0
        if self._cgw_psrterm:
            timeline.append({"name": "stage_inputs", "tid": "main",
                             "t0": t_pre0 - t_run0, "dur": pre_stall,
                             "chunk": 0})
        # created last before the loop so no earlier failure leaks the thread
        writer = pipeline_mod.make_writer(pipelined)
        donation_on = True
        if pipelined and pipeline_mod.donation_unsafe(self.mesh):
            # XLA:CPU + persistent compile cache: executables loaded from
            # the on-disk cache carry input-output aliasing metadata that
            # can disagree with jax's runtime donation bookkeeping — the
            # execution then writes a buffer jax already released, and a
            # later chunk's output lands inside another chunk's drained
            # host copy (observed as whole-chunk stream swaps; see
            # docs/RELIABILITY.md and tests/test_faults.py's warm-cache
            # chaos lane). Donation is a memory optimization, never a
            # values change, so the safe degradation is donation OFF for
            # the run — loudly: flight-recorded, counted, ledger claim
            # withdrawn.
            donation_on = False
            ledger.disable()
            meta["degraded_donation"] = True
            collector.count("faults.degradations")
            obs.flightrec.note("donation_disabled_cpu_cache")
        try:
            with obs.collect(collector):
                while done < nreal:
                    t_chunk0 = obs.now()
                    # every step runs at the full chunk size (the final one
                    # overshoots and is truncated below): the steps are
                    # jitted with a static realization count, so a smaller
                    # tail chunk would recompile the SPMD program
                    rec = {"idx": len(chunk_records), "wall_s": 0.0,
                           "stall_s": pre_stall, "ckpt_wait_s": 0.0,
                           "synced": bool(sync_each or (
                               not pipelined
                               and ((keep_corr and not fused)
                                    or progress is not None)))}
                    pre_stall = 0.0
                    rec["t0_s"] = t_chunk0 - t_run0
                    scratch = None
                    recycled_from = None
                    if pipelined:
                        if len(ring) >= ring_size:
                            # depth bound + donation: wait for the oldest
                            # in-flight chunk's drain, then hand its packed
                            # buffer to this dispatch as donated scratch.
                            # The wait doubles as the per-chunk WATCHDOG
                            # deadline when the recovery policy arms one: a
                            # drain that never completes (hung device
                            # fetch, stuck checkpoint I/O) aborts the run
                            # with a flight-recorder dump instead of
                            # blocking forever (docs/RELIABILITY.md).
                            prev_packed, ev = ring.popleft()
                            t_wait = obs.now()
                            if policy.watchdog_s:
                                if not ev.wait(policy.watchdog_s):
                                    obs.flightrec.note(
                                        "watchdog_abort",
                                        idx=rec["idx"] - ring_size,
                                        deadline_s=policy.watchdog_s)
                                    raise faults_mod.WatchdogTimeout(
                                        f"drain of chunk "
                                        f"{rec['idx'] - ring_size} exceeded "
                                        f"the watchdog deadline "
                                        f"({policy.watchdog_s}s); aborting "
                                        f"— see the flight-recorder dump")
                            else:
                                ev.wait()
                            t_now = obs.now()
                            rec["stall_s"] += t_now - t_wait
                            timeline.append(
                                {"name": "stall", "tid": "main",
                                 "t0": t_wait - t_run0, "dur": t_now - t_wait,
                                 "chunk": rec["idx"]})
                            scratch = prev_packed if donation_on else None
                            recycled_from = (rec["idx"] - ring_size
                                             if donation_on else None)
                        elif donation_on:
                            scratch = jax.device_put(
                                np.zeros((chunk, n_lanes), dtype),
                                scratch_sharding)
                            ledger.alloc()
                    packed, corr = dispatch_recover(done, bulks, scratch,
                                                    rec)
                    obs.flightrec.note("chunk_dispatch", idx=rec["idx"],
                                       offset=done)
                    if recycled_from is not None and scratch is not None:
                        # runtime evidence for the depth-bounded peak-HBM
                        # claim: donation must have consumed the recycled
                        # buffer at dispatch (obs.memwatch; ledger.check()
                        # raises after the loop if it ever did not). The
                        # chaos harness can fake a miss (mc.recycle site);
                        # under the recovery policy a miss DEGRADES —
                        # donation turns off for the rest of the run, the
                        # peak-HBM claim is withdrawn loudly — instead of
                        # aborting at the end-of-run check.
                        consumed = bool(scratch.is_deleted())
                        if faults_mod.check("mc.recycle",
                                            idx=rec["idx"]) == "donation":
                            consumed = False
                        if not consumed and policy.degrade_pipeline:
                            donation_on = False
                            ledger.disable()
                            collector.count("faults.degradations")
                            obs.flightrec.note("degrade_donation",
                                               idx=rec["idx"])
                            timeline.append(
                                {"name": "degrade", "tid": "main",
                                 "t0": obs.now() - t_run0, "dur": None,
                                 "chunk": rec["idx"],
                                 "from": "donated-ring",
                                 "to": "no-donation"})
                            meta["degraded_donation"] = True
                        else:
                            ledger.recycle(consumed)
                        timeline.append(
                            {"name": "recycle", "tid": "main",
                             "t0": obs.now() - t_run0, "dur": None,
                             "chunk": rec["idx"],
                             "from_chunk": recycled_from})
                    rec["live_packed"] = ledger.live_buffers
                    collector.count("pipeline.d2h_async",
                                    pipeline_mod.start_d2h(packed, corr))
                    done += chunk
                    this_done = done
                    if done < nreal:
                        # the NEXT chunk's host-f64 staging overlaps this
                        # chunk's device execution (the dispatch above
                        # returned immediately)
                        t_b0 = obs.now()
                        bulks = self._host_cgw_bulks(base, done, chunk)
                        if self._cgw_psrterm:
                            collector.count("pipeline.h2d_prefetch")
                            timeline.append(
                                {"name": "precompute", "tid": "main",
                                 "t0": t_b0 - t_run0,
                                 "dur": obs.now() - t_b0,
                                 "chunk": rec["idx"] + 1})
                    slot = len(packed_out)
                    packed_out.append(None)
                    if keep_corr:
                        corr_out.append(None)
                    ev = threading.Event()
                    drain = partial(
                        self._drain_chunk, packed, corr, rec, packed_out,
                        slot, corr_out if keep_corr else None, ckpt, seed,
                        nreal, chunk, this_done, progress, nb, n_extra,
                        "donatable" if pipelined else sync_each, ev,
                        t_run0, timeline, retries=policy.max_retries,
                        backoff_s=policy.backoff_s,
                        on_retry=lambda a: collector.count("faults.retries"))
                    if pipelined:
                        rec["stall_s"] += writer.submit(drain, ev.set)
                        ring.append((packed, ev))
                    else:
                        writer.submit(drain)
                    rec["wall_s"] = obs.now() - t_chunk0
                    timeline.append({"name": "dispatch", "tid": "main",
                                     "t0": rec["t0_s"], "dur": rec["wall_s"],
                                     "chunk": rec["idx"]})
                    chunk_records.append(rec)
                # the watchdog also bounds the final flush: a drain hung
                # at close would otherwise block the join forever
                writer.close(timeout=(policy.watchdog_s
                                      * (len(ring) + 2)
                                      if policy.watchdog_s else None))
                # the donated-ring memory bound, asserted with this run's
                # own evidence (never fires unless the engine regressed)
                ledger.check()
                t_f0 = obs.now()
                packed_h = np.concatenate(
                    [to_host(p) for p in packed_out])[:nreal]
                timeline.append({"name": "final_fetch", "tid": "main",
                                 "t0": t_f0 - t_run0,
                                 "dur": obs.now() - t_f0})
                if not np.isfinite(packed_h[:, :nb + 1]).all():
                    # the zero-silent-corruption contract for paths where
                    # no drain materialized host arrays (serial, no
                    # checkpoint/progress): a poisoned output still fails
                    # LOUDLY with a flight-recorder dump
                    obs.flightrec.note("poisoned_output")
                    raise FloatingPointError(
                        "run produced non-finite packed statistics "
                        "(poisoned output); aborting — see the flight-"
                        "recorder dump")
        except BaseException as exc:
            writer.abort()
            sampler.stop()
            obs.flightrec.note("run_abort", error=repr(exc)[:500])
            # post-mortem artifact: the ring + run identity, next to the
            # checkpoint (or $FAKEPTA_TPU_FLIGHTREC_DIR); best-effort — a
            # dump failure must never mask the original exception
            rec_dir = obs.flightrec.dump_dir(checkpoint)
            if rec_dir is not None:
                obs.flightrec.dump(rec_dir, meta, chunks=chunk_records,
                                   error=repr(exc)[:500],
                                   process_index=int(jax.process_index()))
            raise
        total_s = obs.now() - t_run0   # final fetch = device-synced
        obs.flightrec.note("run_end", total_s=round(total_s, 3))
        curves_h, autos_h = unpack_stats(packed_h, nb)
        out = {
            "curves": curves_h,
            "autos": autos_h,
            "bin_centers": np.asarray(self.bin_centers),
        }
        if os_ops is not None:
            from ..detect import operators as detect_ops
            os_vals = packed_h[:, nb + 1:nb + 1 + n_os]
            null_vals = (packed_h[:, nb + 1 + n_os:nb + 1 + 2 * n_os]
                         if os_spec.null else None)
            out["os"] = detect_ops.assemble(os_spec, os_ops, os_vals,
                                            null_vals)
        if lnl_compiled is not None:
            from ..infer import model as infer_model
            out["lnlike"] = infer_model.assemble(
                lnl_spec, lnl_compiled, packed_h[:, nb + 1:])
        if keep_corr:
            out["corr"] = np.concatenate(corr_out)[:nreal]
        if ckpt is not None and jax.process_index() == 0:
            ckpt.delete()

        # --- RunReport (fakepta_tpu.obs): telemetry only, after all outputs
        # are already fetched — a failure here must never cost a result
        self._obs_spans |= set(collector.spans)
        from ..obs import RunReport
        collector.count("obs.chunks", len(chunk_records))
        # cost capture targets the executable the run FINISHED on (the
        # degradation ladder may have stepped the path/precision down)
        lnl_cost = (None if lnl_compiled is None else
                    (self._get_step_lnlike(lnl_spec.model, lnl_spec.mode,
                                           exec_sel["path"], lnl_compiled,
                                           exec_sel["precision"]),
                     lnl_theta, (lnl_k, lnl_l, lnl_spec.mode)))
        cost = self._obs_capture_cost(base, chunk, exec_sel["path"],
                                      exec_sel["prec"], w_os=w_os,
                                      with_null=bool(os_spec.null)
                                      if os_spec else False,
                                      lnl=lnl_cost)
        # HBM watermark (obs.memwatch): allocator stats max-merged over the
        # low-rate sampler's history, a final one-shot capture, and every
        # local device; peak_hbm_bytes falls back to the packed-buffer model
        # (static reservation + live buffers beyond the reservation's one)
        # on stat-less backends so the bench rows always carry the metric
        memory = sampler.stop()
        for k, v in self._obs_memory_stats().items():
            memory[k] = max(memory.get(k, 0), v)
        memory.update(ledger.memory_fields())
        if memory.get("peak_bytes_in_use"):
            memory["peak_hbm_bytes"] = memory["peak_bytes_in_use"]
            memory["peak_hbm_source"] = "allocator"
        elif cost.get("static_reservation_bytes"):
            memory["peak_hbm_bytes"] = (
                int(cost["static_reservation_bytes"])
                + ledger.model_extra_bytes_per_device())
            memory["peak_hbm_source"] = "model"
        report = RunReport.from_collector(
            collector, meta,
            retraces=self._obs_retraces - retraces_before,
            total_s=total_s, cost=cost, memory=memory)
        report.chunks = chunk_records
        report.spans = sorted(self._obs_spans)
        report.timeline = sorted(timeline, key=lambda e: e.get("t0", 0.0))
        self.last_report = report
        out["report"] = report
        if eventlog is not None:
            # per-host event-log shard (every process writes its own file;
            # `obs trace <dir>/events-p*.jsonl` merges them into one
            # Perfetto timeline with a pid lane per host)
            from pathlib import Path
            shard_dir = Path(eventlog)
            shard_dir.mkdir(parents=True, exist_ok=True)
            report.save(shard_dir /
                        f"events-p{int(jax.process_index()):03d}.jsonl")
        return out
