"""The Gateway: multi-tenant front door over a ServeFleet.

One request's path through the tier (docs/GATEWAY.md):

1. **authenticate** — bearer token -> :class:`~.tenants.Tenant`
   (constant-time compare; ``gateway.auth_failures`` otherwise);
2. **admit** — weighted fair-share check over in-flight slots; a tenant
   past its share (or a full gateway) gets :class:`~.tenants.GatewayBusy`
   with a *per-tenant* ``retry_after_s`` so one hot tenant's backlog never
   inflates another's retry hints (``gateway.admit`` chaos site fires
   before any state moves);
3. **result store** — content-addressed lookup keyed
   ``spec_hash x lane token x (seed, n) x engine fingerprint``
   (:mod:`.store`); a hit is served with zero device-seconds and the
   producing run's ``service_s`` credited to ``device_s_saved``;
4. **single-flight** — identical concurrent requests coalesce onto one
   fleet dispatch and fan the same response out (sound because the serve
   layer's RNG-lane contract makes the response bit-identical to every
   requester's solo run); the table is LRU-bounded — at capacity new keys
   *bypass* coalescing (``gateway.coalesce_bypass``) rather than grow it;
5. **dispatch** — everything else forwards to ``fleet.submit`` unchanged
   (trace ids ride the request object, so flight-recorder flows stay
   continuous through the gateway hop); a fleet-level
   :class:`~fakepta_tpu.serve.ServeBusy` is re-raised as a per-tenant 429.

Completion callbacks resolve futures OUTSIDE the admission lock (the
fleet-wide discipline; ``Gateway._lock`` is first in
``analysis/policy.LOCK_ORDER`` and is never held across a fleet, store, or
future call). Stream-affine and named-spec requests are forwarded without
caching or coalescing: appends mutate state and names are resolved by the
owning pool, so neither is content-addressable here.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Optional, Sequence, Union

import numpy as np

from .. import faults, obs
from ..obs import flightrec
from ..serve.scheduler import ServeResult
from ..serve.spec import ArraySpec, ServeBusy
from ..tune import defaults as tune_defaults
from ..tune.fingerprint import Fingerprint, fingerprint
from .store import ResultStore, request_key
from .tenants import GatewayBusy, Tenant, TenantTable


class _Flight:
    """One in-flight single-flight entry: the leader's outer future plus
    every coalesced follower's."""

    __slots__ = ("key", "leader", "followers", "dispatched")

    def __init__(self, key: str):
        self.key = key
        self.leader: Future = Future()
        self.followers: list = []     # (Future, tenant_id, t_admit)
        self.dispatched = False


class Gateway:
    """Tenant-aware caching/coalescing tier in front of a ServeFleet."""

    def __init__(self, fleet, tenants: Union[TenantTable, Sequence[Tenant]],
                 store: Optional[ResultStore] = None,
                 fp: Optional[Fingerprint] = None,
                 max_inflight: int = tune_defaults.GATEWAY_MAX_INFLIGHT,
                 singleflight_cap: int =
                 tune_defaults.GATEWAY_SINGLEFLIGHT_CAP):
        self.fleet = fleet
        self.tenants = (tenants if isinstance(tenants, TenantTable)
                        else TenantTable(tenants,
                                         max_inflight=max_inflight))
        self.store = store if store is not None else ResultStore()
        self.fp = fp if fp is not None else fingerprint()
        self.singleflight_cap = int(singleflight_cap)
        self._lock = threading.Lock()
        self._flights: dict = {}       # key -> _Flight (bounded by
        #                              # singleflight_cap at admission)
        self._inflight = 0
        self._requests = 0
        self._hits = 0
        self._coalesced = 0
        self._throttles = 0
        self._bypassed = 0
        self._dispatched = 0
        self._device_s_saved = 0.0
        self._cutovers = 0
        self._closed = False

    # -- keys --------------------------------------------------------------
    def _request_key(self, req) -> Optional[str]:
        """Content address for a cacheable request, else None (stream
        kinds mutate state; named specs resolve pool-side)."""
        if getattr(req, "stream_affine", False):
            return None
        spec = getattr(req, "spec", None)
        if not isinstance(spec, ArraySpec):
            return None
        return request_key(spec.spec_hash(), req.lane_token(),
                           req.seed, req.n, self.fp)

    # -- admission ---------------------------------------------------------
    def submit(self, req, token: Optional[str] = None) -> Future:
        """Admit one tenant request; returns a Future of ServeResult (or,
        for stream kinds, the stream payload dict). Raises
        :class:`GatewayAuthError` / :class:`GatewayBusy` at the gate."""
        tenant = self.tenants.authenticate(token)
        tid = tenant.tenant_id
        faults.check("gateway.admit", tenant=tid)
        st = self.tenants.states[tid]
        t0 = obs.now()
        throttle_hint = None
        with self._lock:
            if self._closed:
                raise ServeBusy("gateway is closed", retry_after_s=1.0)
            st.requests += 1
            self._requests += 1
            if st.t_first is None:
                st.t_first = t0
            if (self._inflight >= self.tenants.max_inflight
                    or st.inflight >= self.tenants.share(tid)):
                st.throttles += 1
                self._throttles += 1
                throttle_hint = self.tenants.retry_hint(st)
            else:
                st.inflight += 1
                self._inflight += 1
        obs.count("gateway.requests")
        if throttle_hint is not None:
            obs.count("gateway.throttles")
            flightrec.note("gateway_throttle", tenant=tid,
                           retry_after_s=round(throttle_hint, 4),
                           trace=getattr(req, "trace_id", None))
            raise GatewayBusy(
                f"tenant {tid!r} is over its fair share "
                f"({self.tenants.share(tid)} slots); retry in "
                f"~{throttle_hint:.3f}s",
                retry_after_s=throttle_hint, tenant=tid)
        try:
            return self._serve_admitted(req, tid, st, t0)
        except BaseException:
            self._release(tid, t0, completed=False)
            raise

    def _serve_admitted(self, req, tid: str, st, t0: float) -> Future:
        key = self._request_key(req)
        if key is not None:
            got = self.store.get(key, self.fp, key.split("/")[1])
            if got is not None:
                meta, arrays = got
                res = self._result_from_payload(meta, arrays,
                                                latency_s=obs.now() - t0)
                with self._lock:
                    st.hits += 1
                    self._hits += 1
                    saved = float(meta.get("service_s", 0.0))
                    st.device_s_saved += saved
                    self._device_s_saved += saved
                obs.count("gateway.hits")
                flightrec.note("gateway_cache_hit", key=key, tenant=tid,
                               trace=getattr(req, "trace_id", None))
                self._release(tid, t0, completed=True)
                fut: Future = Future()
                fut.set_result(res)
                return fut
            with self._lock:
                fl = self._flights.get(key)
                if fl is not None:
                    follower: Future = Future()
                    fl.followers.append((follower, tid, t0))
                    st.coalesced += 1
                    self._coalesced += 1
                    attach = True
                elif len(self._flights) >= self.singleflight_cap:
                    # table at its LRU bound: dispatch directly instead of
                    # growing it (a bounded table is the day-one contract)
                    self._bypassed += 1
                    key = None
                    attach = False
                else:
                    fl = _Flight(key)
                    self._flights[key] = fl
                    attach = False
            if attach:
                obs.count("gateway.coalesced")
                flightrec.note("gateway_coalesced", key=key, tenant=tid,
                               trace=getattr(req, "trace_id", None))
                return follower
            if key is None:
                obs.count("gateway.coalesce_bypass")
        return self._dispatch(req, tid, t0, key)

    def _dispatch(self, req, tid: str, t0: float,
                  key: Optional[str]) -> Future:
        fl = None
        if key is not None:
            with self._lock:
                fl = self._flights.get(key)
        try:
            inner = self.fleet.submit(req)
        except ServeBusy as exc:
            # fleet-level backpressure surfaces as THIS tenant's 429
            if fl is not None:
                self._abort_flight(fl, exc)
            with self._lock:
                st = self.tenants.states[tid]
                st.throttles += 1
                self._throttles += 1
            obs.count("gateway.throttles")
            raise GatewayBusy(
                f"fleet busy for tenant {tid!r}: {exc}",
                retry_after_s=float(getattr(exc, "retry_after_s", 0.1)),
                tenant=tid) from exc
        with self._lock:
            self._dispatched += 1
        if fl is None:
            inner.add_done_callback(
                lambda f: self._on_plain_done(f, tid, t0))
            return inner
        fl.dispatched = True
        inner.add_done_callback(
            lambda f: self._on_flight_done(f, fl, req, tid, t0))
        return fl.leader

    # -- completion (futures resolve OUTSIDE the lock) ---------------------
    def _release(self, tid: str, t0: float, completed: bool) -> None:
        t1 = obs.now()
        with self._lock:
            st = self.tenants.states[tid]
            st.inflight = max(0, st.inflight - 1)
            self._inflight = max(0, self._inflight - 1)
            if completed:
                st.completed += 1
                st.latencies_ms.append((t1 - t0) * 1e3)
                st.t_last = t1

    def _on_plain_done(self, inner: Future, tid: str, t0: float) -> None:
        self._release(tid, t0, completed=inner.exception() is None)

    def _abort_flight(self, fl: _Flight, exc: BaseException) -> None:
        with self._lock:
            self._flights.pop(fl.key, None)
            followers = list(fl.followers)
        for fut, f_tid, f_t0 in followers:
            self._release(f_tid, f_t0, completed=False)
            if not fut.done():
                fut.set_exception(exc)
        if not fl.leader.done():
            fl.leader.set_exception(exc)

    def _on_flight_done(self, inner: Future, fl: _Flight, req,
                        tid: str, t0: float) -> None:
        exc = inner.exception()
        with self._lock:
            self._flights.pop(fl.key, None)
            followers = list(fl.followers)
        if exc is not None:
            self._release(tid, t0, completed=False)
            for fut, f_tid, f_t0 in followers:
                self._release(f_tid, f_t0, completed=False)
                if not fut.done():
                    fut.set_exception(exc)
            if not fl.leader.done():
                fl.leader.set_exception(exc)
            return
        res = inner.result()
        arrays = self._payload_arrays(res)
        if arrays is not None:
            meta = {"spec_hash": fl.key.split("/")[1], "fp": self.fp.hash,
                    "platform": self.fp.platform,
                    "lane": repr(tuple(req.lane_token())),
                    "seed": int(req.seed), "n": int(req.n),
                    "service_s": float(res.service_s),
                    "bucket": int(res.bucket)}
            try:
                self.store.put(fl.key, meta, arrays)
            except Exception as exc:   # noqa: BLE001 — recorded: caching
                # is best-effort; a store failure must degrade to "this
                # response is not cached", never strand the followers
                # waiting on this callback to fan the result out
                flightrec.note("gateway_store_put_failed", key=fl.key,
                               error=repr(exc)[:160])
        self._release(tid, t0, completed=True)
        for fut, f_tid, f_t0 in followers:
            self._release(f_tid, f_t0, completed=True)
            if not fut.done():
                fut.set_result(res)
        if not fl.leader.done():
            fl.leader.set_result(res)

    # -- payload <-> ServeResult ------------------------------------------
    @staticmethod
    def _payload_arrays(res: ServeResult) -> Optional[dict]:
        """Flatten a ServeResult into npz-able arrays, or None when a lane
        payload is not representable (then the response is simply not
        cached — correctness never depends on cacheability)."""
        try:
            arrays = {"curves": np.asarray(res.curves),
                      "autos": np.asarray(res.autos),
                      "bin_centers": np.asarray(res.bin_centers)}
            for prefix, d in (("os", res.os), ("lnlike", res.lnlike)):
                if not d:
                    continue
                for k, v in d.items():
                    a = np.asarray(v)
                    if a.dtype == object:
                        return None
                    arrays[f"{prefix}__{k}"] = a
        except (TypeError, ValueError):
            return None
        return arrays

    @staticmethod
    def _result_from_payload(meta: dict, arrays: dict,
                             latency_s: float) -> ServeResult:
        os_d: dict = {}
        ln_d: dict = {}
        plain: dict = {}
        for k, v in arrays.items():
            if k.startswith("os__"):
                os_d[k[len("os__"):]] = v
            elif k.startswith("lnlike__"):
                ln_d[k[len("lnlike__"):]] = v
            else:
                plain[k] = v
        return ServeResult(
            curves=plain["curves"], autos=plain["autos"],
            bin_centers=plain["bin_centers"],
            os=os_d or None, lnlike=ln_d or None,
            queued_s=0.0, service_s=0.0, latency_s=float(latency_s),
            cohort_requests=1, bucket=int(meta.get("bucket", 0)),
            pad_waste_frac=0.0, replica="gateway-cache", failovers=0)

    # -- sync + stats surface ---------------------------------------------
    def serve(self, req, token: Optional[str] = None,
              timeout: Optional[float] = None):
        return self.submit(req, token).result(timeout)

    def cutover(self, name: str, spec, checkpoint=None) -> dict:
        """Frozen-grid migration as a gateway-managed operation — see
        :func:`fakepta_tpu.gateway.cutover.cutover_stream`."""
        from .cutover import cutover_stream

        info = cutover_stream(self.fleet, name, spec,
                              checkpoint=checkpoint)
        with self._lock:
            self._cutovers += 1
        return info

    def gateway_summary(self) -> dict:
        with self._lock:
            completed = sum(s.completed
                            for s in self.tenants.states.values())
            return {
                "requests": int(self._requests),
                "dispatched": int(self._dispatched),
                "hits": int(self._hits),
                "coalesced": int(self._coalesced),
                "throttles": int(self._throttles),
                "coalesce_bypass": int(self._bypassed),
                "cache_rejects": int(self.store.rejects),
                "store_entries": len(self.store),
                "flights_open": len(self._flights),
                "inflight": int(self._inflight),
                "completed": int(completed),
                "hit_rate": round(self._hits / self._requests, 4)
                            if self._requests else 0.0,
                "device_s_saved": round(self._device_s_saved, 6),
                "cutovers": int(self._cutovers),
            }

    def tenant_summary(self) -> dict:
        with self._lock:
            return self.tenants.summary()

    def slo_summary(self) -> dict:
        out = dict(self.fleet.slo_summary())
        for k, v in self.gateway_summary().items():
            out[f"gateway_{k}"] = v
        return out

    def telemetry_rollup(self) -> dict:
        # ServeFleet and ServePool both expose telemetry_rollup; a
        # duck-typed target without one still gets the gateway sections.
        base = getattr(self.fleet, "telemetry_rollup", None)
        rollup = dict(base()) if base is not None else {}
        rollup["tenants"] = self.tenant_summary()
        rollup["gateway"] = self.gateway_summary()
        return rollup

    def metrics_text(self) -> str:
        from ..obs import promfmt

        return promfmt.render(self.telemetry_rollup())

    def reset_stats(self) -> None:
        with self._lock:
            self._requests = self._hits = self._coalesced = 0
            self._throttles = self._bypassed = self._dispatched = 0
            self._cutovers = 0
            self._device_s_saved = 0.0
            for st in self.tenants.states.values():
                st.requests = st.throttles = st.hits = 0
                st.coalesced = st.completed = 0
                st.device_s_saved = 0.0
                st.latencies_ms.clear()
                st.t_first = st.t_last = None
        self.fleet.reset_stats()

    def close(self, close_fleet: bool = True) -> None:
        with self._lock:
            self._closed = True
            flights = list(self._flights.values())
            self._flights.clear()
        for fl in flights:
            for fut, _tid, _t0 in fl.followers:
                if not fut.done():
                    fut.cancel()
        if close_fleet:
            self.fleet.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
