"""Gateway-orchestrated frozen-grid migration cutover.

A stream is pinned to the frozen-grid template it opened with
(docs/STREAMING.md); when its data outgrows the pinned Tspan the answer is
a *managed re-stage onto a wider template*, not a reconfiguration. The
fence + swap mechanics live with the stream registry
(:meth:`~fakepta_tpu.serve.streams.StreamManager.cutover`); this module is
the gateway's control half — find the replica that owns the stream, drive
the operation, and account for it (``gateway.cutovers`` /
``gateway.cutover_aborts``, flight-recorder bracketing).

Only in-process replicas (:class:`~fakepta_tpu.serve.LocalReplica`, or a
bare :class:`~fakepta_tpu.serve.ServePool`) can host a gateway-driven
cutover today; a subprocess replica reaches the same code through the
``cutover`` protocol kind of its own serve CLI.
"""

from __future__ import annotations

from .. import obs
from ..obs import flightrec
from ..serve.spec import ServeError


def _owning_pool(target, name: str):
    """The ServePool that owns stream ``name`` under ``target`` (a pool,
    a LocalReplica, or a ServeFleet of them)."""
    if hasattr(target, "cutover_stream"):
        return target                     # a pool (or pool-compatible)
    pool = getattr(target, "pool", None)  # a LocalReplica
    if pool is not None:
        return pool
    replicas = getattr(target, "replicas", None)
    if replicas:
        remote = 0
        for rep in list(replicas.values()):
            pool = getattr(rep, "pool", None)
            if pool is None:
                remote += 1
                continue
            if name in pool.stream_summary():
                return pool
        if remote:
            raise ServeError(
                f"stream {name!r} is not on any in-process replica; "
                f"drive the cutover through the owning subprocess "
                f"replica's 'cutover' protocol kind instead")
    raise ServeError(f"no pool under {type(target).__name__} owns stream "
                     f"{name!r}")


def cutover_stream(target, name: str, spec, checkpoint=None) -> dict:
    """Run one migration cutover as a managed operation; returns the
    cutover info row (TOA conservation + oracle already enforced by the
    manager — an abort leaves the old state installed and raises)."""
    t0 = obs.now()
    flightrec.note("gateway_cutover_begin", stream=str(name))
    pool = _owning_pool(target, str(name))
    try:
        info = pool.cutover_stream(str(name), spec, checkpoint=checkpoint)
    except BaseException as exc:
        obs.count("gateway.cutover_aborts")
        flightrec.note("gateway_cutover_failed", stream=str(name),
                       error=repr(exc)[:160])
        raise
    obs.count("gateway.cutovers")
    info = dict(info, managed_ms=round((obs.now() - t0) * 1e3, 3))
    return info
