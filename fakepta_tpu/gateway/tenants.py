"""Tenants: auth tokens, weighted fair-share admission, per-tenant 429s.

The gateway's isolation contract is *strict weighted shares over in-flight
slots*: tenant ``t`` may hold at most ``max(1, floor(cap * w_t / sum(w)))``
of the gateway's :data:`~fakepta_tpu.tune.defaults.GATEWAY_MAX_INFLIGHT`
slots at once. A hot tenant that saturates its share gets a
:class:`GatewayBusy` (a :class:`~fakepta_tpu.serve.ServeBusy` subclass, so
polite clients need no new handling) whose ``retry_after_s`` is computed
from *that tenant's own* recent completion latencies — one hot tenant can
neither occupy another tenant's slots nor inflate another tenant's retry
hints, which is the starvation property docs/GATEWAY.md pins.

Auth is deliberately boring: opaque bearer tokens compared with
:func:`hmac.compare_digest` (constant-time — a gateway that leaks token
prefixes through timing is a worse bug than any it prevents). Unknown
tokens raise :class:`GatewayAuthError` and count ``gateway.auth_failures``.
"""

from __future__ import annotations

import collections
import dataclasses
import hmac
from typing import Dict, Optional, Sequence

from .. import obs
from ..serve.spec import ServeBusy, ServeError
from ..tune import defaults as tune_defaults


class GatewayAuthError(ServeError):
    """Unknown or missing tenant token."""


class GatewayBusy(ServeBusy):
    """Per-tenant 429: carries the tenant id beside the retry hint."""

    def __init__(self, msg: str, retry_after_s: float = 0.1,
                 tenant: str = ""):
        super().__init__(msg, retry_after_s=retry_after_s)
        self.tenant = tenant


@dataclasses.dataclass
class Tenant:
    """One tenant's identity + quota configuration."""

    tenant_id: str
    token: str
    weight: float = float(tune_defaults.GATEWAY_DEFAULT_WEIGHT)


class _TenantState:
    """Mutable per-tenant accounting (guarded by the TenantTable's owner —
    the Gateway — under its admission lock)."""

    __slots__ = ("tenant", "inflight", "requests", "throttles", "hits",
                 "coalesced", "completed", "device_s_saved", "latencies_ms",
                 "t_first", "t_last")

    def __init__(self, tenant: Tenant):
        self.tenant = tenant
        self.inflight = 0
        self.requests = 0
        self.throttles = 0
        self.hits = 0
        self.coalesced = 0
        self.completed = 0
        self.device_s_saved = 0.0
        self.latencies_ms = collections.deque(
            maxlen=tune_defaults.GATEWAY_LATENCY_RING)
        self.t_first: Optional[float] = None
        self.t_last: Optional[float] = None


class TenantTable:
    """Token -> tenant resolution plus fair-share arithmetic.

    The table is immutable after construction (tenancy changes are a
    gateway restart; elastic tenancy is future work in docs/GATEWAY.md),
    so reads need no lock — only the per-tenant *state* mutates, and that
    is owned by the Gateway's admission lock.
    """

    def __init__(self, tenants: Sequence[Tenant],
                 max_inflight: int = tune_defaults.GATEWAY_MAX_INFLIGHT):
        if not tenants:
            raise ValueError("a gateway needs at least one tenant")
        ids = [t.tenant_id for t in tenants]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate tenant ids: {ids}")
        self.max_inflight = int(max_inflight)
        self._by_token: Dict[str, Tenant] = {t.token: t for t in tenants}
        if len(self._by_token) != len(tenants):
            raise ValueError("tenant tokens must be unique")
        self.states: Dict[str, _TenantState] = {
            t.tenant_id: _TenantState(t) for t in tenants}
        total = sum(max(0.0, float(t.weight)) for t in tenants)
        if total <= 0:
            raise ValueError("tenant weights must sum positive")
        self._share: Dict[str, int] = {
            t.tenant_id: max(1, int(self.max_inflight
                                    * max(0.0, float(t.weight)) / total))
            for t in tenants}

    def authenticate(self, token: Optional[str]) -> Tenant:
        """Resolve a bearer token; constant-time compare per entry."""
        if token:
            for known, tenant in self._by_token.items():
                if hmac.compare_digest(known, token):
                    return tenant
        obs.count("gateway.auth_failures")
        raise GatewayAuthError("unknown tenant token")

    def share(self, tenant_id: str) -> int:
        """The tenant's in-flight slot allocation (its weighted share of
        the gateway total, floored at one slot)."""
        return self._share[tenant_id]

    def retry_hint(self, state: _TenantState) -> float:
        """Per-tenant retry_after_s: scale the tenant's own median recent
        latency by its queue pressure; floored/capped by the knobs so a
        cold tenant re-probes quickly and a backed-up one backs off."""
        lat = sorted(state.latencies_ms)
        share = self._share[state.tenant.tenant_id]
        if lat:
            p50_s = lat[len(lat) // 2] / 1e3
            hint = p50_s * max(1.0, state.inflight / max(1, share))
        else:
            hint = tune_defaults.GATEWAY_RETRY_MIN_S
        return float(min(tune_defaults.GATEWAY_RETRY_CAP_S,
                         max(tune_defaults.GATEWAY_RETRY_MIN_S, hint)))

    def summary(self) -> dict:
        """Per-tenant observability rows (the ``tenants`` table of stats
        replies, the telemetry rollup, promfmt and ``obs top``)."""
        out = {}
        for tid, st in sorted(self.states.items()):
            window_s = ((st.t_last - st.t_first)
                        if st.t_first is not None and st.t_last is not None
                        and st.t_last > st.t_first else 0.0)
            row = {
                "requests": int(st.requests),
                "throttles": int(st.throttles),
                "hits": int(st.hits),
                "coalesced": int(st.coalesced),
                "completed": int(st.completed),
                "inflight": int(st.inflight),
                "weight": float(st.tenant.weight),
                "share_slots": int(self._share[tid]),
                "queue_share": round(st.inflight
                                     / max(1, self.max_inflight), 4),
                "hit_rate": round(st.hits / st.requests, 4)
                            if st.requests else 0.0,
                "device_s_saved": round(st.device_s_saved, 6),
                "qps": round(st.completed / window_s, 3)
                       if window_s > 0 else 0.0,
            }
            lat = sorted(st.latencies_ms)
            if lat:
                row["p50_ms"] = round(lat[len(lat) // 2], 3)
                row["p99_ms"] = round(
                    lat[min(len(lat) - 1, int(0.99 * len(lat)))], 3)
            out[tid] = row
        return out
