"""Content-addressed result store: served responses keyed by what produced
them.

A served :class:`~fakepta_tpu.serve.ServeResult` is a pure function of
``(spec_hash, RNG-lane token, seed, n)`` on a given engine build — the
serve layer's bit-identical-per-lane contract (docs/SERVING.md) is what
makes the response *content-addressable* at all. The store keys every
entry by exactly that tuple plus the platform/engine
:class:`~fakepta_tpu.tune.fingerprint.Fingerprint`, so a repeat request is
a cache hit served with zero device-seconds, and a response produced by a
different engine build can never be served as if it were current.

Lifecycle mirrors :mod:`fakepta_tpu.tune.store` (tests pin each case):

- **fingerprint mismatch** — an entry produced on another platform /
  device count / jax version is a loud miss-and-recompute, flight-recorded
  (``gateway_fingerprint_mismatch``) and counted ``gateway.cache_rejects``;
- **schema-version bump** — entries written by another store version are
  ignored, never reinterpreted (``gateway_entry_schema_mismatch``);
- **corrupt / torn payload** — a CRC mismatch between the index and the
  payload file raises a :class:`RuntimeWarning`, drops the entry, and
  recomputes (``gateway_store_corrupt_entry``); index-file corruption
  empties the store the same way the tune store does.

Payload files are one ``.npz`` per entry written through
:func:`fakepta_tpu.utils.io.write_atomic` (tmp + fsync + rename), with the
returned CRC32 recorded in the JSON index; the index itself is rewritten
atomically on every put. The in-memory decoded-payload cache and the
on-disk entry table are both explicitly bounded
(:data:`~fakepta_tpu.tune.defaults.GATEWAY_RESULT_CACHE_CAP` /
:data:`~fakepta_tpu.tune.defaults.GATEWAY_STORE_CAP`) — the
``unbounded-cache`` analysis rule holds this module to its own standard.
"""

from __future__ import annotations

import collections
import hashlib
import io
import json
import os
import threading
import warnings
import zlib
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from ..obs import flightrec, metrics as obs_metrics
from ..tune import defaults as tune_defaults
from ..tune.fingerprint import Fingerprint


def request_key(spec_hash: str, lane_token, seed: int, n: int,
                fp: Fingerprint) -> str:
    """The content address of one served response:
    ``<fp-hash>/<spec-hash>/<lane-hash>/<seed>x<n>``."""
    lane = hashlib.sha1(repr(tuple(lane_token)).encode()).hexdigest()[:12]
    return f"{fp.hash}/{spec_hash}/{lane}/{int(seed)}x{int(n)}"


def default_gateway_dir() -> Optional[Path]:
    """``$FAKEPTA_TPU_GATEWAY_DIR`` wins; else a ``gateway/`` directory
    beside the tune store (responses and the knobs that produced them
    amortize together); None when neither resolves."""
    env = os.environ.get(tune_defaults.GATEWAY_DIR_ENV)
    if env:
        return Path(env)
    from ..tune.store import default_store_path

    tune_path = default_store_path()
    return tune_path.parent / "gateway" if tune_path is not None else None


class ResultStore:
    """Bounded content-addressed store of served response payloads."""

    def __init__(self, path=None,
                 cache_cap: int = tune_defaults.GATEWAY_RESULT_CACHE_CAP,
                 store_cap: int = tune_defaults.GATEWAY_STORE_CAP):
        self.dir: Optional[Path] = (Path(path) if path is not None
                                    else default_gateway_dir())
        self.cache_cap = int(cache_cap)
        self.store_cap = int(store_cap)
        self._lock = threading.Lock()
        # serializes index-file writes: write_atomic stages through one
        # fixed tmp name per path, so two concurrent put()s racing their
        # os.replace would unlink each other's staged bytes. Ordered
        # BEFORE _lock (the flusher re-snapshots under _lock so the last
        # writer always lands the newest index).
        self._io_lock = threading.Lock()
        self._entries: Optional[dict] = None   # key -> meta (index order =
        #                                      # insertion order = eviction)
        # decoded-payload LRU: key -> (meta, arrays); bounded at cache_cap
        self._mem: "collections.OrderedDict" = collections.OrderedDict()
        self.hits = 0
        self.rejects = 0
        self.puts = 0

    # -- index -------------------------------------------------------------
    def _index_path(self) -> Optional[Path]:
        if self.dir is None:
            return None
        return self.dir / tune_defaults.GATEWAY_INDEX_FILENAME

    def _load_index(self) -> dict:
        """Raw ``key -> meta``; empty (loudly) on corruption or a schema
        bump — the tune-store contract, verbatim."""
        path = self._index_path()
        if path is None or not path.exists():
            return {}
        try:
            data = json.loads(path.read_text(encoding="utf-8"))
            if not isinstance(data, dict) or "entries" not in data:
                raise ValueError("gateway index has no 'entries' table")
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            warnings.warn(
                f"corrupt gateway result index {path}: {exc!r}; ignoring "
                f"it and recomputing (the next put rewrites it atomically)",
                RuntimeWarning, stacklevel=2)
            flightrec.note("gateway_store_corrupt", path=str(path),
                           error=repr(exc)[:160])
            return {}
        if data.get("schema") != tune_defaults.GATEWAY_STORE_SCHEMA or \
                int(data.get("version", -1)) != \
                tune_defaults.GATEWAY_STORE_VERSION:
            warnings.warn(
                f"gateway result index {path} has schema "
                f"{data.get('schema')!r} v{data.get('version')!r} != "
                f"{tune_defaults.GATEWAY_STORE_SCHEMA!r} "
                f"v{tune_defaults.GATEWAY_STORE_VERSION}; ignoring it",
                RuntimeWarning, stacklevel=2)
            flightrec.note("gateway_store_schema_mismatch", path=str(path),
                           schema=str(data.get("schema")),
                           version=data.get("version"))
            return {}
        entries = data.get("entries")
        return dict(entries) if isinstance(entries, dict) else {}

    def _entries_locked(self) -> dict:
        if self._entries is None:
            self._entries = self._load_index()
        return self._entries

    def _write_index(self, entries: dict) -> None:
        path = self._index_path()
        if path is None:
            return
        from ..utils.io import write_atomic

        payload = {"schema": tune_defaults.GATEWAY_STORE_SCHEMA,
                   "version": tune_defaults.GATEWAY_STORE_VERSION,
                   "entries": entries}
        path.parent.mkdir(parents=True, exist_ok=True)
        write_atomic(path,
                     (json.dumps(payload, indent=1, sort_keys=True) + "\n")
                     .encode())

    def _flush_index(self) -> None:
        """Persist the index under the IO lock, re-snapshotting so the
        last writer always lands a state at least as new as its own
        insert — concurrent put()s can't clobber each other's entries or
        race write_atomic's staged tmp file."""
        with self._io_lock:
            with self._lock:
                snapshot = dict(self._entries_locked())
            self._write_index(snapshot)

    def _payload_path(self, key: str) -> Optional[Path]:
        if self.dir is None:
            return None
        h = hashlib.sha1(key.encode()).hexdigest()[:20]
        return self.dir / f"{h}.npz"

    # -- read --------------------------------------------------------------
    def _reject(self, note: str, **ctx) -> None:
        with self._lock:
            self.rejects += 1
        obs_metrics.count("gateway.cache_rejects")
        flightrec.note(note, **ctx)

    def get(self, key: str, fp: Fingerprint,
            spec_hash: str) -> Optional[Tuple[dict, dict]]:
        """``(meta, arrays)`` for a valid entry, else None.

        Every miss path that *could* have been a hit is loud: a
        fingerprint or schema mismatch and a torn payload are
        flight-recorded and counted ``gateway.cache_rejects`` — a stale or
        corrupt response is never served.
        """
        with self._lock:
            cached = self._mem.get(key)
            if cached is not None:
                self._mem.move_to_end(key)
                self.hits += 1
                return cached
            meta = self._entries_locked().get(key)
        if meta is None:
            # same spec/lane under another fingerprint: the diagnosable
            # near-miss (new platform / jax bump), mirrored from the tune
            # store's lookup
            tail = key.split("/", 1)[1] if "/" in key else key
            with self._lock:
                near = next((other for other in self._entries_locked()
                             if other.endswith(tail) and other != key),
                            None)
            if near is not None:
                self._reject("gateway_fingerprint_mismatch", want=fp.hash,
                             have=near.split("/", 1)[0],
                             spec_hash=spec_hash)
            return None
        if int(meta.get("version", -1)) != \
                tune_defaults.GATEWAY_STORE_VERSION or \
                meta.get("schema") != tune_defaults.GATEWAY_STORE_SCHEMA:
            self._reject("gateway_entry_schema_mismatch", key=key,
                         have=str(meta.get("schema")),
                         version=meta.get("version"))
            return None
        if meta.get("fp") != fp.hash:
            self._reject("gateway_fingerprint_mismatch", key=key,
                         want=fp.hash, have=str(meta.get("fp")))
            return None
        if meta.get("spec_hash") != spec_hash:
            self._reject("gateway_entry_spec_mismatch", key=key,
                         want=spec_hash, have=str(meta.get("spec_hash")))
            return None
        path = self._payload_path(key)
        try:
            blob = path.read_bytes()
        except OSError as exc:
            self._drop(key)
            self._reject("gateway_store_missing_payload", key=key,
                         error=repr(exc)[:160])
            return None
        if zlib.crc32(blob) != int(meta.get("crc", -1)):
            warnings.warn(
                f"torn gateway result payload {path} (CRC mismatch); "
                f"dropping the entry and recomputing",
                RuntimeWarning, stacklevel=2)
            self._drop(key)
            self._reject("gateway_store_corrupt_entry", key=key,
                         path=str(path))
            return None
        try:
            with np.load(io.BytesIO(blob), allow_pickle=False) as npz:
                arrays = {k: np.asarray(npz[k]) for k in npz.files}
        except (OSError, ValueError) as exc:
            self._drop(key)
            self._reject("gateway_store_corrupt_entry", key=key,
                         error=repr(exc)[:160])
            return None
        entry = (dict(meta), arrays)
        with self._lock:
            self._mem[key] = entry
            self._mem.move_to_end(key)
            while len(self._mem) > self.cache_cap:
                self._mem.popitem(last=False)
            self.hits += 1
        return entry

    def _drop(self, key: str) -> None:
        """Forget one entry (bad payload); index rewritten on next put."""
        with self._lock:
            self._entries_locked().pop(key, None)
            self._mem.pop(key, None)

    # -- write -------------------------------------------------------------
    def put(self, key: str, meta: dict, arrays: dict) -> Optional[str]:
        """Insert one entry: atomic payload write, CRC recorded in the
        index, oldest entries evicted past the store cap. Returns the
        payload path, or None when no store dir is configured."""
        path = self._payload_path(key)
        if path is None:
            flightrec.note("gateway_store_unconfigured", key=key)
            return None
        from ..utils.io import npz_bytes, write_atomic

        blob = npz_bytes(**arrays)
        path.parent.mkdir(parents=True, exist_ok=True)
        crc = write_atomic(path, blob)
        full = dict(meta, crc=int(crc),
                    schema=tune_defaults.GATEWAY_STORE_SCHEMA,
                    version=tune_defaults.GATEWAY_STORE_VERSION)
        evicted = []
        with self._lock:
            entries = self._entries_locked()
            entries.pop(key, None)
            entries[key] = full
            self._mem[key] = (dict(full), dict(arrays))
            self._mem.move_to_end(key)
            while len(self._mem) > self.cache_cap:
                self._mem.popitem(last=False)
            while len(entries) > self.store_cap:
                old_key = next(iter(entries))
                entries.pop(old_key)
                self._mem.pop(old_key, None)
                evicted.append(old_key)
            self.puts += 1
        for old_key in evicted:
            obs_metrics.count("gateway.store_evictions")
            old_path = self._payload_path(old_key)
            try:
                old_path.unlink()
            except OSError:
                pass              # index no longer references it: harmless
        self._flush_index()
        obs_metrics.count("gateway.store_puts")
        flightrec.note("gateway_store_put", key=key, path=str(path))
        return str(path)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries_locked())
