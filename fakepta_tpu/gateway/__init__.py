"""fakepta_tpu.gateway — multi-tenant gateway + content-addressed results.

The tier that turns the serve fleet into a *service* (docs/GATEWAY.md):
per-tenant auth/quota/fair-share admission with per-tenant 429 retry
hints, single-flight coalescing of identical concurrent requests (sound
under the serve layer's bit-identical-per-RNG-lane contract), a
content-addressed result store keyed by
``spec_hash x lane token x (seed, n) x engine fingerprint`` with the tune
store's atomic-write/CRC/schema-bump lifecycle, and the frozen-grid
migration cutover as a gateway-managed operation.

Embeddable surface::

    from fakepta_tpu.gateway import Gateway, Tenant
    from fakepta_tpu.serve import ArraySpec, LocalReplica, ServeFleet,
        SimRequest

    fleet = ServeFleet([LocalReplica("r0")])
    gw = Gateway(fleet, [Tenant("acme", token="tok-acme", weight=2)])
    res = gw.serve(SimRequest(spec=ArraySpec(npsr=20), n=32, seed=7),
                   token="tok-acme")     # repeat = cache hit, 0 device-s
"""

from .core import Gateway
from .cutover import cutover_stream
from .store import ResultStore, default_gateway_dir, request_key
from .tenants import GatewayAuthError, GatewayBusy, Tenant, TenantTable

__all__ = [
    "Gateway", "GatewayAuthError", "GatewayBusy", "ResultStore", "Tenant",
    "TenantTable", "cutover_stream", "default_gateway_dir", "request_key",
]
