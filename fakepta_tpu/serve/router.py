"""Consistent-hash request routing for the serve fleet (docs/SERVING.md).

The fleet's whole performance story is **warm-pool affinity**: every
replica holds an LRU-bounded warm pool of compiled executables
(``serve/pool.py``), so aggregate warm capacity scales with the replica
count ONLY if the same spec keeps landing on the same replica. The router
therefore consistent-hashes ``spec_hash`` onto a ring of virtual nodes:

- each replica owns ``vnodes`` pseudo-random points on a 64-bit ring
  (SHA-1 of ``"replica_id#k"`` — stable across processes and runs, no
  Python ``hash()`` randomization);
- a spec routes to the first replica point clockwise of
  ``SHA-1(spec_hash)`` — the spec's **owner**;
- :meth:`HashRing.preference` lists the owner first and then the distinct
  successors around the ring — the spillover/failover order, so a
  saturated or dead owner degrades to the *same* sibling every time
  (the sibling's warm pool converges on the spilled shard instead of the
  whole fleet churning);
- adding or removing a replica only remaps the arcs adjacent to its
  points: ~1/N of the spec space moves on a join/leave, the rest of the
  fleet's warm pools stay hot (pinned by
  ``tests/test_fleet.py::test_ring_join_leave_remaps_about_one_nth``).

Pure host-side data structure: no jax, no sockets, no threads — the fleet
(``serve/fleet.py``) owns liveness and dispatch.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Sequence, Tuple

#: virtual nodes per replica: enough that per-replica load imbalance and
#: the join/leave remap fraction both concentrate near 1/N (stddev ~
#: 1/sqrt(vnodes)) while a full ring rebuild stays microseconds
DEFAULT_VNODES = 64


def _point(label: str) -> int:
    """Stable 64-bit ring coordinate of a label (no seed, no salt: two
    processes building the same ring agree bit-for-bit)."""
    return int.from_bytes(hashlib.sha1(label.encode()).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring of replica ids (see module docstring).

    >>> ring = HashRing(["r0", "r1", "r2"])
    >>> ring.owner("a1b2c3")                    # stable owner
    >>> ring.preference("a1b2c3")               # owner + failover order
    """

    def __init__(self, replica_ids: Sequence[str],
                 vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = int(vnodes)
        self._points: List[Tuple[int, str]] = []
        self._keys: List[int] = []
        self._ids: List[str] = []
        for rid in replica_ids:
            self.add(rid)

    # -- membership --------------------------------------------------------
    def add(self, replica_id: str) -> None:
        """Join one replica (idempotence is an error: duplicate ids would
        silently double the replica's arc share)."""
        rid = str(replica_id)
        if rid in self._ids:
            raise ValueError(f"replica {rid!r} is already on the ring")
        self._ids.append(rid)
        for k in range(self.vnodes):
            self._points.append((_point(f"{rid}#{k}"), rid))
        self._rebuild()

    def remove(self, replica_id: str) -> None:
        """Leave: only the departing replica's arcs remap (~1/N of specs)."""
        rid = str(replica_id)
        if rid not in self._ids:
            raise ValueError(f"replica {rid!r} is not on the ring")
        self._ids.remove(rid)
        self._points = [(p, r) for p, r in self._points if r != rid]
        self._rebuild()

    def _rebuild(self) -> None:
        self._points.sort()
        self._keys = [p for p, _ in self._points]

    @property
    def replica_ids(self) -> Tuple[str, ...]:
        return tuple(self._ids)

    def __len__(self) -> int:
        return len(self._ids)

    # -- routing -----------------------------------------------------------
    def _walk(self, spec_hash: str):
        """Ring points clockwise of the spec's coordinate, wrapped."""
        if not self._points:
            raise ValueError("the ring has no replicas")
        start = bisect.bisect_right(self._keys, _point(str(spec_hash)))
        n = len(self._points)
        for i in range(n):
            yield self._points[(start + i) % n][1]

    def owner(self, spec_hash: str) -> str:
        """The replica owning ``spec_hash`` (its warm-pool home)."""
        return next(self._walk(spec_hash))

    def preference(self, spec_hash: str) -> List[str]:
        """Every replica, owner first then distinct ring successors — the
        spillover order when the owner is saturated and the failover order
        when it dies (deterministic per spec, so degraded traffic converges
        on one sibling's warm pool)."""
        order: List[str] = []
        seen: Dict[str, bool] = {}
        for rid in self._walk(spec_hash):
            if rid not in seen:
                seen[rid] = True
                order.append(rid)
                if len(order) == len(self._ids):
                    break
        return order

    def shard(self, spec_hashes: Sequence[str]) -> Dict[str, List[str]]:
        """Owner -> owned spec hashes (introspection + the tests' remap
        accounting)."""
        out: Dict[str, List[str]] = {rid: [] for rid in self._ids}
        for h in spec_hashes:
            out[self.owner(h)].append(h)
        return out
