"""SLO-driven autoscaling for the serve fleet (docs/RELIABILITY.md).

The fleet's SLO rollup (:meth:`ServeFleet.slo_summary`) already measures
demand — ``fleet_qps`` against what one replica sustains, ``fleet_p99_ms``
against the latency objective. The autoscaler turns that into a **target
replica count** and actuates it through the elastic-membership machinery
(:meth:`ServeFleet.join` / :meth:`ServeFleet.retire`), with three
flap-killers baked into the policy:

- **step-by-one**: each :meth:`Autoscaler.step` changes membership by at
  most one replica, so a demand spike never triggers a thundering herd of
  cold joins;
- **hysteresis**: scale UP when demand exceeds current capacity (or p99
  blows past ``p99_high_ms``); scale DOWN only when demand sits below
  ``(1 - hysteresis)`` of the *post-shrink* capacity AND p99 is already
  under ``p99_low_ms`` — the up and down thresholds never meet, so a
  steady load cannot oscillate the count;
- **cooldown**: ``cooldown_s`` between actuations — a join's prewarm and
  the ring remap are fully absorbed before the next decision reads the
  SLOs they perturbed.

The policy itself (:meth:`Autoscaler.target`) is a pure function of the
SLO dict — unit-testable with no fleet, no threads, no clock — and the
actuator (:meth:`Autoscaler.step`) is explicitly driven (the loadgen's
elastic mode calls it; an operator loop would call it on a timer), so
tests control exactly when scaling happens.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from .. import obs
from ..obs import flightrec
from ..tune import defaults as knobs


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Policy knobs (defaults from ``tune/defaults.py``)."""

    min_replicas: int = 1
    max_replicas: int = 8
    target_qps_per_replica: float = knobs.AUTOSCALE_TARGET_QPS_PER_REPLICA
    hysteresis: float = knobs.AUTOSCALE_HYSTERESIS
    p99_high_ms: float = knobs.AUTOSCALE_P99_HIGH_MS
    p99_low_ms: float = knobs.AUTOSCALE_P99_LOW_MS
    cooldown_s: float = knobs.AUTOSCALE_COOLDOWN_S


class Autoscaler:
    """Policy + actuator over a :class:`~fakepta_tpu.serve.ServeFleet`.

    ``spawn`` builds a fresh un-joined replica for a scale-up —
    ``spawn(index) -> replica`` — so the transport (LocalReplica,
    SocketReplica, a k8s pod) is the caller's choice, not the policy's.
    """

    def __init__(self, fleet, spawn: Callable[[int], object],
                 config: Optional[AutoscaleConfig] = None):
        self.fleet = fleet
        self.spawn = spawn
        self.config = config or AutoscaleConfig()
        self.scale_events = 0
        self._spawned = 0
        self._last_action_t: Optional[float] = None

    # -- the pure policy ---------------------------------------------------
    def target(self, slo: dict) -> int:
        """Desired replica count from one SLO rollup (pure; see module
        docstring for the hysteresis contract)."""
        cfg = self.config
        alive = max(int(slo.get("fleet_replicas_alive", 1)), 1)
        qps = float(slo.get("fleet_qps", 0.0))
        p99 = float(slo.get("fleet_p99_ms", 0.0))
        demand = qps / cfg.target_qps_per_replica    # replicas of load
        want = alive
        if p99 > cfg.p99_high_ms or demand > alive:
            want = alive + 1
        elif (p99 < cfg.p99_low_ms and alive > 1
                and demand < (alive - 1) * (1.0 - cfg.hysteresis)):
            want = alive - 1
        return max(cfg.min_replicas, min(cfg.max_replicas, want))

    # -- the actuator ------------------------------------------------------
    def step(self, now: Optional[float] = None) -> dict:
        """One control-loop tick: read the SLOs, move membership at most
        one replica toward the target (honoring the cooldown). Returns
        the decision record (also flight-recorded)."""
        cfg = self.config
        now = obs.now() if now is None else float(now)
        slo = self.fleet.slo_summary()
        alive = max(int(slo.get("fleet_replicas_alive", 1)), 1)
        want = self.target(slo)
        decision = {"alive": alive, "want": want, "action": "hold"}
        if want == alive:
            return decision
        if (self._last_action_t is not None
                and now - self._last_action_t < cfg.cooldown_s):
            decision["action"] = "cooldown"
            return decision
        if want > alive:
            self._spawned += 1
            index = len(self.fleet.replicas) + self._spawned
            replica = self.spawn(index)
            joined = self.fleet.join(replica)
            decision.update(action="up", replica=replica.id,
                            warm_loads=joined.get("warm_loads", 0))
        else:
            # deterministic victim: the lexicographically last live
            # replica (scale-downs retire the newest `scale-N` join
            # first, never the seed replicas)
            victim = sorted(self.fleet.alive_replicas())[-1]
            self.fleet.retire(victim)
            decision.update(action="down", replica=victim)
        self._last_action_t = now
        self.scale_events += 1
        obs.count("fleet.scale_events")
        flightrec.note("fleet_scale", **{k: v for k, v in decision.items()
                                         if isinstance(v, (int, str))})
        return decision
