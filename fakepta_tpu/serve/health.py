"""Active health plane for the serve fleet: heartbeats + circuit breakers.

Before this module, a *wedged* replica (process alive, dispatcher stuck —
a hung drain, a blocked socket) was only discovered when a user request
timed out into it: the transport's ``io_timeout_s`` is minutes, so one
stuck replica cost minutes of client-visible latency per routed request.
The health plane probes every replica **out of band** and classifies it
before traffic does (docs/RELIABILITY.md "Fleet lifecycle"):

- **probe**: a tiny no-op ``ping`` over the replica's existing mux'd
  connection (protocol kind ``ping``, serve/cli.py) with a bounded
  deadline (``HEARTBEAT_DEADLINE_S``) — nothing to compile, nothing to
  queue behind the scheduler, so a missed probe means the *process or its
  reader/writer plumbing* is stuck, not that it is merely busy;
- **states**: ``healthy`` -> (consecutive misses) -> ``suspect`` ->
  ``wedged`` -> (transport EOF / kill) -> ``dead``. Suspect and wedged
  replicas are **breakered**: :meth:`HealthMonitor.routable` returns
  False and the router stops handing them new work, while probing
  continues with exponential backoff (``BREAKER_BACKOFF_BASE_S`` doubling
  to ``BREAKER_BACKOFF_CAP_S``);
- **breaker close**: only after ``BREAKER_CLOSE_AFTER`` *consecutive*
  probe successes does a breakered replica take traffic again — a single
  lucky probe never closes the breaker;
- **dead**: transport-level death (reader EOF, SIGKILL) is detected by
  the fleet's reader threads immediately — typically *faster* than one
  heartbeat period — and the monitor just records the terminal state.

Chaos: every probe passes the ``fleet.heartbeat`` fault site with
``replica=<id>`` context, so a ``hang`` spec (matched to one replica via
``FaultSpec.match``) simulates a wedge — the probe sleeps past its
deadline and counts as a miss — and a ``transient`` is one flaky probe.

The monitor is one daemon thread owned by the fleet
(:meth:`ServeFleet.enable_health`); it holds NO fleet lock while probing
(a probe can block for the deadline), snapshots the replica map instead,
and shuts down with a bounded join (the ``unbounded-thread-join``
invariant, docs/INVARIANTS.md).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional

from .. import faults as faults_mod
from .. import obs
from ..obs import flightrec
from ..tune import defaults as knobs

#: the health states, in degradation order
STATES = ("healthy", "suspect", "wedged", "dead")


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Heartbeat/breaker knobs (defaults from ``tune/defaults.py`` — the
    sanctioned knob home; tests shrink the periods, production keeps
    them)."""

    period_s: float = knobs.HEARTBEAT_PERIOD_S
    probe_deadline_s: float = knobs.HEARTBEAT_DEADLINE_S
    suspect_after: int = knobs.HEARTBEAT_SUSPECT_AFTER
    wedged_after: int = knobs.HEARTBEAT_WEDGED_AFTER
    close_after: int = knobs.BREAKER_CLOSE_AFTER
    backoff_base_s: float = knobs.BREAKER_BACKOFF_BASE_S
    backoff_cap_s: float = knobs.BREAKER_BACKOFF_CAP_S
    #: telemetry scrape cadence: scrape every Nth successful probe of a
    #: replica (0 disables scraping). The scrape RIDES the heartbeat —
    #: same mux'd connection, no new sockets (docs/OBSERVABILITY.md)
    scrape_every: int = knobs.TELEMETRY_SCRAPE_EVERY


class _ReplicaHealth:
    __slots__ = ("state", "misses", "ok_streak", "next_probe_t",
                 "backoff_s", "probes", "total_misses")

    def __init__(self):
        self.state = "healthy"
        self.misses = 0            # consecutive
        self.ok_streak = 0         # consecutive
        self.next_probe_t = 0.0    # monotonic; 0 -> probe immediately
        self.backoff_s = 0.0
        self.probes = 0
        self.total_misses = 0


class HealthMonitor:
    """The fleet's heartbeat thread (module docstring).

    ``fleet`` is duck-typed: it exposes ``replicas`` (id -> replica with
    ``alive`` and ``ping(deadline_s)``) and ``_lock`` guarding the map.
    """

    def __init__(self, fleet, config: Optional[HealthConfig] = None,
                 aggregator=None):
        self.fleet = fleet
        self.config = config or HealthConfig()
        #: fleet-level TelemetryAggregator fed by the heartbeat scrape
        #: (None = health plane only, no telemetry)
        self.aggregator = aggregator
        self._states: Dict[str, _ReplicaHealth] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.heartbeat_misses = 0
        self.breaker_opens = 0
        self.breaker_closes = 0
        self.probes = 0
        self.scrapes = 0
        self.scrape_errors = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "HealthMonitor":
        if self._thread is not None:
            raise RuntimeError("health monitor already started")
        self._thread = threading.Thread(target=self._run,
                                        name="fleet-health", daemon=True)
        self._thread.start()
        flightrec.note("health_start",
                       period_s=self.config.period_s,
                       deadline_s=self.config.probe_deadline_s)
        return self

    def stop(self, timeout_s: float = 30.0) -> None:
        """Bounded shutdown: a probe stuck in an injected hang may hold
        the thread for its ``hang_s``; the join is bounded and an expiry
        is flight-recorded, never a silent hang (the
        ``unbounded-thread-join`` invariant)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout_s)
            if t.is_alive():
                flightrec.note("health_stop_join_timeout",
                               timeout_s=timeout_s)

    # -- routing hook ------------------------------------------------------
    def routable(self, rid: str) -> bool:
        """False while the replica's breaker is open (suspect/wedged) or
        it is dead; a replica the monitor has not probed yet is routable
        (innocent until a missed heartbeat)."""
        st = self._states.get(rid)
        return st is None or st.state == "healthy"

    def state(self, rid: str) -> str:
        st = self._states.get(rid)
        return st.state if st is not None else "healthy"

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {rid: st.state for rid, st in self._states.items()}

    def forget(self, rid: str) -> None:
        """Drop a retired replica's record (fleet.retire)."""
        with self._lock:
            self._states.pop(rid, None)

    def stats(self) -> dict:
        """The ``fleet_*`` health counters merged into
        :meth:`ServeFleet.slo_summary` (direction tables: misses and
        breaker opens regress upward)."""
        with self._lock:
            wedged = sum(1 for s in self._states.values()
                         if s.state == "wedged")
            breakered = sum(1 for s in self._states.values()
                            if s.state in ("suspect", "wedged"))
            return {
                "fleet_probes": self.probes,
                "fleet_heartbeat_misses": self.heartbeat_misses,
                "fleet_breaker_opens": self.breaker_opens,
                "fleet_breaker_closes": self.breaker_closes,
                "fleet_breakered": breakered,
                "fleet_wedged": wedged,
                "fleet_scrapes": self.scrapes,
                "fleet_scrape_errors": self.scrape_errors,
            }

    def reset_counters(self) -> None:
        """Loadgen warmup/measure boundary (states are NOT reset — a
        breakered replica stays breakered across the boundary)."""
        with self._lock:
            self.heartbeat_misses = 0
            self.breaker_opens = 0
            self.breaker_closes = 0
            self.probes = 0
            self.scrapes = 0
            self.scrape_errors = 0

    # -- the monitor thread ------------------------------------------------
    def _run(self) -> None:
        # the loop quantum bounds stop() latency without busy-waiting;
        # probes themselves are scheduled per replica on period/backoff
        quantum = min(max(self.config.period_s / 4.0, 0.005), 0.25)
        while not self._stop.is_set():
            now = obs.now()
            with self.fleet._lock:
                replicas = dict(self.fleet.replicas)
            for rid, replica in replicas.items():
                if self._stop.is_set():
                    break
                with self._lock:
                    st = self._states.setdefault(rid, _ReplicaHealth())
                if st.state == "dead":
                    continue
                if not getattr(replica, "alive", False):
                    self._transition(rid, st, "dead", why="transport dead")
                    continue
                if now < st.next_probe_t:
                    continue
                self._probe(rid, replica, st)
            self._stop.wait(quantum)

    def _probe(self, rid: str, replica, st: _ReplicaHealth) -> None:
        cfg = self.config
        t0 = obs.now()
        ok = True
        why = ""
        try:
            # chaos site: a matched `hang` sleeps HERE (in the monitor
            # thread) past the deadline -> a missed probe, exactly what a
            # wedged replica looks like; `transient` is one flaky probe
            faults_mod.check("fleet.heartbeat", replica=rid)
            replica.ping(cfg.probe_deadline_s)
        except faults_mod.TransientFault:
            ok, why = False, "injected transient probe failure"
        except BaseException as exc:  # noqa: BLE001 — a probe may fail
            # with anything the transport can raise (timeout, OSError,
            # ReplicaDead); every failure is a miss, never a crash of the
            # monitor thread
            ok, why = False, repr(exc)[:120]
        elapsed = obs.now() - t0
        if elapsed > cfg.probe_deadline_s:
            ok, why = False, (why or f"probe took {elapsed:.3f}s "
                                     f"> {cfg.probe_deadline_s}s deadline")
        now = obs.now()
        with self._lock:
            self.probes += 1
            st.probes += 1
        if ok:
            st.misses = 0
            st.ok_streak += 1
            if (st.state in ("suspect", "wedged")
                    and st.ok_streak >= cfg.close_after):
                st.backoff_s = 0.0
                self._transition(rid, st, "healthy",
                                 why=f"{st.ok_streak} consecutive probe "
                                     f"successes")
                with self._lock:
                    self.breaker_closes += 1
            # telemetry piggyback: the scrape reuses the probe's mux'd
            # connection on the probe's cadence — by construction there is
            # no telemetry socket, timer, or thread to add
            self._scrape(rid, replica, st)
            st.next_probe_t = now + (cfg.period_s if st.state == "healthy"
                                     else st.backoff_s or cfg.period_s)
            return
        # a miss
        st.ok_streak = 0
        st.misses += 1
        st.total_misses += 1
        with self._lock:
            self.heartbeat_misses += 1
        obs.count("fleet.heartbeat_misses")
        if not getattr(replica, "alive", False):
            self._transition(rid, st, "dead", why=why)
            return
        if st.state == "healthy" and st.misses >= cfg.suspect_after:
            # breaker OPENS: drain new routes, probe with backoff
            st.backoff_s = cfg.backoff_base_s
            self._transition(rid, st, "suspect", why=why)
            with self._lock:
                self.breaker_opens += 1
            obs.count("fleet.breaker_opens")
        elif st.state == "suspect" and st.misses >= cfg.wedged_after:
            self._transition(rid, st, "wedged", why=why)
        if st.state in ("suspect", "wedged"):
            st.next_probe_t = now + st.backoff_s
            st.backoff_s = min(st.backoff_s * 2.0 or cfg.backoff_base_s,
                               cfg.backoff_cap_s)
        else:
            st.next_probe_t = now + cfg.period_s
        return

    def _scrape(self, rid: str, replica, st: _ReplicaHealth) -> None:
        """Scrape one replica's telemetry snapshot into the aggregator.

        Best-effort by contract: a failed scrape is counted and
        flight-recorded but is NEVER a heartbeat miss — telemetry must not
        be able to breaker a healthy replica. Runs only after a probe
        SUCCESS, so it adds zero traffic to a struggling replica."""
        agg = self.aggregator
        cfg = self.config
        if agg is None or cfg.scrape_every <= 0:
            return
        if st.probes % cfg.scrape_every:
            return
        scrape = getattr(replica, "telemetry", None)
        if scrape is None:
            return
        try:
            # chaos site: a `transient`/`hang` here exercises exactly the
            # scrape path, distinct from the heartbeat's own site
            faults_mod.check("telemetry.scrape", replica=rid)
            snap = scrape(cfg.probe_deadline_s)
        except BaseException as exc:  # noqa: BLE001 — best-effort scrape
            with self._lock:
                self.scrape_errors += 1
            obs.count("telemetry.scrape_errors")
            flightrec.note("telemetry_scrape_failed", replica=rid,
                           error=repr(exc)[:160])
            return
        if not snap:
            return
        agg.ingest(rid, snap, health={
            "state": st.state, "misses": st.misses,
            "breaker_open": st.state in ("suspect", "wedged")})
        with self._lock:
            self.scrapes += 1

    def _transition(self, rid: str, st: _ReplicaHealth, to: str,
                    why: str = "") -> None:
        if st.state == to:
            return
        flightrec.note("health_transition", replica=rid,
                       frm=st.state, to=to, misses=st.misses,
                       why=str(why)[:160])
        st.state = to
