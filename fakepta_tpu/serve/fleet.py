"""Horizontal scale-out: a spec-hash-routed fleet of ServePool replicas.

One :class:`~fakepta_tpu.serve.ServePool` is one dispatcher on one
process: aggregate throughput is capped at a single chip's coalescing win
and warm capacity at one LRU pool (``max_specs`` resident specs). The
fleet tier puts a router in front of N replicas (docs/SERVING.md "Fleet"):

- **spec-hash routing** (:mod:`.router`): requests consistent-hash by
  ``spec_hash`` so each replica's warm pool stays hot on its shard of the
  spec space — aggregate warm capacity scales N×, and on multi-chip hosts
  the N dispatchers run in parallel on disjoint devices;
- **spillover**: a saturated owner (its fleet in-flight bound, or a
  ``ServeBusy`` from its own admission control) spills to the ring's next
  replica — deterministic per spec, so degraded traffic converges on one
  sibling's warm pool instead of churning the whole fleet;
- **fleet-wide backpressure**: when every live replica is saturated the
  router raises its own :class:`~fakepta_tpu.serve.ServeBusy` whose
  ``retry_after_s`` aggregates the per-replica backlog hints (the
  smallest — the first replica expected to free up);
- **failover**: a dead or wedged replica (connection loss, closed pool,
  an injected ``fleet.replica`` kill) triggers mid-flight re-dispatch of
  its in-flight requests to the next live sibling. This is
  correctness-safe because of the per-request RNG-lane contract: a
  re-dispatched request draws the same streams on any replica, so the
  failed-over response is bit-identical to a solo run at the same
  executable shape (tests/test_fleet.py pins it);
- **shared compile cache**: every replica points at the same persistent
  compile cache (``FAKEPTA_TPU_COMPILE_CACHE``), so a replica cold-start
  — or a sibling absorbing a failed replica's shard — is a cache *load*,
  not a compile;
- **posterior-as-a-service** (:class:`SamplingSession`): long-running
  sampling runs with replica affinity, segment-boundary checkpoints as
  the migration unit on failover (cross-mesh resume is bit-exact, PR 8),
  and per-segment streamed thinned-sample delivery.

Two replica transports share one interface: :class:`LocalReplica` wraps an
in-process pool (embedding + the lean tier-1 tests), :class:`SocketReplica`
spawns ``python -m fakepta_tpu.serve replica`` and speaks the JSON-lines
socket protocol (the production shape; ``serve/cli.py``). The fleet itself
is transport-agnostic.

Observability: :meth:`ServeFleet.slo_summary` rolls the router's counters
(``fleet_qps_per_chip``, ``fleet_p50_ms``/``fleet_p99_ms``,
``fleet_failovers``, ``fleet_warm_hit_rate``, ...) into the obs direction
tables; per-replica RunReports carry a ``process_index`` so ``obs trace``
merges them into one timeline with a pid lane per replica.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import socket
import subprocess
import sys
import threading
import time
from concurrent.futures import Future
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import faults as faults_mod
from .. import obs
from ..obs import flightrec
from .router import HashRing
from .scheduler import ServeConfig, ServePool, ServeResult
from .spec import (ArraySpec, ServeBusy, ServeClosed, ServeError,
                   SimRequest, resolve_spec_hash)

#: maximum protocol line a replica client will read before declaring the
#: frame malformed (mirrors the server-side bound in serve/cli.py)
MAX_LINE_BYTES = 8 * 1024 * 1024


class ReplicaDead(ServeError):
    """The target replica is gone (process death, connection loss, closed
    pool): the router fails over instead of retrying in place."""


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Router-tier knobs (per-replica scheduler knobs stay in
    :class:`~fakepta_tpu.serve.ServeConfig`).

    ``max_inflight_per_replica`` is the router's own admission bound — the
    fleet-side analog of ``ServeConfig.max_queue_depth`` (both exist: the
    router bounds what it hands a replica, the replica bounds what it
    accepts from everyone). ``max_failovers`` caps per-request
    re-dispatches so a poisoned request cannot tour the fleet forever.
    """

    max_inflight_per_replica: int = 64
    max_failovers: int = 2
    vnodes: int = 64
    result_window: int = 4096        # fleet SLO ring capacity (requests)


class _Inflight:
    __slots__ = ("req", "spec_hash", "outer", "t_enq", "failovers",
                 "replica_id", "owner_id")

    def __init__(self, req, spec_hash, outer, t_enq, owner_id):
        self.req = req
        self.spec_hash = spec_hash
        self.outer = outer
        self.t_enq = t_enq
        self.failovers = 0
        self.replica_id = None
        self.owner_id = owner_id


# ---------------------------------------------------------------------------
# replica transports
# ---------------------------------------------------------------------------

class LocalReplica:
    """An in-process replica: one :class:`ServePool` behind the fleet
    interface (embedding, and the transport the lean tier-1 fleet tests
    run — no subprocess startup, same routing/failover semantics)."""

    def __init__(self, replica_id: str, mesh=None,
                 config: Optional[ServeConfig] = None,
                 compile_cache_dir: Optional[str] = None, index: int = 0):
        self.id = str(replica_id)
        self.index = int(index)
        self.pool = ServePool(mesh=mesh, config=config,
                              compile_cache_dir=compile_cache_dir)
        self.alive = True
        self._compile_cache_dir = self.pool._pool.cache_dir

    @property
    def n_devices(self) -> int:
        return self.pool.n_devices

    def device_ids(self) -> Tuple[int, ...]:
        return tuple(int(d.id) for d in self.pool.mesh.devices.flat)

    def submit(self, req) -> Future:
        if not self.alive:
            raise ReplicaDead(f"replica {self.id} is dead")
        try:
            return self.pool.submit(req)
        except ServeClosed as exc:
            self.alive = False
            raise ReplicaDead(f"replica {self.id} pool is closed") from exc

    def retry_hint(self) -> float:
        with self.pool._lock:
            return self.pool._retry_after_locked()

    def slo_summary(self) -> dict:
        return self.pool.slo_summary()

    def report(self):
        rep = self.pool.report()
        rep.meta["process_index"] = self.index
        rep.meta["replica_id"] = self.id
        return rep

    def sampling_run(self, sess: "SampleSessionSpec"):
        """Build the session's :class:`~fakepta_tpu.sample.SamplingRun` on
        THIS replica's mesh (the affinity contract: the staged moments and
        warm start live with the replica that owns the session)."""
        return build_session_run(sess, self.pool.mesh,
                                 compile_cache_dir=self._compile_cache_dir)

    def ping(self, deadline_s: float = 1.0) -> bool:
        """Health probe (serve/health.py): alive means the pool's
        dispatcher thread is actually running, not just the flag."""
        if not self.alive or not self.pool._dispatcher.is_alive():
            raise ReplicaDead(f"replica {self.id} dispatcher is gone")
        return True

    def telemetry(self, deadline_s: float = 1.0) -> dict:
        """Telemetry scrape (serve/health.py piggyback): one publisher
        snapshot, read in-process — the deadline is the socket
        transport's concern."""
        if not self.alive:
            raise ReplicaDead(f"replica {self.id} is dead")
        return self.pool.telemetry_snapshot()

    def kill(self) -> None:
        """Simulated replica death: pending work fails like a crashed
        process (the in-process analog of SIGKILL for the chaos tests)."""
        self.alive = False
        self.pool.close(drain=False)

    def close(self) -> None:
        self.alive = False
        self.pool.close()


class SocketReplica:
    """A subprocess replica speaking the JSON-lines socket protocol.

    Spawns ``python -m fakepta_tpu.serve replica --port 0`` (the hardened
    socket server), reads its one-line JSON ready banner for the bound
    port, and multiplexes requests over a single connection: a writer
    lock serializes request lines, one reader thread resolves futures by
    ``id``. Reader EOF or a socket error marks the replica dead and fails
    every in-flight future with :class:`ReplicaDead` — which is what
    triggers the router's mid-flight failover.
    """

    def __init__(self, replica_id: str, spec_defaults: Optional[ArraySpec] = None,
                 compile_cache_dir: Optional[str] = None,
                 buckets: Optional[Sequence[int]] = None, index: int = 0,
                 devices: Optional[int] = 1, jax_platform: str = "cpu",
                 startup_timeout_s: float = 120.0,
                 io_timeout_s: float = 600.0, report_path=None,
                 connect: Optional[Tuple[str, int]] = None,
                 n_devices: int = 1):
        self.id = str(replica_id)
        self.index = int(index)
        self.alive = False
        self._lock = threading.Lock()
        self._pending: dict = {}          # req id -> Future
        self._next_id = 0
        if connect is not None:
            # attach mode (the join handshake, docs/RELIABILITY.md "Fleet
            # lifecycle"): the replica process already exists — it dialed
            # the router's admin port with a `hello` — so there is nothing
            # to spawn; we connect to its advertised serving port. kill()
            # severs the connection instead of killing a process we do
            # not own.
            self.proc = None
            host, self.port = str(connect[0]), int(connect[1])
            self.n_devices = int(n_devices)
        else:
            if spec_defaults is None:
                raise ValueError("spawn mode needs spec_defaults "
                                 "(attach mode passes connect=)")
            cmd = [sys.executable, "-m", "fakepta_tpu.serve", "replica",
                   "--port", "0", "--emit", "full",
                   "--index", str(self.index),
                   "--npsr", str(spec_defaults.npsr),
                   "--ntoa", str(spec_defaults.ntoa)]
            if jax_platform:
                cmd += ["--jax-platform", jax_platform]
            if devices:
                cmd += ["--devices", str(devices)]
            import jax
            if jax.config.jax_enable_x64:
                # the replica must share the router's x64 mode: scalar
                # promotion differences would break response bit-identity
                cmd += ["--x64"]
            if compile_cache_dir:
                cmd += ["--compile-cache", str(compile_cache_dir)]
            if buckets:
                cmd += ["--buckets"] + [str(b) for b in buckets]
            if report_path is not None:
                cmd += ["--report", str(report_path)]
            # the package root on the child's import path regardless of the
            # caller's cwd (python -m resolves from cwd)
            pkg_root = str(Path(__file__).resolve().parents[2])
            self.proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                         stderr=subprocess.DEVNULL, text=True,
                                         cwd=pkg_root)
            banner = self._read_banner(startup_timeout_s)
            self.port = int(banner["port"])
            self.n_devices = int(banner.get("n_devices", 1))
            host = "127.0.0.1"
        self.sock = socket.create_connection((host, self.port),
                                             timeout=io_timeout_s)
        # the connect timeout persists as the I/O deadline: a wedged (not
        # just dead) replica surfaces as a timed-out read -> ReplicaDead
        # -> failover, never a pinned reader thread (the
        # unbounded-socket-io invariant, docs/INVARIANTS.md)
        self.sock.settimeout(io_timeout_s)
        self._rfile = self.sock.makefile("rb")
        self.alive = True
        self._reader = threading.Thread(target=self._read_loop,
                                        name=f"fleet-reader-{self.id}",
                                        daemon=True)
        self._reader.start()

    def _read_banner(self, timeout_s: float) -> dict:
        """The replica's ready line; a subprocess that dies before binding
        surfaces as a loud startup error, never a hang."""
        done = {}

        def wait_line():
            done["line"] = self.proc.stdout.readline()

        t = threading.Thread(target=wait_line, daemon=True)
        t.start()
        t.join(timeout_s)
        line = done.get("line")
        if not line:
            self.proc.kill()
            raise ReplicaDead(
                f"replica {self.id} printed no ready banner within "
                f"{timeout_s}s (startup failure)")
        banner = json.loads(line)
        if banner.get("event") != "ready":
            raise ReplicaDead(f"replica {self.id} bad banner: {banner!r}")
        return banner

    def device_ids(self) -> Tuple[int, ...]:
        return ()

    def submit(self, req) -> Future:
        from .cli import request_to_json

        if not self.alive:
            raise ReplicaDead(f"replica {self.id} is dead")
        fut: Future = Future()
        send_exc: Optional[OSError] = None
        with self._lock:
            req_id = self._next_id
            self._next_id += 1
            self._pending[req_id] = fut
            line = json.dumps(request_to_json(req, req_id)) + "\n"
            try:
                self.sock.sendall(line.encode())
            except OSError as exc:
                self._pending.pop(req_id, None)
                send_exc = exc
        if send_exc is not None:
            self._die(repr(send_exc))
            raise ReplicaDead(
                f"replica {self.id} send failed: {send_exc!r}") from send_exc
        return fut

    def _read_loop(self):
        try:
            for raw in iter(lambda: self._rfile.readline(MAX_LINE_BYTES + 1),
                            b""):
                if len(raw) > MAX_LINE_BYTES:
                    raise ReplicaDead(
                        f"replica {self.id} sent an oversized frame")
                self._on_line(json.loads(raw.decode("utf-8", "replace")))
        except (OSError, ValueError, ReplicaDead) as exc:
            self._die(repr(exc))
            return
        self._die("connection closed (EOF)")

    def _on_line(self, d: dict):
        with self._lock:
            fut = self._pending.pop(d.get("id"), None)
        if fut is None:
            return
        if d.get("ok"):
            fut.set_result(_result_from_json(d))
            return
        code = d.get("code")
        if code == "busy":
            fut.set_exception(ServeBusy(
                d.get("error", "replica busy"),
                retry_after_s=float(d.get("retry_after_s", 0.0))))
        else:
            from .spec import ServeTimeout
            exc_cls = ServeTimeout if code == "timeout" else ServeError
            fut.set_exception(exc_cls(d.get("error", f"replica error "
                                                     f"({code})")))

    def _die(self, why: str):
        """Mark the replica dead and fail its in-flight futures.

        Two phases: state flips under ``self._lock``, futures resolve
        OUTSIDE it. ``set_exception`` runs completion callbacks
        synchronously — the fleet's failover callback re-submits to a
        *sibling* replica and takes the fleet lock plus the sibling's
        lock, so resolving under our own lock is a cross-instance ABBA
        (two replicas dying concurrently while dispatch fails over in
        the other direction deadlock; the lock-order-inversion rule
        catches exactly this shape)."""
        with self._lock:
            if not self.alive and not self._pending:
                return
            self.alive = False
            pending, self._pending = self._pending, {}
        flightrec.note("fleet_replica_lost", replica=self.id, why=why[:200])
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(ReplicaDead(
                    f"replica {self.id} died mid-flight: {why}"))

    def stats(self, timeout: float = 60.0) -> dict:
        """The replica's live ServePool SLO summary (protocol kind
        ``stats`` — how the router audits warm-pool health fleet-wide)."""
        if not self.alive:
            raise ReplicaDead(f"replica {self.id} is dead")
        fut: Future = Future()
        send_exc: Optional[OSError] = None
        with self._lock:
            req_id = self._next_id
            self._next_id += 1
            self._pending[req_id] = fut
            try:
                self.sock.sendall(
                    (json.dumps({"id": req_id, "kind": "stats"}) + "\n")
                    .encode())
            except OSError as exc:
                self._pending.pop(req_id, None)
                send_exc = exc
        if send_exc is not None:
            self._die(repr(send_exc))
            raise ReplicaDead(
                f"replica {self.id} send failed: {send_exc!r}") from send_exc
        got = fut.result(timeout=timeout)
        return got if isinstance(got, dict) else {}

    def retry_hint(self) -> float:
        return 0.0

    def ping(self, deadline_s: float = 1.0) -> bool:
        """Health probe over the mux'd connection (protocol kind
        ``ping`` — answered inline by the replica's connection thread, no
        scheduler queue behind it, so a miss means the process or its
        socket plumbing is stuck, not merely busy). A deadline expiry
        raises; the late pong, if it ever lands, resolves a future nobody
        holds."""
        import concurrent.futures

        if not self.alive:
            raise ReplicaDead(f"replica {self.id} is dead")
        fut: Future = Future()
        send_exc: Optional[OSError] = None
        with self._lock:
            req_id = self._next_id
            self._next_id += 1
            self._pending[req_id] = fut
            try:
                self.sock.sendall(
                    (json.dumps({"id": req_id, "kind": "ping"}) + "\n")
                    .encode())
            except OSError as exc:
                self._pending.pop(req_id, None)
                send_exc = exc
        if send_exc is not None:
            self._die(repr(send_exc))
            raise ReplicaDead(
                f"replica {self.id} send failed: {send_exc!r}") from send_exc
        try:
            fut.result(timeout=deadline_s)
        except concurrent.futures.TimeoutError:
            with self._lock:
                self._pending.pop(req_id, None)
            raise
        return True

    def telemetry(self, deadline_s: float = 1.0) -> dict:
        """Telemetry scrape over the SAME mux'd connection as requests and
        pings (protocol kind ``telemetry``) — the zero-new-connections
        contract of the heartbeat piggyback (docs/OBSERVABILITY.md). A
        deadline expiry raises like :meth:`ping`; the late snapshot, if it
        lands, resolves a future nobody holds."""
        import concurrent.futures

        if not self.alive:
            raise ReplicaDead(f"replica {self.id} is dead")
        fut: Future = Future()
        send_exc: Optional[OSError] = None
        with self._lock:
            req_id = self._next_id
            self._next_id += 1
            self._pending[req_id] = fut
            try:
                self.sock.sendall(
                    (json.dumps({"id": req_id, "kind": "telemetry"}) + "\n")
                    .encode())
            except OSError as exc:
                self._pending.pop(req_id, None)
                send_exc = exc
        if send_exc is not None:
            self._die(repr(send_exc))
            raise ReplicaDead(
                f"replica {self.id} send failed: {send_exc!r}") from send_exc
        try:
            got = fut.result(timeout=deadline_s)
        except concurrent.futures.TimeoutError:
            with self._lock:
                self._pending.pop(req_id, None)
            raise
        return got if isinstance(got, dict) else {}

    def kill(self) -> None:
        """SIGKILL the replica process (the chaos lever: in-flight
        requests fail over through the reader thread's EOF); an adopted
        replica (attach mode) has no process handle — severing the
        connection is the same lever."""
        if self.proc is not None:
            self.proc.kill()
        else:
            try:
                self.sock.close()
            except OSError:
                pass

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        # _die (not a bare attribute write): `alive` is read by dispatch
        # and health threads, so the flip must happen under self._lock,
        # and any straggler in-flight futures must fail rather than hang
        self._die("replica closed")


def _result_from_json(d: dict):
    """A full-emit response line -> :class:`ServeResult` (the socket
    transport reconstitutes exactly what the in-process pool returns; a
    ``stats`` or stream payload passes through as a dict)."""
    if "pong" in d and "curves" not in d:
        return {"pong": True}
    if "stats" in d and "curves" not in d:
        return d["stats"]
    if "telemetry" in d and "curves" not in d:
        return d["telemetry"]
    if "metrics" in d and "curves" not in d:
        return d["metrics"]
    if "stream" in d and "curves" not in d:
        return d["stream"]
    res = ServeResult(
        curves=np.asarray(d["curves"]),
        autos=np.asarray(d["autos"]),
        bin_centers=np.asarray(d.get("bin_centers", [])),
        cohort_requests=int(d.get("cohort_requests", 1)),
        bucket=int(d.get("bucket", 0)))
    res.latency_s = float(d.get("latency_ms", 0.0)) / 1e3
    res.queued_s = float(d.get("queued_ms", 0.0)) / 1e3
    if d.get("os") is not None:
        res.os = d["os"]
    if d.get("lnl") is not None:
        res.lnlike = {"lnl": np.asarray(d["lnl"])}
    return res


# ---------------------------------------------------------------------------
# the router tier
# ---------------------------------------------------------------------------

class _FleetStats:
    def __init__(self, window: int):
        self.latency_ms = collections.deque(maxlen=window)
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self.cancelled = 0
        self.failovers = 0
        self.spillovers = 0
        self.deaths = 0
        self.joins = 0
        self.drains = 0
        self.owner_served = 0
        self.per_replica = collections.Counter()
        self.t_first = None
        self.t_last = None


class ServeFleet:
    """N replicas + the consistent-hash router (module docstring).

    >>> fleet = ServeFleet([LocalReplica("r0"), LocalReplica("r1")])
    >>> res = fleet.serve(SimRequest(spec=ArraySpec(npsr=8), n=4, seed=7))
    >>> res.replica, res.failovers
    """

    def __init__(self, replicas: Sequence, config: Optional[FleetConfig] = None):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.config = config or FleetConfig()
        self.replicas = {r.id: r for r in replicas}
        if len(self.replicas) != len(replicas):
            raise ValueError("replica ids must be unique")
        self.ring = HashRing([r.id for r in replicas],
                             vnodes=self.config.vnodes)
        self._lock = threading.Lock()
        self._inflight = collections.Counter()      # replica id -> count
        self._stats = _FleetStats(self.config.result_window)
        self._closed = False
        # trace propagation (docs/OBSERVABILITY.md): the router mints a
        # trace_id per request (unless the client line carried one) and a
        # router-lane timeline of route spans + failover markers — the
        # fleet report becomes its own pid lane in the merged Chrome trace
        self._t0 = obs.now()
        self._trace_seq = 0
        self._trace_nonce = flightrec.spec_hash(
            {"kind": "fleet-trace", "nonce": id(self)})[:6]
        self._timeline = collections.deque(
            maxlen=self.config.result_window)
        # fleet-level telemetry rollups, fed by the heartbeat scrape
        # (serve/health.py) once enable_health() runs
        from ..obs import telemetry as telemetry_mod
        self.telemetry = telemetry_mod.TelemetryAggregator()
        # the served working set (spec -> buckets it ran at), LRU-bounded:
        # what join() prewarms onto a new replica's absorbed shard
        self._recent: "collections.OrderedDict" = collections.OrderedDict()
        self._recent_cap = 64
        self.health = None                 # HealthMonitor, enable_health()
        self._admin_sock = None            # the join-handshake listener
        self._admin_thread = None
        flightrec.note("fleet_start", replicas=len(replicas))

    # -- chip accounting ---------------------------------------------------
    @property
    def n_chips(self) -> int:
        """Distinct chips under the fleet: local replicas may share
        devices (the CPU stand-in), subprocess replicas own theirs."""
        local_ids: set = set()
        remote = 0
        for r in self.replicas.values():
            ids = r.device_ids()
            if ids:
                local_ids.update(ids)
            else:
                remote += int(r.n_devices)
        return max(len(local_ids) + remote, 1)

    def alive_replicas(self) -> List[str]:
        return [rid for rid, r in self.replicas.items() if r.alive]

    # -- admission / routing ----------------------------------------------
    def submit(self, req) -> Future:
        """Route one request; returns a Future resolving to a
        :class:`ServeResult` whose ``replica``/``failovers`` fields record
        where it ran. Raises :class:`ServeBusy` (with the aggregated
        ``retry_after_s``) when every live replica is saturated,
        :class:`ServeClosed` after shutdown, :class:`ServeError` when no
        replica is alive."""
        with self._lock:
            if self._closed:
                raise ServeClosed("fleet is closed")
        if getattr(req, "stream_affine", False):
            # stream affinity: the routing identity is the STREAM NAME —
            # every append/stats request for one stream prefers the same
            # ring owner, where the accumulated moments live
            spec_hash = flightrec.spec_hash(
                {"kind": "stream", "name": req.affinity_key()})
        elif not isinstance(req.spec, str):
            spec_hash = resolve_spec_hash(req.spec, {})
        else:
            spec_hash = flightrec.spec_hash(
                {"kind": "registered", "name": req.spec})
        if getattr(req, "trace_id", None) is None:
            # mint at the router; a client-supplied trace_id is kept so
            # callers can stitch fleet spans into their own traces
            with self._lock:
                self._trace_seq += 1
                seq = self._trace_seq
            try:
                req = dataclasses.replace(
                    req, trace_id=f"t{self._trace_nonce}-{seq:06d}")
            except TypeError:
                pass          # non-dataclass request object: stays untraced
        outer: Future = Future()
        t = obs.now()
        # ring reads under the fleet lock: membership mutates live now
        # (join/retire), and HashRing is not internally synchronized
        with self._lock:
            owner = self.ring.owner(spec_hash)
        inf = _Inflight(req, spec_hash, outer, t, owner_id=owner)
        with self._lock:
            self._stats.submitted += 1
            if self._stats.t_first is None:
                self._stats.t_first = t
        self._dispatch(inf, exclude=())
        return outer

    def serve(self, req, timeout: Optional[float] = None):
        return self.submit(req).result(timeout=timeout)

    def _mark_dead(self, rid: str, why: str) -> None:
        r = self.replicas.get(rid)
        newly = r is not None and r.alive
        if r is not None:
            r.alive = False
        with self._lock:
            if newly:
                self._stats.deaths += 1
        if newly:
            flightrec.note("fleet_replica_dead", replica=rid,
                           why=str(why)[:200])

    def _dispatch(self, inf: _Inflight, exclude: Tuple[str, ...]) -> None:
        """Try the spec's preference order once; busy replicas spill to
        the next, dead ones are skipped. Runs on the submitter's thread
        first and on a replica's completion thread after a failover."""
        hints: List[float] = []
        spilled = False
        # stream-affine requests NEVER spill on saturation: the stream's
        # moments live on exactly one replica, so a busy owner means
        # ServeBusy, not a sibling (dead owners ARE skipped — failover
        # re-opens the stream, continuous via a shared checkpoint)
        affine = bool(getattr(inf.req, "stream_affine", False))
        hm = self.health
        with self._lock:
            pref = list(self.ring.preference(inf.spec_hash))
        for rid in pref:
            if rid in exclude:
                continue
            replica = self.replicas.get(rid)
            if replica is None or not replica.alive:
                continue
            if hm is not None and not hm.routable(rid):
                # breaker open (suspect/wedged): the health plane drained
                # this replica BEFORE any request could time out into it
                continue
            with self._lock:
                saturated = (self._inflight[rid]
                             >= self.config.max_inflight_per_replica)
                if not saturated:
                    self._inflight[rid] += 1
            if saturated and affine:
                hints.append(replica.retry_hint()
                             if hasattr(replica, "retry_hint") else 0.0)
                break
            if saturated:
                # the hint read takes the replica pool's own lock — NEVER
                # under the fleet lock (a dying pool dispatcher holds its
                # lock while our completion callback takes the fleet
                # lock; nesting the other way would be an ABBA deadlock)
                hints.append(replica.retry_hint()
                             if hasattr(replica, "retry_hint") else 0.0)
                spilled = True
                continue
            # chaos site (docs/RELIABILITY.md): the router's dispatch to a
            # replica — `kill` takes the replica down mid-flight, the
            # failover path must finish the request elsewhere
            try:
                faults_mod.check("fleet.replica", replica=rid)
            except faults_mod.TransientFault:
                with self._lock:
                    self._inflight[rid] -= 1
                spilled = True
                continue
            except faults_mod.KillFault:
                with self._lock:
                    self._inflight[rid] -= 1
                self._mark_dead(rid, "injected fleet.replica kill")
                replica.kill()
                continue
            try:
                inner = replica.submit(inf.req)
            except ServeBusy as busy:
                with self._lock:
                    self._inflight[rid] -= 1
                hints.append(getattr(busy, "retry_after_s", 0.0))
                if affine:
                    break              # no spillover for stream affinity
                with self._lock:
                    self._stats.spillovers += 1
                spilled = True
                continue
            except (ReplicaDead, ConnectionError, OSError) as exc:
                with self._lock:
                    self._inflight[rid] -= 1
                self._mark_dead(rid, repr(exc))
                continue
            except BaseException:
                # validation errors etc. propagate to the submitter, but
                # must not leak the in-flight slot
                with self._lock:
                    self._inflight[rid] -= 1
                raise
            if spilled:
                with self._lock:
                    self._stats.spillovers += 1
                flightrec.note("fleet_spillover", spec=inf.spec_hash,
                               to=rid)
            inf.replica_id = rid
            inner.add_done_callback(
                lambda f, inf=inf, rid=rid: self._on_done(inf, rid, f))
            return
        # nobody took it
        if not self.alive_replicas():
            with self._lock:
                self._stats.failed += 1
            err = ServeError("no live replica in the fleet")
        else:
            hint = min(hints) if hints else 0.0
            with self._lock:
                self._stats.rejected += 1
            flightrec.note("fleet_busy", spec=inf.spec_hash,
                           retry_after_s=round(hint, 4))
            err = ServeBusy(
                f"every live replica is saturated; retry in ~{hint:.3f}s",
                retry_after_s=hint)
        # sync path (first dispatch, called from submit) raises; the
        # failover path resolves the future instead
        if inf.failovers == 0 and not inf.outer.done():
            raise err
        if not inf.outer.done():
            inf.outer.set_exception(err)

    def _on_done(self, inf: _Inflight, rid: str, inner: Future) -> None:
        with self._lock:
            self._inflight[rid] -= 1
        exc = inner.exception()
        if exc is None:
            res = inner.result()
            if isinstance(res, dict):  # stream payloads are plain dicts
                res = dict(res, replica=rid, failovers=inf.failovers)
            else:
                res.replica = rid
                res.failovers = inf.failovers
                # remember the served working set: (spec, bucket) pairs a
                # joining replica prewarms for its absorbed shard
                if not isinstance(getattr(inf.req, "spec", None), str) \
                        and getattr(inf.req, "spec", None) is not None:
                    with self._lock:
                        _spec, buckets = self._recent.setdefault(
                            inf.spec_hash, (inf.req.spec, set()))
                        buckets.add(int(res.bucket))
                        self._recent.move_to_end(inf.spec_hash)
                        while len(self._recent) > self._recent_cap:
                            self._recent.popitem(last=False)
            t_done = obs.now()
            with self._lock:
                st = self._stats
                st.completed += 1
                st.t_last = t_done
                st.latency_ms.append((t_done - inf.t_enq) * 1e3)
                st.per_replica[rid] += 1
                if rid == inf.owner_id:
                    st.owner_served += 1
                ev = {"name": "route", "tid": "router",
                      "t0": inf.t_enq - self._t0,
                      "dur": t_done - inf.t_enq, "replica": rid,
                      "failovers": inf.failovers,
                      "req_kind": getattr(inf.req, "kind", "?")}
                if getattr(inf.req, "trace_id", None):
                    ev["trace_id"] = inf.req.trace_id
                self._timeline.append(ev)
            inf.outer.set_result(res)
            return
        verdict = faults_mod.classify_replica(exc)
        if (verdict == "replica_death"
                and inf.failovers < self.config.max_failovers):
            self._mark_dead(rid, repr(exc))
            inf.failovers += 1
            with self._lock:
                self._stats.failovers += 1
                ev = {"name": "fleet_failover", "tid": "router",
                      "t0": obs.now() - self._t0,
                      "from_replica": rid, "attempt": inf.failovers}
                if getattr(inf.req, "trace_id", None):
                    ev["trace_id"] = inf.req.trace_id
                self._timeline.append(ev)
            flightrec.note("fleet_failover", spec=inf.spec_hash,
                           from_replica=rid, attempt=inf.failovers)
            # re-dispatch to the ring's next live sibling: per-request RNG
            # lanes make the rerun bit-identical per executable shape
            try:
                self._dispatch(inf, exclude=(rid,))
            except ServeBusy as busy:
                if not inf.outer.done():
                    inf.outer.set_exception(busy)
            return
        if isinstance(exc, ServeBusy) and not getattr(
                inf.req, "stream_affine", False) and inf.failovers \
                < self.config.max_failovers:
            # async 429 from a socket replica: spill, not fail (stream-
            # affine requests surface the busy instead — no spillover)
            inf.failovers += 1
            with self._lock:
                self._stats.spillovers += 1
            try:
                self._dispatch(inf, exclude=(rid,))
            except ServeBusy as busy:
                if not inf.outer.done():
                    inf.outer.set_exception(busy)
            return
        from .spec import ServeTimeout
        with self._lock:
            if isinstance(exc, ServeTimeout):
                self._stats.cancelled += 1
            else:
                self._stats.failed += 1
        if not inf.outer.done():
            inf.outer.set_exception(exc)

    # -- observability -----------------------------------------------------
    def slo_summary(self) -> dict:
        """Fleet-level SLO rollup (the ``fleet_*`` rows in
        docs/SERVING.md's metric table, direction-aware under
        ``obs compare``/``gate``)."""
        with self._lock:
            st = self._stats
            lat = np.asarray(st.latency_ms, dtype=float)
            span = ((st.t_last - st.t_first)
                    if st.t_last is not None and st.t_first is not None
                    else 0.0)
            qps = st.completed / span if span > 0 else 0.0
            out = {
                "fleet_replicas": len(self.replicas),
                "fleet_replicas_alive": len(self.alive_replicas()),
                "fleet_requests": st.completed,
                "fleet_failed": st.failed,
                "fleet_rejected": st.rejected,
                "fleet_qps": round(qps, 3),
                "fleet_qps_per_chip": round(qps / self.n_chips, 3),
                "fleet_p50_ms": round(float(np.percentile(lat, 50)), 3)
                if lat.size else 0.0,
                "fleet_p99_ms": round(float(np.percentile(lat, 99)), 3)
                if lat.size else 0.0,
                "fleet_failovers": st.failovers,
                "fleet_spillovers": st.spillovers,
                "fleet_timeouts": st.cancelled,
                "fleet_joins": st.joins,
                "fleet_drains": st.drains,
                # derived, not the router's counter: a death detected by
                # the transport alone (reader EOF with nothing in flight)
                # must still show up here
                "fleet_replica_deaths": (len(self.replicas)
                                         - len(self.alive_replicas())),
                # the affinity health metric: fraction of completed
                # requests served by their spec's ring owner — the warm
                # pools are hot exactly when this stays ~1.0
                "fleet_warm_hit_rate": round(
                    st.owner_served / st.completed, 4)
                if st.completed else 0.0,
            }
        # per-replica pool health where the transport exposes it (local
        # pools always; socket replicas answer the `stats` protocol kind)
        import concurrent.futures

        compiles = retraces = 0
        seen = 0
        for r in self.replicas.values():
            if not r.alive:
                continue
            try:
                s = (r.slo_summary() if hasattr(r, "slo_summary")
                     else r.stats(timeout=30.0))
            except (ServeError, OSError, RuntimeError,
                    concurrent.futures.TimeoutError):
                continue
            if not isinstance(s, dict) or "serve_steady_compiles" not in s:
                continue
            seen += 1
            compiles += int(s.get("serve_steady_compiles", 0))
            retraces += int(s.get("serve_retraces", 0))
        if seen:
            out["fleet_steady_compiles"] = compiles
            out["fleet_retraces"] = retraces
        hm = self.health
        if hm is not None:
            out.update(hm.stats())
        return out

    def reset_stats(self) -> None:
        """Zero the router's SLO accumulators (the loadgen warmup/measure
        boundary); replica pools reset theirs separately."""
        with self._lock:
            self._stats = _FleetStats(self.config.result_window)
            self._timeline.clear()
            self._t0 = obs.now()
        if self.health is not None:
            self.health.reset_counters()
        for r in self.replicas.values():
            if isinstance(r, LocalReplica) and r.alive:
                r.pool.reset_stats()

    def report(self):
        """Fleet-level RunReport (kind ``serve_fleet``): the router's SLO
        rollup; per-replica reports merge into a pid-lane trace via
        :meth:`replica_reports` + ``obs trace``."""
        from ..obs import RunReport

        meta = {
            "kind": "serve_fleet",
            "replicas": len(self.replicas),
            "n_chips": self.n_chips,
            "extra_metrics": self.slo_summary(),
        }
        rep = RunReport(meta=meta)
        with self._lock:
            timeline = list(self._timeline)
        rep.timeline = sorted(timeline, key=lambda e: e.get("t0", 0.0))
        return rep

    def replica_reports(self) -> List:
        """Per-replica RunReports (local transports), each stamped with
        its ``process_index`` — ``obs.tracefmt.build_trace`` renders them
        as one merged timeline with a pid lane per replica (socket
        replicas write the same artifact through ``--report``)."""
        return [r.report() for r in self.replicas.values()
                if hasattr(r, "report") and r.alive]

    # -- posterior-as-a-service -------------------------------------------
    def start_session(self, sess: "SampleSessionSpec",
                      checkpoint) -> "SamplingSession":
        """Open a sampling session with replica affinity (the session's
        hash routes it like any spec) and ``checkpoint`` as the migration
        unit on failover."""
        return SamplingSession(self, sess, checkpoint)

    # -- health plane ------------------------------------------------------
    def enable_health(self, config=None):
        """Start the heartbeat monitor (:mod:`.health`): out-of-band
        ``ping`` probes classify replicas healthy/suspect/wedged/dead and
        open a circuit breaker BEFORE user traffic times out into a
        wedged replica. Idempotent; stopped by :meth:`close`."""
        from .health import HealthMonitor

        if self.health is None:
            # the monitor's probe loop doubles as the telemetry scraper
            # (same mux'd connections — docs/OBSERVABILITY.md)
            self.health = HealthMonitor(
                self, config, aggregator=self.telemetry).start()
        return self.health

    # -- telemetry plane ---------------------------------------------------
    def telemetry_rollup(self) -> dict:
        """The fleet-wide windowed rollup (``obs top``'s data)."""
        return self.telemetry.rollup()

    def metrics_text(self) -> str:
        """Prometheus text exposition of the fleet rollup (the router-side
        twin of the replica ``metrics`` protocol kind)."""
        from ..obs import promfmt
        return promfmt.render(self.telemetry.rollup())

    # -- elastic membership ------------------------------------------------
    def join(self, replica, prewarm: bool = True,
             warm_timeout_s: float = 300.0) -> dict:
        """Adopt ``replica`` into the ring (docs/RELIABILITY.md "Fleet
        lifecycle"): compute the ~1/N shard the post-join ring will route
        to it, prewarm that shard's served working set directly on the
        replica (shared-compile-cache warm loads — 0 steady compiles),
        then add it to the membership under the lock. Prewarm happens
        BEFORE the ring flips so no request ever lands on a cold shard.
        """
        with self._lock:
            if self._closed:
                raise ServeClosed("fleet is closed")
            if replica.id in self.replicas:
                raise ValueError(
                    f"replica {replica.id!r} is already in the fleet")
            existing = list(self.replicas)
            recent = [(sh, spec, tuple(sorted(buckets)))
                      for sh, (spec, buckets) in self._recent.items()]
        warm_loads = 0
        if prewarm and recent:
            tmp = HashRing(existing + [replica.id],
                           vnodes=self.config.vnodes)
            for sh, spec, buckets in recent:
                if tmp.owner(sh) != replica.id:
                    continue
                for b in buckets:
                    try:
                        replica.submit(
                            SimRequest(spec=spec, n=int(b), seed=0)
                        ).result(timeout=warm_timeout_s)
                        warm_loads += 1
                    except (ServeError, OSError, RuntimeError) as exc:
                        flightrec.note("fleet_join_prewarm_failed",
                                       replica=replica.id,
                                       error=repr(exc)[:160])
        with self._lock:
            self.replicas[replica.id] = replica
            self.ring.add(replica.id)
            self._stats.joins += 1
        obs.count("fleet.joins")
        flightrec.note("fleet_join", replica=replica.id,
                       warm_loads=warm_loads, replicas=len(self.replicas))
        return {"replica": replica.id, "warm_loads": warm_loads}

    def retire(self, rid: str, drain_timeout_s: float = 60.0) -> None:
        """Graceful leave: pull ``rid`` off the ring first (no new routes
        — its shard remaps ~1/N to the survivors, whose shared-cache
        loads keep it warm), drain its in-flight work with a bounded
        wait, then close it. Long-running sampling/stream sessions resume
        on the shard's new owner from their checkpoint boundaries (the
        PR 12/14 migration machinery)."""
        with self._lock:
            r = self.replicas.get(rid)
            if r is None:
                raise ValueError(f"replica {rid!r} is not in the fleet")
            live = [x for x in self.replicas.values() if x.alive]
            if r.alive and len(live) <= 1:
                raise ServeError("cannot retire the last live replica")
            self.ring.remove(rid)
        deadline = obs.now() + drain_timeout_s
        drained = False
        while obs.now() < deadline:
            with self._lock:
                if self._inflight[rid] <= 0:
                    drained = True
                    break
            time.sleep(0.01)
        if not drained:
            flightrec.note("fleet_drain_timeout", replica=rid,
                           timeout_s=drain_timeout_s)
        with self._lock:
            self.replicas.pop(rid, None)
            self._stats.drains += 1
        if self.health is not None:
            self.health.forget(rid)
        # watermark-correct retirement: the replica's telemetry window is
        # frozen under `retired`, not dropped
        self.telemetry.retire(rid)
        obs.count("fleet.drains")
        flightrec.note("fleet_drain", replica=rid, drained=bool(drained),
                       replicas=len(self.replicas))
        try:
            r.close()
        except (ServeError, OSError, RuntimeError) as exc:
            flightrec.note("fleet_replica_close_failed", replica=rid,
                           error=repr(exc)[:160])

    def listen(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """The replica-join handshake listener: a freshly spawned
        ``serve replica --register HOST:PORT`` process dials this socket,
        sends one JSON ``hello`` line (its serving port + identity), and
        is adopted via :class:`SocketReplica` attach mode + :meth:`join`;
        the reply line is ``adopt`` (or ``reject`` with the error).
        Returns the bound admin port. Idempotent."""
        if self._admin_sock is not None:
            return self._admin_sock.getsockname()[1]
        srv = socket.create_server((host, port))
        srv.settimeout(0.25)       # bounded accept: close() can stop us
        self._admin_sock = srv
        self._admin_thread = threading.Thread(
            target=self._admin_loop, name="fleet-admin", daemon=True)
        self._admin_thread.start()
        admin_port = srv.getsockname()[1]
        flightrec.note("fleet_listen", port=admin_port)
        return admin_port

    def _admin_loop(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
            try:
                conn, addr = self._admin_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return                    # listener closed
            try:
                self._adopt(conn, addr)
            except (ServeError, OSError, ValueError, RuntimeError,
                    KeyError) as exc:
                flightrec.note("fleet_adopt_failed",
                               error=repr(exc)[:200])
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _adopt(self, conn, addr) -> None:
        conn.settimeout(30.0)
        raw = conn.makefile("rb").readline(MAX_LINE_BYTES + 1)
        hello = json.loads(raw.decode("utf-8", "replace"))
        if hello.get("event") != "hello" or "port" not in hello:
            conn.sendall((json.dumps(
                {"event": "reject", "error": "bad hello"}) + "\n").encode())
            raise ValueError(f"bad hello line: {raw[:200]!r}")
        rid = str(hello.get("replica_id") or f"joined-{hello['port']}")
        try:
            rep = SocketReplica(rid,
                                connect=(addr[0], int(hello["port"])),
                                index=int(hello.get("index", 0)),
                                n_devices=int(hello.get("n_devices", 1)))
            self.join(rep)
        except BaseException as exc:
            conn.sendall((json.dumps(
                {"event": "reject",
                 "error": repr(exc)[:200]}) + "\n").encode())
            raise
        conn.sendall((json.dumps(
            {"event": "adopt", "replica_id": rid,
             "replicas": len(self.replicas)}) + "\n").encode())

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self.health is not None:
            self.health.stop()
        if self._admin_sock is not None:
            try:
                self._admin_sock.close()
            except OSError:
                pass
            t = self._admin_thread
            if t is not None:
                t.join(5.0)
                if t.is_alive():
                    flightrec.note("fleet_admin_join_timeout")
        for r in self.replicas.values():
            try:
                r.close()
            except (ServeError, OSError, RuntimeError) as exc:
                flightrec.note("fleet_replica_close_failed", replica=r.id,
                               error=repr(exc)[:160])

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# posterior-as-a-service
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SampleSessionSpec:
    """A JSON-expressible long-running sampling session: a synthetic array
    (:class:`ArraySpec` — the data side) posterior-sampled under a CURN
    free-spectrum model (the model-independent headline workload,
    docs/SAMPLING.md). Everything here is a plain scalar so the session
    request crosses the socket protocol verbatim (the ``sample`` kind in
    ``serve/cli.py``)."""

    spec: ArraySpec
    n_steps: int = 32
    seed: int = 0
    segment: Optional[int] = None
    nbin: int = 3
    n_chains: int = 4
    n_temps: int = 1
    warmup: int = 8
    thin: int = 1
    step_size: float = 0.3
    n_leapfrog: int = 4
    data_seed: int = 0
    #: factorized bin-lane routing (sample/factorized.py): this session
    #: samples only free-spectrum bins [bin_offset, bin_offset + nbin) ...
    bin_offset: int = 0
    #: ... of a PARENT model with this many bins — the replica then
    #: synthesizes the session's residuals from the parent model, so every
    #: lane of one factorized run (and a solo/local run of the same lane)
    #: samples the IDENTICAL data vector. None = ordinary joint session.
    data_nbin: Optional[int] = None

    def _model(self, nbin: int, bin_offset: int = 0):
        from ..infer import ComponentSpec, FreeParam, LikelihoodSpec

        return LikelihoodSpec(components=(
            ComponentSpec(target="red", spectrum="batch"),
            ComponentSpec(target="dm", spectrum="batch"),
            ComponentSpec(target="curn", nbin=nbin, bin_offset=bin_offset,
                          spectrum="free_spectrum",
                          free=(FreeParam("log10_rho", (-9.0, -5.0),
                                          per_bin=True),)),
        ))

    def sample_spec(self):
        from ..sample import SampleSpec

        model = self._model(self.nbin, self.bin_offset)
        return SampleSpec(model=model, n_chains=self.n_chains,
                          n_temps=self.n_temps, warmup=self.warmup,
                          thin=self.thin, step_size=self.step_size,
                          n_leapfrog=self.n_leapfrog)

    def session_hash(self) -> str:
        d = dataclasses.asdict(self)
        d["spec"] = self.spec.spec_dict()
        d["kind"] = "SampleSession"
        return flightrec.spec_hash(d)


def build_session_run(sess: "SampleSessionSpec", mesh,
                      compile_cache_dir=None):
    """Construct a session's :class:`~fakepta_tpu.sample.SamplingRun` —
    the ONE construction path shared by :meth:`LocalReplica.sampling_run`
    and the socket protocol's ``sample`` kind (serve/cli.py), so a lane
    routed anywhere in the fleet builds the same run a solo caller would.

    For a factorized bin-lane session (``data_nbin`` set) the replica
    reproduces a local :class:`~fakepta_tpu.sample.FactorizedRun` lane
    exactly: residuals are synthesized from the PARENT model at
    ``data_seed`` (a pure function of ``(parent model, batch,
    data_seed)``), the parent moments are staged and the pinned
    components marginalized
    (:func:`~fakepta_tpu.sample.factorized.marginalized_window_moments`),
    and the run is built over the lane-only model with those moments
    injected — so a lane's draws are bit-identical whichever replica
    hosts it and bit-identical to the coalesced local run.
    """
    from ..infer import model as infer_model
    from ..sample import SamplingRun
    from ..sample.factorized import marginalized_window_moments
    from ..sample.run import stage_moments, synthesize_residuals

    batch, _gwb = sess.spec.parts()
    if sess.data_nbin is not None:
        parent = infer_model.build(sess._model(int(sess.data_nbin)), batch)
        truth = parent.theta_from_unit(np.full(parent.D, 0.5))
        residuals = synthesize_residuals(parent, batch, truth,
                                         sess.data_seed)
        mom = stage_moments(parent, batch, residuals)
        lo = int(sess.bin_offset)
        lane_mom = marginalized_window_moments(parent, batch, mom, lo,
                                               lo + int(sess.nbin))
        free_comp = next(c for c in parent.spec.components if c.free)
        lane_comp = dataclasses.replace(free_comp, nbin=int(sess.nbin),
                                        bin_offset=lo)
        lane_spec = dataclasses.replace(
            sess.sample_spec(),
            model=type(parent.spec)(components=(lane_comp,)))
        return SamplingRun(batch, lane_spec, mesh=mesh, moments=lane_mom,
                           data_seed=sess.data_seed,
                           compile_cache_dir=compile_cache_dir)
    return SamplingRun(batch, sess.sample_spec(), mesh=mesh,
                       data_seed=sess.data_seed,
                       compile_cache_dir=compile_cache_dir)


class SamplingSession:
    """One long-running posterior run with replica affinity + failover.

    The session routes to its hash's ring owner and runs there
    segment-by-segment with a checkpoint at every segment boundary. A
    replica death mid-run (an injected ``sample.segment`` /
    ``fleet.replica`` kill, a lost process) migrates the session to the
    ring's next live sibling, which **resumes from the checkpoint** — and
    because cross-mesh segment resume is bit-exact (PR 8,
    tests/test_sample.py), the migrated chains are bit-identical to an
    uninterrupted run. ``on_segment`` streams each post-warmup segment's
    thinned draws as it drains (the socket protocol's ``sample`` kind
    forwards them as one JSON line per segment).
    """

    def __init__(self, fleet: ServeFleet, sess: SampleSessionSpec,
                 checkpoint):
        self.fleet = fleet
        self.sess = sess
        self.checkpoint = Path(checkpoint)
        self.session_hash = sess.session_hash()
        self.migrations = 0
        with fleet._lock:
            self.replica_id = fleet.ring.owner(self.session_hash)

    def _next_replica(self, exclude):
        with self.fleet._lock:
            pref = list(self.fleet.ring.preference(self.session_hash))
        for rid in pref:
            r = self.fleet.replicas.get(rid)
            if (r is not None and r.alive and rid not in exclude
                    and hasattr(r, "sampling_run")):
                return rid
        raise ServeError("no live replica can host the sampling session")

    def run(self, on_segment=None, pipeline_depth: int = 0) -> dict:
        """Drive the session to completion (synchronously; long-running
        sessions get their own thread/connection). Returns the
        :meth:`SamplingRun.run` result dict plus ``session`` bookkeeping.
        """
        tried: list = []
        while True:
            rid = self._next_replica(tried)
            self.replica_id = rid
            replica = self.fleet.replicas[rid]
            flightrec.note("fleet_session_assign", session=self.session_hash,
                           replica=rid, migrations=self.migrations)
            try:
                run = replica.sampling_run(self.sess)
                out = run.run(self.sess.n_steps, seed=self.sess.seed,
                              segment=self.sess.segment,
                              checkpoint=str(self.checkpoint),
                              pipeline_depth=pipeline_depth,
                              on_segment=on_segment)
                out["session"] = {"hash": self.session_hash,
                                  "replica": rid,
                                  "migrations": self.migrations}
                return out
            except BaseException as exc:   # noqa: BLE001 — triaged: only
                # replica-death verdicts migrate, everything else re-raises
                if (faults_mod.classify_replica(exc) != "replica_death"
                        or self.migrations
                        >= self.fleet.config.max_failovers):
                    raise
                self.fleet._mark_dead(rid, repr(exc))
                tried.append(rid)
                self.migrations += 1
                flightrec.note("fleet_session_migrate",
                               session=self.session_hash, from_replica=rid,
                               attempt=self.migrations)
