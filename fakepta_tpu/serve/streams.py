"""StreamManager: the pool-side executor for stream-affine requests.

Stream requests never enter the microbatch scheduler — there is nothing to
coalesce (an append mutates ONE stream's accumulated moments, in order)
and nothing to bucket at the cohort level (the stream buckets its own
append blocks on the :mod:`fakepta_tpu.tune.defaults` ladder).
:meth:`ServePool.submit` intercepts ``stream_affine`` requests before
admission and hands them here; execution is synchronous on the submitter's
thread under a per-stream lock, so appends to one stream serialize (the
additive-update order IS the stream's history) while distinct streams
proceed concurrently.

Sessions are opened lazily by the first :class:`~fakepta_tpu.serve.spec
.AppendRequest` naming a stream: its ``spec``'s synthetic array becomes
the frozen-grid template, and ``ecorr_dt``/``watch``/``checkpoint`` are
open-time options (a later request repeating them is flight-recorded and
ignored — the grid contract forbids reconfiguring a live stream). With a
``checkpoint`` path the open REPLAYS any consistent on-disk blocks, which
is how a fleet failover resumes a stream on a sibling replica.
"""

from __future__ import annotations

import collections
import threading

import numpy as np

from .. import faults, obs
from ..obs import flightrec
from ..tune import defaults as tune_defaults
from .spec import ArraySpec, ServeError

#: payload schema tag for stream responses (mirrors STREAM_SCHEMA's role
#: for on-disk artifacts; versioned separately because the wire payload is
#: a serve-layer contract)
STREAM_PAYLOAD_SCHEMA = "fakepta_tpu.serve-stream/1"


class _StreamSlot:
    """One registered stream: its per-stream lock plus the CURRENT state.

    ``state`` is only read or replaced while holding ``lock`` — that is
    the migration-cutover fence: an appender that was waiting on the lock
    while :meth:`StreamManager.cutover` swapped the state lands its block
    on the NEW template, never on the retired one (zero dropped appends,
    docs/STREAMING.md "Migration cutover")."""

    __slots__ = ("lock", "state")

    def __init__(self, state):
        self.lock = threading.Lock()
        self.state = state


class StreamManager:
    """Named :class:`~fakepta_tpu.stream.StreamState` sessions for one
    pool. ``mesh=None`` keeps stream device arrays unsharded — stream
    state is per-pulsar small and pool meshes need not divide a stream
    template's pulsar count."""

    def __init__(self, mesh=None):
        self.mesh = mesh
        self._lock = threading.Lock()
        self._streams: dict = {}      # name -> _StreamSlot
        # per-stream append-latency rings (telemetry plane): bounded like
        # every other telemetry buffer, read by summary()
        self._append_ms: dict = collections.defaultdict(
            lambda: collections.deque(
                maxlen=tune_defaults.TELEMETRY_RING_SIZE))

    def _session(self, req) -> "_StreamSlot":
        """The :class:`_StreamSlot` for ``req.stream``, opening it when
        the request carries a spec.

        Two-phase open: the registry lock is held only for the dict
        lookups — :class:`StreamState` construction (device allocation,
        checkpoint REPLAY, potentially seconds of work) happens with no
        manager lock held, so appends to every *other* stream keep
        flowing while one stream opens (the blocking-under-lock
        invariant). A racing open of the same name keeps the first
        registered state and discards the loser (replay is read-only, so
        the discarded state touched nothing)."""
        name = str(req.stream)
        if not name:
            raise ServeError("stream requests need a non-empty stream name")
        with self._lock:
            entry = self._streams.get(name)
        if entry is not None:
            if getattr(req, "spec", None) is not None:
                flightrec.note("serve_stream_reopen_ignored",
                               stream=name)
            return entry
        spec = getattr(req, "spec", None)
        if spec is None:
            raise ServeError(
                f"stream {name!r} is not open; the first append must "
                f"carry a spec (its array is the frozen-grid template)")
        if not isinstance(spec, ArraySpec):
            raise ServeError("stream templates must be declarative "
                             "ArraySpecs (named simulator "
                             "registrations have no batch to pin a "
                             "grid from)")
        from ..stream import StreamState

        template, _gwb = spec.parts()
        state = StreamState(template, mesh=self.mesh,
                            ecorr_dt=req.ecorr_dt, watch=req.watch,
                            checkpoint=req.checkpoint)
        entry = _StreamSlot(state)
        with self._lock:
            raced = self._streams.get(name)
            if raced is not None:
                entry = None
            else:
                self._streams[name] = entry
        if entry is None:
            flightrec.note("serve_stream_open_race", stream=name)
            return raced
        flightrec.note("serve_stream_open", stream=name,
                       npsr=state.npsr,
                       replayed=int(state.appends),
                       rolled_back=int(state.rolled_back))
        return entry

    def handle(self, req) -> dict:
        """Execute one stream-affine request; returns the wire payload."""
        slot = self._session(req)
        name = str(req.stream)
        if req.kind == "append":
            if req.toas is None or req.residuals is None:
                raise ServeError("append needs toas and residuals")
            t0 = obs.now()
            with slot.lock:
                # state re-read UNDER the lock: a cutover that swapped the
                # slot while this append queued lands it on the new state
                info = slot.state.append(req.toas, req.residuals,
                                         sigma2=req.sigma2,
                                         freqs=req.freqs,
                                         ecorr_amp=req.ecorr_amp,
                                         counts=req.counts)
            dt = obs.now() - t0
            obs.observe("serve.append_latency_s", dt)
            with self._lock:
                self._append_ms[name].append(dt * 1e3)
            return dict(info, kind="append", stream=name,
                        payload_schema=STREAM_PAYLOAD_SCHEMA)
        if req.kind == "stream":
            with slot.lock:
                stats = slot.state.stats()
            return dict(stats, kind="stream", stream=name,
                        payload_schema=STREAM_PAYLOAD_SCHEMA)
        raise ServeError(f"unknown stream request kind {req.kind!r}")

    # ------------------------------------------------------------------
    # migration cutover (docs/STREAMING.md "Migration cutover")
    # ------------------------------------------------------------------
    def cutover(self, name: str, spec, *, checkpoint=None,
                rtol=None) -> dict:
        """Re-stage one stream onto a wider frozen-grid template behind a
        checkpoint fence and atomically swap — zero dropped appends.

        Protocol (the gateway's managed operation drives this):

        1. the NEW :class:`~fakepta_tpu.stream.StreamState` is built
           *outside* any lock (device allocation + template staging must
           not stall sibling streams — the blocking-under-lock invariant);
        2. the per-stream lock is taken: the **fence**. In-flight appends
           that already hold it finish on the old state; later ones queue;
        3. the old state's raw store (absolute TOAs — why the store keeps
           them) replays onto the new template as one bulk append;
        4. the swap is refused unless the TOA count is conserved AND the
           append≡restage oracle holds on the new state (its accumulated
           moments match a fresh restage within ``rtol``) — on refusal the
           old state stays installed, untouched;
        5. the slot's state pointer swaps; queued appends land on the new
           template. ``gateway.cutover`` chaos-site checks fire before the
           restage and before the swap.
        """
        name = str(name)
        with self._lock:
            slot = self._streams.get(name)
        if slot is None:
            raise ServeError(f"stream {name!r} is not open; nothing to "
                             f"cut over")
        if not isinstance(spec, ArraySpec):
            raise ServeError("cutover templates must be declarative "
                             "ArraySpecs")
        if rtol is None:
            rtol = tune_defaults.GATEWAY_CUTOVER_RTOL
        from ..stream import StreamState

        t0 = obs.now()
        template, _gwb = spec.parts()
        peek = slot.state          # open-time options carry over
        fresh = StreamState(template, mesh=self.mesh,
                            ecorr_dt=peek.ecorr_dt,
                            watch=peek._watch_orf, checkpoint=checkpoint)
        with slot.lock:            # -- the fence: appends queue here -----
            old = slot.state
            faults.check("gateway.cutover", stream=name, stage="restage")
            raw = old.raw_data()
            n_before = int(raw["counts"].sum())
            if n_before:
                kwargs = dict(sigma2=raw["sigma2"], freqs=raw["freqs"],
                              counts=raw["counts"])
                if old.ecorr_dt is not None:
                    kwargs["ecorr_amp"] = raw["ecorr"]
                fresh.append(raw["t"], raw["r"], **kwargs)
            n_after = int(fresh._n.sum())
            if n_after != n_before:
                flightrec.note("gateway_cutover_abort", stream=name,
                               reason="toa_conservation",
                               before=n_before, after=n_after)
                raise ServeError(
                    f"cutover of {name!r} aborted: restage carried "
                    f"{n_after} TOAs, expected {n_before}; old state "
                    f"stays installed")
            got = [np.asarray(x) for x in fresh.moments()]
            want = [np.asarray(x) for x in fresh.restage_moments()]
            for g, w in zip(got, want):
                if not np.allclose(g, w, rtol=rtol, atol=1e-12):
                    flightrec.note("gateway_cutover_abort", stream=name,
                                   reason="oracle",
                                   max_rel=float(np.max(np.abs(g - w))))
                    raise ServeError(
                        f"cutover of {name!r} aborted: append/restage "
                        f"oracle failed on the new template; old state "
                        f"stays installed")
            faults.check("gateway.cutover", stream=name, stage="swap")
            slot.state = fresh     # -- the atomic swap -------------------
        info = {"stream": name, "toas": n_after,
                "appends_replayed": int(old.appends),
                "old_tspan_s": float(old.tspan),
                "new_tspan_s": float(fresh.tspan),
                "new_capacity": int(fresh._cap),
                "cutover_ms": round((obs.now() - t0) * 1e3, 3)}
        flightrec.note("gateway_cutover", **info)
        return info

    def stream_names(self):
        with self._lock:
            return sorted(self._streams)

    def summary(self) -> dict:
        """Per-stream telemetry: append totals and windowed latencies —
        the ``streams`` source of the replica's TelemetryPublisher and
        the enriched ``stats`` protocol reply."""
        with self._lock:
            entries = list(self._streams.items())
            lat = {name: list(ring)
                   for name, ring in self._append_ms.items()}
        out = {}
        for name, slot in entries:
            state = slot.state
            ms = lat.get(name, [])
            row = {"appends": int(state.appends),
                   "toas": int(state._n.sum()),
                   "rebuckets": int(state.rebuckets)}
            if ms:
                row["append_mean_ms"] = round(sum(ms) / len(ms), 4)
                row["append_last_ms"] = round(ms[-1], 4)
            out[name] = row
        return out

    def close(self) -> None:
        with self._lock:
            self._streams.clear()
