"""StreamManager: the pool-side executor for stream-affine requests.

Stream requests never enter the microbatch scheduler — there is nothing to
coalesce (an append mutates ONE stream's accumulated moments, in order)
and nothing to bucket at the cohort level (the stream buckets its own
append blocks on the :mod:`fakepta_tpu.tune.defaults` ladder).
:meth:`ServePool.submit` intercepts ``stream_affine`` requests before
admission and hands them here; execution is synchronous on the submitter's
thread under a per-stream lock, so appends to one stream serialize (the
additive-update order IS the stream's history) while distinct streams
proceed concurrently.

Sessions are opened lazily by the first :class:`~fakepta_tpu.serve.spec
.AppendRequest` naming a stream: its ``spec``'s synthetic array becomes
the frozen-grid template, and ``ecorr_dt``/``watch``/``checkpoint`` are
open-time options (a later request repeating them is flight-recorded and
ignored — the grid contract forbids reconfiguring a live stream). With a
``checkpoint`` path the open REPLAYS any consistent on-disk blocks, which
is how a fleet failover resumes a stream on a sibling replica.
"""

from __future__ import annotations

import collections
import threading

from .. import obs
from ..obs import flightrec
from ..tune import defaults as tune_defaults
from .spec import ArraySpec, ServeError

#: payload schema tag for stream responses (mirrors STREAM_SCHEMA's role
#: for on-disk artifacts; versioned separately because the wire payload is
#: a serve-layer contract)
STREAM_PAYLOAD_SCHEMA = "fakepta_tpu.serve-stream/1"


class StreamManager:
    """Named :class:`~fakepta_tpu.stream.StreamState` sessions for one
    pool. ``mesh=None`` keeps stream device arrays unsharded — stream
    state is per-pulsar small and pool meshes need not divide a stream
    template's pulsar count."""

    def __init__(self, mesh=None):
        self.mesh = mesh
        self._lock = threading.Lock()
        self._streams: dict = {}      # name -> (threading.Lock, StreamState)
        # per-stream append-latency rings (telemetry plane): bounded like
        # every other telemetry buffer, read by summary()
        self._append_ms: dict = collections.defaultdict(
            lambda: collections.deque(
                maxlen=tune_defaults.TELEMETRY_RING_SIZE))

    def _session(self, req):
        """The (lock, state) pair for ``req.stream``, opening it when the
        request carries a spec.

        Two-phase open: the registry lock is held only for the dict
        lookups — :class:`StreamState` construction (device allocation,
        checkpoint REPLAY, potentially seconds of work) happens with no
        manager lock held, so appends to every *other* stream keep
        flowing while one stream opens (the blocking-under-lock
        invariant). A racing open of the same name keeps the first
        registered state and discards the loser (replay is read-only, so
        the discarded state touched nothing)."""
        name = str(req.stream)
        if not name:
            raise ServeError("stream requests need a non-empty stream name")
        with self._lock:
            entry = self._streams.get(name)
        if entry is not None:
            if getattr(req, "spec", None) is not None:
                flightrec.note("serve_stream_reopen_ignored",
                               stream=name)
            return entry
        spec = getattr(req, "spec", None)
        if spec is None:
            raise ServeError(
                f"stream {name!r} is not open; the first append must "
                f"carry a spec (its array is the frozen-grid template)")
        if not isinstance(spec, ArraySpec):
            raise ServeError("stream templates must be declarative "
                             "ArraySpecs (named simulator "
                             "registrations have no batch to pin a "
                             "grid from)")
        from ..stream import StreamState

        template, _gwb = spec.parts()
        state = StreamState(template, mesh=self.mesh,
                            ecorr_dt=req.ecorr_dt, watch=req.watch,
                            checkpoint=req.checkpoint)
        entry = (threading.Lock(), state)
        with self._lock:
            raced = self._streams.get(name)
            if raced is not None:
                entry = None
            else:
                self._streams[name] = entry
        if entry is None:
            flightrec.note("serve_stream_open_race", stream=name)
            return raced
        flightrec.note("serve_stream_open", stream=name,
                       npsr=state.npsr,
                       replayed=int(state.appends),
                       rolled_back=int(state.rolled_back))
        return entry

    def handle(self, req) -> dict:
        """Execute one stream-affine request; returns the wire payload."""
        lock, state = self._session(req)
        name = str(req.stream)
        if req.kind == "append":
            if req.toas is None or req.residuals is None:
                raise ServeError("append needs toas and residuals")
            t0 = obs.now()
            with lock:
                info = state.append(req.toas, req.residuals,
                                    sigma2=req.sigma2, freqs=req.freqs,
                                    ecorr_amp=req.ecorr_amp,
                                    counts=req.counts)
            dt = obs.now() - t0
            obs.observe("serve.append_latency_s", dt)
            with self._lock:
                self._append_ms[name].append(dt * 1e3)
            return dict(info, kind="append", stream=name,
                        payload_schema=STREAM_PAYLOAD_SCHEMA)
        if req.kind == "stream":
            with lock:
                stats = state.stats()
            return dict(stats, kind="stream", stream=name,
                        payload_schema=STREAM_PAYLOAD_SCHEMA)
        raise ServeError(f"unknown stream request kind {req.kind!r}")

    def stream_names(self):
        with self._lock:
            return sorted(self._streams)

    def summary(self) -> dict:
        """Per-stream telemetry: append totals and windowed latencies —
        the ``streams`` source of the replica's TelemetryPublisher and
        the enriched ``stats`` protocol reply."""
        with self._lock:
            entries = list(self._streams.items())
            lat = {name: list(ring)
                   for name, ring in self._append_ms.items()}
        out = {}
        for name, (_lock, state) in entries:
            ms = lat.get(name, [])
            row = {"appends": int(state.appends),
                   "toas": int(state._n.sum()),
                   "rebuckets": int(state.rebuckets)}
            if ms:
                row["append_mean_ms"] = round(sum(ms) / len(ms), 4)
                row["append_last_ms"] = round(ms[-1], 4)
            out[name] = row
        return out

    def close(self) -> None:
        with self._lock:
            self._streams.clear()
