"""Request and spec surface of the serving layer (docs/SERVING.md).

A request names *what* to simulate (a spec), *how much* of it
(``n`` realizations), and *whose stream* it is (``seed``) — nothing about
executables, buckets, or batching. The scheduler owns those: requests with
the same ``(spec_hash, lane token)`` coalesce into one padded chunk
dispatch, and each request's results come from its own RNG lane
(``fold_in(key(seed), i)``), so a response is bit-identical to
``EnsembleSimulator.run(n, seed=seed)`` no matter how it was batched.

Specs come in two forms: a declarative :class:`ArraySpec` (synthetic array
+ GWB parameters, hashed structurally — the CLI/JSON surface), or a name
registered on the pool with a prebuilt :class:`EnsembleSimulator` (the
embeddable multi-tenant surface). Both resolve to a stable ``spec_hash``
via :func:`fakepta_tpu.obs.flightrec.spec_hash` — the same identity hash
the crash flight recorder stamps on runs, so serve artifacts and engine
artifacts group by configuration the same way.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple, Union

import numpy as np

from ..obs import flightrec
from ..tune import defaults as tune_defaults

#: default microbatch bucket ladder — single-sourced from
#: :mod:`fakepta_tpu.tune.defaults` (the one place dispatch-knob literals
#: may live; the ``hardcoded-dispatch-knob`` analysis rule enforces it).
#: Geometric with ratio 2: padding a cohort up to the next bucket wastes
#: < 50% of slots worst-case and the warm pool compiles O(log(max/min))
#: executables per lane config. A platform-tuned ladder replaces it via
#: ``ServePool(tuned=True)`` (docs/TUNING.md).
DEFAULT_BUCKETS: Tuple[int, ...] = tune_defaults.DEFAULT_BUCKETS


class ServeError(RuntimeError):
    """Base class for serving-layer failures."""


class ServeBusy(ServeError):
    """Admission rejected: the pending-request queue is at its configured
    depth (the 429 of the serving layer — back off and retry).

    ``retry_after_s`` is the scheduler's computed backoff hint — the
    estimated time to drain the current backlog (pending realizations /
    recent dispatch service rate, floored at the coalesce window) — the
    serving analog of a 429's ``Retry-After`` header. Clients honoring it
    (the built-in loadgen does) converge on the pool's actual service rate
    instead of hammering a fixed sleep."""

    def __init__(self, msg: str = "", retry_after_s: float = 0.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class ServeTimeout(ServeError):
    """The request's deadline expired before its cohort dispatched (the
    scheduler cancels not-yet-dispatched work only; a dispatched cohort
    always completes)."""


class ServeClosed(ServeError):
    """The pool is shut down and admits no new requests."""


@dataclasses.dataclass(frozen=True)
class ArraySpec:
    """Declarative synthetic-array + ensemble spec a request names.

    The JSON-facing subset of what ``PulsarBatch.synthetic`` +
    ``GWBConfig`` + ``EnsembleSimulator`` accept: enough to serve
    simulation/detection/likelihood requests over a synthetic PTA. Richer
    configurations (real arrays, sampled hyperpriors, CGW populations)
    enter through :meth:`ServePool.register` with a prebuilt simulator.
    ``gwb_orf=''`` disables the common signal. ``data_seed`` seeds the
    array geometry, NOT the realization streams — those are per-request.
    """

    npsr: int = 20
    ntoa: int = 156
    tspan_years: float = 15.0
    toaerr: float = 1e-7
    n_red: int = 10
    n_dm: int = 10
    data_seed: int = 0
    gwb_log10_A: float = float(np.log10(2e-15))
    gwb_gamma: float = 13.0 / 3.0
    gwb_ncomp: int = 10
    gwb_orf: str = "hd"
    nbins: int = 15

    def spec_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["kind"] = "ArraySpec"
        return d

    def spec_hash(self) -> str:
        """Stable identity of this spec (the warm-pool key ingredient) —
        single-sourced with the flight recorder's run identity hash."""
        return flightrec.spec_hash(self.spec_dict())

    def parts(self):
        """``(batch, gwb)`` — the constructor ingredients this spec
        describes (shared by :meth:`build` and the autotuner's
        :func:`fakepta_tpu.tune.search`, so the two stage the identical
        array)."""
        from .. import spectrum as spectrum_lib
        from ..batch import PulsarBatch
        from ..parallel.montecarlo import GWBConfig

        batch = PulsarBatch.synthetic(
            npsr=self.npsr, ntoa=self.ntoa, tspan_years=self.tspan_years,
            toaerr=self.toaerr, n_red=self.n_red, n_dm=self.n_dm,
            seed=self.data_seed)
        gwb = None
        if self.gwb_orf:
            f = np.arange(1, self.gwb_ncomp + 1) / float(batch.tspan_common)
            psd = np.asarray(spectrum_lib.powerlaw(
                f, log10_A=self.gwb_log10_A, gamma=self.gwb_gamma))
            gwb = GWBConfig(psd=psd, orf=self.gwb_orf)
        return batch, gwb

    def build(self, mesh=None, compile_cache_dir=None):
        """Construct the :class:`EnsembleSimulator` this spec describes."""
        from ..parallel.montecarlo import EnsembleSimulator

        batch, gwb = self.parts()
        return EnsembleSimulator(batch, gwb=gwb, mesh=mesh,
                                 nbins=self.nbins,
                                 compile_cache_dir=compile_cache_dir)


SpecLike = Union[str, ArraySpec]


@dataclasses.dataclass(frozen=True)
class SimRequest:
    """One user's simulation request: ``n`` realizations of ``spec`` drawn
    from the request's own RNG lane (``seed``). ``deadline_s`` is relative
    to submission; expired requests are cancelled *before* dispatch with
    :class:`ServeTimeout` (dispatched work always completes)."""

    spec: SpecLike
    n: int
    seed: int = 0
    deadline_s: Optional[float] = None
    #: request trace identity (docs/OBSERVABILITY.md "Trace propagation").
    #: Minted by the fleet router (or accepted from the client line) and
    #: carried through coalescing, dispatch, and failover re-dispatch, so
    #: every span a request produces — on any replica — links back to it.
    #: ``None`` means untraced (solo-pool submissions keep zero overhead).
    trace_id: Optional[str] = None

    kind = "sim"

    def lane_token(self):
        """Hashable executable-lane identity: requests coalesce only when
        their (spec, lane token) match — one packed-extras layout and one
        step executable per cohort."""
        return ("sim",)

    def run_kwargs(self) -> dict:
        """The ``EnsembleSimulator.run``/``warm_start`` lane kwargs."""
        return {}


@dataclasses.dataclass(frozen=True)
class OSRequest(SimRequest):
    """A detection request: the on-device optimal-statistic lane rides the
    cohort's chunk program; per-request ``amp2``/``snr`` (and, with
    ``null=True``, the request's own paired-null calibration) come from the
    request's slice alone, so results are cohort-independent."""

    orf: Union[str, Sequence[str]] = "hd"
    weighting: str = "noise"
    null: bool = False

    kind = "os"

    def os_spec(self):
        from ..detect import operators as detect_ops
        orf = self.orf if isinstance(self.orf, str) else tuple(self.orf)
        return detect_ops.as_spec(detect_ops.OSSpec(
            orf=orf, weighting=self.weighting, null=bool(self.null)))

    def lane_token(self):
        spec = self.os_spec()
        return ("os", spec.orfs, spec.weighting, bool(spec.null))

    def run_kwargs(self) -> dict:
        return {"os": self.os_spec()}


@dataclasses.dataclass(frozen=True)
class InferRequest(SimRequest):
    """A likelihood request: the GP-marginalized Woodbury lnL lane
    (``fakepta_tpu.infer``) evaluated at the request's theta grid for each
    of its realizations. ``lnlike`` is an :class:`~fakepta_tpu.infer
    .InferSpec`; requests sharing (spec, model, mode, theta) coalesce."""

    lnlike: object = None

    kind = "infer"

    def lane_token(self):
        if self.lnlike is None:
            raise ValueError("InferRequest needs an InferSpec (lnlike=...)")
        theta = np.asarray(self.lnlike.theta)
        return ("infer", self.lnlike.model, self.lnlike.mode,
                theta.shape, theta.tobytes())

    def run_kwargs(self) -> dict:
        return {"lnlike": self.lnlike}


@dataclasses.dataclass(frozen=True)
class AppendRequest:
    """Streaming ingestion: append a TOA block to the named stream
    (docs/STREAMING.md). The first touch of a ``stream`` name must carry a
    ``spec`` — its synthetic array becomes the stream's frozen-grid
    template (:class:`~fakepta_tpu.stream.StreamState`); ``ecorr_dt`` /
    ``watch`` / ``checkpoint`` are open-time options, ignored (with a
    flight-recorder note) once the stream exists. ``toas``/``residuals``
    are (P, B) absolute seconds / seconds; ``counts`` marks the valid
    prefix per pulsar.

    Stream requests are AFFINE: the fleet routes them by stream name (not
    spec hash) to the owning replica and never spills them to a sibling on
    saturation — the accumulated moments live on exactly one replica.
    Failover on replica death opens a fresh stream on the next ring
    sibling, which is only continuous when the stream was opened with a
    ``checkpoint`` on a shared filesystem (the sampling-session contract).
    """

    stream: str = ""
    toas: object = None
    residuals: object = None
    spec: Optional[SpecLike] = None
    sigma2: object = None
    freqs: object = None
    ecorr_amp: object = None
    counts: object = None
    ecorr_dt: Optional[float] = None
    watch: Optional[str] = None
    checkpoint: Optional[str] = None
    deadline_s: Optional[float] = None
    trace_id: Optional[str] = None

    kind = "append"
    stream_affine = True

    def affinity_key(self) -> str:
        """The fleet routing identity: the stream NAME, so every request
        touching one stream lands on the same replica."""
        return f"stream:{self.stream}"


@dataclasses.dataclass(frozen=True)
class StreamRequest:
    """Read the named stream's rolling state: totals, bucket/recompile
    counters, and the last detection statistic (``StreamState.stats()``).
    Affine like :class:`AppendRequest` — stats come from the replica that
    owns the moments."""

    stream: str = ""
    deadline_s: Optional[float] = None
    trace_id: Optional[str] = None

    kind = "stream"
    stream_affine = True

    def affinity_key(self) -> str:
        return f"stream:{self.stream}"


def curn_grid_spec(k: int = 4, log10_A=(-15.2, -14.2), gamma=(3.0, 6.0),
                   nbin: int = 10):
    """A small CURN (log10_A, gamma) grid InferSpec — the JSON-expressible
    likelihood request (the CLI's ``"grid"`` form and the bench recipe)."""
    from ..infer import (ComponentSpec, FreeParam, InferSpec, LikelihoodSpec,
                         theta_grid)

    model = LikelihoodSpec(components=(
        ComponentSpec(target="red", spectrum="batch"),
        ComponentSpec(target="dm", spectrum="batch"),
        ComponentSpec(target="curn", nbin=nbin, free=(
            FreeParam("log10_A", tuple(log10_A)),
            FreeParam("gamma", tuple(gamma)))),
    ))
    return InferSpec(model=model, theta=theta_grid(model, k))


def resolve_spec_hash(spec: SpecLike, named: dict) -> str:
    """spec -> stable hash; named registrations resolve through ``named``."""
    if isinstance(spec, str):
        if spec not in named:
            raise ServeError(f"unknown registered spec {spec!r}; "
                             f"known: {sorted(named)}")
        return named[spec]
    if isinstance(spec, ArraySpec):
        return spec.spec_hash()
    raise TypeError(f"request spec must be a registered name or an "
                    f"ArraySpec, got {type(spec).__name__}")
