"""CLI: ``python -m fakepta_tpu.serve loadgen|stdin|socket|replica|fleet``.

Five drivers over the serving layer:

- ``loadgen`` — the built-in synthetic load generator / benchmark
  (:mod:`.loadgen`): prints ONE JSON row with the SLO metrics (and, with
  ``--baseline``, the serial-dispatch comparison + ``serve_speedup_x``);
- ``stdin`` — JSON-lines request/response over stdin/stdout: each input
  line is a request object, each output line a response (responses stream
  in completion order; match them by ``id``);
- ``socket`` — the same JSON-lines protocol over TCP (one connection per
  client, threaded), for processes that are not children of the server;
- ``replica`` — the fleet endpoint (docs/SERVING.md "Fleet"): the socket
  server plus a one-line JSON ready banner on stdout (``{"event":
  "ready", "port": ..., "n_devices": ...}`` — how the router learns the
  bound port when spawned with ``--port 0``) and ``--index`` stamping the
  report's ``process_index`` so ``obs trace`` merges replica artifacts
  into per-replica pid lanes;
- ``fleet`` — the multi-replica load benchmark (``run_loadgen(fleet=N)``,
  :mod:`.fleet`): spawns N ``replica`` subprocesses behind the
  consistent-hash router and prints one fleet row (``fleet_qps_per_chip``,
  ``fleet_p50_ms``/``p99``, failover count, warm-pool hit rate).

Request line schema (shared by stdin/socket/replica)::

    {"id": 1, "kind": "sim"|"os"|"infer", "n": 16, "seed": 7,
     "spec": {"npsr": 20, ...} | "registered-name",   # optional: default spec
     "deadline_ms": 250,                               # optional
     "orf": "hd", "weighting": "noise", "null": false, # kind == "os"
     "grid": {"k": 4, "nbin": 10},                     # kind == "infer"
     "lnlike": {"schema": "fakepta_tpu.infer-spec/1", ...}}  # infer, exact

(``"lnlike"`` is a full :mod:`fakepta_tpu.infer.schema` InferSpec document
— the exact likelihood request; ``"grid"`` remains the shorthand.)

Streaming ingestion kinds (docs/STREAMING.md; requests are replica-affine
— a fleet routes them by stream name, never spilling to a sibling)::

    {"id": 2, "kind": "append", "stream": "ng20", "toas": [[...]],
     "residuals": [[...]],                             # (P, B) seconds
     "sigma2": [[...]], "freqs": [[...]],              # optional
     "ecorr_amp": [[...]], "counts": [...],            # optional
     "spec": {...}, "ecorr_dt": 2592000.0,             # open-time options
     "watch": "hd", "checkpoint": "/shared/stream"}    # (first touch only)
    {"id": 3, "kind": "stream", "stream": "ng20"}      # rolling stats

Both answer ``{"id", "ok": true, "stream": {...payload...}}`` — the
append payload carries latency/bucket/recompile counters plus the rolling
detection statistic when the stream was opened with ``watch``.

plus five fleet-protocol kinds: ``{"id", "kind": "ping"}`` answers
``{"id", "ok": true, "pong": true}`` inline on the connection thread —
the health plane's heartbeat probe (serve/health.py): nothing queues
behind the scheduler, so a missed pong means the process or its socket
plumbing is stuck, not merely busy; ``{"id", "kind": "stats"}`` answers
with the pool's live SLO summary plus ``health`` (ladder state),
``pool`` (warm-pool occupancy), and ``streams`` (open-stream counts);
``{"id", "kind": "telemetry"}`` answers with one TelemetryPublisher
snapshot (the health plane's scrape rides this kind on the SAME mux'd
connection as the heartbeat — zero new sockets, docs/OBSERVABILITY.md);
``{"id", "kind": "metrics"}`` answers with Prometheus text-format
exposition in the ``metrics`` field; and ``{"id", "kind": "sample", "steps": 64,
"seed": 7, "spec": {...}, "session": {"n_chains": 4, ...},
"checkpoint": "/shared/ck"}`` opens a posterior-as-a-service session that
STREAMS one line per drained segment (``{"id", "ok": true, "seg": k,
...thinned draws...}``) and a final ``{"id", "ok": true, "done": true,
"summary": {...}}`` — with ``checkpoint`` on a shared filesystem, a
sibling replica resumes the session bit-exactly after a failover
(segment-boundary checkpoints are the migration unit).

Responses: ``{"id", "ok": true, "n", "latency_ms", "queued_ms", "bucket",
"cohort_requests", ...results}`` with ``--emit summary`` (per-request curve
means) or ``--emit full`` (full per-realization arrays). Failures:
``{"id", "ok": false, "code": "busy"|"timeout"|"error", "error": msg}`` —
``busy`` is the 429-style admission rejection and carries the scheduler's
``retry_after_s`` hint (docs/SERVING.md).

Socket hardening (the fleet endpoint is exposed to non-child processes):
per-connection idle ``settimeout`` (``--idle-timeout``), a bounded
request-line length (:data:`MAX_REQUEST_LINE`), and loud flight-recorder
notes on malformed frames — a stalled or hostile client can no longer pin
a handler thread forever (the ``unbounded-socket-io`` analysis rule keeps
library socket reads bounded repo-wide, docs/INVARIANTS.md).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import threading

import numpy as np

from ..obs import flightrec
from .scheduler import ServeConfig, ServePool
from .spec import (AppendRequest, ArraySpec, InferRequest, OSRequest,
                   ServeBusy, ServeTimeout, SimRequest, StreamRequest,
                   curn_grid_spec)

#: longest request line a server will read before declaring the frame
#: malformed and closing the connection (a hostile client could otherwise
#: grow one "line" without bound — host memory is the blast radius)
MAX_REQUEST_LINE = 1 * 1024 * 1024

#: default per-connection idle timeout: a stalled client's handler thread
#: is reclaimed instead of pinned forever
DEFAULT_IDLE_TIMEOUT_S = 300.0


def _spec_from_args(args) -> ArraySpec:
    return ArraySpec(npsr=args.npsr, ntoa=args.ntoa,
                     tspan_years=args.tspan_years, n_red=args.n_red,
                     n_dm=args.n_dm, gwb_orf=args.gwb_orf,
                     gwb_ncomp=args.gwb_ncomp)


def _config_from_args(args) -> ServeConfig:
    kw = {}
    if args.buckets:
        kw["buckets"] = tuple(args.buckets)
    if args.max_queue_depth is not None:
        kw["max_queue_depth"] = args.max_queue_depth
    if args.window_ms is not None:
        kw["coalesce_window_s"] = args.window_ms / 1e3
    if args.prewarm_buckets:
        kw["prewarm_buckets"] = tuple(args.prewarm_buckets)
    return ServeConfig(**kw)


def request_from_json(d: dict, default_spec: ArraySpec):
    """One request line -> request object (see module docstring schema)."""
    kind = d.get("kind", "sim")
    spec = d.get("spec")
    if kind in ("append", "stream"):
        # stream-affine kinds: no n/seed, spec only as an open-time
        # template (never defaulted — an already-open stream needs none)
        stream_spec = ArraySpec(**spec) if isinstance(spec, dict) else None
        deadline = d.get("deadline_ms")
        deadline_s = (float(deadline) / 1e3 if deadline is not None
                      else None)
        trace_id = d.get("trace_id")
        if kind == "stream":
            return StreamRequest(stream=str(d["stream"]),
                                 deadline_s=deadline_s,
                                 trace_id=trace_id)
        arr = lambda k: (np.asarray(d[k], dtype=np.float64)  # noqa: E731
                         if d.get(k) is not None else None)
        return AppendRequest(
            stream=str(d["stream"]), toas=arr("toas"),
            residuals=arr("residuals"), spec=stream_spec,
            sigma2=arr("sigma2"), freqs=arr("freqs"),
            ecorr_amp=arr("ecorr_amp"), counts=arr("counts"),
            ecorr_dt=(float(d["ecorr_dt"])
                      if d.get("ecorr_dt") is not None else None),
            watch=d.get("watch"), checkpoint=d.get("checkpoint"),
            deadline_s=deadline_s, trace_id=trace_id)
    if spec is None:
        spec = default_spec
    elif isinstance(spec, dict):
        spec = ArraySpec(**spec)
    elif not isinstance(spec, str):
        raise ValueError("spec must be an object or a registered name")
    n = int(d["n"])
    seed = int(d.get("seed", 0))
    deadline = d.get("deadline_ms")
    deadline_s = float(deadline) / 1e3 if deadline is not None else None
    trace_id = d.get("trace_id")
    if kind == "sim":
        return SimRequest(spec=spec, n=n, seed=seed, deadline_s=deadline_s,
                          trace_id=trace_id)
    if kind == "os":
        return OSRequest(spec=spec, n=n, seed=seed, deadline_s=deadline_s,
                         orf=d.get("orf", "hd"),
                         weighting=d.get("weighting", "noise"),
                         null=bool(d.get("null", False)),
                         trace_id=trace_id)
    if kind == "infer":
        if d.get("lnlike") is not None:
            # the exact form: a full infer.schema InferSpec document —
            # what lets ANY InferRequest cross the socket protocol
            from ..infer import spec_from_json
            lnlike = spec_from_json(d["lnlike"])
        else:
            grid = d.get("grid") or {}
            lnlike = curn_grid_spec(
                k=int(grid.get("k", 4)),
                log10_A=tuple(grid.get("log10_A", (-15.2, -14.2))),
                gamma=tuple(grid.get("gamma", (3.0, 6.0))),
                nbin=int(grid.get("nbin", 10)))
        return InferRequest(spec=spec, n=n, seed=seed, deadline_s=deadline_s,
                            lnlike=lnlike, trace_id=trace_id)
    raise ValueError(f"unknown request kind {kind!r}")


def response_json(req_id, res, emit: str = "summary") -> dict:
    if isinstance(res, dict):
        # stream-affine kinds resolve to plain payload dicts (already
        # JSON-shaped; no per-realization arrays to thin by emit mode)
        return {"id": req_id, "ok": True, "stream": res}
    out = {
        "id": req_id, "ok": True, "n": int(res.curves.shape[0]),
        "latency_ms": round(res.latency_s * 1e3, 3),
        "queued_ms": round(res.queued_s * 1e3, 3),
        "bucket": res.bucket, "cohort_requests": res.cohort_requests,
    }
    if emit == "full":
        out["curves"] = np.asarray(res.curves).tolist()
        out["autos"] = np.asarray(res.autos).tolist()
        out["bin_centers"] = np.asarray(res.bin_centers).tolist()
        if res.os is not None:
            out["os"] = {orf: {k: (np.asarray(v).tolist()
                                   if isinstance(v, np.ndarray) else v)
                               for k, v in entry.items()}
                         for orf, entry in res.os["stats"].items()}
        if res.lnlike is not None:
            out["lnl"] = np.asarray(res.lnlike["lnl"]).tolist()
    else:
        out["curve_mean"] = np.asarray(res.curves).mean(axis=0).tolist()
        out["autos_mean"] = float(np.asarray(res.autos).mean())
        if res.os is not None:
            out["os"] = {orf: {"amp2_mean": float(np.mean(e["amp2"])),
                               "snr_mean": float(np.mean(e["snr"]))}
                         for orf, e in res.os["stats"].items()}
        if res.lnlike is not None:
            out["lnl_max"] = float(np.max(res.lnlike["lnl"]))
    return out


def request_to_json(req, req_id) -> dict:
    """Request object -> protocol line (the client half of
    :func:`request_from_json`; the fleet's socket transport uses it).
    ``InferRequest`` serializes its :class:`InferSpec` through
    :mod:`fakepta_tpu.infer.schema`, so likelihood and stream requests
    cross the socket like every other kind."""
    if getattr(req, "stream_affine", False):
        d = {"id": req_id, "kind": req.kind, "stream": str(req.stream)}
        if req.deadline_s is not None:
            d["deadline_ms"] = req.deadline_s * 1e3
        if getattr(req, "trace_id", None):
            d["trace_id"] = req.trace_id
        if req.kind == "append":
            for key in ("toas", "residuals", "sigma2", "freqs",
                        "ecorr_amp", "counts"):
                val = getattr(req, key)
                if val is not None:
                    d[key] = np.asarray(val).tolist()
            if req.spec is not None:
                if not isinstance(req.spec, ArraySpec):
                    raise ValueError("only ArraySpec stream templates "
                                     "cross the socket protocol")
                d["spec"] = dataclasses.asdict(req.spec)
            if req.ecorr_dt is not None:
                d["ecorr_dt"] = float(req.ecorr_dt)
            if req.watch is not None:
                d["watch"] = str(req.watch)
            if req.checkpoint is not None:
                d["checkpoint"] = str(req.checkpoint)
        return d
    d = {"id": req_id, "kind": req.kind, "n": int(req.n),
         "seed": int(req.seed)}
    if req.deadline_s is not None:
        d["deadline_ms"] = req.deadline_s * 1e3
    if getattr(req, "trace_id", None):
        # the propagation contract (docs/OBSERVABILITY.md): the router's
        # minted trace identity crosses the socket with the request, so
        # replica-side spans join the client's causal lane
        d["trace_id"] = req.trace_id
    if isinstance(req.spec, str):
        d["spec"] = req.spec
    elif isinstance(req.spec, ArraySpec):
        d["spec"] = dataclasses.asdict(req.spec)
    else:
        raise ValueError("only named or ArraySpec requests cross the "
                         "socket protocol")
    if isinstance(req, InferRequest):
        from ..infer import spec_to_json
        d["lnlike"] = spec_to_json(req.lnlike)
    if isinstance(req, OSRequest):
        d["orf"] = (req.orf if isinstance(req.orf, str) else list(req.orf))
        d["weighting"] = req.weighting
        d["null"] = bool(req.null)
    return d


def error_json(req_id, exc) -> dict:
    code = ("busy" if isinstance(exc, ServeBusy)
            else "timeout" if isinstance(exc, ServeTimeout) else "error")
    out = {"id": req_id, "ok": False, "code": code, "error": str(exc)}
    hint = getattr(exc, "retry_after_s", None)
    if hint is not None:
        # the 429 Retry-After hint crosses the wire, so a fleet router can
        # aggregate per-replica backlog into its own 429s
        out["retry_after_s"] = round(float(hint), 4)
    return out


def _serve_sample(pool, d: dict, req_id, emit_line, default_spec,
                  emit: str) -> None:
    """One posterior-as-a-service session (protocol kind ``sample``):
    streams a line per drained segment, then the summary line. Runs
    synchronously on the connection's handler thread — one connection is
    one session (docs/SERVING.md "Fleet")."""
    from .fleet import SampleSessionSpec, build_session_run

    spec = d.get("spec")
    spec = ArraySpec(**spec) if isinstance(spec, dict) else default_spec
    knob_names = ("nbin", "n_chains", "n_temps", "warmup", "thin",
                  "step_size", "n_leapfrog", "data_seed", "bin_offset",
                  "data_nbin")
    knobs = {k: v for k, v in (d.get("session") or {}).items()
             if k in knob_names}
    sess = SampleSessionSpec(spec=spec, n_steps=int(d.get("steps", 32)),
                             seed=int(d.get("seed", 0)),
                             segment=d.get("segment"), **knobs)
    run = build_session_run(sess, pool.mesh,
                            compile_cache_dir=pool._pool.cache_dir)

    def on_segment(idx, arr):
        msg = {"id": req_id, "ok": True, "seg": int(idx),
               "n": int(arr.shape[0])}
        if emit == "full":
            msg["theta"] = np.asarray(arr).tolist()
        else:
            msg["theta_mean"] = np.asarray(arr).mean(axis=(0, 1)).tolist()
        emit_line(msg)

    out = run.run(sess.n_steps, seed=sess.seed, segment=sess.segment,
                  checkpoint=d.get("checkpoint"), pipeline_depth=0,
                  on_segment=on_segment)
    emit_line({"id": req_id, "ok": True, "done": True,
               "summary": out["summary"],
               "n_kept": int(out["theta"].shape[0]),
               "param_names": list(out["param_names"])})


def _serve_stream(pool, lines, write, default_spec, emit: str) -> int:
    """Drive the pool from an iterator of request lines; responses stream
    through ``write`` in completion order. Returns served count."""
    wlock = threading.Lock()
    futs = []

    def emit_line(obj):
        with wlock:
            write(json.dumps(obj) + "\n")

    for raw in lines:
        raw = raw.strip()
        if not raw:
            continue
        d = None
        try:
            d = json.loads(raw)
            req_id = d.get("id")
            kind = d.get("kind", "sim")
            if kind == "ping":
                # heartbeat probe: answered inline on this connection
                # thread, nothing dispatched — the health plane times the
                # round-trip against its probe deadline
                emit_line({"id": req_id, "ok": True, "pong": True})
                continue
            if kind == "stats":
                # fleet-protocol introspection: the router audits each
                # replica's warm-pool health (steady compiles, retraces).
                # "stats" keeps its historical SLO-summary shape; the
                # health-ladder state, warm-pool occupancy, and stream
                # counts ride alongside under their own keys
                out = {"id": req_id, "ok": True,
                       "stats": pool.slo_summary(),
                       "health": pool.health_summary(),
                       "pool": pool.warm_summary(),
                       "streams": pool.stream_summary()}
                # a gateway front (fakepta_tpu.gateway) adds its tenant
                # table — per-tenant qps/429s/queue-share/hit-rate rows
                tenants = getattr(pool, "tenant_summary", None)
                if tenants is not None:
                    out["tenants"] = tenants()
                emit_line(out)
                continue
            if kind == "telemetry":
                # the health plane's scrape: one bounded publisher
                # snapshot, answered inline like ping — it rides the
                # heartbeat's mux'd connection, never a new socket
                emit_line({"id": req_id, "ok": True,
                           "telemetry": pool.telemetry_snapshot()})
                continue
            if kind == "metrics":
                # Prometheus text-format exposition of this replica's
                # own rollup (docs/OBSERVABILITY.md metric-name table)
                emit_line({"id": req_id, "ok": True,
                           "metrics": pool.metrics_text()})
                continue
            if kind == "sample":
                _serve_sample(pool, d, req_id, emit_line, default_spec,
                              emit)
                continue
            if kind == "cutover":
                # frozen-grid migration (docs/STREAMING.md "Migration
                # cutover"): synchronous by design — the reply IS the
                # fence release, so the driver knows the swap landed
                spec = d.get("spec")
                if not isinstance(spec, dict):
                    raise ValueError("cutover needs a spec object (the "
                                     "wider template)")
                try:
                    info = pool.cutover_stream(
                        str(d["stream"]), ArraySpec(**spec),
                        checkpoint=d.get("checkpoint"))
                except Exception as exc:   # abort -> error line, old
                    emit_line(error_json(req_id, exc))   # state installed
                else:
                    emit_line({"id": req_id, "ok": True, "cutover": info})
                continue
            req = request_from_json(d, default_spec)
        except (ValueError, KeyError, TypeError, AttributeError) as exc:
            flightrec.note("serve_bad_request", error=repr(exc)[:200])
            emit_line({"id": d.get("id") if isinstance(d, dict) else None,
                       "ok": False, "code": "bad_request",
                       "error": str(exc)})
            continue
        try:
            fut = pool.submit(req)
        except Exception as exc:   # Busy/Closed/ValueError -> error line
            emit_line(error_json(req_id, exc))
            continue

        def _done(f, req_id=req_id,
                  trace_id=getattr(req, "trace_id", None)):
            exc = f.exception()
            out = (error_json(req_id, exc) if exc is not None
                   else response_json(req_id, f.result(), emit))
            if trace_id:
                # echo the trace identity so the client's span and the
                # replica's span share one causal lane in `obs trace`
                out["trace_id"] = trace_id
            emit_line(out)

        fut.add_done_callback(_done)
        futs.append(fut)
    for f in futs:
        try:
            f.result(timeout=600.0)
        # fakepta: allow[swallowed-exception] every failure was already
        # emitted as an error line by the future's done callback above
        except Exception:
            pass
    return len(futs)


def _cmd_loadgen(args) -> int:
    from .loadgen import run_loadgen

    row = run_loadgen(
        spec=_spec_from_args(args), n_requests=args.requests,
        sizes=tuple(args.sizes), kind=args.kind, rate_hz=args.rate,
        seed=args.seed, baseline=args.baseline, verify=args.verify,
        config=_config_from_args(args),
        compile_cache_dir=args.compile_cache, report_path=args.report)
    print(json.dumps(row))
    return 0


def _cmd_stdin(args) -> int:
    pool = ServePool(config=_config_from_args(args),
                     compile_cache_dir=args.compile_cache)
    try:
        n = _serve_stream(pool, sys.stdin, sys.stdout.write,
                          _spec_from_args(args), args.emit)
        sys.stdout.flush()
    finally:
        if args.report:
            pool.save_report(args.report)
        pool.close()
    print(f"served {n} request(s)", file=sys.stderr)
    return 0


def _bounded_lines(rfile, connection, idle_timeout_s: float):
    """Request lines from a socket file, hardened: a per-connection idle
    ``settimeout`` bounds every blocking read, the line length is bounded
    by :data:`MAX_REQUEST_LINE`, and both failure modes leave a loud
    flight-recorder note instead of a pinned handler thread."""
    import socket as socket_mod

    if idle_timeout_s:
        connection.settimeout(idle_timeout_s)
    while True:
        try:
            raw = rfile.readline(MAX_REQUEST_LINE + 1)
        except socket_mod.timeout:
            flightrec.note("serve_socket_idle_timeout")
            return
        except OSError as exc:
            flightrec.note("serve_socket_read_error",
                           error=repr(exc)[:160])
            return
        if not raw:
            return
        if len(raw) > MAX_REQUEST_LINE:
            flightrec.note("serve_socket_oversized_frame", bytes=len(raw))
            return
        yield raw.decode("utf-8", "replace")


def _socket_server(pool, args, idle_timeout_s: float):
    """The hardened threaded JSON-lines TCP server (shared by the
    ``socket`` and ``replica`` commands)."""
    import socketserver

    default_spec = _spec_from_args(args)
    emit = args.emit

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            try:
                _serve_stream(pool,
                              _bounded_lines(self.rfile, self.connection,
                                             idle_timeout_s),
                              lambda s: (self.wfile.write(s.encode()),
                                         self.wfile.flush()),
                              default_spec, emit)
            except OSError as exc:
                # client went away mid-response: connection-scoped, the
                # pool and every other connection are unaffected
                flightrec.note("serve_socket_write_error",
                               error=repr(exc)[:160])

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    return Server((args.host, args.port), Handler)


def _register_with_router(register: str, replica_id: str,
                          serving_port: int, n_devices: int, index: int,
                          timeout_s: float = 30.0) -> None:
    """The replica side of the join handshake (docs/RELIABILITY.md "Fleet
    lifecycle"): dial the router's admin port, send one JSON ``hello``
    line advertising our serving port, await the ``adopt`` reply. Bounded
    at every step — a dead router is a loud startup failure."""
    import socket as socket_mod

    host, _, port_s = register.rpartition(":")
    conn = socket_mod.create_connection((host or "127.0.0.1", int(port_s)),
                                        timeout=timeout_s)
    try:
        conn.settimeout(timeout_s)
        conn.sendall((json.dumps(
            {"event": "hello", "port": int(serving_port),
             "replica_id": replica_id, "index": int(index),
             "n_devices": int(n_devices)}) + "\n").encode())
        line = conn.makefile("rb").readline(MAX_REQUEST_LINE + 1)
        reply = json.loads(line.decode("utf-8", "replace")) if line else {}
        if reply.get("event") != "adopt":
            raise RuntimeError(f"router rejected the join: {reply!r}")
        flightrec.note("replica_adopted", router=register,
                       replicas=int(reply.get("replicas", 0)))
    finally:
        conn.close()


def _cmd_socket(args, banner: bool = False) -> int:
    if getattr(args, "jax_platform", None):
        # the replica endpoint must pin its backend BEFORE the pool's
        # first device use (env JAX_PLATFORMS alone is not honored when a
        # TPU plugin self-registers; cf. tests/conftest.py)
        import jax
        jax.config.update("jax_platforms", args.jax_platform)
    if getattr(args, "x64", False):
        import jax
        # a replica subprocess must mirror its router's x64 mode or
        # scalar promotion desyncs the response bit-identity contract;
        # set at process entry before any device use
        jax.config.update("jax_enable_x64", True)
    mesh = None
    if getattr(args, "devices", None):
        import jax
        from ..parallel.mesh import make_mesh
        mesh = make_mesh(jax.devices()[:args.devices])
    pool = ServePool(mesh=mesh, config=_config_from_args(args),
                     compile_cache_dir=args.compile_cache)
    with _socket_server(pool, args, args.idle_timeout) as server:
        if banner:
            # the fleet router spawns replicas with --port 0 and learns
            # the bound port from this one-line JSON banner
            print(json.dumps({"event": "ready",
                              "port": server.server_address[1],
                              "n_devices": pool.n_devices,
                              "index": getattr(args, "index", 0)}),
                  flush=True)
        else:
            print(f"serving on {args.host}:{server.server_address[1]} "
                  f"(JSON-lines; ^C to stop)", file=sys.stderr)
        register = getattr(args, "register", None)
        register_failed = []
        if register:
            # the handshake MUST run while the server is accepting: the
            # router's _adopt pre-warms the joiner over its serving port
            # BEFORE sending the adopt reply, so registering from the
            # main thread ahead of serve_forever() deadlocks — router
            # waits on a prewarm the replica cannot serve, replica waits
            # on an adopt the router cannot send — until the reply read
            # times out and the replica dies with its listener's embryo
            # connections RST. Register from a side thread instead;
            # failure shuts the server down loudly.
            rid = (getattr(args, "replica_id", None)
                   or f"replica-{server.server_address[1]}")

            def _register():
                try:
                    _register_with_router(register, rid,
                                          server.server_address[1],
                                          pool.n_devices,
                                          getattr(args, "index", 0))
                except (OSError, RuntimeError, ValueError) as exc:
                    flightrec.note("replica_register_failed",
                                   error=repr(exc)[:200])
                    print(f"register with {register} failed: {exc!r}",
                          file=sys.stderr)
                    register_failed.append(exc)
                    server.shutdown()

            threading.Thread(target=_register, name="replica-register",
                             daemon=True).start()
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass
        if register_failed:
            pool.close()
            return 2
    if args.report:
        rep = pool.report()
        rep.meta["process_index"] = int(getattr(args, "index", 0))
        rep.save(args.report)
    pool.close()
    return 0


def _cmd_fleet(args) -> int:
    from .loadgen import run_loadgen

    row = run_loadgen(
        spec=_spec_from_args(args), n_requests=args.requests,
        sizes=tuple(args.sizes), kind=args.kind, seed=args.seed,
        baseline=args.baseline, verify=args.verify,
        config=_config_from_args(args),
        compile_cache_dir=args.compile_cache, report_path=args.report,
        fleet=args.replicas, fleet_transport=args.transport,
        n_specs=args.specs,
        kill_one_at=args.kill_one_at)
    print(json.dumps(row))
    return 0


def _add_common(p):
    p.add_argument("--npsr", type=int, default=20)
    p.add_argument("--ntoa", type=int, default=156)
    p.add_argument("--tspan-years", type=float, default=15.0)
    p.add_argument("--n-red", type=int, default=10)
    p.add_argument("--n-dm", type=int, default=10)
    p.add_argument("--gwb-orf", default="hd",
                   help="common-signal ORF ('' disables the GWB)")
    p.add_argument("--gwb-ncomp", type=int, default=10)
    p.add_argument("--buckets", type=int, nargs="*", default=None,
                   help="microbatch bucket ladder (default: "
                        "16..1024, ratio 2)")
    p.add_argument("--prewarm-buckets", type=int, nargs="*", default=None)
    p.add_argument("--max-queue-depth", type=int, default=None)
    p.add_argument("--window-ms", type=float, default=None,
                   help="coalesce window in milliseconds (default 2)")
    p.add_argument("--compile-cache", default=None,
                   help="persistent compile cache dir (default: "
                        "$FAKEPTA_TPU_COMPILE_CACHE)")
    p.add_argument("--report", default=None,
                   help="write the pool's obs RunReport artifact here")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m fakepta_tpu.serve",
        description="warm-pool serving layer with a microbatch coalescing "
                    "scheduler (docs/SERVING.md)")
    sub = parser.add_subparsers(dest="command", required=True)

    lg = sub.add_parser("loadgen", help="synthetic load benchmark: one "
                                        "JSON row of SLO metrics")
    _add_common(lg)
    lg.add_argument("--requests", type=int, default=64)
    lg.add_argument("--sizes", type=int, nargs="*", default=[4, 8, 16, 32])
    lg.add_argument("--kind", choices=("sim", "os", "infer"), default="sim")
    lg.add_argument("--rate", type=float, default=None,
                    help="submission rate in Hz (default: flat-out)")
    lg.add_argument("--seed", type=int, default=0)
    lg.add_argument("--baseline", action="store_true",
                    help="also measure serial per-request run() dispatch "
                         "and report serve_speedup_x")
    lg.add_argument("--verify", type=int, default=3,
                    help="solo-check this many served responses "
                         "bit-for-bit (0 disables)")

    st = sub.add_parser("stdin", help="JSON-lines request/response over "
                                      "stdin/stdout")
    _add_common(st)
    st.add_argument("--emit", choices=("summary", "full"), default="summary")

    def _add_socket_common(p):
        p.add_argument("--host", default="127.0.0.1")
        p.add_argument("--port", type=int, default=8791,
                       help="TCP port (0 = bind any free port)")
        p.add_argument("--emit", choices=("summary", "full"),
                       default="summary")
        p.add_argument("--idle-timeout", type=float,
                       default=DEFAULT_IDLE_TIMEOUT_S,
                       help="per-connection idle timeout in seconds "
                            "(0 disables; default 300)")
        p.add_argument("--devices", type=int, default=None,
                       help="serve on the first N local devices (default: "
                            "all; fleet replicas on the CPU stand-in pin "
                            "1 so parent-side bit-verification shares the "
                            "executable shape)")

    so = sub.add_parser("socket", help="JSON-lines over TCP")
    _add_common(so)
    _add_socket_common(so)

    rp = sub.add_parser("replica", help="fleet endpoint: the socket "
                                        "server + a JSON ready banner "
                                        "(docs/SERVING.md Fleet)")
    _add_common(rp)
    _add_socket_common(rp)
    rp.set_defaults(emit="full")     # failover bit-verification needs
    #                                  full per-realization arrays
    rp.add_argument("--index", type=int, default=0,
                    help="replica index (the report's process_index — "
                         "one pid lane per replica under `obs trace`)")
    rp.add_argument("--jax-platform", default=None,
                    help="pin the jax backend before the pool starts "
                         "(subprocess replicas on the CPU stand-in)")
    rp.add_argument("--x64", action="store_true",
                    help="enable jax x64 mode (a replica must match its "
                         "router's mode or scalar promotion desyncs the "
                         "bit-identity contract)")
    rp.add_argument("--register", default=None, metavar="HOST:PORT",
                    help="dial a running router's admin port "
                         "(ServeFleet.listen) and join its ring via the "
                         "hello/adopt handshake (docs/RELIABILITY.md "
                         "'Fleet lifecycle')")
    rp.add_argument("--replica-id", default=None,
                    help="fleet identity to join as "
                         "(default: replica-<port>)")

    fl = sub.add_parser("fleet", help="multi-replica load benchmark: one "
                                      "JSON row of fleet SLO metrics")
    _add_common(fl)
    fl.add_argument("--replicas", type=int, default=3)
    fl.add_argument("--transport", choices=("process", "inproc"),
                    default="process",
                    help="replica transport: subprocess sockets (the "
                         "production shape) or in-process pools")
    fl.add_argument("--requests", type=int, default=96)
    fl.add_argument("--sizes", type=int, nargs="*", default=[1, 2, 4])
    fl.add_argument("--specs", type=int, default=6,
                    help="distinct specs in the traffic (the spec-space "
                         "working set the ring shards)")
    fl.add_argument("--kind", choices=("sim", "os"), default="sim")
    fl.add_argument("--seed", type=int, default=0)
    fl.add_argument("--baseline", action="store_true",
                    help="also serve the same traffic through ONE pool "
                         "and report fleet_speedup_x")
    fl.add_argument("--verify", type=int, default=3)
    fl.add_argument("--kill-one-at", type=float, default=None,
                    help="kill one replica after this fraction of "
                         "requests is submitted (the failover A/B; "
                         "responses stay bit-verified)")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "loadgen":
        return _cmd_loadgen(args)
    if args.command == "stdin":
        return _cmd_stdin(args)
    if args.command == "replica":
        return _cmd_socket(args, banner=True)
    if args.command == "fleet":
        return _cmd_fleet(args)
    return _cmd_socket(args)


if __name__ == "__main__":                               # pragma: no cover
    sys.exit(main())
