"""CLI: ``python -m fakepta_tpu.serve loadgen|stdin|socket ...``.

Three drivers over one :class:`ServePool`:

- ``loadgen`` — the built-in synthetic load generator / benchmark
  (:mod:`.loadgen`): prints ONE JSON row with the SLO metrics (and, with
  ``--baseline``, the serial-dispatch comparison + ``serve_speedup_x``);
- ``stdin`` — JSON-lines request/response over stdin/stdout: each input
  line is a request object, each output line a response (responses stream
  in completion order; match them by ``id``);
- ``socket`` — the same JSON-lines protocol over TCP (one connection per
  client, threaded), for processes that are not children of the server.

Request line schema (shared by stdin/socket)::

    {"id": 1, "kind": "sim"|"os"|"infer", "n": 16, "seed": 7,
     "spec": {"npsr": 20, ...} | "registered-name",   # optional: default spec
     "deadline_ms": 250,                               # optional
     "orf": "hd", "weighting": "noise", "null": false, # kind == "os"
     "grid": {"k": 4, "nbin": 10}}                     # kind == "infer"

Responses: ``{"id", "ok": true, "n", "latency_ms", "queued_ms", "bucket",
"cohort_requests", ...results}`` with ``--emit summary`` (per-request curve
means) or ``--emit full`` (full per-realization arrays). Failures:
``{"id", "ok": false, "code": "busy"|"timeout"|"error", "error": msg}`` —
``busy`` is the 429-style admission rejection (docs/SERVING.md).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import threading

import numpy as np

from .scheduler import ServeConfig, ServePool
from .spec import (ArraySpec, InferRequest, OSRequest, ServeBusy,
                   ServeTimeout, SimRequest, curn_grid_spec)


def _spec_from_args(args) -> ArraySpec:
    return ArraySpec(npsr=args.npsr, ntoa=args.ntoa,
                     tspan_years=args.tspan_years, n_red=args.n_red,
                     n_dm=args.n_dm, gwb_orf=args.gwb_orf,
                     gwb_ncomp=args.gwb_ncomp)


def _config_from_args(args) -> ServeConfig:
    kw = {}
    if args.buckets:
        kw["buckets"] = tuple(args.buckets)
    if args.max_queue_depth is not None:
        kw["max_queue_depth"] = args.max_queue_depth
    if args.window_ms is not None:
        kw["coalesce_window_s"] = args.window_ms / 1e3
    if args.prewarm_buckets:
        kw["prewarm_buckets"] = tuple(args.prewarm_buckets)
    return ServeConfig(**kw)


def request_from_json(d: dict, default_spec: ArraySpec):
    """One request line -> request object (see module docstring schema)."""
    kind = d.get("kind", "sim")
    spec = d.get("spec")
    if spec is None:
        spec = default_spec
    elif isinstance(spec, dict):
        spec = ArraySpec(**spec)
    elif not isinstance(spec, str):
        raise ValueError("spec must be an object or a registered name")
    n = int(d["n"])
    seed = int(d.get("seed", 0))
    deadline = d.get("deadline_ms")
    deadline_s = float(deadline) / 1e3 if deadline is not None else None
    if kind == "sim":
        return SimRequest(spec=spec, n=n, seed=seed, deadline_s=deadline_s)
    if kind == "os":
        return OSRequest(spec=spec, n=n, seed=seed, deadline_s=deadline_s,
                         orf=d.get("orf", "hd"),
                         weighting=d.get("weighting", "noise"),
                         null=bool(d.get("null", False)))
    if kind == "infer":
        grid = d.get("grid") or {}
        lnlike = curn_grid_spec(
            k=int(grid.get("k", 4)),
            log10_A=tuple(grid.get("log10_A", (-15.2, -14.2))),
            gamma=tuple(grid.get("gamma", (3.0, 6.0))),
            nbin=int(grid.get("nbin", 10)))
        return InferRequest(spec=spec, n=n, seed=seed, deadline_s=deadline_s,
                            lnlike=lnlike)
    raise ValueError(f"unknown request kind {kind!r}")


def response_json(req_id, res, emit: str = "summary") -> dict:
    out = {
        "id": req_id, "ok": True, "n": int(res.curves.shape[0]),
        "latency_ms": round(res.latency_s * 1e3, 3),
        "queued_ms": round(res.queued_s * 1e3, 3),
        "bucket": res.bucket, "cohort_requests": res.cohort_requests,
    }
    if emit == "full":
        out["curves"] = np.asarray(res.curves).tolist()
        out["autos"] = np.asarray(res.autos).tolist()
        out["bin_centers"] = np.asarray(res.bin_centers).tolist()
        if res.os is not None:
            out["os"] = {orf: {k: (np.asarray(v).tolist()
                                   if isinstance(v, np.ndarray) else v)
                               for k, v in entry.items()}
                         for orf, entry in res.os["stats"].items()}
        if res.lnlike is not None:
            out["lnl"] = np.asarray(res.lnlike["lnl"]).tolist()
    else:
        out["curve_mean"] = np.asarray(res.curves).mean(axis=0).tolist()
        out["autos_mean"] = float(np.asarray(res.autos).mean())
        if res.os is not None:
            out["os"] = {orf: {"amp2_mean": float(np.mean(e["amp2"])),
                               "snr_mean": float(np.mean(e["snr"]))}
                         for orf, e in res.os["stats"].items()}
        if res.lnlike is not None:
            out["lnl_max"] = float(np.max(res.lnlike["lnl"]))
    return out


def error_json(req_id, exc) -> dict:
    code = ("busy" if isinstance(exc, ServeBusy)
            else "timeout" if isinstance(exc, ServeTimeout) else "error")
    return {"id": req_id, "ok": False, "code": code, "error": str(exc)}


def _serve_stream(pool, lines, write, default_spec, emit: str) -> int:
    """Drive the pool from an iterator of request lines; responses stream
    through ``write`` in completion order. Returns served count."""
    wlock = threading.Lock()
    futs = []

    def emit_line(obj):
        with wlock:
            write(json.dumps(obj) + "\n")

    for raw in lines:
        raw = raw.strip()
        if not raw:
            continue
        try:
            d = json.loads(raw)
            req = request_from_json(d, default_spec)
            req_id = d.get("id")
        except (ValueError, KeyError, TypeError) as exc:
            emit_line({"id": None, "ok": False, "code": "bad_request",
                       "error": str(exc)})
            continue
        try:
            fut = pool.submit(req)
        except Exception as exc:   # Busy/Closed/ValueError -> error line
            emit_line(error_json(req_id, exc))
            continue

        def _done(f, req_id=req_id):
            exc = f.exception()
            emit_line(error_json(req_id, exc) if exc is not None
                      else response_json(req_id, f.result(), emit))

        fut.add_done_callback(_done)
        futs.append(fut)
    for f in futs:
        try:
            f.result(timeout=600.0)
        # fakepta: allow[swallowed-exception] every failure was already
        # emitted as an error line by the future's done callback above
        except Exception:
            pass
    return len(futs)


def _cmd_loadgen(args) -> int:
    from .loadgen import run_loadgen

    row = run_loadgen(
        spec=_spec_from_args(args), n_requests=args.requests,
        sizes=tuple(args.sizes), kind=args.kind, rate_hz=args.rate,
        seed=args.seed, baseline=args.baseline, verify=args.verify,
        config=_config_from_args(args),
        compile_cache_dir=args.compile_cache, report_path=args.report)
    print(json.dumps(row))
    return 0


def _cmd_stdin(args) -> int:
    pool = ServePool(config=_config_from_args(args),
                     compile_cache_dir=args.compile_cache)
    try:
        n = _serve_stream(pool, sys.stdin, sys.stdout.write,
                          _spec_from_args(args), args.emit)
        sys.stdout.flush()
    finally:
        if args.report:
            pool.save_report(args.report)
        pool.close()
    print(f"served {n} request(s)", file=sys.stderr)
    return 0


def _cmd_socket(args) -> int:
    import socketserver

    pool = ServePool(config=_config_from_args(args),
                     compile_cache_dir=args.compile_cache)
    default_spec = _spec_from_args(args)
    emit = args.emit

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            lines = (raw.decode("utf-8", "replace") for raw in self.rfile)
            _serve_stream(pool, lines,
                          lambda s: (self.wfile.write(s.encode()),
                                     self.wfile.flush()),
                          default_spec, emit)

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    with Server((args.host, args.port), Handler) as server:
        print(f"serving on {args.host}:{server.server_address[1]} "
              f"(JSON-lines; ^C to stop)", file=sys.stderr)
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass
    if args.report:
        pool.save_report(args.report)
    pool.close()
    return 0


def _add_common(p):
    p.add_argument("--npsr", type=int, default=20)
    p.add_argument("--ntoa", type=int, default=156)
    p.add_argument("--tspan-years", type=float, default=15.0)
    p.add_argument("--n-red", type=int, default=10)
    p.add_argument("--n-dm", type=int, default=10)
    p.add_argument("--gwb-orf", default="hd",
                   help="common-signal ORF ('' disables the GWB)")
    p.add_argument("--gwb-ncomp", type=int, default=10)
    p.add_argument("--buckets", type=int, nargs="*", default=None,
                   help="microbatch bucket ladder (default: "
                        "16..1024, ratio 2)")
    p.add_argument("--prewarm-buckets", type=int, nargs="*", default=None)
    p.add_argument("--max-queue-depth", type=int, default=None)
    p.add_argument("--window-ms", type=float, default=None,
                   help="coalesce window in milliseconds (default 2)")
    p.add_argument("--compile-cache", default=None,
                   help="persistent compile cache dir (default: "
                        "$FAKEPTA_TPU_COMPILE_CACHE)")
    p.add_argument("--report", default=None,
                   help="write the pool's obs RunReport artifact here")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m fakepta_tpu.serve",
        description="warm-pool serving layer with a microbatch coalescing "
                    "scheduler (docs/SERVING.md)")
    sub = parser.add_subparsers(dest="command", required=True)

    lg = sub.add_parser("loadgen", help="synthetic load benchmark: one "
                                        "JSON row of SLO metrics")
    _add_common(lg)
    lg.add_argument("--requests", type=int, default=64)
    lg.add_argument("--sizes", type=int, nargs="*", default=[4, 8, 16, 32])
    lg.add_argument("--kind", choices=("sim", "os", "infer"), default="sim")
    lg.add_argument("--rate", type=float, default=None,
                    help="submission rate in Hz (default: flat-out)")
    lg.add_argument("--seed", type=int, default=0)
    lg.add_argument("--baseline", action="store_true",
                    help="also measure serial per-request run() dispatch "
                         "and report serve_speedup_x")
    lg.add_argument("--verify", type=int, default=3,
                    help="solo-check this many served responses "
                         "bit-for-bit (0 disables)")

    st = sub.add_parser("stdin", help="JSON-lines request/response over "
                                      "stdin/stdout")
    _add_common(st)
    st.add_argument("--emit", choices=("summary", "full"), default="summary")

    so = sub.add_parser("socket", help="JSON-lines over TCP")
    _add_common(so)
    so.add_argument("--host", default="127.0.0.1")
    so.add_argument("--port", type=int, default=8791)
    so.add_argument("--emit", choices=("summary", "full"), default="summary")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "loadgen":
        return _cmd_loadgen(args)
    if args.command == "stdin":
        return _cmd_stdin(args)
    return _cmd_socket(args)


if __name__ == "__main__":                               # pragma: no cover
    sys.exit(main())
