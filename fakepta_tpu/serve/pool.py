"""Warm pool: LRU-bounded spec_hash -> ready-to-dispatch simulator entries.

The pool is the serving layer's executable cache above jax's own two:

- a **live simulator** per spec (its per-step jit caches hold the traced
  executables once a bucket has dispatched once);
- the **persistent compile cache** underneath (``compile_cache_dir=`` /
  ``FAKEPTA_TPU_COMPILE_CACHE``): bucket prewarms AOT-compile through
  :meth:`EnsembleSimulator.warm_start(..., lane_keys=True)`, which lands
  the serve-key executable in the on-disk cache so the first real dispatch
  of that bucket *loads* instead of compiling — and so a later process (or
  a manual ``warm_start`` of the same spec) hits the same entry, because
  the step selection is single-sourced in ``EnsembleSimulator._exec_plan``.

Entries are LRU-evicted past ``max_entries`` (a spec's HBM/host footprint
dies with its simulator); simulators registered by name through
:meth:`ServePool.register` are pinned — the embeddable multi-tenant case
owns their lifecycle.
"""

from __future__ import annotations

import collections
from typing import Optional, Tuple

from .. import obs
from ..obs import flightrec
from ..parallel import pipeline as pipeline_mod
from .spec import ArraySpec, ServeError


class PoolEntry:
    """One warm spec: the simulator plus its prewarmed-bucket bookkeeping."""

    def __init__(self, spec_hash: str, sim, pinned: bool = False):
        self.spec_hash = spec_hash
        self.sim = sim
        self.pinned = pinned
        # (lane_token, bucket) pairs already warmed: the retrace-guard
        # contract is zero recompiles for any pair in this set
        self.warmed = set()
        self.warm_s = 0.0            # total seconds spent prewarming
        # lane_token -> host-f64 OS operators (the demux re-assembles each
        # request's detection statistics; the O(npsr^2) operator build is
        # per-spec-per-lane, not per-dispatch)
        self.os_ops = {}

    def ensure_warm(self, bucket: int, lane_token, run_kwargs: dict,
                    cache_active: bool) -> float:
        """Warm one (lane config, bucket) executable; idempotent.

        With the persistent compile cache active the AOT ``warm_start``
        populates the on-disk entry the dispatch-time jit compile then
        loads; without it the AOT executable could not be handed to the
        dispatch path anyway (separate jit cache), so the first dispatch
        itself is the warmup and this only primes the one-time cost
        capture. Returns the seconds spent (0.0 when already warm).
        """
        key = (lane_token, int(bucket))
        if key in self.warmed:
            return 0.0
        t0 = obs.now()
        if cache_active:
            self.sim.warm_start(bucket, lane_keys=True, **run_kwargs)
        # prime the one-time XLA cost capture so the first dispatch's
        # RunReport assembly never pays an AOT lower mid-traffic
        try:
            self.sim.chunk_cost(bucket, **run_kwargs)
        except Exception as exc:   # noqa: BLE001 — recorded, not swallowed
            # cost model missing on this backend: run() copes too, but the
            # flight recorder keeps the reason the cost fields are absent
            flightrec.note("warm_cost_capture_failed",
                           bucket=int(bucket), error=repr(exc)[:160])
        self.warmed.add(key)
        spent = obs.now() - t0
        self.warm_s += spent
        return spent


class WarmPool:
    """LRU-bounded ``spec_hash -> PoolEntry`` map (see module docstring)."""

    def __init__(self, mesh, max_entries: int = 4,
                 compile_cache_dir: Optional[str] = None):
        self.mesh = mesh
        self.max_entries = int(max_entries)
        # honors FAKEPTA_TPU_COMPILE_CACHE when no dir is given; the
        # returned path doubles as the "is a persistent cache active" flag
        self.cache_dir = pipeline_mod.configure_compile_cache(
            compile_cache_dir)
        self._entries: "collections.OrderedDict[str, PoolEntry]" = \
            collections.OrderedDict()
        self._named: dict = {}               # name -> spec_hash
        self.builds = 0
        self.evictions = 0

    # -- registration (the embeddable multi-tenant surface) ---------------
    def register(self, name: str, sim) -> str:
        """Pin a prebuilt simulator under ``name``; returns its spec hash."""
        from ..obs import flightrec

        spec_hash = flightrec.spec_hash({"kind": "registered", "name": name})
        self._named[name] = spec_hash
        self._entries[spec_hash] = PoolEntry(spec_hash, sim, pinned=True)
        self._entries.move_to_end(spec_hash)
        return spec_hash

    @property
    def named(self) -> dict:
        return self._named

    # -- lookup ------------------------------------------------------------
    def get(self, spec_hash: str, spec) -> PoolEntry:
        """The entry for ``spec_hash``, building it from ``spec`` on a miss
        (LRU-evicting unpinned entries past ``max_entries``)."""
        entry = self._entries.get(spec_hash)
        if entry is not None:
            self._entries.move_to_end(spec_hash)
            return entry
        if not isinstance(spec, ArraySpec):
            raise ServeError(
                f"spec {spec!r} is not resident (registered sims are pinned "
                f"at register time; only ArraySpec specs build on demand)")
        sim = spec.build(mesh=self.mesh, compile_cache_dir=self.cache_dir)
        entry = PoolEntry(spec_hash, sim)
        self._entries[spec_hash] = entry
        self.builds += 1
        while len(self._entries) > self.max_entries:
            victim = next((k for k, e in self._entries.items()
                           if not e.pinned and k != spec_hash), None)
            if victim is None:
                break
            del self._entries[victim]
            self.evictions += 1
        return entry

    def evict(self, spec_hash: str) -> bool:
        """Evict one entry's *executables* (the poisoned-executable
        recovery hook, docs/RELIABILITY.md).

        Unpinned (ArraySpec-built) entries are dropped wholesale — the
        next :meth:`get` rebuilds the simulator from the spec,
        deterministically. Pinned (registered) entries own their
        simulator's lifecycle, so only the compiled state is cleared
        (:meth:`EnsembleSimulator.clear_executables`) and the
        prewarmed-bucket bookkeeping reset — the next dispatch re-traces
        and recompiles from clean state. Returns True when something was
        evicted.
        """
        entry = self._entries.get(spec_hash)
        if entry is None:
            return False
        if entry.pinned:
            entry.sim.clear_executables()
            entry.warmed.clear()
            entry.os_ops.clear()
        else:
            del self._entries[spec_hash]
        self.evictions += 1
        flightrec.note("pool_evict", spec=spec_hash,
                       pinned=bool(entry.pinned))
        return True

    def prewarm(self, entry: PoolEntry, buckets: Tuple[int, ...],
                lane_token=("sim",), run_kwargs: Optional[dict] = None
                ) -> float:
        """Warm a bucket ladder for one lane config; returns seconds."""
        spent = 0.0
        for b in buckets:
            spent += entry.ensure_warm(b, lane_token, run_kwargs or {},
                                       cache_active=bool(self.cache_dir))
        return spent

    def __len__(self) -> int:
        return len(self._entries)
