"""Microbatch coalescing scheduler + the :class:`ServePool` facade.

The serving-side analogue of the engine's batching argument: one compiled
executable and one device round-trip amortize across as many users as the
queue holds. Requests are admitted into per-``(spec_hash, lane token)``
queues, coalesced into cohorts inside a short window, padded up to a fixed
**bucket ladder** shape (so dispatch never recompiles — every bucket's
executable is prewarmed or compiled exactly once), dispatched through the
existing ``EnsembleSimulator.run()`` pipeline with one RNG **lane** per
request, and demultiplexed into per-request slices on a writer-side demux
thread. Results are bit-identical to each request's own solo
``run(n, seed)`` regardless of cohort, padding, or mesh (the engine's
``_chunk_keys`` lane contract).

Robustness is part of the lane, not an afterthought:

- **backpressure**: admission past ``max_queue_depth`` pending requests
  raises :class:`ServeBusy` (429-style — the caller backs off); the demux
  hand-off queue is bounded too, so a slow consumer throttles dispatch
  instead of growing host memory;
- **deadlines**: a request whose relative ``deadline_s`` expires before
  its cohort dispatches is cancelled with :class:`ServeTimeout`
  (dispatched work always completes — device programs are not preempted);
- **failure telemetry**: a failed dispatch fails every cohort member with
  :class:`ServeError` and drops a note in the crash flight recorder
  (``obs.flightrec``), so a dead serving process leaves evidence.

Observability: every request contributes a timeline span and the pool
rolls them up into SLO summaries (``serve_p50_ms`` / ``serve_p99_ms`` /
``serve_qps_per_chip``, ``queue_depth``, ``coalesce_factor``,
``pad_waste_frac``) through the existing ``fakepta_tpu.obs`` schema —
:meth:`ServePool.save_report` writes a RunReport artifact that
``obs summarize`` prints and ``obs compare`` / ``obs gate`` band with the
serve-aware direction tables (docs/SERVING.md).
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import threading
from concurrent.futures import Future
from typing import Optional, Tuple

import numpy as np

from .. import faults as faults_mod
from .. import obs
from ..obs import flightrec
from .pool import WarmPool
from .spec import (DEFAULT_BUCKETS, ArraySpec, ServeBusy, ServeClosed,
                   ServeError, ServeTimeout, SimRequest, resolve_spec_hash)

_STOP = object()

#: shutdown join bound: generous against any legitimate drain, but finite
#: — a wedged worker thread becomes a flight-recorder note, not a caller
#: hung in close() forever
_SHUTDOWN_JOIN_S = 60.0


class _PoisonedOutput(RuntimeError):
    """A dispatch returned non-finite statistics: the executable (or its
    cached state) is poisoned — recovery evicts and recompiles."""


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Scheduler/pool knobs (defaults serve small-array traffic).

    ``buckets`` is the microbatch ladder: cohorts pad to the smallest
    bucket >= their total realization count, so every dispatch reuses one
    of O(len(ladder)) executables — the pad-waste / compile-count tradeoff
    is the ladder ratio (docs/SERVING.md). ``max_queue_depth`` bounds the
    pending-request count across all queues (admission past it raises
    ServeBusy). ``coalesce_window_s`` is how long the scheduler holds the
    oldest request to let batchmates arrive; a full max-size cohort
    dispatches immediately. ``prewarm_buckets`` (default: none) AOT-warms
    the plain-sim lane for those buckets when a spec enters the pool.
    """

    buckets: Tuple[int, ...] = DEFAULT_BUCKETS
    max_queue_depth: int = 256
    coalesce_window_s: float = 0.002
    max_specs: int = 4
    prewarm_buckets: Tuple[int, ...] = ()
    pipeline_depth: int = 0          # single-chunk dispatches: serial loop
    result_window: int = 4096        # SLO ring capacity (requests)
    # recovery (docs/RELIABILITY.md): transient dispatch failures retry
    # with bounded backoff before the cohort is failed; a poisoned
    # executable (non-finite output) is evicted from the warm pool and the
    # cohort re-dispatched once against the recompiled entry
    max_dispatch_retries: int = 2
    retry_backoff_s: float = 0.05


@dataclasses.dataclass
class ServeResult:
    """One request's demultiplexed slice of its cohort dispatch."""

    curves: np.ndarray               # (n, nbins)
    autos: np.ndarray                # (n,)
    bin_centers: np.ndarray
    os: Optional[dict] = None        # per-request detect assembly
    lnlike: Optional[dict] = None    # per-request infer lanes
    queued_s: float = 0.0            # admission -> dispatch
    service_s: float = 0.0           # dispatch -> result ready
    latency_s: float = 0.0           # admission -> result ready
    cohort_requests: int = 1         # how many requests rode the dispatch
    bucket: int = 0                  # padded dispatch shape
    pad_waste_frac: float = 0.0      # 1 - cohort realizations / bucket
    # fleet routing facts (serve/fleet.py): which replica served it, and
    # how many mid-flight failovers the request survived (0 = first try)
    replica: str = ""
    failovers: int = 0


class _Pending:
    __slots__ = ("req", "fut", "spec_hash", "cohort_key", "t_enq",
                 "deadline")

    def __init__(self, req, fut, spec_hash, cohort_key, t_enq, deadline):
        self.req = req
        self.fut = fut
        self.spec_hash = spec_hash
        self.cohort_key = cohort_key
        self.t_enq = t_enq
        self.deadline = deadline


class _CohortQueue:
    """FIFO of pending requests plus an O(1) realization total, so the
    dispatcher's window check never rescans the queue under the lock (a
    rescan per submit notification is O(n^2) across a burst)."""

    __slots__ = ("q", "total", "min_deadline")

    def __init__(self, maxlen: int):
        self.q = collections.deque(maxlen=maxlen)
        self.total = 0
        # earliest deadline ever queued here — conservative (never relaxed
        # on pop): the dispatcher may wake a beat early and recheck, but a
        # deadline can never sleep through its own coalesce window
        self.min_deadline = None

    def append(self, p) -> None:
        self.q.append(p)
        self.total += int(p.req.n)
        if p.deadline is not None and (self.min_deadline is None
                                       or p.deadline < self.min_deadline):
            self.min_deadline = p.deadline

    def popleft(self):
        p = self.q.popleft()
        self.total -= int(p.req.n)
        return p

    def __bool__(self) -> bool:
        return bool(self.q)

    def __len__(self) -> int:
        return len(self.q)


class _Stats:
    """SLO accumulators (bounded rings; guarded by the pool lock)."""

    def __init__(self, window: int):
        self.latency_ms = collections.deque(maxlen=window)
        self.queued_ms = collections.deque(maxlen=window)
        self.service_ms = collections.deque(maxlen=window)
        self.coalesce = collections.deque(maxlen=window)
        self.pad_waste = collections.deque(maxlen=window)
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.cancelled = 0
        self.failed = 0
        self.retried = 0             # transient dispatch retries
        self.evicted = 0             # poisoned-executable evictions
        self.dispatches = 0
        self.realizations = 0
        self.queue_depth_max = 0
        self.retraces = 0
        self.steady_compiles = 0     # compiles on an already-warm cohort
        self.warm_s = 0.0
        self.t_first = None          # first admission
        self.t_last = None           # last completion


class ServePool:
    """The embeddable serving facade (docs/SERVING.md).

    One dispatcher thread forms cohorts and drives the device; one demux
    thread slices results and resolves futures — so result assembly for
    cohort *k* overlaps the dispatch of cohort *k+1*. All jax dispatch
    happens on the dispatcher thread.

    >>> pool = ServePool()
    >>> res = pool.serve(SimRequest(spec=ArraySpec(npsr=8), n=32, seed=7))
    >>> pool.close()
    """

    def __init__(self, mesh=None, config: Optional[ServeConfig] = None,
                 compile_cache_dir: Optional[str] = None,
                 tuned: bool = False):
        import jax

        self.config = config or ServeConfig()
        if mesh is None:
            from ..parallel.mesh import make_mesh
            mesh = make_mesh(jax.devices())
        self.mesh = mesh
        self.n_devices = int(mesh.devices.size)
        n_real = int(mesh.shape.get("real", 1))
        if tuned:
            # platform-tuned bucket ladder (fakepta_tpu.tune, docs/TUNING
            # .md): replaces the hand-set ladder — and becomes the prewarm
            # set when none was configured, so a tuned pool warms exactly
            # the executables it will dispatch. A store miss keeps the
            # hand-set ladder, diagnosably.
            from .. import tune as tune_mod
            ladder = tune_mod.resolve_buckets()
            if ladder:
                legal = tuple(b for b in ladder if b % max(n_real, 1) == 0)
                if legal:
                    self.config = dataclasses.replace(
                        self.config, buckets=legal,
                        prewarm_buckets=(self.config.prewarm_buckets
                                         or legal))
                    flightrec.note("serve_tuned_buckets",
                                   buckets=list(legal))
            else:
                flightrec.note("serve_tuned_miss")
        buckets = sorted({int(b) for b in self.config.buckets})
        bad = [b for b in buckets if b % n_real]
        if bad or not buckets:
            raise ValueError(
                f"every bucket must be a positive multiple of the mesh's "
                f"'real' axis ({n_real}); offending buckets: {bad or buckets}")
        self._buckets = tuple(buckets)
        self._max_bucket = buckets[-1]
        self._pool = WarmPool(mesh, max_entries=self.config.max_specs,
                              compile_cache_dir=compile_cache_dir)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: dict = {}          # cohort_key -> deque[_Pending]
        self._pending = 0
        self._closed = False
        self._stream_mgr = None          # lazy StreamManager (streams.py)
        self._t0 = obs.now()             # pool epoch for timeline spans
        self._stats = _Stats(self.config.result_window)
        self._timeline = collections.deque(maxlen=self.config.result_window)
        # bounded hand-off to the demux thread: a slow consumer throttles
        # dispatch instead of buffering unbounded cohorts on the host
        self._demux_q: "queue.Queue" = queue.Queue(maxsize=8)
        self._demux_thread = threading.Thread(
            target=self._demux_loop, name="fakepta-serve-demux", daemon=True)
        self._demux_thread.start()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="fakepta-serve-dispatch",
            daemon=True)
        self._dispatcher.start()
        # telemetry plane (docs/OBSERVABILITY.md): the replica-side
        # publisher. Costs nothing until something scrapes it — sources
        # run only inside snapshot(), and the heartbeat scraper is the
        # only steady-state caller
        from ..obs import telemetry as telemetry_mod
        self.telemetry = telemetry_mod.TelemetryPublisher()
        self.telemetry.add_source("slo", self.slo_summary)
        self.telemetry.add_source("pool", self.warm_summary)
        self.telemetry.add_source("streams", self.stream_summary)
        self.telemetry.add_source("health", self.health_summary)
        # lazy single-replica aggregator behind the `metrics` exposition
        # kind (metrics_text); None until the first scrape
        self._metrics_agg = None

    # -- registration / admission ------------------------------------------
    def register(self, name: str, sim, prewarm: bool = True) -> str:
        """Pin a prebuilt simulator under ``name`` (multi-tenant surface);
        requests then pass ``spec=name``. Returns the spec hash."""
        spec_hash = self._pool.register(name, sim)
        if prewarm and self.config.prewarm_buckets:
            entry = self._pool.get(spec_hash, None)
            self._stats.warm_s += self._pool.prewarm(
                entry, self.config.prewarm_buckets)
        return spec_hash

    def submit(self, req: SimRequest) -> Future:
        """Admit one request; returns a Future resolving to a
        :class:`ServeResult`. Raises :class:`ServeBusy` past the configured
        queue depth, :class:`ServeClosed` after shutdown, ``ValueError``
        for an unserveable shape."""
        if getattr(req, "stream_affine", False):
            # stream-affine kinds bypass the microbatch scheduler: nothing
            # to coalesce (appends mutate ONE stream, in order) — executed
            # synchronously under the StreamManager's per-stream lock
            return self._submit_stream(req)
        n = int(req.n)
        if not 0 < n <= self._max_bucket:
            raise ValueError(
                f"request n={n} does not fit the bucket ladder (max "
                f"{self._max_bucket}); split the request or extend "
                f"ServeConfig.buckets")
        spec_hash = resolve_spec_hash(req.spec, self._pool.named)
        cohort_key = (spec_hash, req.lane_token())
        fut: Future = Future()
        t = obs.now()
        deadline = t + req.deadline_s if req.deadline_s is not None else None
        with self._cond:
            if self._closed:
                raise ServeClosed("pool is closed")
            if self._pending >= self.config.max_queue_depth:
                self._stats.rejected += 1
                hint = self._retry_after_locked()
                flightrec.note("serve_busy", pending=self._pending,
                               depth=self.config.max_queue_depth,
                               retry_after_s=round(hint, 4))
                raise ServeBusy(
                    f"{self._pending} requests pending >= max_queue_depth="
                    f"{self.config.max_queue_depth}; retry in ~{hint:.3f}s",
                    retry_after_s=hint)
            q = self._queues.get(cohort_key)
            if q is None:
                # per-cohort FIFO; maxlen mirrors the global admission bound
                q = _CohortQueue(self.config.max_queue_depth)
                self._queues[cohort_key] = q
            q.append(_Pending(req, fut, spec_hash, cohort_key, t, deadline))
            self._pending += 1
            self._stats.submitted += 1
            if self._stats.t_first is None:
                self._stats.t_first = t
            self._stats.queue_depth_max = max(self._stats.queue_depth_max,
                                              self._pending)
            self._cond.notify_all()
        return fut

    def _submit_stream(self, req) -> Future:
        """Admit + execute one stream-affine request (docs/STREAMING.md).
        Synchronous by design — an append is O(new-block) on the stream's
        warm kernels — but still future-shaped so the fleet transports and
        ``serve()`` treat every kind uniformly. ServeError subclasses
        raise at the submit site (admission semantics, like ``n``
        validation); anything else resolves the future exceptionally."""
        with self._lock:
            if self._closed:
                raise ServeClosed("pool is closed")
            mgr = self._stream_mgr
            if mgr is None:
                from .streams import StreamManager
                mgr = self._stream_mgr = StreamManager()
        fut: Future = Future()
        try:
            fut.set_result(mgr.handle(req))
        except ServeError:
            raise                      # admission semantics: raise at submit
        except Exception as exc:       # noqa: BLE001 — future contract
            fut.set_exception(exc)
        obs.count("serve.stream_requests")
        return fut

    def _retry_after_locked(self) -> float:
        """The ServeBusy backoff hint: estimated backlog drain time —
        dispatches needed to clear the queued realizations times the
        recent mean service time, floored at one coalesce window and
        capped at 5 s (a hint, not a promise). Caller holds the lock."""
        st = self._stats
        mean_service_s = (float(np.mean(st.service_ms)) / 1e3
                          if st.service_ms else
                          self.config.coalesce_window_s)
        backlog = sum(q.total for q in self._queues.values())
        dispatches = max(1, -(-int(backlog) // self._max_bucket))
        return float(min(max(dispatches * mean_service_s,
                             self.config.coalesce_window_s), 5.0))

    def serve(self, req: SimRequest, timeout: Optional[float] = None
              ) -> ServeResult:
        """Blocking convenience: ``submit`` + wait."""
        return self.submit(req).result(timeout=timeout)

    @property
    def buckets(self) -> Tuple[int, ...]:
        """The validated microbatch bucket ladder."""
        return self._buckets

    # -- scheduling ---------------------------------------------------------
    def bucket_for(self, total: int) -> int:
        """Smallest ladder bucket >= ``total`` realizations."""
        for b in self._buckets:
            if b >= total:
                return b
        return self._max_bucket

    def _oldest_key(self):
        best = None
        for key, q in self._queues.items():
            if q and (best is None or q.q[0].t_enq < best[1]):
                best = (key, q.q[0].t_enq)
        return best[0] if best else None

    def _dispatch_loop(self):
        # a dead dispatcher used to strand every queued request in a
        # silent hang; now the death is flight-recorded and every pending
        # future fails LOUDLY with the cause (docs/RELIABILITY.md)
        try:
            self._dispatch_loop_inner()
        except BaseException as exc:   # noqa: BLE001 — recorded + failed
            flightrec.note("serve_dispatcher_died", error=repr(exc)[:300])
            err = ServeError(f"serve dispatcher thread died: {exc!r}; "
                             f"queued requests failed, pool is closed")
            err.__cause__ = exc
            # collect under the lock, resolve OUTSIDE it: set_exception
            # fires completion callbacks synchronously (fleet failover
            # re-enters replica/fleet locks), so failing futures under
            # self._cond is a lock-order inversion (see the analyzer's
            # lock-order-inversion rule and docs/INVARIANTS.md)
            doomed = []
            with self._cond:
                self._closed = True
                for q in self._queues.values():
                    while q:
                        doomed.append(q.popleft())
                self._pending -= len(doomed)
                self._stats.failed += len(doomed)
                self._cond.notify_all()
            for p in doomed:
                p.fut.set_exception(err)
            raise

    def _dispatch_loop_inner(self):
        while True:
            with self._cond:
                while self._pending == 0 and not self._closed:
                    self._cond.wait()
                if self._pending == 0 and self._closed:
                    return
                key = self._oldest_key()
                q = self._queues[key]
                # hold the oldest request one coalesce window so batchmates
                # land in the same dispatch; a ladder-filling cohort (or
                # shutdown drain) goes immediately
                window_end = q.q[0].t_enq + self.config.coalesce_window_s
                while not self._closed and q.total < self._max_bucket:
                    # the window closes early at the earliest queued
                    # deadline, so an expiring request is cancelled
                    # promptly instead of sleeping out the full window
                    t_end = (window_end if q.min_deadline is None
                             else min(window_end, q.min_deadline))
                    now = obs.now()
                    if now >= t_end:
                        break
                    self._cond.wait(timeout=max(t_end - now, 1e-4))
                cohort, expired, total = [], [], 0
                now = obs.now()
                while q:
                    p = q.q[0]
                    if p.deadline is not None and now > p.deadline:
                        expired.append(q.popleft())
                        continue
                    if total + p.req.n > self._max_bucket:
                        break
                    cohort.append(q.popleft())
                    total += p.req.n
                self._pending -= len(cohort) + len(expired)
                self._stats.cancelled += len(expired)
            for p in expired:
                flightrec.note("serve_deadline_cancel", kind=p.req.kind,
                               n=int(p.req.n), waited_s=round(
                                   obs.now() - p.t_enq, 4))
                p.fut.set_exception(ServeTimeout(
                    f"deadline ({p.req.deadline_s}s) expired before "
                    f"dispatch"))
            if cohort:
                self._dispatch(cohort, total)

    def _dispatch(self, cohort, total: int):
        p0 = cohort[0]
        run_kwargs = p0.req.run_kwargs()
        bucket = self.bucket_for(total)
        lanes = [(p.req.seed, p.req.n) for p in cohort]
        t_d0 = obs.now()
        attempts, evicted = 0, False
        delay = self.config.retry_backoff_s
        while True:
            try:
                # chaos site: the serve dispatcher (docs/RELIABILITY.md)
                act = faults_mod.check("serve.dispatch",
                                       cohort=len(cohort),
                                       bucket=int(bucket))
                entry = self._pool.get(p0.spec_hash, p0.req.spec)
                warm_s = entry.ensure_warm(
                    bucket, p0.req.lane_token(), run_kwargs,
                    cache_active=bool(self._pool.cache_dir))
                out = entry.sim.run(
                    bucket, chunk=bucket, lanes=lanes,
                    pipeline_depth=self.config.pipeline_depth,
                    **run_kwargs)
                if act == "poison":
                    out["curves"] = np.asarray(out["curves"]) * np.nan
                if not np.isfinite(np.asarray(out["curves"])).all():
                    raise _PoisonedOutput(
                        f"dispatch returned non-finite curves at bucket "
                        f"{bucket} (poisoned executable)")
                break
            except BaseException as exc:   # noqa: BLE001 — triaged below,
                # forwarded to callers when recovery is exhausted
                if (isinstance(exc, _PoisonedOutput) and not evicted):
                    # degradation ladder: evict the poisoned executable
                    # from the warm pool, recompile, re-dispatch ONCE —
                    # the rebuilt entry serves the same lanes
                    # bit-identically (docs/RELIABILITY.md)
                    flightrec.note("serve_poisoned_executable",
                                   spec=p0.spec_hash, bucket=int(bucket))
                    self._pool.evict(p0.spec_hash)
                    evicted = True
                    with self._lock:
                        self._stats.evicted += 1
                    continue
                if (not isinstance(exc, _PoisonedOutput)
                        and faults_mod.classify(exc) == "transient"
                        and attempts < self.config.max_dispatch_retries):
                    attempts += 1
                    flightrec.note("serve_dispatch_retry",
                                   attempt=attempts,
                                   error=repr(exc)[:200])
                    with self._lock:
                        self._stats.retried += 1
                    faults_mod.sleep(delay)
                    delay = min(delay * 2.0, 2.0)
                    continue
                flightrec.note("serve_request_failed", kind=p0.req.kind,
                               cohort=len(cohort), bucket=int(bucket),
                               error=repr(exc)[:300])
                err = ServeError(f"dispatch failed: {exc!r}")
                err.__cause__ = exc
                with self._lock:
                    self._stats.failed += len(cohort)
                for p in cohort:
                    p.fut.set_exception(err)
                if not isinstance(exc, Exception):
                    # BaseException (simulated process kill, interpreter
                    # shutdown): the cohort is failed loudly above, then
                    # the dispatcher itself dies — _dispatch_loop fails
                    # every still-queued request and flight-records the
                    # death, so nothing ever hangs silently
                    raise
                return
        t_d1 = obs.now()
        rep = out["report"]
        with self._lock:
            st = self._stats
            st.dispatches += 1
            st.realizations += total
            st.coalesce.append(len(cohort))
            st.pad_waste.append(1.0 - total / bucket)
            st.retraces += rep.retraces
            st.warm_s += warm_s
            if warm_s == 0.0 and rep.compile_s > 0:
                # an already-warm (lane, bucket) pair paid a compile: the
                # steady-state recompile the warm pool exists to prevent
                st.steady_compiles += 1
            ev = {"name": "serve_dispatch", "tid": "serve",
                  "t0": t_d0 - self._t0, "dur": t_d1 - t_d0,
                  "cohort": len(cohort), "bucket": int(bucket),
                  "req_kind": p0.req.kind}
            # trace propagation (docs/OBSERVABILITY.md): the cohort span
            # carries every member's trace_id, so a request's router span
            # links to the replica dispatch that served it
            traced = [p.req.trace_id for p in cohort
                      if getattr(p.req, "trace_id", None)]
            if traced:
                ev["trace_ids"] = traced
            self._timeline.append(ev)
        # writer-side demux: slicing/assembly happens off the dispatch
        # thread so the next cohort's device work starts immediately
        self._demux_q.put((cohort, out, entry, run_kwargs, bucket, total,
                           t_d0, t_d1))

    # -- demux --------------------------------------------------------------
    def _demux_loop(self):
        while True:
            item = self._demux_q.get()
            if item is _STOP:
                return
            cohort, out, entry, run_kwargs, bucket, total, t_d0, t_d1 = item
            try:
                self._demux(cohort, out, entry, run_kwargs, bucket, total,
                            t_d0)
            except BaseException as exc:   # noqa: BLE001 — forwarded
                err = ServeError(f"demux failed: {exc!r}")
                err.__cause__ = exc
                for p in cohort:
                    if not p.fut.done():
                        p.fut.set_exception(err)
                flightrec.note("serve_demux_failed", error=repr(exc)[:300])
                with self._lock:
                    self._stats.failed += sum(
                        1 for p in cohort if p.fut.exception() is err)

    def _demux(self, cohort, out, entry, run_kwargs, bucket, total, t_d0):
        os_vals = null_vals = os_ops = os_spec = None
        if out.get("os") is not None:
            from ..detect import operators as detect_ops

            res = out["os"]
            os_spec = run_kwargs["os"]
            # the engine's assembly is per-realization except the null
            # calibration (quantiles/p-values over the cohort's null
            # sample); re-assembling each request's slice keeps every
            # response a pure function of its own lane — cohort-independent
            os_vals = np.stack([res["stats"][o]["amp2"] for o in res["orfs"]],
                               axis=1)
            if res["null"]:
                null_vals = np.stack([res["stats"][o]["null_amp2"]
                                      for o in res["orfs"]], axis=1)
            token = cohort[0].req.lane_token()
            os_ops = entry.os_ops.get(token)
            if os_ops is None:
                os_ops = entry.sim._prepare_lanes(os_spec, None)["os_ops"]
                entry.os_ops[token] = os_ops
            assemble = detect_ops.assemble
        pos = 0
        done = []
        for p in cohort:
            n = int(p.req.n)
            sl = slice(pos, pos + n)
            pos += n
            result = ServeResult(
                curves=np.array(out["curves"][sl]),
                autos=np.array(out["autos"][sl]),
                bin_centers=out["bin_centers"],
                cohort_requests=len(cohort), bucket=int(bucket),
                pad_waste_frac=1.0 - total / bucket)
            if os_vals is not None:
                result.os = assemble(
                    os_spec, os_ops, os_vals[sl],
                    null_vals[sl] if null_vals is not None else None)
            if out.get("lnlike") is not None:
                lnl = out["lnlike"]
                # only the per-realization lanes slice; theta/param_names/
                # schema are cohort-shape-independent and pass through
                result.lnlike = {k: (np.array(v[sl])
                                     if k in ("lnl", "grad", "fisher")
                                     else v)
                                 for k, v in lnl.items()}
            t_done = obs.now()
            result.queued_s = t_d0 - p.t_enq
            result.service_s = t_done - t_d0
            result.latency_s = t_done - p.t_enq
            p.fut.set_result(result)
            done.append((p, result, t_done))
        # ONE stats/timeline critical section per cohort, after every
        # future is already resolved: the hot serving path never makes a
        # waiting caller contend with bookkeeping
        with self._lock:
            st = self._stats
            for p, result, t_done in done:
                st.completed += 1
                st.t_last = t_done
                st.latency_ms.append(result.latency_s * 1e3)
                st.queued_ms.append(result.queued_s * 1e3)
                st.service_ms.append(result.service_s * 1e3)
                ev = {"name": "request", "tid": "serve",
                      "t0": p.t_enq - self._t0, "dur": result.latency_s,
                      "req_kind": p.req.kind, "n": int(p.req.n)}
                if getattr(p.req, "trace_id", None):
                    ev["trace_id"] = p.req.trace_id
                self._timeline.append(ev)

    def reset_stats(self) -> None:
        """Zero the SLO accumulators and timeline (the load generator's
        warmup/measure boundary); warm-pool state is untouched."""
        with self._lock:
            self._stats = _Stats(self.config.result_window)
            self._timeline.clear()
            self._t0 = obs.now()

    # -- observability -------------------------------------------------------
    def slo_summary(self) -> dict:
        """The SLO rollup (docs/SERVING.md metric table): gate-/compare-
        aware via the ``fakepta_tpu.obs`` direction tables."""
        with self._lock:
            st = self._stats
            lat = np.asarray(st.latency_ms, dtype=float)
            span = ((st.t_last - st.t_first)
                    if st.t_last is not None and st.t_first is not None
                    else 0.0)
            qps = st.completed / span if span > 0 else 0.0
            out = {
                "serve_requests": st.completed,
                "serve_rejected": st.rejected,
                "serve_deadline_cancelled": st.cancelled,
                "serve_failed": st.failed,
                "serve_dispatches": st.dispatches,
                "serve_realizations": st.realizations,
                "serve_qps_per_chip": round(qps / self.n_devices, 3),
                "serve_real_per_s_per_chip": round(
                    st.realizations / span / self.n_devices
                    if span > 0 else 0.0, 3),
                "serve_p50_ms": round(float(np.percentile(lat, 50)), 3)
                if lat.size else 0.0,
                "serve_p99_ms": round(float(np.percentile(lat, 99)), 3)
                if lat.size else 0.0,
                "coalesce_factor": round(float(np.mean(st.coalesce)), 3)
                if st.coalesce else 0.0,
                "pad_waste_frac": round(float(np.mean(st.pad_waste)), 4)
                if st.pad_waste else 0.0,
                "queue_depth": st.queue_depth_max,
                "serve_retraces": st.retraces,
                "serve_steady_compiles": st.steady_compiles,
                "serve_warm_s": round(st.warm_s, 3),
                # recovery health (docs/RELIABILITY.md): transient
                # dispatch retries and poisoned-executable evictions both
                # keep the lower-is-better default — growth past the zero
                # history IS the serving path degrading
                "serve_dispatch_retries": st.retried,
                "serve_evictions": st.evicted,
            }
        return out

    def warm_summary(self) -> dict:
        """Warm-pool occupancy: resident entries, capacity, and per-spec
        prewarmed-executable counts (the ``pool`` telemetry source and the
        enriched ``stats`` protocol reply)."""
        pool = self._pool
        try:
            # the dispatcher mutates the LRU outside the pool lock (its
            # own thread owns it); a scrape racing a resize retries next
            # heartbeat rather than adding a lock to the dispatch path
            items = list(pool._entries.items())
        except RuntimeError:
            items = []
        specs = {h: {"warm_buckets": len(e.warmed),
                     "pinned": bool(e.pinned),
                     "warm_s": round(e.warm_s, 3)}
                 for h, e in items}
        return {"entries": len(items), "max_entries": pool.max_entries,
                "builds": pool.builds, "evictions": pool.evictions,
                "specs": specs}

    def stream_summary(self) -> dict:
        """Per-stream telemetry (append counts + latencies) from the lazy
        StreamManager; empty when no stream was ever opened."""
        with self._lock:
            mgr = self._stream_mgr
        return mgr.summary() if mgr is not None else {}

    def cutover_stream(self, name: str, spec, checkpoint=None) -> dict:
        """Frozen-grid migration cutover for one of this pool's streams
        (the ``cutover`` protocol kind; the gateway's managed operation —
        :meth:`~fakepta_tpu.serve.streams.StreamManager.cutover`)."""
        with self._lock:
            mgr = self._stream_mgr
        if mgr is None:
            raise ServeError(f"stream {name!r} is not open on this pool; "
                             f"nothing to cut over")
        return mgr.cutover(name, spec, checkpoint=checkpoint)

    def health_summary(self) -> dict:
        """The replica's own liveness facts (the fleet's HealthMonitor
        owns the authoritative ladder state; this is what the replica can
        say about itself over the ``stats``/``telemetry`` kinds)."""
        with self._lock:
            closed = self._closed
        alive = self._dispatcher.is_alive() and self._demux_thread.is_alive()
        state = "closed" if closed else ("healthy" if alive else "failed")
        return {"state": state, "dispatcher_alive": bool(alive),
                "closed": bool(closed)}

    def telemetry_snapshot(self) -> dict:
        """One publisher snapshot (the ``telemetry`` protocol kind and the
        LocalReplica scrape path)."""
        return self.telemetry.snapshot()

    def telemetry_rollup(self) -> dict:
        """This pool's own single-replica aggregator rollup — the same
        shape ``ServeFleet.telemetry_rollup`` produces, so a fronting
        :class:`~fakepta_tpu.gateway.Gateway` (or ``obs top``) consumes a
        bare pool and a fleet identically. The pool keeps the aggregator
        alive across calls so rate-style metrics (qps) see a real window
        between scrapes."""
        from ..obs import telemetry as telemetry_mod

        with self._lock:
            agg = self._metrics_agg
            if agg is None:
                agg = self._metrics_agg = telemetry_mod.TelemetryAggregator()
        health = self.health_summary()
        agg.ingest("self", self.telemetry.snapshot(),
                   health={"state": health["state"], "misses": 0,
                           "breaker_open": False})
        return agg.rollup()

    def metrics_text(self) -> str:
        """Prometheus text-format exposition of this pool's own rollup
        (the ``metrics`` protocol kind)."""
        from ..obs import promfmt

        return promfmt.render(self.telemetry_rollup())

    def save_report(self, path) -> str:
        """Write the pool's telemetry as a RunReport artifact: ``obs
        summarize`` prints it, ``obs compare``/``obs gate`` band its SLO
        metrics, ``obs trace`` renders the per-request spans."""
        rep = self.report()
        return rep.save(path)

    def report(self):
        from ..obs import RunReport

        with self._lock:
            timeline = list(self._timeline)
            st = self._stats
            total_s = ((st.t_last - self._t0)
                       if st.t_last is not None else 0.0)
        meta = {
            "kind": "serve",
            "platform": self.mesh.devices.flat[0].platform,
            "n_devices": self.n_devices,
            "mesh_shape": {k: int(v) for k, v in self.mesh.shape.items()},
            "buckets": list(self._buckets),
            "max_queue_depth": int(self.config.max_queue_depth),
            "coalesce_window_s": float(self.config.coalesce_window_s),
            "extra_metrics": self.slo_summary(),
        }
        rep = RunReport(meta=meta, total_s=total_s)
        rep.timeline = sorted(timeline, key=lambda e: e.get("t0", 0.0))
        return rep

    # -- lifecycle ----------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        """Shut down: ``drain=True`` serves everything already admitted
        (new submissions raise ServeClosed), ``drain=False`` fails pending
        requests with ServeClosed."""
        doomed = []
        with self._cond:
            if self._closed:
                return
            self._closed = True
            if not drain:
                for q in self._queues.values():
                    while q:
                        doomed.append(q.popleft())
                        self._pending -= 1
            self._cond.notify_all()
        # futures resolve outside the cond: completion callbacks run
        # synchronously and may take other locks (lock-order-inversion)
        for p in doomed:
            p.fut.set_exception(ServeClosed("pool closed"))
        # bounded joins: a dispatcher wedged in a hung drain must surface
        # as a loud note, never hang the caller's shutdown forever (the
        # unbounded-thread-join invariant, docs/INVARIANTS.md)
        self._dispatcher.join(_SHUTDOWN_JOIN_S)
        if self._dispatcher.is_alive():
            flightrec.note("serve_close_join_timeout", thread="dispatcher",
                           timeout_s=_SHUTDOWN_JOIN_S)
        self._demux_q.put(_STOP)
        self._demux_thread.join(_SHUTDOWN_JOIN_S)
        if self._demux_thread.is_alive():
            flightrec.note("serve_close_join_timeout", thread="demux",
                           timeout_s=_SHUTDOWN_JOIN_S)
        if self._stream_mgr is not None:
            self._stream_mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
