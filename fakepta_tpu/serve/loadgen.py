"""Synthetic load generator: the serving layer's built-in benchmark.

Drives a :class:`ServePool` with a reproducible stream of requests (sizes
drawn from a small palette so the serial baseline warms a bounded set of
executables), optionally measures the **serial baseline** — the same
request list dispatched one ``run(n, seed)`` at a time, the pre-serve
consumer pattern — and emits one benchmark row with the SLO metrics and
the coalescing speedup. Correctness is asserted, not assumed: a sampled
subset of served responses is compared bit-for-bit against its own solo
``run()`` (the RNG-lane contract), so a throughput number can never ship
from a wrong-answer path.

Used by ``python -m fakepta_tpu.serve loadgen`` (docs/SERVING.md recipe),
``bench.py`` and ``benchmarks/suite.py`` (the ``serve_*`` row fields,
banded by ``obs gate``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence

import numpy as np

from .. import obs
from .scheduler import ServeConfig, ServePool
from .spec import ArraySpec, InferRequest, OSRequest, ServeBusy, SimRequest

#: default request-size palette: a few distinct sizes (not a continuum) so
#: the serial baseline pays a bounded number of compiles and the coalesced
#: path exercises several ladder buckets
DEFAULT_SIZES = (4, 8, 16, 32)


def make_requests(spec: ArraySpec, n_requests: int, sizes: Sequence[int],
                  kind: str = "sim", seed: int = 0, lnlike=None,
                  deadline_s: Optional[float] = None):
    """The reproducible request list (seeds distinct per request)."""
    rng = np.random.default_rng(seed)
    ns = rng.choice(np.asarray(sizes, dtype=int), size=n_requests)
    reqs = []
    for i, n in enumerate(ns):
        req_seed = 1000 + i
        if kind == "sim":
            reqs.append(SimRequest(spec=spec, n=int(n), seed=req_seed,
                                   deadline_s=deadline_s))
        elif kind == "os":
            reqs.append(OSRequest(spec=spec, n=int(n), seed=req_seed,
                                  deadline_s=deadline_s))
        elif kind == "infer":
            reqs.append(InferRequest(spec=spec, n=int(n), seed=req_seed,
                                     deadline_s=deadline_s, lnlike=lnlike))
        else:
            raise ValueError(f"unknown request kind {kind!r}")
    return reqs


def _serial_baseline(sim, reqs, repeats: int = 3) -> dict:
    """The same requests, one ``run()`` dispatch each — per-request chunk
    shapes, warmed once per distinct size so the figure is steady-state
    dispatch cost, not compile cost. Best-of-``repeats`` passes: the tiny
    per-request runs are timer-noisy, and taking the serial side's BEST
    pass makes the reported speedup the conservative one."""
    for n in sorted({r.n for r in reqs}):
        sim.run(n, seed=0, chunk=n, pipeline_depth=0, **reqs[0].run_kwargs())
    elapsed = float("inf")
    for _ in range(repeats):
        t0 = obs.now()
        for r in reqs:
            sim.run(r.n, seed=r.seed, chunk=r.n, pipeline_depth=0,
                    **r.run_kwargs())
        elapsed = min(elapsed, obs.now() - t0)
    return {"elapsed_s": elapsed, "qps": len(reqs) / elapsed,
            "real_per_s": sum(r.n for r in reqs) / elapsed}


def run_loadgen(spec: Optional[ArraySpec] = None, *, mesh=None,
                n_requests: int = 64, sizes: Sequence[int] = DEFAULT_SIZES,
                kind: str = "sim", rate_hz: Optional[float] = None,
                seed: int = 0, baseline: bool = False, verify: int = 3,
                config: Optional[ServeConfig] = None,
                compile_cache_dir: Optional[str] = None,
                report_path=None, lnlike=None) -> dict:
    """Generate load, serve it, return one benchmark row (see module doc).

    ``rate_hz`` paces submissions open-loop (None = submit as fast as
    admission allows — the max-coalescing regime); ``verify`` solo-checks
    that many served responses bit-for-bit; ``baseline=True`` adds the
    serial figures and the ``serve_speedup_x`` ratio.
    """
    spec = spec or ArraySpec()
    pool = ServePool(mesh=mesh, config=config,
                     compile_cache_dir=compile_cache_dir)
    reqs = make_requests(spec, n_requests, sizes, kind=kind, seed=seed,
                         lnlike=lnlike)
    try:
        # warmup: exercise every ladder bucket once (a full-bucket request
        # each), so the measured window reports steady-state serving —
        # symmetric with the serial baseline, which is warmed per size.
        # Compile cost is a one-time figure the engine benchmarks already
        # record (compile_s / warm_start), not a per-request SLO.
        for b in pool.buckets:
            # one request per ladder bucket, served to completion before
            # the next — submitting them together would coalesce into one
            # (bigger) bucket and leave the smaller executables cold
            pool.submit(dataclasses.replace(reqs[0], n=b,
                                            seed=0)).result(timeout=600.0)
        pool.reset_stats()

        futs = []
        for r in reqs:
            while True:
                try:
                    futs.append(pool.submit(r))
                    break
                except ServeBusy as busy:
                    # the backpressure contract in action: honor the
                    # scheduler's computed Retry-After hint (estimated
                    # backlog drain time) instead of hammering a fixed
                    # sleep — the client converges on the pool's actual
                    # service rate
                    time.sleep(max(getattr(busy, "retry_after_s", 0.0),
                                   0.002))
            if rate_hz:
                time.sleep(1.0 / rate_hz)
        results = [f.result(timeout=600.0) for f in futs]
        row = dict(pool.slo_summary())
        row["serve_kind"] = kind

        if verify:
            # the RNG-lane contract, asserted on real served traffic, in
            # its two layers (docs/SERVING.md): (1) BIT-identical to the
            # same request served alone at the same bucket shape — cohort,
            # padding and slot cannot change a response; (2) equal to the
            # classic solo run(n, seed) at the engine's reduction
            # tolerance — XLA's statistic-reduction order is executable-
            # shape-dependent, so differently-shaped programs may differ
            # in the last ULP while the drawn streams are bit-identical
            entry = pool._pool.get(spec.spec_hash(), spec)
            rng = np.random.default_rng(seed + 1)
            for idx in rng.choice(len(reqs), size=min(verify, len(reqs)),
                                  replace=False):
                r, res = reqs[idx], results[idx]
                alone = entry.sim.run(res.bucket, chunk=res.bucket,
                                      lanes=[(r.seed, r.n)],
                                      pipeline_depth=0, **r.run_kwargs())
                if not (np.array_equal(alone["curves"][:r.n], res.curves)
                        and np.array_equal(alone["autos"][:r.n],
                                           res.autos)):
                    raise AssertionError(
                        f"served response for request {idx} differs from "
                        f"the same request served alone at bucket "
                        f"{res.bucket} — the RNG-lane contract is broken")
                solo = entry.sim.run(r.n, seed=r.seed, chunk=r.n,
                                     pipeline_depth=0, **r.run_kwargs())
                scale = float(np.abs(solo["curves"]).max()) or 1.0
                if not (np.allclose(solo["curves"], res.curves, rtol=1e-5,
                                    atol=1e-5 * scale)
                        and np.allclose(solo["autos"], res.autos,
                                        rtol=1e-5)):
                    raise AssertionError(
                        f"served response for request {idx} disagrees "
                        f"with its solo run beyond reduction tolerance")
            row["serve_verified"] = int(min(verify, len(reqs)))
        if report_path is not None:
            pool.save_report(report_path)
    finally:
        pool.close()

    if baseline:
        sim = spec.build(mesh=mesh, compile_cache_dir=compile_cache_dir)
        ser = _serial_baseline(sim, reqs)
        import jax
        n_dev = (int(mesh.devices.size) if mesh is not None
                 else len(jax.devices()))
        row["serve_serial_qps_per_chip"] = round(ser["qps"] / n_dev, 3)
        if ser["qps"] > 0 and row.get("serve_qps_per_chip"):
            row["serve_speedup_x"] = round(
                row["serve_qps_per_chip"]
                / (ser["qps"] / n_dev), 2)
    return row
