"""Synthetic load generator: the serving layer's built-in benchmark.

Drives a :class:`ServePool` with a reproducible stream of requests (sizes
drawn from a small palette so the serial baseline warms a bounded set of
executables), optionally measures the **serial baseline** — the same
request list dispatched one ``run(n, seed)`` at a time, the pre-serve
consumer pattern — and emits one benchmark row with the SLO metrics and
the coalescing speedup. Correctness is asserted, not assumed: a sampled
subset of served responses is compared bit-for-bit against its own solo
``run()`` (the RNG-lane contract), so a throughput number can never ship
from a wrong-answer path.

Used by ``python -m fakepta_tpu.serve loadgen`` (docs/SERVING.md recipe),
``bench.py`` and ``benchmarks/suite.py`` (the ``serve_*`` row fields,
banded by ``obs gate``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence

import numpy as np

from .. import faults as faults_mod
from .. import obs
from ..obs import flightrec
from .scheduler import ServeConfig, ServePool
from .spec import (AppendRequest, ArraySpec, InferRequest, OSRequest,
                   ServeBusy, SimRequest, StreamRequest)

#: default request-size palette: a few distinct sizes (not a continuum) so
#: the serial baseline pays a bounded number of compiles and the coalesced
#: path exercises several ladder buckets
DEFAULT_SIZES = (4, 8, 16, 32)


def make_requests(spec: ArraySpec, n_requests: int, sizes: Sequence[int],
                  kind: str = "sim", seed: int = 0, lnlike=None,
                  deadline_s: Optional[float] = None):
    """The reproducible request list (seeds distinct per request)."""
    rng = np.random.default_rng(seed)
    ns = rng.choice(np.asarray(sizes, dtype=int), size=n_requests)
    reqs = []
    for i, n in enumerate(ns):
        req_seed = 1000 + i
        if kind == "sim":
            reqs.append(SimRequest(spec=spec, n=int(n), seed=req_seed,
                                   deadline_s=deadline_s))
        elif kind == "os":
            reqs.append(OSRequest(spec=spec, n=int(n), seed=req_seed,
                                  deadline_s=deadline_s))
        elif kind == "infer":
            reqs.append(InferRequest(spec=spec, n=int(n), seed=req_seed,
                                     deadline_s=deadline_s, lnlike=lnlike))
        else:
            raise ValueError(f"unknown request kind {kind!r}")
    return reqs


def _serial_baseline(sim, reqs, repeats: int = 3) -> dict:
    """The same requests, one ``run()`` dispatch each — per-request chunk
    shapes, warmed once per distinct size so the figure is steady-state
    dispatch cost, not compile cost. Best-of-``repeats`` passes: the tiny
    per-request runs are timer-noisy, and taking the serial side's BEST
    pass makes the reported speedup the conservative one."""
    for n in sorted({r.n for r in reqs}):
        sim.run(n, seed=0, chunk=n, pipeline_depth=0, **reqs[0].run_kwargs())
    elapsed = float("inf")
    for _ in range(repeats):
        t0 = obs.now()
        for r in reqs:
            sim.run(r.n, seed=r.seed, chunk=r.n, pipeline_depth=0,
                    **r.run_kwargs())
        elapsed = min(elapsed, obs.now() - t0)
    return {"elapsed_s": elapsed, "qps": len(reqs) / elapsed,
            "real_per_s": sum(r.n for r in reqs) / elapsed}


def run_loadgen(spec: Optional[ArraySpec] = None, *, mesh=None,
                n_requests: int = 64, sizes: Sequence[int] = DEFAULT_SIZES,
                kind: str = "sim", rate_hz: Optional[float] = None,
                seed: int = 0, baseline: bool = False, verify: int = 3,
                config: Optional[ServeConfig] = None,
                compile_cache_dir: Optional[str] = None,
                report_path=None, lnlike=None, fleet=None,
                fleet_transport: str = "process", n_specs: int = 6,
                kill_one_at: Optional[float] = None) -> dict:
    """Generate load, serve it, return one benchmark row (see module doc).

    ``rate_hz`` paces submissions open-loop (None = submit as fast as
    admission allows — the max-coalescing regime); ``verify`` solo-checks
    that many served responses bit-for-bit; ``baseline=True`` adds the
    serial figures and the ``serve_speedup_x`` ratio.

    ``fleet`` switches to the **multi-replica mode** (docs/SERVING.md
    "Fleet"): an int spawns that many replicas (``fleet_transport`` picks
    subprocess sockets or in-process pools), a prebuilt
    :class:`~fakepta_tpu.serve.fleet.ServeFleet` is driven as-is. The
    traffic covers ``n_specs`` distinct specs (the spec-space working set
    the ring shards), the baseline becomes ONE ServePool serving the same
    request list (``fleet_speedup_x``), and ``kill_one_at`` kills a
    replica after that fraction of submissions — the failover A/B: the
    row records lost requests (must be 0) and every failed-over response
    stays bit-verified against its solo run.
    """
    if fleet is not None:
        return run_fleet_loadgen(
            spec=spec, fleet=fleet, transport=fleet_transport,
            n_requests=n_requests, sizes=sizes, kind=kind, seed=seed,
            baseline=baseline, verify=verify, n_specs=n_specs,
            kill_one_at=kill_one_at, config=config,
            compile_cache_dir=compile_cache_dir, report_path=report_path,
            mesh=mesh)
    spec = spec or ArraySpec()
    pool = ServePool(mesh=mesh, config=config,
                     compile_cache_dir=compile_cache_dir)
    reqs = make_requests(spec, n_requests, sizes, kind=kind, seed=seed,
                         lnlike=lnlike)
    try:
        # warmup: exercise every ladder bucket once (a full-bucket request
        # each), so the measured window reports steady-state serving —
        # symmetric with the serial baseline, which is warmed per size.
        # Compile cost is a one-time figure the engine benchmarks already
        # record (compile_s / warm_start), not a per-request SLO.
        for b in pool.buckets:
            # one request per ladder bucket, served to completion before
            # the next — submitting them together would coalesce into one
            # (bigger) bucket and leave the smaller executables cold
            pool.submit(dataclasses.replace(reqs[0], n=b,
                                            seed=0)).result(timeout=600.0)
        pool.reset_stats()

        futs = []
        for r in reqs:
            while True:
                try:
                    futs.append(pool.submit(r))
                    break
                except ServeBusy as busy:
                    # the backpressure contract in action: honor the
                    # scheduler's computed Retry-After hint (estimated
                    # backlog drain time) instead of hammering a fixed
                    # sleep — the client converges on the pool's actual
                    # service rate
                    time.sleep(max(getattr(busy, "retry_after_s", 0.0),
                                   0.002))
            if rate_hz:
                time.sleep(1.0 / rate_hz)
        results = [f.result(timeout=600.0) for f in futs]
        row = dict(pool.slo_summary())
        row["serve_kind"] = kind

        if verify:
            # the RNG-lane contract, asserted on real served traffic, in
            # its two layers (docs/SERVING.md): (1) BIT-identical to the
            # same request served alone at the same bucket shape — cohort,
            # padding and slot cannot change a response; (2) equal to the
            # classic solo run(n, seed) at the engine's reduction
            # tolerance — XLA's statistic-reduction order is executable-
            # shape-dependent, so differently-shaped programs may differ
            # in the last ULP while the drawn streams are bit-identical
            entry = pool._pool.get(spec.spec_hash(), spec)
            rng = np.random.default_rng(seed + 1)
            for idx in rng.choice(len(reqs), size=min(verify, len(reqs)),
                                  replace=False):
                r, res = reqs[idx], results[idx]
                alone = entry.sim.run(res.bucket, chunk=res.bucket,
                                      lanes=[(r.seed, r.n)],
                                      pipeline_depth=0, **r.run_kwargs())
                if not (np.array_equal(alone["curves"][:r.n], res.curves)
                        and np.array_equal(alone["autos"][:r.n],
                                           res.autos)):
                    raise AssertionError(
                        f"served response for request {idx} differs from "
                        f"the same request served alone at bucket "
                        f"{res.bucket} — the RNG-lane contract is broken")
                solo = entry.sim.run(r.n, seed=r.seed, chunk=r.n,
                                     pipeline_depth=0, **r.run_kwargs())
                scale = float(np.abs(solo["curves"]).max()) or 1.0
                if not (np.allclose(solo["curves"], res.curves, rtol=1e-5,
                                    atol=1e-5 * scale)
                        and np.allclose(solo["autos"], res.autos,
                                        rtol=1e-5)):
                    raise AssertionError(
                        f"served response for request {idx} disagrees "
                        f"with its solo run beyond reduction tolerance")
            row["serve_verified"] = int(min(verify, len(reqs)))
        if report_path is not None:
            pool.save_report(report_path)
    finally:
        pool.close()

    if baseline:
        sim = spec.build(mesh=mesh, compile_cache_dir=compile_cache_dir)
        ser = _serial_baseline(sim, reqs)
        import jax
        n_dev = (int(mesh.devices.size) if mesh is not None
                 else len(jax.devices()))
        row["serve_serial_qps_per_chip"] = round(ser["qps"] / n_dev, 3)
        if ser["qps"] > 0 and row.get("serve_qps_per_chip"):
            row["serve_speedup_x"] = round(
                row["serve_qps_per_chip"]
                / (ser["qps"] / n_dev), 2)
    return row


# ---------------------------------------------------------------------------
# multi-replica (fleet) mode — docs/SERVING.md "Fleet"
# ---------------------------------------------------------------------------

def make_fleet_requests(specs: Sequence[ArraySpec], n_requests: int,
                        sizes: Sequence[int], kind: str = "sim",
                        seed: int = 0):
    """The fleet's reproducible request list: sizes from the palette,
    specs CYCLED in order — the LRU-adversarial access pattern, so a
    single pool whose ``max_specs`` is below the working set misses on
    (nearly) every request while the sharded fleet stays hot."""
    rng = np.random.default_rng(seed)
    ns = rng.choice(np.asarray(sizes, dtype=int), size=n_requests)
    reqs = []
    for i, n in enumerate(ns):
        spec = specs[i % len(specs)]
        req_seed = 1000 + i
        if kind == "sim":
            reqs.append(SimRequest(spec=spec, n=int(n), seed=req_seed))
        elif kind == "os":
            reqs.append(OSRequest(spec=spec, n=int(n), seed=req_seed))
        else:
            raise ValueError(f"fleet loadgen serves sim/os requests, "
                             f"not {kind!r}")
    return reqs


def _build_fleet(n_replicas: int, transport: str, spec: ArraySpec,
                 config, compile_cache_dir, mesh):
    """N replicas behind the router (subprocess sockets, spawned
    concurrently so startup is one cold-start wall, or in-process pools)."""
    import threading

    from .fleet import FleetConfig, LocalReplica, ServeFleet, SocketReplica

    if transport == "inproc":
        import jax
        from ..parallel.mesh import make_mesh

        replicas = [LocalReplica(
            f"r{i}", mesh=mesh or make_mesh(jax.devices()[:1]),
            config=config, compile_cache_dir=compile_cache_dir, index=i)
            for i in range(n_replicas)]
        return ServeFleet(replicas, FleetConfig())
    if transport != "process":
        raise ValueError(f"unknown fleet transport {transport!r}")
    buckets = tuple(config.buckets) if config is not None else None
    out: list = [None] * n_replicas
    errs: list = []

    def spawn(i):
        try:
            out[i] = SocketReplica(f"r{i}", spec_defaults=spec,
                                   compile_cache_dir=compile_cache_dir,
                                   buckets=buckets, index=i)
        except Exception as exc:   # noqa: BLE001 — re-raised below
            errs.append(exc)

    threads = [threading.Thread(target=spawn, args=(i,))
               for i in range(n_replicas)]
    for t in threads:
        t.start()
    for t in threads:
        # bounded: a wedged replica spawn surfaces as a loud startup
        # failure (its None slot below), never a hung loadgen (the
        # unbounded-thread-join invariant, docs/INVARIANTS.md)
        t.join(180.0)
        if t.is_alive():
            flightrec.note("fleet_spawn_join_timeout", timeout_s=180.0)
    if errs or any(r is None for r in out):
        for r in out:
            if r is not None:
                r.close()
        raise RuntimeError(f"fleet startup failed: {errs!r}")
    return ServeFleet(out, FleetConfig())


def _submit_politely(fleet, req, futs):
    """Admission with the backpressure contract: honor aggregated
    Retry-After hints instead of hammering."""
    while True:
        try:
            futs.append(fleet.submit(req))
            return
        except ServeBusy as busy:
            time.sleep(max(getattr(busy, "retry_after_s", 0.0), 0.002))


def _verify_fleet_responses(reqs, results, verify: int, seed: int, mesh,
                            compile_cache_dir) -> set:
    """The RNG-lane contract on fleet traffic: ``verify`` sampled
    responses PLUS every failed-over response, bit-compared against the
    same request served alone at the same bucket shape. Returns the
    verified index set (shared by the fleet and elastic loadgen modes)."""
    rng = np.random.default_rng(seed + 1)
    done = [i for i, r in enumerate(results) if r is not None]
    picks = set(rng.choice(done, size=min(verify, len(done)),
                           replace=False).tolist())
    picks |= {i for i in done if results[i].failovers > 0}
    sims: dict = {}
    import jax
    from ..parallel.mesh import make_mesh

    solo_mesh = mesh or make_mesh(jax.devices()[:1])
    for i in sorted(picks):
        r, res = reqs[i], results[i]
        sh = r.spec.spec_hash()
        if sh not in sims:
            sims[sh] = r.spec.build(mesh=solo_mesh,
                                    compile_cache_dir=compile_cache_dir)
        alone = sims[sh].run(res.bucket, chunk=res.bucket,
                             lanes=[(r.seed, r.n)],
                             pipeline_depth=0, **r.run_kwargs())
        if not (np.array_equal(alone["curves"][:r.n], res.curves)
                and np.array_equal(alone["autos"][:r.n], res.autos)):
            raise AssertionError(
                f"fleet response for request {i} (replica "
                f"{res.replica}, failovers {res.failovers}) "
                f"differs from the same request served alone — "
                f"the RNG-lane contract is broken")
    return picks


def run_fleet_loadgen(spec: Optional[ArraySpec] = None, *, fleet=3,
                      transport: str = "process", n_requests: int = 96,
                      sizes: Sequence[int] = (1, 2, 4), kind: str = "sim",
                      seed: int = 0, baseline: bool = False,
                      verify: int = 3, n_specs: int = 6,
                      kill_one_at: Optional[float] = None, config=None,
                      compile_cache_dir: Optional[str] = None,
                      report_path=None, mesh=None) -> dict:
    """Drive a replica fleet with a sharded-spec workload; one row.

    The traffic cycles ``n_specs`` distinct specs (same shapes, distinct
    ``data_seed`` — one persistent-compile-cache entry serves them all,
    so every replica cold-start is a cache load). The measured comparison
    (``baseline=True``) is the SAME request list through one
    ``ServePool``: on a single chip the fleet's win is aggregate warm
    capacity (N x ``max_specs`` resident specs vs one pool thrashing its
    LRU); on multi-chip hosts the N dispatchers also run in parallel.
    ``kill_one_at`` kills the first spec's owner replica mid-load — the
    row then records ``fleet_lost_requests`` (0 is the acceptance) and
    every failed-over response is bit-verified like any other.
    """
    import dataclasses as dc

    base = spec or ArraySpec(npsr=8, ntoa=64, n_red=4, n_dm=4, gwb_ncomp=4)
    specs = [dc.replace(base, data_seed=100 + i) for i in range(n_specs)]
    reqs = make_fleet_requests(specs, n_requests, sizes, kind=kind,
                               seed=seed)
    if config is None:
        from ..tune import defaults as tune_defaults
        config = ServeConfig(buckets=tune_defaults.DEFAULT_FLEET_BUCKETS)
    flt = fleet if not isinstance(fleet, int) else _build_fleet(
        fleet, transport, base, config, compile_cache_dir, mesh)
    own_fleet = isinstance(fleet, int)
    kill_rid = None
    warm_buckets = sorted({int(b) for b in config.buckets})
    try:
        # warmup: each spec's owner serves one request per ladder bucket,
        # so the measured window is steady-state (mirrors the solo mode)
        for s in specs:
            for b in warm_buckets:
                flt.serve(dc.replace(reqs[0], spec=s, n=b, seed=0),
                          timeout=600.0)
        flt.reset_stats()

        if kill_one_at is not None:
            kill_rid = flt.ring.owner(specs[0].spec_hash())
        kill_at = (int(kill_one_at * len(reqs))
                   if kill_one_at is not None else None)
        futs: list = []
        for i, r in enumerate(reqs):
            if kill_at is not None and i == kill_at:
                flt._mark_dead(kill_rid, "loadgen chaos kill")
                flt.replicas[kill_rid].kill()
            _submit_politely(flt, r, futs)
        results, lost = [], 0
        for f in futs:
            try:
                results.append(f.result(timeout=600.0))
            except Exception as exc:   # noqa: BLE001 — recorded + counted:
                # a lost accepted request is THE failover acceptance
                # failure, surfaced in the row (fleet_lost_requests != 0)
                flightrec.note("fleet_request_lost", error=repr(exc)[:200])
                results.append(None)
                lost += 1
        row = dict(flt.slo_summary())
        row["fleet_kind"] = kind
        row["fleet_transport"] = ("inproc" if not own_fleet
                                  else transport)
        row["fleet_lost_requests"] = lost
        if kill_at is not None:
            row["fleet_killed_replica"] = kill_rid

        if verify:
            picks = _verify_fleet_responses(reqs, results, verify, seed,
                                            mesh, compile_cache_dir)
            row["fleet_verified"] = len(picks)
            row["fleet_verified_failover"] = sum(
                1 for i in picks if results[i].failovers > 0)
        if report_path is not None:
            flt.report().save(report_path)
    finally:
        if own_fleet:
            flt.close()

    if baseline:
        # ONE pool, the SAME traffic: its LRU warm pool is the only spec
        # residency, so the working set thrashes it (docs/SERVING.md
        # "Fleet" has the full accounting of what this A/B measures)
        import jax
        from ..parallel.mesh import make_mesh

        solo = ServePool(mesh=mesh or make_mesh(jax.devices()[:1]),
                         config=config,
                         compile_cache_dir=compile_cache_dir)
        try:
            for s in specs:
                for b in warm_buckets:
                    solo.submit(dc.replace(reqs[0], spec=s, n=b,
                                           seed=0)).result(timeout=600.0)
            solo.reset_stats()
            sfuts: list = []
            for r in reqs:
                while True:
                    try:
                        sfuts.append(solo.submit(r))
                        break
                    except ServeBusy as busy:
                        time.sleep(max(
                            getattr(busy, "retry_after_s", 0.0), 0.002))
            for f in sfuts:
                f.result(timeout=600.0)
            ssum = solo.slo_summary()
        finally:
            solo.close()
        row["fleet_solo_qps"] = ssum.get("serve_qps_per_chip", 0.0) \
            * solo.n_devices
        row["fleet_solo_p50_ms"] = ssum.get("serve_p50_ms", 0.0)
        if row["fleet_solo_qps"] > 0 and row.get("fleet_qps"):
            row["fleet_speedup_x"] = round(
                row["fleet_qps"] / row["fleet_solo_qps"], 2)
    return row


# ---------------------------------------------------------------------------
# elastic chaos mode — docs/RELIABILITY.md "Fleet lifecycle"
# ---------------------------------------------------------------------------

def export_fleet_trace(flt, trace_path) -> dict:
    """One merged, validated Chrome trace for a live fleet: the router's
    report (pid 0: ``route`` spans + failover instants) plus every local
    replica's report (one pid lane each, ``serve`` spans + engine chunk
    spans). Spans sharing a request ``trace_id`` — including a failed-over
    request's spans on the dead and surviving replicas — come out linked
    by flow events (``obs.trace.flow_events``). Returns summary counts
    (``flows`` is the acceptance figure the chaos lane records)."""
    import json

    from ..obs import tracefmt

    reports = [flt.report()] + flt.replica_reports()
    trace = tracefmt.build_trace(reports)
    tracefmt.validate_trace(trace)
    with open(trace_path, "w") as fh:
        json.dump(trace, fh)
    return {"path": str(trace_path), "shards": len(reports),
            "flows": int(trace["metadata"].get("flows", 0))}


def measure_telemetry_overhead(spec: Optional[ArraySpec] = None, *,
                               n_replicas: int = 2, n_requests: int = 48,
                               sizes: Sequence[int] = (1, 2), seed: int = 0,
                               n_specs: int = 2, config=None,
                               compile_cache_dir: Optional[str] = None,
                               mesh=None, health_config=None,
                               rounds: int = 3) -> dict:
    """A/B the telemetry plane's serving cost: the same fleet workload
    with the heartbeat scrape ON (``scrape_every=1``) vs OFF
    (``scrape_every=0``), health plane running in both arms so the delta
    isolates the scrape itself. The arms alternate for ``rounds`` bursts
    and each arm reports its best round — the interleaved best-of-N
    shape of the PR 7 engine-instrumentation A/B, because one warm burst
    at these request counts lasts tens of milliseconds and a single
    sample is scheduler noise, not a measurement. Returns
    ``telemetry_qps_on`` / ``telemetry_qps_off`` /
    ``telemetry_overhead_frac`` (the acceptance bound is 0.02 —
    docs/OBSERVABILITY.md records the measured figure)."""
    import dataclasses as dc

    from .health import HealthConfig

    base = spec or ArraySpec(npsr=8, ntoa=64, n_red=4, n_dm=4, gwb_ncomp=4)
    specs = [dc.replace(base, data_seed=100 + i) for i in range(n_specs)]
    reqs = make_fleet_requests(specs, n_requests, sizes, seed=seed)
    if config is None:
        from ..tune import defaults as tune_defaults
        config = ServeConfig(buckets=tune_defaults.DEFAULT_FLEET_BUCKETS)
    hc = health_config or HealthConfig(period_s=0.02,
                                       probe_deadline_s=0.25)
    warm_buckets = sorted({int(b) for b in config.buckets})
    fleets = {}
    qps = {"off": 0.0, "on": 0.0}
    try:
        for arm, scrape_every in (("off", 0), ("on", 1)):
            flt = fleets[arm] = _build_fleet(n_replicas, "inproc", base,
                                             config, compile_cache_dir, mesh)
            for s in specs:
                for b in warm_buckets:
                    flt.serve(dc.replace(reqs[0], spec=s, n=b, seed=0),
                              timeout=600.0)
            flt.enable_health(dc.replace(hc, scrape_every=scrape_every))
        for _ in range(max(1, int(rounds))):
            for arm in ("off", "on"):
                flt = fleets[arm]
                flt.reset_stats()
                futs: list = []
                for r in reqs:
                    _submit_politely(flt, r, futs)
                for f in futs:
                    f.result(timeout=600.0)
                qps[arm] = max(qps[arm],
                               float(flt.slo_summary().get("fleet_qps",
                                                           0.0)))
    finally:
        for flt in fleets.values():
            flt.close()
    frac = (max(0.0, 1.0 - qps["on"] / qps["off"])
            if qps["off"] > 0 else 0.0)
    return {"telemetry_qps_on": round(qps["on"], 3),
            "telemetry_qps_off": round(qps["off"], 3),
            "telemetry_overhead_frac": round(frac, 4)}


def run_elastic_loadgen(spec: Optional[ArraySpec] = None, *,
                        n_replicas: int = 3, transport: str = "inproc",
                        n_requests: int = 96,
                        sizes: Sequence[int] = (1, 2, 4),
                        kind: str = "sim", seed: int = 0, verify: int = 3,
                        n_specs: int = 6, wedge_at: float = 0.2,
                        kill_at: float = 0.45, join_at: float = 0.7,
                        config=None,
                        compile_cache_dir: Optional[str] = None,
                        mesh=None, health_config=None,
                        hang_s: Optional[float] = None,
                        trace_path=None) -> dict:
    """The fleet lifecycle A/B: ramp load, wedge one replica, SIGKILL
    another, autoscale a third in — one row of acceptance evidence.

    At ``wedge_at`` of submissions a ``fleet.heartbeat`` hang fault
    (matched to one replica via :class:`~fakepta_tpu.faults.FaultSpec`'s
    ``match``) wedges that replica's probes: the health plane must
    breaker it — drained of new routes with ZERO client-visible timeouts,
    because the wedge is caught out of band, never by user traffic. At
    ``kill_at`` a different replica is killed outright (the config13
    failover lever). At ``join_at`` the autoscaler (tiny
    ``target_qps_per_replica``, zero cooldown — a deterministic scale-up)
    spawns and joins a fresh replica that prewarms its absorbed shard
    from the shared compile cache (0 steady compiles).

    Acceptance, recorded in the row: ``fleet_lost_requests == 0``,
    ``fleet_timeouts == 0``, the wedged replica breakered
    (``fleet_wedge_state`` suspect/wedged, ``fleet_breaker_opens >= 1``),
    ``fleet_joins >= 1`` with ``fleet_join_steady_compiles == 0``, and
    every failed-over response bit-verified like any other
    (:func:`_verify_fleet_responses`).

    The telemetry plane rides along: the health monitor's probes double
    as scrapes (``fleet_scrapes``/``fleet_scrape_errors`` in the row,
    ``fleet_alerts`` from the aggregator's firing log), and
    ``trace_path`` exports the chaos run's merged Chrome trace
    (:func:`export_fleet_trace`) with ``row["trace_flows"]`` counting the
    trace-id flow links — the failed-over requests' causal arrows across
    the dead and surviving replicas' pid lanes.
    """
    import dataclasses as dc

    from .autoscale import AutoscaleConfig, Autoscaler
    from .fleet import LocalReplica, SocketReplica
    from .health import HealthConfig

    base = spec or ArraySpec(npsr=8, ntoa=64, n_red=4, n_dm=4, gwb_ncomp=4)
    specs = [dc.replace(base, data_seed=100 + i) for i in range(n_specs)]
    reqs = make_fleet_requests(specs, n_requests, sizes, kind=kind,
                               seed=seed)
    if config is None:
        from ..tune import defaults as tune_defaults
        config = ServeConfig(buckets=tune_defaults.DEFAULT_FLEET_BUCKETS)
    warm_buckets = sorted({int(b) for b in config.buckets})
    hc = health_config or HealthConfig(
        period_s=0.05, probe_deadline_s=0.05, suspect_after=2,
        wedged_after=4, close_after=2, backoff_base_s=0.05,
        backoff_cap_s=0.2)
    hang_s = hang_s if hang_s is not None else 4.0 * hc.probe_deadline_s
    flt = _build_fleet(n_replicas, transport, base, config,
                       compile_cache_dir, mesh)
    joined_id = None
    fault_cm = None
    try:
        for s in specs:
            for b in warm_buckets:
                flt.serve(dc.replace(reqs[0], spec=s, n=b, seed=0),
                          timeout=600.0)
        flt.enable_health(hc)
        flt.reset_stats()

        # victims, chosen BEFORE any membership change: the kill victim
        # owns the first spec; the wedge victim owns some other spec (or
        # is any other live replica when the ring gives one owner both)
        kill_rid = flt.ring.owner(specs[0].spec_hash())
        wedge_rid = next(
            (flt.ring.owner(s.spec_hash()) for s in specs[1:]
             if flt.ring.owner(s.spec_hash()) != kill_rid),
            next(r for r in flt.replicas if r != kill_rid))

        def spawn(index):
            rid = f"scale{index}"
            if transport == "inproc":
                import jax
                from ..parallel.mesh import make_mesh
                return LocalReplica(
                    rid, mesh=mesh or make_mesh(jax.devices()[:1]),
                    config=config, compile_cache_dir=compile_cache_dir,
                    index=index)
            return SocketReplica(
                rid, spec_defaults=base,
                compile_cache_dir=compile_cache_dir,
                buckets=tuple(config.buckets), index=index)

        scaler = Autoscaler(flt, spawn, AutoscaleConfig(
            min_replicas=1, max_replicas=n_replicas + 2,
            target_qps_per_replica=1e-6, cooldown_s=0.0))

        wedge_idx = int(wedge_at * len(reqs))
        kill_idx = int(kill_at * len(reqs))
        join_idx = int(join_at * len(reqs))
        futs: list = []
        for i, r in enumerate(reqs):
            if i == wedge_idx and faults_mod.active() is None:
                fault_cm = faults_mod.inject(faults_mod.FaultPlan([
                    faults_mod.FaultSpec(
                        "fleet.heartbeat", "hang", at=tuple(range(512)),
                        times=512, hang_s=hang_s,
                        match=(("replica", wedge_rid),))]))
                fault_cm.__enter__()
            if i == kill_idx:
                flt._mark_dead(kill_rid, "elastic loadgen chaos kill")
                flt.replicas[kill_rid].kill()
            if i == join_idx:
                # the scale-up must be deterministic: a window that has
                # seen <2 completions reads fleet_qps=0.0 (span 0), which
                # the policy would rightly call over-provisioned and
                # scale DOWN — wait (bounded) for measurable throughput,
                # then demand/target_qps trivially exceeds alive -> up
                jd = obs.now() + 60.0
                while (obs.now() < jd
                       and flt.slo_summary().get("fleet_qps", 0.0) <= 0.0):
                    time.sleep(0.01)
                decision = scaler.step()
                if decision.get("action") == "up":
                    joined_id = decision.get("replica")
            _submit_politely(flt, r, futs)
        results, lost = [], 0
        for f in futs:
            try:
                results.append(f.result(timeout=600.0))
            except Exception as exc:   # noqa: BLE001 — recorded + counted
                flightrec.note("fleet_request_lost", error=repr(exc)[:200])
                results.append(None)
                lost += 1
        # the wedge is caught out of band: give the monitor a bounded
        # window to accumulate its consecutive misses before reading the
        # breaker state (the probes hang for ``hang_s`` each)
        deadline = obs.now() + 20.0 * hang_s + 2.0
        while (obs.now() < deadline
               and flt.health.state(wedge_rid) == "healthy"):
            time.sleep(0.02)
        row = dict(flt.slo_summary())
        row["fleet_kind"] = kind
        row["fleet_transport"] = transport
        row["fleet_lost_requests"] = lost
        row["fleet_killed_replica"] = kill_rid
        row["fleet_wedged_replica"] = wedge_rid
        row["fleet_wedge_state"] = flt.health.state(wedge_rid)
        row["scale_events"] = scaler.scale_events
        if joined_id is not None:
            row["fleet_joined_replica"] = joined_id
            joined = flt.replicas.get(joined_id)
            if joined is not None and joined.alive:
                try:
                    js = (joined.slo_summary()
                          if hasattr(joined, "slo_summary")
                          else joined.stats(timeout=60.0))
                    row["fleet_join_steady_compiles"] = int(
                        js.get("serve_steady_compiles", 0))
                except (ServeBusy, OSError, RuntimeError):
                    pass
        # telemetry-plane acceptance fields: the scrape counters come in
        # via slo_summary (health stats); alerts are the aggregator's
        # full firing history for the measured window
        row["fleet_alerts"] = len(flt.telemetry.alerts.log)
        if trace_path is not None:
            row["trace_flows"] = export_fleet_trace(flt, trace_path)["flows"]
        if verify:
            picks = _verify_fleet_responses(reqs, results, verify, seed,
                                            mesh, compile_cache_dir)
            row["fleet_verified"] = len(picks)
            row["fleet_verified_failover"] = sum(
                1 for i in picks if results[i].failovers > 0)
    finally:
        if fault_cm is not None:
            fault_cm.__exit__(None, None, None)
        flt.close()
    return row


# ---------------------------------------------------------------------------
# multi-tenant gateway mode — docs/GATEWAY.md
# ---------------------------------------------------------------------------

def make_tenant_requests(specs: Sequence[ArraySpec], n_requests: int,
                         sizes: Sequence[int], n_identities: int = 12,
                         seed: int = 0, zipf_s: float = 1.4):
    """The Zipfian hot-spec request stream: a fixed pool of request
    *identities* — distinct ``(spec, seed, n)`` triples, each a distinct
    content address — drawn with popularity ``1/rank^s``, so the traffic
    keeps re-asking its hot identities. That is the regime the gateway's
    content-addressed store and single-flight table exist for: the first
    ask of an identity pays device time, every repeat is a hit (or rides
    the in-flight leader), and the tail identities keep the store's LRU
    honest. Returns ``(requests, identity_index_per_request)``."""
    rng = np.random.default_rng(seed)
    pool = []
    for k in range(n_identities):
        pool.append((specs[k % len(specs)], 1000 + k,
                     int(sizes[k % len(sizes)])))
    ranks = np.arange(1, n_identities + 1, dtype=float)
    probs = ranks ** -float(zipf_s)
    probs /= probs.sum()
    picks = rng.choice(n_identities, size=n_requests, p=probs)
    reqs = [SimRequest(spec=pool[k][0], n=pool[k][2], seed=pool[k][1])
            for k in picks]
    return reqs, [int(k) for k in picks]


def run_gateway_loadgen(spec: Optional[ArraySpec] = None, *,
                        n_tenants: int = 3, n_requests: int = 96,
                        sizes: Sequence[int] = (1, 2, 4), seed: int = 0,
                        n_specs: int = 3, n_identities: int = 12,
                        zipf_s: float = 1.4, n_replicas: int = 2,
                        max_inflight: int = 6, cutover_at: float = 0.5,
                        store_dir=None, config=None,
                        compile_cache_dir: Optional[str] = None,
                        mesh=None) -> dict:
    """Drive a gateway-fronted fleet with a Zipfian multi-tenant mix;
    one row (the ``gw_*`` fields of the bench schema, suite config 16).

    Tenants get distinct auth tokens and a skewed traffic split (tenant 0
    is hot), against a deliberately small ``max_inflight`` so the hot
    tenant runs into its weighted fair share: every 429 must be a
    :class:`~fakepta_tpu.gateway.GatewayBusy` carrying a positive
    per-tenant ``retry_after_s`` — anything else refuses the row. A
    background appender keeps a gateway-opened stream ingesting through
    the measured window, and at ``cutover_at`` of submissions the stream
    is re-staged onto a 2x-Tspan template as a gateway-managed cutover —
    the final stream TOA count must equal exactly what the appender
    landed (zero dropped or duplicated appends) or the row is refused.

    Correctness is the gate, not a sample: EVERY response served from the
    result store is bit-compared against its own solo ``run()`` on the
    same RNG lane, and every other response of the same identity
    (leaders, coalesced followers) must be bit-identical to the verified
    hit. Any mismatch raises — a hit-rate number can never ship from a
    wrong-answer cache.
    """
    import dataclasses as dc
    import tempfile
    import threading

    from ..gateway import Gateway, GatewayBusy, ResultStore, Tenant

    base = spec or ArraySpec(npsr=8, ntoa=64, n_red=4, n_dm=4, gwb_ncomp=4)
    specs = [dc.replace(base, data_seed=100 + i) for i in range(n_specs)]
    reqs, idents = make_tenant_requests(specs, n_requests, sizes,
                                        n_identities=n_identities,
                                        seed=seed, zipf_s=zipf_s)
    # skewed tenant split: tenant 0 is hot (~half the traffic) — the
    # starvation scenario the weighted fair share must absorb
    rng = np.random.default_rng(seed + 7)
    tranks = np.arange(1, n_tenants + 1, dtype=float)
    tprobs = tranks ** -1.5
    tprobs /= tprobs.sum()
    req_tenants = rng.choice(n_tenants, size=n_requests, p=tprobs)
    tenants = [Tenant(f"t{i}", token=f"tok-{i}",
                      weight=(2 if i == 0 else 1))
               for i in range(n_tenants)]
    tokens = {i: f"tok-{i}" for i in range(n_tenants)}

    if config is None:
        from ..tune import defaults as tune_defaults
        config = ServeConfig(buckets=tune_defaults.DEFAULT_FLEET_BUCKETS)
    warm_buckets = sorted({int(b) for b in config.buckets})
    flt = _build_fleet(n_replicas, "inproc", base, config,
                       compile_cache_dir, mesh)
    store = ResultStore(store_dir
                        or tempfile.mkdtemp(prefix="fakepta-gw-loadgen-"))
    gw = Gateway(flt, tenants, store=store, max_inflight=max_inflight)

    stream_name = "gw-loadgen"
    stream_spec = ArraySpec(npsr=4, ntoa=16, tspan_years=3.0, n_red=2,
                            n_dm=2, gwb_ncomp=2)
    span_s = 3.0 * 365.25 * 86400.0
    appended = {"toas": 0, "blocks": 0}
    stop = threading.Event()
    app_errs: list = []

    def _append_block(block_seed, spec_arg=None):
        brng = np.random.default_rng(block_seed)
        t = np.sort(brng.uniform(0.0, 0.9 * span_s, size=(4, 6)), axis=1)
        r = brng.normal(0.0, 1e-7, size=(4, 6))
        req = AppendRequest(stream=stream_name, toas=t, residuals=r,
                            spec=spec_arg)
        while True:
            try:
                gw.serve(req, token=tokens[n_tenants - 1], timeout=300.0)
                appended["toas"] += t.size
                appended["blocks"] += 1
                return
            except GatewayBusy as busy:
                time.sleep(max(busy.retry_after_s, 0.002))

    def _appender():
        k = 0
        while not stop.is_set():
            try:
                _append_block(10_000 + k)
            except Exception as exc:   # noqa: BLE001 — surfaced below:
                # an appender death must refuse the row, never pass as a
                # quiet ingestion gap the TOA-conservation check would
                # blame on the cutover
                app_errs.append(exc)
                return
            k += 1
            time.sleep(0.005)

    cut_info: dict = {}
    try:
        for s in specs:
            for b in warm_buckets:
                flt.serve(dc.replace(reqs[0], spec=s, n=b, seed=0),
                          timeout=600.0)
        _append_block(9_999, spec_arg=stream_spec)   # opens the stream
        gw.reset_stats()
        appender = threading.Thread(target=_appender, daemon=True)
        appender.start()

        cut_idx = int(cutover_at * len(reqs))
        futs: list = []
        throttles = 0
        for i, r in enumerate(reqs):
            if i == cut_idx:
                cut_info = gw.cutover(
                    stream_name,
                    dc.replace(stream_spec, tspan_years=6.0))
            tok = tokens[int(req_tenants[i])]
            while True:
                try:
                    futs.append(gw.submit(r, token=tok))
                    break
                except GatewayBusy as busy:
                    # the per-tenant 429 contract IS the acceptance: a
                    # throttle without an actionable hint refuses the row
                    if busy.retry_after_s <= 0.0 or not busy.tenant:
                        raise RuntimeError(
                            f"gateway 429 without a per-tenant retry "
                            f"hint: tenant={busy.tenant!r} "
                            f"retry_after_s={busy.retry_after_s!r}")
                    throttles += 1
                    time.sleep(busy.retry_after_s)
        results, lost = [], 0
        for f in futs:
            try:
                results.append(f.result(timeout=600.0))
            except Exception as exc:   # noqa: BLE001 — recorded + refused
                flightrec.note("gateway_request_lost",
                               error=repr(exc)[:200])
                results.append(None)
                lost += 1
        if lost:
            raise RuntimeError(f"{lost} admitted request(s) lost — "
                               f"refusing to record the row")

        stop.set()
        appender.join(60.0)
        if appender.is_alive():
            flightrec.note("gateway_loadgen_appender_join_timeout",
                           timeout_s=60.0)
        if app_errs:
            raise RuntimeError(
                f"stream appender died mid-load: {app_errs[0]!r}")
        st = gw.serve(StreamRequest(stream=stream_name),
                      token=tokens[0], timeout=300.0)
        if int(st["n_toas"]) != appended["toas"]:
            raise RuntimeError(
                f"cutover dropped or duplicated appends: stream holds "
                f"{st['n_toas']} TOAs, appender landed "
                f"{appended['toas']} — refusing to record the row")

        # bit-verify EVERY store hit against its own solo run, then pin
        # every sibling response of the same identity to the verified hit
        import jax

        from ..parallel.mesh import make_mesh

        solo_mesh = mesh or make_mesh(jax.devices()[:1])
        sims: dict = {}
        by_ident: dict = {}
        for i, res in enumerate(results):
            by_ident.setdefault(idents[i], []).append(i)
        verified = 0
        for ident, idxs in sorted(by_ident.items()):
            hit_idx = [i for i in idxs
                       if results[i].replica == "gateway-cache"]
            if not hit_idx:
                continue
            i0 = hit_idx[0]
            r, res = reqs[i0], results[i0]
            sh = r.spec.spec_hash()
            if sh not in sims:
                sims[sh] = r.spec.build(
                    mesh=solo_mesh, compile_cache_dir=compile_cache_dir)
            alone = sims[sh].run(res.bucket, chunk=res.bucket,
                                 lanes=[(r.seed, r.n)], pipeline_depth=0,
                                 **r.run_kwargs())
            if not (np.array_equal(alone["curves"][:r.n], res.curves)
                    and np.array_equal(alone["autos"][:r.n], res.autos)):
                raise RuntimeError(
                    f"cache hit for identity {ident} differs from its "
                    f"solo run — refusing to record the row")
            verified += 1
            for j in idxs:
                if j == i0:
                    continue
                if not (np.array_equal(results[j].curves, res.curves)
                        and np.array_equal(results[j].autos, res.autos)):
                    raise RuntimeError(
                        f"responses for identity {ident} disagree across "
                        f"the hit/leader/coalesced paths — refusing to "
                        f"record the row")
                verified += 1

        summ = gw.gateway_summary()
        trows = gw.tenant_summary()
        row = {
            "gw_requests": int(summ["requests"]),
            "gw_tenants": int(n_tenants),
            # the row's hit rate counts BOTH zero-device-work paths: the
            # store and the single-flight fold (bench.py schema)
            "gw_hit_rate": round(
                (summ["hits"] + summ["coalesced"]) / n_requests, 4),
            "gw_coalesced": int(summ["coalesced"]),
            "gw_throttles": int(summ["throttles"]),
            "gw_device_s_saved": float(summ["device_s_saved"]),
            "gw_p99_ms_under_quota": round(
                max((t["p99_ms"] for t in trows.values()), default=0.0),
                3),
            "gw_cutover_ms": float(cut_info.get("cutover_ms", 0.0)),
            "gw_verified": int(verified),
        }
    finally:
        stop.set()
        gw.close()
    return row
