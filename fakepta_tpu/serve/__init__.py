"""fakepta_tpu.serve — warm-pool serving layer + microbatch coalescing.

The request-shaped front door to the ensemble engine (docs/SERVING.md):
many small user requests coalesce into one padded chunk dispatch over a
warm pool of AOT-compiled executables, each request riding its own RNG
lane so responses are bit-identical to a solo ``run(n, seed)`` no matter
how they were batched. Backpressure (:class:`ServeBusy`), per-request
deadlines (:class:`ServeTimeout`), flight-recorder failure notes, and SLO
telemetry (``serve_p50_ms``/``serve_p99_ms``/``serve_qps_per_chip``,
``coalesce_factor``, ``pad_waste_frac``) through ``fakepta_tpu.obs`` are
part of the lane.

Embeddable surface::

    from fakepta_tpu.serve import ArraySpec, ServePool, SimRequest
    pool = ServePool()
    res = pool.serve(SimRequest(spec=ArraySpec(npsr=20), n=32, seed=7))

CLI: ``python -m fakepta_tpu.serve loadgen|stdin|socket`` (the load
generator prints the benchmark row ``bench.py`` records).
"""

from .loadgen import run_loadgen
from .pool import PoolEntry, WarmPool
from .scheduler import ServeConfig, ServePool, ServeResult
from .spec import (DEFAULT_BUCKETS, ArraySpec, InferRequest, OSRequest,
                   ServeBusy, ServeClosed, ServeError, ServeTimeout,
                   SimRequest, curn_grid_spec)

__all__ = [
    "DEFAULT_BUCKETS", "ArraySpec", "InferRequest", "OSRequest",
    "PoolEntry", "ServeBusy", "ServeClosed", "ServeConfig", "ServeError",
    "ServePool", "ServeResult", "ServeTimeout", "SimRequest", "WarmPool",
    "curn_grid_spec", "run_loadgen",
]
