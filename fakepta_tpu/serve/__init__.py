"""fakepta_tpu.serve — warm-pool serving layer + microbatch coalescing.

The request-shaped front door to the ensemble engine (docs/SERVING.md):
many small user requests coalesce into one padded chunk dispatch over a
warm pool of AOT-compiled executables, each request riding its own RNG
lane so responses are bit-identical to a solo ``run(n, seed)`` no matter
how they were batched. Backpressure (:class:`ServeBusy`), per-request
deadlines (:class:`ServeTimeout`), flight-recorder failure notes, and SLO
telemetry (``serve_p50_ms``/``serve_p99_ms``/``serve_qps_per_chip``,
``coalesce_factor``, ``pad_waste_frac``) through ``fakepta_tpu.obs`` are
part of the lane.

Horizontal scale-out (docs/SERVING.md "Fleet"): :class:`ServeFleet` puts
a spec-hash consistent-hash router (:class:`HashRing`) in front of N
replicas — warm-pool affinity per spec shard, saturation spillover,
fleet-wide 429 aggregation, mid-flight failover (bit-identical per RNG
lane), a shared persistent compile cache (replica cold-start = cache
load), and posterior-as-a-service :class:`SamplingSession`\\ s that
migrate between replicas at segment-boundary checkpoints.

Fleet lifecycle (docs/RELIABILITY.md "Fleet lifecycle"): the
:class:`HealthMonitor` heartbeat plane classifies replicas
healthy/suspect/wedged/dead with a circuit breaker so a *wedged* (not
dead) replica is drained before traffic times out into it; elastic
membership (:meth:`ServeFleet.join` / :meth:`ServeFleet.retire` and the
``serve replica --register`` hello/adopt handshake) grows and shrinks
the ring live with shared-cache shard prewarm; and the
:class:`Autoscaler` turns the fleet SLO rollups into a target replica
count with hysteresis + cooldown.

Streaming ingestion (docs/STREAMING.md): :class:`AppendRequest` /
:class:`StreamRequest` feed named :class:`~fakepta_tpu.stream.StreamState`
sessions through the pool's :class:`StreamManager` — O(new-block) appends
with a rolling detection statistic, routed by the fleet with stream
affinity (by stream name, no saturation spillover) to the owning replica.

Embeddable surface::

    from fakepta_tpu.serve import ArraySpec, ServePool, SimRequest
    pool = ServePool()
    res = pool.serve(SimRequest(spec=ArraySpec(npsr=20), n=32, seed=7))

    from fakepta_tpu.serve import LocalReplica, ServeFleet
    fleet = ServeFleet([LocalReplica("r0"), LocalReplica("r1")])
    res = fleet.serve(SimRequest(spec=ArraySpec(npsr=20), n=32, seed=7))

CLI: ``python -m fakepta_tpu.serve loadgen|stdin|socket|replica|fleet``
(the load generator prints the benchmark row ``bench.py`` records; the
fleet command prints the multi-replica row).
"""

from .autoscale import AutoscaleConfig, Autoscaler
from .fleet import (FleetConfig, LocalReplica, ReplicaDead,
                    SampleSessionSpec, SamplingSession, ServeFleet,
                    SocketReplica)
from .health import HealthConfig, HealthMonitor
from .loadgen import (run_elastic_loadgen, run_fleet_loadgen,
                      run_gateway_loadgen, run_loadgen)
from .pool import PoolEntry, WarmPool
from .router import HashRing
from .scheduler import ServeConfig, ServePool, ServeResult
from .spec import (DEFAULT_BUCKETS, AppendRequest, ArraySpec, InferRequest,
                   OSRequest, ServeBusy, ServeClosed, ServeError,
                   ServeTimeout, SimRequest, StreamRequest, curn_grid_spec)
from .streams import StreamManager

__all__ = [
    "DEFAULT_BUCKETS", "AppendRequest", "ArraySpec", "AutoscaleConfig",
    "Autoscaler", "FleetConfig", "HashRing", "HealthConfig",
    "HealthMonitor", "InferRequest", "LocalReplica", "OSRequest",
    "PoolEntry", "ReplicaDead", "SampleSessionSpec", "SamplingSession",
    "ServeBusy", "ServeClosed", "ServeConfig", "ServeError", "ServeFleet",
    "ServePool", "ServeResult", "ServeTimeout", "SimRequest",
    "SocketReplica", "StreamManager", "StreamRequest", "WarmPool",
    "curn_grid_spec", "run_elastic_loadgen", "run_fleet_loadgen",
    "run_gateway_loadgen", "run_loadgen",
]
