"""fakepta_tpu — a TPU-native (JAX/XLA) pulsar-timing-array simulation framework.

Public API mirrors the reference package layout (``fakepta.__init__:1-2`` exposes
``fake_pta`` and ``correlated_noises``): the same module names hold the stateful
user-facing API, while the functional TPU engine lives in ``ops/``, ``models/`` and
``utils/``.
"""

__version__ = "0.1.0"

from . import constants, correlated_noises, ephemeris, fake_pta, spectrum  # noqa: F401
