"""DetectionRun: the host facade over the device optimal-statistic lane.

One object = one null-calibrated detection study: it wraps an
:class:`~fakepta_tpu.parallel.montecarlo.EnsembleSimulator` whose run
carries the OS lane with the paired noise-only stream
(``OSSpec(null=True)``), and reduces the packed lanes to the standard
detection summary — significance, detection rate at 5% false alarm, null
quantiles — without any (R, P, P) fetch. ``save()`` writes a
schema-versioned JSON-lines artifact (``fakepta_tpu.obs`` framing with the
``fakepta_tpu.detect/1`` payload schema) whose summary metrics
``python -m fakepta_tpu.obs compare --fail-on-regression`` diffs like any
engine RunReport.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .operators import DETECT_SCHEMA, OSSpec, as_spec


class DetectionRun:
    """Null-calibrated GWB detection study on the device OS lane.

    Parameters mirror :class:`EnsembleSimulator` (``batch``, ``gwb``,
    ``include``, ``mesh`` and any sampling configs via ``**sim_kwargs``);
    ``os`` is an ORF name / sequence / :class:`OSSpec`. Null calibration is
    forced on — the paired noise-only stream is the study's yardstick; the
    analytic sigma stays in the artifact for comparison.
    """

    def __init__(self, batch, gwb, os="hd", include=("white", "red", "dm",
                                                     "gwb"),
                 mesh=None, **sim_kwargs):
        from ..parallel.montecarlo import EnsembleSimulator

        spec = as_spec(os)
        if not spec.null:
            spec = dataclasses.replace(spec, null=True)
        self.spec: OSSpec = spec
        self.sim = EnsembleSimulator(batch, gwb=gwb, include=include,
                                     mesh=mesh, **sim_kwargs)
        self.last_result = None

    def run(self, nreal: int, seed=0, chunk: int = 1024) -> dict:
        """Run the study; returns the engine output dict plus ``summary``.

        ``out["os"]`` holds the per-ORF statistics (amp2 / snr / null_amp2 /
        p_value, schema ``fakepta_tpu.detect/1``); ``out["summary"]`` the
        flat metric dict the saved artifact exposes to ``obs compare``.
        """
        out = self.sim.run(nreal, seed=seed, chunk=chunk, os=self.spec)
        summary = {}
        for orf in out["os"]["orfs"]:
            s = out["os"]["stats"][orf]
            amp2, null = s["amp2"], s["null_amp2"]
            sigma = max(s["sigma_empirical"], 1e-300)
            q95 = s["null_quantiles"]["q95"]
            summary.update({
                f"os_{orf}_significance_sigma": round(
                    float((amp2.mean() - null.mean()) / sigma), 4),
                f"os_{orf}_detection_rate": round(
                    float((amp2 > q95).mean()), 4),
                f"os_{orf}_amp2_mean": float(amp2.mean()),
                f"os_{orf}_null_amp2_mean": float(null.mean()),
                f"os_{orf}_sigma_empirical": float(sigma),
                f"os_{orf}_sigma_analytic": float(s["sigma_analytic"]),
                f"os_{orf}_null_q95": float(q95),
                f"os_{orf}_p_value_median": float(
                    np.median(s["p_value"])),
            })
        out["summary"] = summary
        self.last_result = out
        return out

    def save(self, path, out=None) -> str:
        """Write the run's summary artifact (JSON-lines, obs framing).

        The file is a loadable :class:`fakepta_tpu.obs.RunReport` whose
        ``summary()`` merges the detection metrics (via the report's
        ``extra_metrics`` meta), so two studies diff with
        ``python -m fakepta_tpu.obs compare old.jsonl new.jsonl``.
        """
        out = out if out is not None else self.last_result
        if out is None:
            raise ValueError("run() the study before saving its artifact")
        report = out["report"]
        report.meta["detect_schema"] = DETECT_SCHEMA
        report.meta["extra_metrics"] = dict(out["summary"])
        return report.save(path)
