"""fakepta_tpu.detect — on-device detection statistics as an engine lane.

The subsystem that turns the engine's "null vs injected" north star into a
first-class workload: the per-realization optimal statistic (amp2, SNR,
sigma) is computed INSIDE the jitted chunk program from the raw pair sums
and packed beside curves/autos, so detection studies never fetch an
(R, P, P) correlation tensor and never disable the fused Pallas path.

Layers (docs/DETECTION.md):

- :mod:`operators` — host-f64 precompute: ORF templates, valid-pair TOA
  counts, noise weighting from the batch's white variances; shared with
  :func:`fakepta_tpu.correlated_noises.optimal_statistic`.
- the device lane — ``EnsembleSimulator.run(os=...)`` (an ORF name, a
  sequence, or an :class:`OSSpec`), including the paired noise-only stream
  for on-device empirical null calibration (``OSSpec(null=True)``).
- :class:`DetectionRun` — the host facade: one call runs a null-calibrated
  detection study and emits a schema-versioned summary artifact that
  ``python -m fakepta_tpu.obs compare`` can diff.
- :class:`StreamingOS` (:mod:`streaming`) — the rolling per-append variant
  over a stream's accumulated Woodbury moments (docs/STREAMING.md).
- CLI: ``python -m fakepta_tpu.detect run ...``.
"""

from .operators import (DETECT_SCHEMA, OSOperator, OSSpec, as_spec,
                        assemble, build_operators, pair_weighting,
                        pulsar_noise_levels)
from .run import DetectionRun
from .streaming import StreamingOS

__all__ = [
    "DETECT_SCHEMA", "DetectionRun", "OSOperator", "OSSpec", "StreamingOS",
    "as_spec", "assemble", "build_operators", "pair_weighting",
    "pulsar_noise_levels",
]
