"""CLI: ``python -m fakepta_tpu.detect run ...``.

Runs a null-calibrated detection study on a synthetic array through the
device OS lane (:class:`~fakepta_tpu.detect.DetectionRun`), prints one JSON
summary line, and optionally saves the schema-versioned artifact that
``python -m fakepta_tpu.obs compare`` diffs. Exit 0 on success, 2 on
usage/configuration errors (mirroring ``fakepta_tpu.analysis`` /
``fakepta_tpu.obs``).
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m fakepta_tpu.detect",
        description="on-device detection statistics (optimal statistic with "
                    "paired null calibration) over synthetic PTA ensembles")
    sub = parser.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="run a null-calibrated detection study")
    run.add_argument("--npsr", type=int, default=40)
    run.add_argument("--ntoa", type=int, default=260)
    run.add_argument("--nreal", type=int, default=2000)
    run.add_argument("--chunk", type=int, default=1000)
    run.add_argument("--log10-A", type=float, default=-14.0,
                     help="injected GWB amplitude (gamma fixed at 13/3)")
    run.add_argument("--orf", nargs="+", default=["hd"],
                     choices=["hd", "monopole", "dipole"],
                     help="ORF template lane(s) to compute")
    run.add_argument("--weighting", choices=["noise", "none"],
                     default="noise")
    run.add_argument("--seed", type=int, default=1)
    run.add_argument("--platform", default=None,
                     help="force a jax platform (e.g. cpu)")
    run.add_argument("--out", default=None,
                     help="save the summary artifact (JSON-lines) here; "
                          "diff two with `python -m fakepta_tpu.obs "
                          "compare`")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import numpy as np

    from .. import spectrum as spectrum_lib
    from ..batch import PulsarBatch
    from ..parallel.mesh import make_mesh
    from ..parallel.montecarlo import GWBConfig
    from .operators import OSSpec
    from .run import DetectionRun

    try:
        batch = PulsarBatch.synthetic(npsr=args.npsr, ntoa=args.ntoa,
                                      tspan_years=15.0, toaerr=1e-7,
                                      n_red=30, n_dm=30, seed=0)
        f = np.arange(1, 31) / float(batch.tspan_common)
        psd = np.asarray(spectrum_lib.powerlaw(f, log10_A=args.log10_A,
                                               gamma=13 / 3))
        study = DetectionRun(
            batch, gwb=GWBConfig(psd=psd, orf="hd"),
            os=OSSpec(orf=tuple(args.orf), weighting=args.weighting,
                      null=True),
            mesh=make_mesh(jax.devices()))
        out = study.run(args.nreal, seed=args.seed, chunk=args.chunk)
    except (ValueError, TypeError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    row = {"npsr": args.npsr, "nreal": args.nreal,
           "log10_A": args.log10_A, "orfs": list(args.orf),
           "weighting": args.weighting, **out["summary"]}
    if args.out:
        row["artifact"] = study.save(args.out)
    print(json.dumps(row))
    return 0


if __name__ == "__main__":                               # pragma: no cover
    sys.exit(main())
