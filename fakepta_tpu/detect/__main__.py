"""``python -m fakepta_tpu.detect`` entry point."""

import sys

from .cli import main

sys.exit(main())
