"""Rolling on-device optimal-statistic tracker for streaming ingestion.

The batch OS lane (:mod:`fakepta_tpu.detect`) cross-correlates engine
realizations inside the chunk program; a *stream* has exactly one
realization — the sky — but its data grows, and the question "is the CURN
process showing cross-correlations yet?" should be answerable after every
append without restaging anything. :class:`StreamingOS` answers it from
the stream's accumulated Woodbury moments alone:

- per pulsar, the conditional-mean GP coefficients at a pinned reference
  theta, ``b_a = Sigma_a^{-1} dT_a`` (one Cholesky solve — the same Wiener
  filter as :func:`fakepta_tpu.ops.woodbury.conditional_mean`), restricted
  to the CURN basis columns;
- pair correlation ``rho_ab = c_a . c_b`` with variance
  ``v_ab = sum_k (Sigma_a^{-1})_kk (Sigma_b^{-1})_kk`` over the same
  columns (the diagonal via one triangular inverse, the
  ``lnlike_and_grad_phi`` pattern);
- the ORF-matched filter ``X = sum_pairs gam_ab rho_ab / v_ab`` with
  normalization ``sum_pairs gam_ab^2 / v_ab`` — ``amp2 = X / norm`` is the
  OS amplitude estimate and ``snr = X / sqrt(norm)`` its significance in
  sigma units.

Everything is one jitted program over ``(M, dT)``; the moment shapes never
change (they are capacity-independent), so the tracker compiles ONCE per
stream and each refresh is a single device dispatch. Crossings of the
significance threshold are obs-gated: flight-recorded
(``stream_detection``) and counted (``stream.detections``) on the upward
crossing, never spammed per append.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.linalg import cho_solve, cholesky, solve_triangular

from .. import obs
from ..ops import gwb as gwb_ops
from ..ops.woodbury import _phi_floor
from ..utils.compat import enable_x64


class StreamingOS:
    """Per-append detection-statistic tracker over stream moments.

    ``compiled`` is the stream's :class:`~fakepta_tpu.infer.model
    .CompiledLikelihood` (must contain exactly ONE CURN component — the
    statistic is a cross-correlation of that process's coefficients);
    ``batch_views`` the namespace ``compiled.phi`` reads (the stream's
    frozen template views); ``pos`` the (P, 3) sky positions; ``orf`` an
    ORF template name (:data:`fakepta_tpu.detect.KNOWN_ORFS`, 'curn'
    excluded for the same reason as the batch lane: no cross-correlation
    signal to match). ``theta_ref`` pins the noise model the filter
    whitens against (default: the compiled model's box midpoint).
    """

    def __init__(self, compiled, batch_views, pos, orf: str = "hd",
                 theta_ref=None, threshold_sigma: float = 3.0):
        curn = [(s, e) for (t, s, e) in compiled.column_slices()
                if t == "curn"]
        if len(curn) != 1:
            raise ValueError(f"StreamingOS needs exactly one 'curn' "
                             f"component in the model, found {len(curn)}")
        self._lo, self._hi = curn[0]
        self.orf = str(orf)
        if self.orf == "curn":
            raise ValueError("'curn' has no cross-correlation signature; "
                             "pick 'hd', 'monopole' or 'dipole'")
        self.threshold_sigma = float(threshold_sigma)
        pos = np.asarray(pos, dtype=np.float64)
        npsr = pos.shape[0]
        if npsr < 2:
            raise ValueError("the optimal statistic needs >= 2 pulsars")
        orfs = np.asarray(gwb_ops.build_orf(self.orf, pos))
        a, b = np.triu_indices(npsr, k=1)
        self._a, self._b = a, b
        self._gam = orfs[a, b]
        if not np.any(self._gam != 0.0):
            raise ValueError(f"ORF {self.orf!r} is zero on every pulsar "
                             f"pair for these positions")
        if theta_ref is None:
            theta_ref = compiled.theta_from_unit(np.full(compiled.D, 0.5))
        self.theta_ref = np.asarray(theta_ref, dtype=np.float64)
        self._compiled = compiled
        self._views = batch_views
        self._phi = None
        self._stat = None
        self.count = 0
        self.last: Optional[dict] = None
        self._above = False

    def _ctx(self, dtype):
        return (enable_x64() if np.dtype(dtype).itemsize == 8
                else contextlib.nullcontext())

    def _stat_fn(self, dtype):
        if self._stat is not None:
            return self._stat
        lo, hi = self._lo, self._hi
        a_idx = jnp.asarray(self._a)
        b_idx = jnp.asarray(self._b)
        gam = jnp.asarray(self._gam, dtype)
        ncols = self._compiled.ncols
        eye = jnp.eye(ncols, dtype=dtype)

        def per_pulsar(m, dt_, ph):
            ph = jnp.maximum(ph, _phi_floor(ph.dtype))
            sigma = m + jnp.diag(1.0 / ph)
            low = cholesky(sigma, lower=True)
            coeff = cho_solve((low, True), dt_)
            linv = solve_triangular(low, eye, lower=True)
            sdiag = jnp.sum(linv * linv, axis=0)
            return coeff[lo:hi], sdiag[lo:hi]

        def stat(m, dt_, ph):
            coeff, sdiag = jax.vmap(per_pulsar)(m, dt_, ph)
            rho = jnp.sum(coeff[a_idx] * coeff[b_idx], axis=1)
            var = jnp.sum(sdiag[a_idx] * sdiag[b_idx], axis=1)
            num = jnp.sum(gam * rho / var)
            den = jnp.sum(gam * gam / var)
            return num / den, num / jnp.sqrt(den)

        self._stat = jax.jit(stat)
        return self._stat

    def update(self, moments) -> dict:
        """Refresh the statistic from finished stream moments
        ``(M, lndetN, n_valid, d0, dT)``; returns (and keeps as ``last``)
        ``{"amp2", "snr", "significance_sigma"}``."""
        m, _, _, _, dt_ = moments
        dtype = m.dtype
        with self._ctx(dtype):
            if self._phi is None:
                self._phi = self._compiled.phi(
                    jnp.asarray(self.theta_ref, dtype), self._views)
            amp2, snr = self._stat_fn(dtype)(m, dt_, self._phi)
            amp2, snr = float(amp2), float(snr)
        self.count += 1
        out = {"amp2": amp2, "snr": snr, "significance_sigma": snr}
        self.last = out
        above = snr >= self.threshold_sigma
        if above and not self._above:
            obs.count("stream.detections")
            obs.flightrec.note("stream_detection", orf=self.orf,
                               snr=round(snr, 3), amp2=amp2,
                               update=self.count)
        self._above = above
        return out
