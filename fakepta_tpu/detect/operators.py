"""Host-float64 precompute for the device optimal-statistic (OS) lane.

The noise-weighted optimal statistic is, per realization ``r`` with
pair-correlation matrix ``rho_ab = S_ab / counts_ab`` (``S`` the raw pair
sums the engine's one collective produces),

    amp2_r = sum_{a<b} rho_ab Gamma_ab / Var_ab  /  sum_{a<b} Gamma_ab^2 / Var_ab
    Var_ab = sigma2_a sigma2_b / counts_ab

i.e. exactly :func:`fakepta_tpu.correlated_noises.optimal_statistic` — which
shares :func:`pair_weighting` below so the two cannot drift. The key
algebraic fact this module packages: substituting ``rho = S / counts`` makes
the per-pair counts cancel, so the whole statistic is ONE static (P, P)
weight matrix contracted against the raw pair sums,

    amp2_r = sum_ab S_ab W_ab,     W_ab = Gamma_ab / (sigma2_a sigma2_b) / (2 denom)

— the same shape as the engine's angular-binning/auto weights. That is what
lets the OS ride the packed statistic lanes (``pack_stats``) beside
curves/autos, with no (R, P, P) tensor ever leaving the device
(docs/DETECTION.md).

Everything here is one-off host staging at float64 (ORF closed forms, count
matrices, weight normalizations — the same sanctioned precision layer as the
ORF Cholesky); the contraction itself runs on device at the batch dtype.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple, Union

import numpy as np

from ..ops import gwb as gwb_ops

#: schema tag for detection-run artifacts (summary dicts, saved JSON-lines)
DETECT_SCHEMA = "fakepta_tpu.detect/1"

#: ORF templates the OS lane accepts. 'curn' is deliberately rejected at
#: operator build time — it is diagonal, so the cross-correlation statistic
#: is undefined for it (the host ``optimal_statistic`` raises identically).
KNOWN_ORFS = ("hd", "monopole", "dipole", "curn", "anisotropic")

#: null-ensemble quantiles recorded per run (per-mille precision needs more
#: realizations than a typical run carries; these four are the standard
#: detection thresholds)
NULL_QUANTILES = (0.5, 0.9, 0.95, 0.99)


@dataclasses.dataclass(frozen=True)
class OSSpec:
    """Configuration of the device OS lane (``EnsembleSimulator.run(os=...)``).

    ``orf`` names one or several ORF templates ('hd', 'monopole', 'dipole';
    'anisotropic' additionally needs ``h_map``); each gets its own packed
    lane. ``weighting`` is ``'noise'`` (per-pulsar white-noise variance from
    the batch + valid-pair TOA counts — the standard inverse-variance OS) or
    ``'none'`` (uniform weights: the plain ORF-matched filter). ``sigma2``
    optionally overrides the per-pulsar noise levels (a (P,) array, e.g. an
    ensemble-measured diagonal). ``null=True`` additionally runs a paired
    noise-only stream inside the same device program (keys derived per
    realization with the engine's 0xD7 domain tag) and packs its OS values as
    extra lanes — the on-device empirical null calibration: per-run null
    quantiles, empirical sigma, and per-realization detection p-values.
    """

    orf: Union[str, Sequence[str]] = "hd"
    weighting: str = "noise"
    null: bool = False
    sigma2: Optional[np.ndarray] = None
    h_map: Optional[np.ndarray] = None

    @property
    def orfs(self) -> Tuple[str, ...]:
        names = ((self.orf,) if isinstance(self.orf, str)
                 else tuple(self.orf))
        return names


def as_spec(os) -> OSSpec:
    """Coerce a run's ``os=`` argument (str | sequence | OSSpec) to OSSpec."""
    if isinstance(os, OSSpec):
        spec = os
    elif isinstance(os, str):
        spec = OSSpec(orf=os)
    elif isinstance(os, (list, tuple)):
        spec = OSSpec(orf=tuple(os))
    else:
        raise TypeError(f"os must be an ORF name, a sequence of ORF names or "
                        f"an OSSpec, got {type(os).__name__}")
    if spec.weighting not in ("noise", "none"):
        raise ValueError(f"OSSpec.weighting must be 'noise' or 'none', got "
                         f"{spec.weighting!r}")
    if not spec.orfs:
        raise ValueError("OSSpec needs at least one ORF template")
    for name in spec.orfs:
        if name not in KNOWN_ORFS:
            raise ValueError(f"unknown ORF template {name!r}; known: "
                             f"{KNOWN_ORFS}")
    return spec


def pulsar_noise_levels(sigma2, mask) -> np.ndarray:
    """(P,) mean white-noise variance over each pulsar's valid TOAs.

    The per-pulsar noise autocorrelation level entering ``Var_ab`` — computed
    from the batch's per-TOA variances at host f64 (padding TOAs excluded).
    """
    sigma2 = np.asarray(sigma2, dtype=np.float64)
    mask = np.asarray(mask, dtype=np.float64)
    n = np.maximum(mask.sum(axis=1), 1.0)
    return (sigma2 * mask).sum(axis=1) / n


def pair_weighting(orfs, sigma2, counts):
    """Strict-upper-triangle OS weighting pieces, shared with the host path.

    Returns ``(a, b, gam, inv_var, denom)``: pair indices, ORF template
    values, inverse pair variances ``counts_ab / (sigma2_a sigma2_b)`` and
    the normalization ``denom = sum gam^2 inv_var``. This is the single
    source of truth for the weighting — both
    :func:`fakepta_tpu.correlated_noises.optimal_statistic` and the device
    lane's :func:`build_operators` call it.
    """
    orfs = np.asarray(orfs, dtype=np.float64)
    npsr = orfs.shape[0]
    a, b = np.triu_indices(npsr, 1)
    gam = orfs[a, b]
    sigma2 = np.asarray(sigma2, dtype=np.float64)
    counts = np.asarray(counts, dtype=np.float64)
    inv_var = counts[a, b] / (sigma2[a] * sigma2[b])
    denom = float((gam ** 2 * inv_var).sum())
    return a, b, gam, inv_var, denom


@dataclasses.dataclass(frozen=True)
class OSOperator:
    """One ORF's precomputed OS contraction.

    ``weights`` is the (P, P) float64 matrix whose contraction against a
    realization's RAW pair-sum matrix yields ``amp2`` directly (counts and
    normalization folded in); ``sigma`` the analytic null standard deviation
    ``denom**-0.5`` of ``amp2`` under independent white noise.
    """

    orf: str
    weights: np.ndarray
    sigma: float
    denom: float

    def apply(self, corr_raw) -> np.ndarray:
        """Host reference contraction: (R,) amp2 from raw pair sums."""
        corr_raw = np.asarray(corr_raw, dtype=np.float64)
        if corr_raw.ndim == 2:
            corr_raw = corr_raw[None]
        return np.einsum("rpq,pq->r", corr_raw, self.weights)


def build_operators(spec: OSSpec, pos, mask, sigma2_toa,
                    pair_counts=None) -> Tuple[OSOperator, ...]:
    """Host-f64 OS operators for every ORF in ``spec``.

    ``pos`` (P, 3) unit vectors, ``mask`` (P, T) validity, ``sigma2_toa``
    (P, T) per-TOA white variances (only read under ``weighting='noise'``
    with no ``spec.sigma2`` override). ``pair_counts`` defaults to
    ``mask @ mask.T``.
    """
    pos = np.asarray(pos, dtype=np.float64)
    mask_f = np.asarray(mask, dtype=np.float64)
    counts = (mask_f @ mask_f.T if pair_counts is None
              else np.asarray(pair_counts, dtype=np.float64))
    npsr = pos.shape[0]
    if spec.weighting == "noise":
        if spec.sigma2 is not None:
            sigma2 = np.asarray(spec.sigma2, dtype=np.float64).reshape(npsr)
        else:
            sigma2 = pulsar_noise_levels(sigma2_toa, mask)
    else:
        sigma2 = np.ones(npsr)

    ops = []
    for name in spec.orfs:
        orfs = np.asarray(gwb_ops.build_orf(name, pos, spec.h_map))
        if spec.weighting == "noise":
            a, b, gam, inv_var, denom = pair_weighting(orfs, sigma2, counts)
            if denom <= 0.0:
                raise ValueError(
                    f"ORF {name!r} has no weighted cross-correlation signal "
                    f"(e.g. 'curn' is diagonal, or no pulsar pair shares "
                    f"TOAs) — the optimal statistic is undefined for it")
            # rho = S / counts makes counts cancel against inv_var: the raw
            # pair sums contract directly (module docstring)
            w_pair = gam / (sigma2[a] * sigma2[b]) / (2.0 * denom)
        else:
            a, b, gam, _, denom = pair_weighting(orfs, sigma2,
                                                 np.ones((npsr, npsr)))
            if denom <= 0.0:
                raise ValueError(
                    f"ORF {name!r} has no cross-correlation signal (e.g. "
                    f"'curn' is diagonal) — the matched filter is undefined "
                    f"for it")
            # unweighted statistic averages rho, so the raw sums divide by
            # their pair counts (clamped: a zero-count pair's S is exactly 0)
            w_pair = gam / np.maximum(counts[a, b], 1.0) / (2.0 * denom)
        weights = np.zeros((npsr, npsr))
        weights[a, b] = w_pair
        weights[b, a] = w_pair
        ops.append(OSOperator(orf=name, weights=weights,
                              sigma=denom ** -0.5, denom=denom))
    return tuple(ops)


def assemble(spec: OSSpec, ops: Sequence[OSOperator], os_vals,
             null_vals=None) -> dict:
    """Per-ORF detection statistics from the packed OS lanes.

    ``os_vals`` (R, K) device amp2 lanes in operator order; ``null_vals``
    the paired noise-only lanes when ``spec.null``. Returns the schema-
    versioned result dict attached as ``out["os"]``: per ORF ``amp2``,
    ``sigma`` (empirical when a null stream ran, else analytic), ``snr``,
    and under null calibration the ``null_amp2`` sample, its quantiles and
    per-realization p-values ``(1 + #{null >= amp2}) / (N + 1)``.
    """
    os_vals = np.asarray(os_vals, dtype=np.float64)
    stats = {}
    for k, op in enumerate(ops):
        amp2 = os_vals[:, k]
        entry = {"amp2": amp2, "sigma_analytic": op.sigma}
        if null_vals is not None:
            null = np.asarray(null_vals[:, k], dtype=np.float64)
            sigma = float(np.std(null, ddof=1)) if null.size >= 2 else op.sigma
            qs = np.quantile(null, NULL_QUANTILES)
            # one-sided empirical p-value with the standard +1 regularization
            # (a p of exactly 0 is never claimable from a finite null sample)
            rank = np.searchsorted(np.sort(null), amp2, side="left")
            pval = (1.0 + null.size - rank) / (null.size + 1.0)
            entry.update({
                "null_amp2": null,
                "sigma_empirical": sigma,
                "null_quantiles": {f"q{int(100 * q)}": float(v)
                                   for q, v in zip(NULL_QUANTILES, qs)},
                "p_value": pval,
            })
        else:
            sigma = op.sigma
        entry["sigma"] = sigma
        entry["snr"] = amp2 / sigma
        stats[op.orf] = entry
    return {
        "schema": DETECT_SCHEMA,
        "weighting": spec.weighting,
        "orfs": [op.orf for op in ops],
        "null": null_vals is not None,
        "stats": stats,
    }
