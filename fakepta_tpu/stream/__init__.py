"""fakepta_tpu.stream — append-TOA ingestion: O(new-epoch), not O(restage).

Everything else in the engine is batch over a frozen dataset; real PTAs
accrete TOAs for decades, and an always-on served product (ROADMAP item 5)
should never pay a full restage when one epoch of data arrives. The
per-pulsar Woodbury moments (``T^T N^-1 T``, ``T^T N^-1 r``,
``r^T N^-1 r``, ``ln det N``) are plain sums over TOAs, so new data is a
rank-k *additive* update (:func:`fakepta_tpu.ops.woodbury.append_parts`)
plus an ECORR epoch-block extension — provided the Fourier grid is FROZEN
(docs/STREAMING.md has the algebra and the one trap: a grid that rescaled
with Tspan would silently change every old basis value).

Layers:

- :class:`StreamState` (:mod:`state`) — the per-pulsar container: pinned
  frequency grids from a template batch, accumulated device moments,
  bucketed append kernels that ride a serve-style ladder so shape churn
  never recompiles, a full-restage oracle path, and an atomic
  :class:`StreamCheckpoint` (torn appends roll back to the last consistent
  state; chaos site ``ingest.append``).
- :class:`~fakepta_tpu.detect.streaming.StreamingOS` — the rolling
  on-device detection statistic, refreshed from the stream's moments after
  every append with obs-gated significance tracking.
- :class:`PosteriorRefresher` (:mod:`refresh`) — continuous posterior
  refresh: each data arrival warm-starts a new
  :class:`~fakepta_tpu.sample.SamplingRun` from the previous posterior's
  Laplace mode and final chain state, and promotes the new posterior only
  through an R-hat gate. :class:`RefreshPolicy` +
  :meth:`~PosteriorRefresher.maybe_refresh` schedule the cycles (refresh
  on accumulated appends or rolling-|SNR| movement, never per-append).
- :class:`FactorizedRefresher` (:mod:`refresh`) — the per-frequency
  incremental variant for per-bin free-spectrum streams (ROADMAP item 4):
  bin-block lanes built once, each refresh slices the stream's current
  moments per lane and re-samples ONLY the lanes whose ``dT`` projection
  moved — O(bins-touched) per appended block, zero steady-state
  recompiles, same R-hat promotion gate.
- the served surface — ``AppendRequest``/``StreamRequest``
  (:mod:`fakepta_tpu.serve.spec`), executed by the pool's
  :class:`~fakepta_tpu.serve.streams.StreamManager` and routed by the
  fleet with stream affinity to the owning replica.
"""

from .refresh import FactorizedRefresher, PosteriorRefresher, RefreshPolicy
from .state import (STREAM_SCHEMA, StreamCheckpoint, StreamState,
                    default_stream_model)

__all__ = ["STREAM_SCHEMA", "FactorizedRefresher", "PosteriorRefresher",
           "RefreshPolicy", "StreamCheckpoint", "StreamState",
           "default_stream_model"]
