"""The streaming lane's A/B recipe: incremental append vs full restage.

Shared by ``bench.py`` and ``benchmarks/suite.py`` (config 14) the way the
serve lanes share ``run_loadgen``: one function stages a stream with bulk
history, then measures a single-epoch append against a full restage of the
same accumulated store on the SAME kernels (``restage`` deliberately
reuses the append executable at the store's capacity rung, so the A/B is
pure O(new-epoch)-vs-O(history) work, not a compiler difference). Timing
rides the obs clock (:func:`fakepta_tpu.obs.now` — the same clock behind
every recorded latency in the repo); the first append at each rung and the
first restage are warmup (they pay the compile), the recorded figures are
best-of-``repeats`` steady state.

Row metrics (``obs compare``/``gate`` directions in ``obs/report.py``):
``append_latency_ms`` (lower-better), ``restage_ms`` (the baseline side),
``append_speedup_x`` = restage/append (higher-better; the acceptance is
>= 5x at the flagship config), ``stream_rebuckets`` (a shape fact) and
``stream_recompiles`` (zero-expected canary — any retrace means the
bucket ladder stopped covering the traffic).
"""

from __future__ import annotations

import numpy as np

from .. import obs
from ..batch import PulsarBatch
from .state import StreamState, default_stream_model


def run_append_ab(*, npsr: int = 16, ntoa: int = 260,
                  tspan_years: float = 15.0, n_red: int = 10,
                  n_dm: int = 10, nbin: int = 10, history: int = 512,
                  epoch_width: int = 8, ecorr_dt=None, mesh=None,
                  repeats: int = 3, seed: int = 0) -> dict:
    """Stage ``history`` TOAs/pulsar of bulk history, then A/B one
    ``epoch_width``-TOA append against a full restage. Returns the bench
    row fragment (module docstring)."""
    import jax

    from .. import constants as const
    from ..utils.compat import enable_x64

    with enable_x64():
        template = PulsarBatch.synthetic(npsr=npsr, ntoa=ntoa,
                                         tspan_years=tspan_years,
                                         n_red=n_red, n_dm=n_dm, seed=seed,
                                         dtype=jax.numpy.float64)
        stream = StreamState(template, default_stream_model(nbin=nbin),
                             ecorr_dt=ecorr_dt, mesh=mesh)
    rng = np.random.default_rng(seed + 1)
    tspan = tspan_years * const.yr

    def block(lo, hi, width):
        t = np.sort(rng.uniform(lo * tspan, hi * tspan, (npsr, width)),
                    axis=1)
        kw = {}
        if ecorr_dt is not None:
            kw["ecorr_amp"] = np.abs(rng.normal(3e-7, 1e-7,
                                                (npsr, width)))
        return (t, rng.normal(0.0, 1e-7, (npsr, width))), kw

    # bulk history in two blocks (exercises a mid-stream epoch extension),
    # then one warmup epoch append that compiles the steady-state kernel
    # at the final (block bucket, epoch capacity) pair
    half = history // 2
    for lo, hi, width in ((0.0, 0.45, half), (0.45, 0.9, history - half)):
        (t, r), kw = block(lo, hi, width)
        stream.append(t, r, **kw)
    (t, r), kw = block(0.90, 0.97, epoch_width)
    stream.append(t, r, **kw)

    append_ms = float("inf")
    for k in range(repeats):
        (t, r), kw = block(0.97, 1.0, epoch_width)
        append_ms = min(append_ms, stream.append(t, r, **kw)["latency_ms"])

    stream.restage()                       # warmup: the restage compile
    restage_ms = float("inf")
    for _ in range(repeats):
        t0 = obs.now()
        stream.restage()
        restage_ms = min(restage_ms, (obs.now() - t0) * 1e3)
    restage_ms = round(restage_ms, 3)

    return {
        "append_latency_ms": append_ms,
        "restage_ms": restage_ms,
        "append_speedup_x": round(restage_ms / max(append_ms, 1e-9), 2),
        "stream_appends": int(stream.appends),
        "stream_toas": int(stream._n.sum()),
        "stream_rebuckets": int(stream.rebuckets),
        "stream_recompiles": int(stream.recompiles),
    }
