"""Continuous posterior refresh: re-sample on data arrival, warm-started.

A streaming PTA wants a CURRENT posterior, not a nightly batch job. Each
refresh builds a fresh :class:`~fakepta_tpu.sample.SamplingRun` over the
stream's accumulated data (``batch_view``/``residuals_view`` — the frozen
grids, so the model is the SAME model the moments live on) and recycles
two things from the previous posterior instead of starting cold:

- ``warm_from``: the previous Laplace mode seeds the damped-Newton fit.
  With one epoch of new data the mode barely moves, so the fit converges
  in a handful of iterations instead of tens (``laplace_iters`` is
  surfaced per refresh precisely so the win is measurable).
- ``init_z``: the previous chains' final whitened positions, REMAPPED into
  the new run's whitened frame. Chains sample ``v = mode + z C^T`` (C
  upper-triangular, ``C C^T = (-H)^{-1}``); keeping the *physical*
  positions fixed across the frame change solves
  ``mode_old + z_old C_old^T = mode_new + z_new C_new^T`` for ``z_new`` —
  a host-f64 triangular solve. Cached in-chain likelihood parts are NOT
  recycled (the data changed); the sampler's snapshot refresh recomputes
  them against the new moments on the first step.

Promotion is R-hat gated: the refreshed posterior replaces ``posterior``
only when ``rhat_max <= rhat_gate``; a non-converged refresh is kept out
(flight-recorded ``stream_refresh_reject``) while the warm state still
advances — the Laplace mode is a deterministic fit, valid regardless of
chain convergence.

Scheduling (ROADMAP item 5): refreshing after every append wastes chains
on a posterior that barely moved. :class:`RefreshPolicy` decides when a
refresh is DUE — after ``every_appends`` appended blocks since the last
refresh, or earlier when the stream's rolling ``|SNR|`` moved by at least
``min_snr_gain`` (data arriving that *changes the answer* should not wait
out the epoch counter). :meth:`PosteriorRefresher.maybe_refresh` applies
the policy: not-due calls are counted (``stream.refresh_skips``) and
flight-recorded, never sampled.

Per-frequency incremental refresh (ROADMAP item 4):
:class:`FactorizedRefresher` is the factorized counterpart for per-bin
free-spectrum streams. Its bin-block lanes
(:func:`~fakepta_tpu.sample.factor_plan`) are built ONCE against the
stream's frozen grids; each refresh slices the stream's CURRENT
accumulated Woodbury moments per lane
(``restrict_moments`` — O(ncols^2), never an O(history) restage) and
re-samples ONLY the lanes whose data projection actually moved: an
appended block perturbs ``dT`` only in the bins it touches, so the
refresh cost is O(bins-touched), not O(nbin). Untouched lanes keep their
previous draws — their conditional posterior did not change. Promotion
stays R-hat gated (over the lanes that ran), and steady-state refreshes
retrace nothing: lane programs take moments as ARGUMENTS.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from .. import obs
from ..infer import model as infer_model
from ..sample import SampleSpec, SamplingRun, as_spec
from ..sample.factorized import (_restrict_np, factor_plan, lane_seed,
                                 marginalize_nuisance_np, nuisance_phi_np,
                                 recombine_draws)
from ..tune import defaults as knobs
from .state import STREAM_SCHEMA


@dataclasses.dataclass(frozen=True)
class RefreshPolicy:
    """When is a posterior refresh due? (defaults from ``tune/defaults.py``)

    - ``every_appends``: refresh after this many appended TOA blocks since
      the last refresh (the epoch-count trigger; always active).
    - ``min_snr_gain``: refresh as soon as the stream's rolling detection
      statistic moved this much in ``|SNR|`` since the last refresh
      (0 disables; streams without a ``watch`` statistic never trip it).
    """

    every_appends: int = knobs.REFRESH_EVERY_APPENDS
    min_snr_gain: float = knobs.REFRESH_MIN_SNR_GAIN


class PosteriorRefresher:
    """Warm-started, R-hat-gated posterior refresh loop over a stream.

    ``spec`` is a :class:`~fakepta_tpu.sample.SampleSpec` (or None for the
    stream's model with SampleSpec defaults); its model must BE the
    stream's model — the posterior must describe the same process the
    stream accumulates moments for.
    """

    def __init__(self, stream, spec=None, *, rhat_gate: float = 1.05,
                 mesh=None, compile_cache_dir=None,
                 policy: Optional[RefreshPolicy] = None):
        self.stream = stream
        self.spec = (SampleSpec(model=stream.model) if spec is None
                     else as_spec(spec))
        if self.spec.model != stream.model:
            raise ValueError("PosteriorRefresher spec.model must be the "
                             "stream's model (same basis, same moments)")
        self.rhat_gate = float(rhat_gate)
        self.mesh = mesh
        self.compile_cache_dir = compile_cache_dir
        self.policy = policy or RefreshPolicy()
        self.posterior: Optional[dict] = None
        self.refreshes = 0
        self.promotions = 0
        self.skips = 0
        self._warm: Optional[dict] = None
        self._last_z: Optional[np.ndarray] = None
        # scheduling baselines: appends/SNR as of the last refresh (the
        # construction point counts as "refreshed" — maybe_refresh measures
        # accumulation, not absolute stream age)
        self._mark_appends = int(getattr(stream, "appends", 0))
        self._mark_snr = self._current_snr()

    def _current_snr(self) -> Optional[float]:
        """The stream's rolling |SNR|, or None without a watch statistic."""
        snr = self.stream.stats().get("snr")
        return None if snr is None else abs(float(snr))

    @staticmethod
    def _remap_z(z_prev, prev, new) -> np.ndarray:
        """Whitened positions from the previous frame re-expressed in the
        new one, holding the physical positions fixed (module docstring)."""
        k, t, d = z_prev.shape
        v = (np.asarray(prev["mode_v"])[None, None, :]
             + np.asarray(z_prev, dtype=np.float64)
             @ np.asarray(prev["chol_cov"]).T)
        delta = (v - np.asarray(new["mode_v"])[None, None, :])
        z_new = np.linalg.solve(np.asarray(new["chol_cov"]).T,
                                delta.reshape(-1, d).T).T
        return z_new.reshape(k, t, d)

    def refresh(self, n_steps: int = 200, seed: int = 0, **run_kwargs
                ) -> dict:
        """One refresh cycle: Laplace re-fit (warm), chains (warm),
        R-hat-gated promotion. Returns the cycle's stats dict; the
        promoted posterior (when the gate passes) is ``self.posterior``.
        """
        t0 = obs.now()
        warm = self._warm
        run = SamplingRun(self.stream.batch_view(), self.spec,
                          residuals=self.stream.residuals_view(),
                          mesh=self.mesh,
                          compile_cache_dir=self.compile_cache_dir,
                          warm_from=warm)
        init_z = None
        if self._last_z is not None and warm is not None:
            init_z = self._remap_z(self._last_z, warm, run.laplace_state())
        result = run.run(int(n_steps), seed=seed, init_z=init_z,
                         **run_kwargs)
        rhat = float(result["summary"].get("rhat_max", float("nan")))
        promoted = bool(np.isfinite(rhat) and rhat <= self.rhat_gate)
        cycle = self.refreshes
        self.refreshes += 1
        if promoted:
            self.posterior = result
            self.promotions += 1
            obs.count("stream.promotions")
        else:
            obs.flightrec.note("stream_refresh_reject", refresh=cycle,
                               rhat_max=rhat, gate=self.rhat_gate)
        self._warm = run.laplace_state()
        self._last_z = run.last_z
        self._mark_appends = int(getattr(self.stream, "appends", 0))
        self._mark_snr = self._current_snr()
        obs.count("stream.refreshes")
        info = {
            "schema": STREAM_SCHEMA, "refresh": cycle,
            "rhat_max": rhat, "promoted": promoted,
            "warm_started": warm is not None,
            "chains_warm_started": init_z is not None,
            "laplace_iters": int(run.laplace_iters),
            "n_steps": int(n_steps),
            "n_toas": int(self.stream._n.sum()),
            "latency_ms": round((obs.now() - t0) * 1e3, 3),
        }
        return info

    def maybe_refresh(self, n_steps: int = 200, seed: int = 0, **run_kwargs
                      ) -> dict:
        """Refresh only when the :class:`RefreshPolicy` says one is due.

        Due → delegates to :meth:`refresh` (the returned info dict gains a
        ``trigger`` key: ``"appends"`` or ``"snr"``). Not due → no chains
        run; the skip is counted (``stream.refresh_skips``) and
        flight-recorded, and a ``{"skipped": True, ...}`` dict reports how
        far each trigger has accumulated.
        """
        pol = self.policy
        since = int(getattr(self.stream, "appends", 0)) - self._mark_appends
        snr = self._current_snr()
        gain = (abs(snr - self._mark_snr)
                if snr is not None and self._mark_snr is not None
                else (snr if snr is not None else 0.0))
        due_appends = since >= int(pol.every_appends)
        due_snr = pol.min_snr_gain > 0 and gain >= pol.min_snr_gain
        if not (due_appends or due_snr):
            self.skips += 1
            obs.count("stream.refresh_skips")
            # the telemetry plane watches the gate decision stream: holds
            # vs opens are how `obs top` shows whether refresh work is
            # keeping pace with arrivals (docs/OBSERVABILITY.md)
            obs.count("stream.refresh_gate_holds")
            obs.telemetry.publish("stream.refresh_gate_holds",
                                  int(self.skips))
            obs.flightrec.note("stream_refresh_skip", appends_since=since,
                               snr_gain=round(float(gain), 6))
            return {"schema": STREAM_SCHEMA, "skipped": True,
                    "appends_since": since, "snr_gain": float(gain)}
        obs.count("stream.refresh_gate_opens")
        obs.telemetry.publish("stream.refresh_gate_opens",
                              int(self.refreshes) + 1)
        info = self.refresh(n_steps, seed=seed, **run_kwargs)
        info["trigger"] = "appends" if due_appends else "snr"
        info["skipped"] = False
        return info


class FactorizedRefresher:
    """O(bins-touched) incremental posterior refresh for per-bin
    free-spectrum streams (module docstring; docs/SAMPLING.md).

    Requires the stream's model to be exactly factorizable by
    :func:`~fakepta_tpu.sample.factor_plan` (one ``per_bin`` free
    component; batch-pinned nuisances ride along). Lanes and their jitted
    programs are built on the FIRST refresh and reused forever — later
    refreshes only inject freshly restricted moments
    (:meth:`~fakepta_tpu.sample.SamplingRun.restage`), so the steady
    state compiles nothing.

    ``touch_tol`` is the relative ``dT`` movement (Frobenius, over the
    lane's own quadrature columns) above which a lane's conditional
    posterior is considered moved; defaults to ``tune/defaults.py
    FS_TOUCH_TOL``. ``refresh(force_all=True)`` is the A/B baseline: every
    lane re-sampled, same code path (suite config 18 measures the ratio).
    """

    def __init__(self, stream, spec=None, *, lane_bins=None,
                 rhat_gate: float = 1.05, touch_tol=None, mesh=None,
                 compile_cache_dir=None):
        self.stream = stream
        self.spec = (SampleSpec(model=stream.model) if spec is None
                     else as_spec(spec))
        if self.spec.model != stream.model:
            raise ValueError("FactorizedRefresher spec.model must be the "
                             "stream's model (same basis, same moments)")
        self.rhat_gate = float(rhat_gate)
        self.touch_tol = float(knobs.FS_TOUCH_TOL if touch_tol is None
                               else touch_tol)
        self.lane_bins = lane_bins
        self.mesh = mesh
        self.compile_cache_dir = compile_cache_dir
        self.posterior: Optional[dict] = None
        self.refreshes = 0
        self.promotions = 0
        self._compiled = None
        self._plan = None
        self._lanes = None
        self._dt_mark: Optional[np.ndarray] = None
        self._lane_results: dict = {}
        self._lane_warm: dict = {}
        self._lane_z: dict = {}

    def _moments_np(self):
        return tuple(np.asarray(x, dtype=np.float64)
                     for x in self.stream.moments())

    def _build(self, mom):
        """First-refresh lane construction: the ONLY trace point.

        The build-time batch AND the pinned nuisance ``phi`` are cached so
        the marginalization operator stays FIXED across refreshes — only
        the data moments move with appends, which keeps touch detection
        stable and the per-refresh fold a single host solve.
        """
        self._batch = self.stream.batch_view()
        self._compiled = infer_model.build(self.spec.model, self._batch)
        self._plan = factor_plan(self._compiled, self.lane_bins)
        self._keep = sorted({c for lp in self._plan
                             for c in lp.free_cols})
        self._nuis = self._plan[0].nuisance_cols
        self._phi_nuis = nuisance_phi_np(self._compiled, self._batch,
                                         self._nuis)
        marg = self._marg(mom)
        self._lanes = []
        for lp in self._plan:
            lane_spec = dataclasses.replace(self.spec, model=lp.model)
            self._lanes.append(SamplingRun(
                self._batch, lane_spec, mesh=self.mesh,
                moments=_restrict_np(marg, lp.marg_cols),
                compile_cache_dir=self.compile_cache_dir))
        return marg

    def _marg(self, mom):
        """Fold the pinned nuisances into the moments (Ntilde metric),
        with the build-time cached nuisance ``phi`` — the pinned prior is
        theta-independent, so the fold stays one pure host solve."""
        return marginalize_nuisance_np(mom, self._keep, self._nuis,
                                       self._phi_nuis)

    def _touched(self, dt_new) -> list:
        """Lane indices whose data projection moved since the last refresh
        — the appended block perturbs the PARENT ``dT`` only in bins it
        excites, so excitation is read off the raw projections (the
        marginalized ``dT~`` folds nuisance projections into every column
        via ``M_kn A^-1 dT_n`` and would flood-fill the touch set on
        irregular grids; the R-hat gate catches any misprediction)."""
        out = []
        for lp in self._plan:
            cols = list(lp.free_cols)
            base = float(np.linalg.norm(self._dt_mark[:, cols]))
            delta = float(np.linalg.norm(dt_new[:, cols]
                                         - self._dt_mark[:, cols]))
            if delta > self.touch_tol * (base + 1e-300):
                out.append(lp.index)
        return out

    @property
    def lane_count(self) -> int:
        return 0 if self._plan is None else len(self._plan)

    def refresh(self, n_steps: int = 200, seed: int = 0, *,
                force_all: bool = False, **run_kwargs) -> dict:
        """One incremental cycle: slice current moments, re-sample the
        touched lanes warm, recombine, R-hat-gated promotion.

        The first call (and any ``force_all=True`` call) refreshes every
        lane — that IS the full-refresh baseline, same code path. Returns
        the cycle stats (``fs_*`` keys); the promoted recombined posterior
        is ``self.posterior``.
        """
        t0 = obs.now()
        cold = self._lanes is None
        mom = self._moments_np()
        marg = self._build(mom) if cold else self._marg(mom)
        dt_new = np.asarray(mom[4])
        if cold or force_all or self._dt_mark is None:
            touched = [lp.index for lp in self._plan]
        else:
            touched = self._touched(dt_new)
        bins = sum(self._plan[i].hi - self._plan[i].lo for i in touched)
        retr0 = sum(lane.retraces for lane in self._lanes)
        rhat_ran = []
        for i in touched:
            lp, lane = self._plan[i], self._lanes[i]
            warm = self._lane_warm.get(i)
            if not cold:
                lane.restage(moments=_restrict_np(marg, lp.marg_cols))
            init_z = None
            z_prev = self._lane_z.get(i)
            if z_prev is not None and warm is not None:
                init_z = PosteriorRefresher._remap_z(
                    z_prev, warm, lane.laplace_state())
            res = lane.run(int(n_steps), seed=lane_seed(seed, i),
                           init_z=init_z, **run_kwargs)
            self._lane_results[i] = res
            self._lane_warm[i] = lane.laplace_state()
            self._lane_z[i] = lane.last_z
            rhat_ran.append(float(res["summary"].get("rhat_max",
                                                     float("nan"))))
            obs.count("stream.fs_lanes_refreshed")
        recompiles = sum(lane.retraces for lane in self._lanes) - retr0
        rhat_max = max(rhat_ran) if rhat_ran else float("nan")
        cycle = self.refreshes
        self.refreshes += 1
        promoted = bool(rhat_ran) and bool(np.isfinite(rhat_max)
                                           and rhat_max <= self.rhat_gate)
        if promoted:
            results = [self._lane_results[lp.index] for lp in self._plan]
            theta = recombine_draws([lp.theta_idx for lp in self._plan],
                                    results, self._compiled.D)
            mode_theta = np.zeros(self._compiled.D)
            for lp, lane in zip(self._plan, self._lanes):
                mode_theta[list(lp.theta_idx)] = lane.mode_theta
            self.posterior = {
                "schema": STREAM_SCHEMA,
                "theta": theta,
                "param_names": list(self._compiled.param_names),
                "bounds": np.asarray(self._compiled.bounds),
                "mode_theta": mode_theta,
                "summary": {
                    "rhat_max": round(max(
                        r["summary"]["rhat_max"] for r in results), 5),
                    "ess_min": round(min(
                        r["summary"]["ess_min"] for r in results), 2),
                    "fs_lane_count": len(self._plan),
                },
            }
            self.promotions += 1
            obs.count("stream.promotions")
        elif rhat_ran:
            obs.flightrec.note("stream_fs_refresh_reject", refresh=cycle,
                               rhat_max=rhat_max, gate=self.rhat_gate)
        self._dt_mark = dt_new.copy()
        obs.count("stream.fs_refreshes")
        obs.count("stream.fs_bins_touched", bins)
        obs.telemetry.publish("stream.fs_bins_touched", int(bins))
        info = {
            "schema": STREAM_SCHEMA, "refresh": cycle,
            "fs_lane_count": len(self._plan),
            "fs_lanes_touched": len(touched),
            "fs_bins_touched": int(bins),
            "fs_recompiles": int(recompiles),
            "rhat_max": rhat_max, "promoted": promoted,
            "warm_started": not cold and not force_all,
            "n_steps": int(n_steps),
            "fs_refresh_ms": round((obs.now() - t0) * 1e3, 3),
        }
        return info
