"""Continuous posterior refresh: re-sample on data arrival, warm-started.

A streaming PTA wants a CURRENT posterior, not a nightly batch job. Each
refresh builds a fresh :class:`~fakepta_tpu.sample.SamplingRun` over the
stream's accumulated data (``batch_view``/``residuals_view`` — the frozen
grids, so the model is the SAME model the moments live on) and recycles
two things from the previous posterior instead of starting cold:

- ``warm_from``: the previous Laplace mode seeds the damped-Newton fit.
  With one epoch of new data the mode barely moves, so the fit converges
  in a handful of iterations instead of tens (``laplace_iters`` is
  surfaced per refresh precisely so the win is measurable).
- ``init_z``: the previous chains' final whitened positions, REMAPPED into
  the new run's whitened frame. Chains sample ``v = mode + z C^T`` (C
  upper-triangular, ``C C^T = (-H)^{-1}``); keeping the *physical*
  positions fixed across the frame change solves
  ``mode_old + z_old C_old^T = mode_new + z_new C_new^T`` for ``z_new`` —
  a host-f64 triangular solve. Cached in-chain likelihood parts are NOT
  recycled (the data changed); the sampler's snapshot refresh recomputes
  them against the new moments on the first step.

Promotion is R-hat gated: the refreshed posterior replaces ``posterior``
only when ``rhat_max <= rhat_gate``; a non-converged refresh is kept out
(flight-recorded ``stream_refresh_reject``) while the warm state still
advances — the Laplace mode is a deterministic fit, valid regardless of
chain convergence.

Scheduling (ROADMAP item 5): refreshing after every append wastes chains
on a posterior that barely moved. :class:`RefreshPolicy` decides when a
refresh is DUE — after ``every_appends`` appended blocks since the last
refresh, or earlier when the stream's rolling ``|SNR|`` moved by at least
``min_snr_gain`` (data arriving that *changes the answer* should not wait
out the epoch counter). :meth:`PosteriorRefresher.maybe_refresh` applies
the policy: not-due calls are counted (``stream.refresh_skips``) and
flight-recorded, never sampled.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from .. import obs
from ..sample import SampleSpec, SamplingRun, as_spec
from ..tune import defaults as knobs
from .state import STREAM_SCHEMA


@dataclasses.dataclass(frozen=True)
class RefreshPolicy:
    """When is a posterior refresh due? (defaults from ``tune/defaults.py``)

    - ``every_appends``: refresh after this many appended TOA blocks since
      the last refresh (the epoch-count trigger; always active).
    - ``min_snr_gain``: refresh as soon as the stream's rolling detection
      statistic moved this much in ``|SNR|`` since the last refresh
      (0 disables; streams without a ``watch`` statistic never trip it).
    """

    every_appends: int = knobs.REFRESH_EVERY_APPENDS
    min_snr_gain: float = knobs.REFRESH_MIN_SNR_GAIN


class PosteriorRefresher:
    """Warm-started, R-hat-gated posterior refresh loop over a stream.

    ``spec`` is a :class:`~fakepta_tpu.sample.SampleSpec` (or None for the
    stream's model with SampleSpec defaults); its model must BE the
    stream's model — the posterior must describe the same process the
    stream accumulates moments for.
    """

    def __init__(self, stream, spec=None, *, rhat_gate: float = 1.05,
                 mesh=None, compile_cache_dir=None,
                 policy: Optional[RefreshPolicy] = None):
        self.stream = stream
        self.spec = (SampleSpec(model=stream.model) if spec is None
                     else as_spec(spec))
        if self.spec.model != stream.model:
            raise ValueError("PosteriorRefresher spec.model must be the "
                             "stream's model (same basis, same moments)")
        self.rhat_gate = float(rhat_gate)
        self.mesh = mesh
        self.compile_cache_dir = compile_cache_dir
        self.policy = policy or RefreshPolicy()
        self.posterior: Optional[dict] = None
        self.refreshes = 0
        self.promotions = 0
        self.skips = 0
        self._warm: Optional[dict] = None
        self._last_z: Optional[np.ndarray] = None
        # scheduling baselines: appends/SNR as of the last refresh (the
        # construction point counts as "refreshed" — maybe_refresh measures
        # accumulation, not absolute stream age)
        self._mark_appends = int(getattr(stream, "appends", 0))
        self._mark_snr = self._current_snr()

    def _current_snr(self) -> Optional[float]:
        """The stream's rolling |SNR|, or None without a watch statistic."""
        snr = self.stream.stats().get("snr")
        return None if snr is None else abs(float(snr))

    @staticmethod
    def _remap_z(z_prev, prev, new) -> np.ndarray:
        """Whitened positions from the previous frame re-expressed in the
        new one, holding the physical positions fixed (module docstring)."""
        k, t, d = z_prev.shape
        v = (np.asarray(prev["mode_v"])[None, None, :]
             + np.asarray(z_prev, dtype=np.float64)
             @ np.asarray(prev["chol_cov"]).T)
        delta = (v - np.asarray(new["mode_v"])[None, None, :])
        z_new = np.linalg.solve(np.asarray(new["chol_cov"]).T,
                                delta.reshape(-1, d).T).T
        return z_new.reshape(k, t, d)

    def refresh(self, n_steps: int = 200, seed: int = 0, **run_kwargs
                ) -> dict:
        """One refresh cycle: Laplace re-fit (warm), chains (warm),
        R-hat-gated promotion. Returns the cycle's stats dict; the
        promoted posterior (when the gate passes) is ``self.posterior``.
        """
        t0 = obs.now()
        warm = self._warm
        run = SamplingRun(self.stream.batch_view(), self.spec,
                          residuals=self.stream.residuals_view(),
                          mesh=self.mesh,
                          compile_cache_dir=self.compile_cache_dir,
                          warm_from=warm)
        init_z = None
        if self._last_z is not None and warm is not None:
            init_z = self._remap_z(self._last_z, warm, run.laplace_state())
        result = run.run(int(n_steps), seed=seed, init_z=init_z,
                         **run_kwargs)
        rhat = float(result["summary"].get("rhat_max", float("nan")))
        promoted = bool(np.isfinite(rhat) and rhat <= self.rhat_gate)
        cycle = self.refreshes
        self.refreshes += 1
        if promoted:
            self.posterior = result
            self.promotions += 1
            obs.count("stream.promotions")
        else:
            obs.flightrec.note("stream_refresh_reject", refresh=cycle,
                               rhat_max=rhat, gate=self.rhat_gate)
        self._warm = run.laplace_state()
        self._last_z = run.last_z
        self._mark_appends = int(getattr(self.stream, "appends", 0))
        self._mark_snr = self._current_snr()
        obs.count("stream.refreshes")
        info = {
            "schema": STREAM_SCHEMA, "refresh": cycle,
            "rhat_max": rhat, "promoted": promoted,
            "warm_started": warm is not None,
            "chains_warm_started": init_z is not None,
            "laplace_iters": int(run.laplace_iters),
            "n_steps": int(n_steps),
            "n_toas": int(self.stream._n.sum()),
            "latency_ms": round((obs.now() - t0) * 1e3, 3),
        }
        return info

    def maybe_refresh(self, n_steps: int = 200, seed: int = 0, **run_kwargs
                      ) -> dict:
        """Refresh only when the :class:`RefreshPolicy` says one is due.

        Due → delegates to :meth:`refresh` (the returned info dict gains a
        ``trigger`` key: ``"appends"`` or ``"snr"``). Not due → no chains
        run; the skip is counted (``stream.refresh_skips``) and
        flight-recorded, and a ``{"skipped": True, ...}`` dict reports how
        far each trigger has accumulated.
        """
        pol = self.policy
        since = int(getattr(self.stream, "appends", 0)) - self._mark_appends
        snr = self._current_snr()
        gain = (abs(snr - self._mark_snr)
                if snr is not None and self._mark_snr is not None
                else (snr if snr is not None else 0.0))
        due_appends = since >= int(pol.every_appends)
        due_snr = pol.min_snr_gain > 0 and gain >= pol.min_snr_gain
        if not (due_appends or due_snr):
            self.skips += 1
            obs.count("stream.refresh_skips")
            # the telemetry plane watches the gate decision stream: holds
            # vs opens are how `obs top` shows whether refresh work is
            # keeping pace with arrivals (docs/OBSERVABILITY.md)
            obs.count("stream.refresh_gate_holds")
            obs.telemetry.publish("stream.refresh_gate_holds",
                                  int(self.skips))
            obs.flightrec.note("stream_refresh_skip", appends_since=since,
                               snr_gain=round(float(gain), 6))
            return {"schema": STREAM_SCHEMA, "skipped": True,
                    "appends_since": since, "snr_gain": float(gain)}
        obs.count("stream.refresh_gate_opens")
        obs.telemetry.publish("stream.refresh_gate_opens",
                              int(self.refreshes) + 1)
        info = self.refresh(n_steps, seed=seed, **run_kwargs)
        info["trigger"] = "appends" if due_appends else "snr"
        info["skipped"] = False
        return info
