"""Continuous posterior refresh: re-sample on data arrival, warm-started.

A streaming PTA wants a CURRENT posterior, not a nightly batch job. Each
refresh builds a fresh :class:`~fakepta_tpu.sample.SamplingRun` over the
stream's accumulated data (``batch_view``/``residuals_view`` — the frozen
grids, so the model is the SAME model the moments live on) and recycles
two things from the previous posterior instead of starting cold:

- ``warm_from``: the previous Laplace mode seeds the damped-Newton fit.
  With one epoch of new data the mode barely moves, so the fit converges
  in a handful of iterations instead of tens (``laplace_iters`` is
  surfaced per refresh precisely so the win is measurable).
- ``init_z``: the previous chains' final whitened positions, REMAPPED into
  the new run's whitened frame. Chains sample ``v = mode + z C^T`` (C
  upper-triangular, ``C C^T = (-H)^{-1}``); keeping the *physical*
  positions fixed across the frame change solves
  ``mode_old + z_old C_old^T = mode_new + z_new C_new^T`` for ``z_new`` —
  a host-f64 triangular solve. Cached in-chain likelihood parts are NOT
  recycled (the data changed); the sampler's snapshot refresh recomputes
  them against the new moments on the first step.

Promotion is R-hat gated: the refreshed posterior replaces ``posterior``
only when ``rhat_max <= rhat_gate``; a non-converged refresh is kept out
(flight-recorded ``stream_refresh_reject``) while the warm state still
advances — the Laplace mode is a deterministic fit, valid regardless of
chain convergence.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import obs
from ..sample import SampleSpec, SamplingRun, as_spec
from .state import STREAM_SCHEMA


class PosteriorRefresher:
    """Warm-started, R-hat-gated posterior refresh loop over a stream.

    ``spec`` is a :class:`~fakepta_tpu.sample.SampleSpec` (or None for the
    stream's model with SampleSpec defaults); its model must BE the
    stream's model — the posterior must describe the same process the
    stream accumulates moments for.
    """

    def __init__(self, stream, spec=None, *, rhat_gate: float = 1.05,
                 mesh=None, compile_cache_dir=None):
        self.stream = stream
        self.spec = (SampleSpec(model=stream.model) if spec is None
                     else as_spec(spec))
        if self.spec.model != stream.model:
            raise ValueError("PosteriorRefresher spec.model must be the "
                             "stream's model (same basis, same moments)")
        self.rhat_gate = float(rhat_gate)
        self.mesh = mesh
        self.compile_cache_dir = compile_cache_dir
        self.posterior: Optional[dict] = None
        self.refreshes = 0
        self.promotions = 0
        self._warm: Optional[dict] = None
        self._last_z: Optional[np.ndarray] = None

    @staticmethod
    def _remap_z(z_prev, prev, new) -> np.ndarray:
        """Whitened positions from the previous frame re-expressed in the
        new one, holding the physical positions fixed (module docstring)."""
        k, t, d = z_prev.shape
        v = (np.asarray(prev["mode_v"])[None, None, :]
             + np.asarray(z_prev, dtype=np.float64)
             @ np.asarray(prev["chol_cov"]).T)
        delta = (v - np.asarray(new["mode_v"])[None, None, :])
        z_new = np.linalg.solve(np.asarray(new["chol_cov"]).T,
                                delta.reshape(-1, d).T).T
        return z_new.reshape(k, t, d)

    def refresh(self, n_steps: int = 200, seed: int = 0, **run_kwargs
                ) -> dict:
        """One refresh cycle: Laplace re-fit (warm), chains (warm),
        R-hat-gated promotion. Returns the cycle's stats dict; the
        promoted posterior (when the gate passes) is ``self.posterior``.
        """
        t0 = obs.now()
        warm = self._warm
        run = SamplingRun(self.stream.batch_view(), self.spec,
                          residuals=self.stream.residuals_view(),
                          mesh=self.mesh,
                          compile_cache_dir=self.compile_cache_dir,
                          warm_from=warm)
        init_z = None
        if self._last_z is not None and warm is not None:
            init_z = self._remap_z(self._last_z, warm, run.laplace_state())
        result = run.run(int(n_steps), seed=seed, init_z=init_z,
                         **run_kwargs)
        rhat = float(result["summary"].get("rhat_max", float("nan")))
        promoted = bool(np.isfinite(rhat) and rhat <= self.rhat_gate)
        cycle = self.refreshes
        self.refreshes += 1
        if promoted:
            self.posterior = result
            self.promotions += 1
            obs.count("stream.promotions")
        else:
            obs.flightrec.note("stream_refresh_reject", refresh=cycle,
                               rhat_max=rhat, gate=self.rhat_gate)
        self._warm = run.laplace_state()
        self._last_z = run.last_z
        obs.count("stream.refreshes")
        info = {
            "schema": STREAM_SCHEMA, "refresh": cycle,
            "rhat_max": rhat, "promoted": promoted,
            "warm_started": warm is not None,
            "chains_warm_started": init_z is not None,
            "laplace_iters": int(run.laplace_iters),
            "n_steps": int(n_steps),
            "n_toas": int(self.stream._n.sum()),
            "latency_ms": round((obs.now() - t0) * 1e3, 3),
        }
        return info
