"""StreamState: the per-pulsar append-TOA container (docs/STREAMING.md).

**The frozen-grid contract.** Woodbury moments are additive over TOAs only
if every TOA — old and new — is projected onto the SAME Fourier basis. The
batch layer normalizes times by Tspan (``t/Tspan_p`` per pulsar,
``t/Tspan_array`` for CURN), so a naive "rebuild the batch with the new
data" changes Tspan and with it every *old* basis value: the old moments
would be sums over a basis that no longer exists, and nothing is additive.
A stream therefore pins its grids ONCE from a template batch — ``df_own``
(per-pulsar bin width, 1/Tspan_ref) and ``tspan_common`` — and normalizes
every appended absolute TOA against those frozen scales. Appends are then
exactly additive by construction (:func:`fakepta_tpu.ops.woodbury
.append_parts`), which the f64 oracle test pins at <= 1e-8 per pulsar.
ECORR epochs use *global* ids (``floor(t_abs / ecorr_dt)``) for the same
reason: an epoch's identity never changes when later data arrives.

**Re-bucket policy.** Three shapes churn as a stream grows, and each rides
its own geometric ladder (:mod:`fakepta_tpu.tune.defaults`:
``STREAM_BLOCK_BUCKETS`` / ``STREAM_GROWTH_RATIO``) so the compiled-kernel
key set stays O(log growth): the append-block width (pads to the smallest
ladder rung), the ECORR epoch capacity, and the host storage capacity.
Appends within the current rungs reuse the cached executable — ZERO
recompiles, enforced by the same trace-count retrace guard the engine uses
(``stream_recompiles`` is a zero-expected bench canary). A rung crossing is
one counted ``stream.rebuckets`` event and at most one fresh compile.

**Torn-append recovery.** With a checkpoint attached, every appended block
lands as its own ``.b<k>.npz`` via :func:`fakepta_tpu.utils.io
.write_atomic` with a CRC32 manifest; resume replays the blocks through the
same append kernels (bit-identical — appends are deterministic), and a torn
final block rolls back to the last consistent state (chaos site
``ingest.append``, kind ``torn``; docs/RELIABILITY.md).
"""

from __future__ import annotations

import dataclasses
from types import SimpleNamespace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as Psp

from .. import faults
from .. import obs
from ..infer import model as infer_model
from ..ops import woodbury
from ..parallel.mesh import PSR_AXIS
from ..tune import defaults as tune_defaults
from ..utils.compat import enable_x64

#: schema tag for stream artifacts (manifest + served stats payloads)
STREAM_SCHEMA = "fakepta_tpu.stream/1"


def default_stream_model(nbin: int = 10, log10_A=(-15.5, -13.5),
                         gamma=(2.0, 6.0)):
    """The standard streaming model: batch-pinned red + DM noise plus a
    free-powerlaw CURN component (the process the rolling detection
    statistic watches). Mirrors :func:`fakepta_tpu.serve.spec
    .curn_grid_spec`'s model with the stream's default bounds."""
    return infer_model.LikelihoodSpec(components=(
        infer_model.ComponentSpec(target="red", spectrum="batch"),
        infer_model.ComponentSpec(target="dm", spectrum="batch"),
        infer_model.ComponentSpec(target="curn", nbin=int(nbin), free=(
            infer_model.FreeParam("log10_A", tuple(log10_A)),
            infer_model.FreeParam("gamma", tuple(gamma)))),
    ))


def _snap(n: int, ladder, ratio: int) -> int:
    """Smallest ladder rung >= n; past the top rung, keep multiplying by
    ``ratio`` (so bulk history appends stay legal with O(log) extra
    compiles)."""
    if n <= 0:
        raise ValueError(f"bucket size must be positive, got {n}")
    for b in ladder:
        if n <= b:
            return int(b)
    b = int(ladder[-1])
    while b < n:
        b *= int(ratio)
    return b


class StreamCheckpoint:
    """Append-block checkpoint: one small ``.b<k>.npz`` per append plus a
    CRC32 manifest, every file via :func:`~fakepta_tpu.utils.io
    .write_atomic`. Resume replays the raw blocks through the stream's own
    append kernels — deterministic, so the resumed state is bit-identical —
    and a torn block rolls back to the last consistent append
    (``stream_rollback`` flight-recorded, ``faults.rollbacks`` counted)."""

    def __init__(self, path):
        from pathlib import Path
        self.path = Path(path)
        self._sums: dict = {}        # block index -> CRC32

    def _block_path(self, k: int):
        return self.path.with_name(self.path.name + f".b{k:06d}.npz")

    def _write_manifest(self, ident: dict, n_blocks: int) -> None:
        from ..utils.io import npz_bytes, write_atomic
        manifest = dict(
            npsr=np.int64(ident["npsr"]), ncols=np.int64(ident["ncols"]),
            ecorr_dt=np.float64(ident["ecorr_dt"]),
            n_blocks=np.int64(n_blocks),
            sums=np.asarray([self._sums.get(k, 0) for k in range(n_blocks)],
                            dtype=np.int64))
        write_atomic(self.path, npz_bytes(**manifest))

    def save_block(self, ident: dict, k: int, arrays: dict) -> None:
        from ..utils.io import npz_bytes, write_atomic
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._sums[k] = write_atomic(self._block_path(k),
                                     npz_bytes(**arrays))
        self._write_manifest(ident, k + 1)

    def corrupt_block(self, k: int) -> None:
        """Chaos-harness hook: simulate the torn write fsync cannot prevent
        (failing storage drops the block's pages after the rename became
        durable) — resume must detect the bad CRC and roll back."""
        p = self._block_path(k)
        data = p.read_bytes()
        p.write_bytes(data[:max(len(data) // 2, 1)])

    def load_blocks(self, ident: dict):
        """``(blocks, rolled_back)`` — verified raw append blocks in order,
        after rolling back past the first torn/corrupt one."""
        import io as _io
        import zipfile
        import zlib
        if not self.path.exists():
            return [], 0
        try:
            with np.load(self.path, allow_pickle=False) as z:
                manifest = {k: z[k] for k in z.files}
        except (OSError, ValueError, zipfile.BadZipFile) as exc:
            obs.flightrec.note("stream_manifest_corrupt",
                               path=str(self.path), error=repr(exc)[:200])
            self.delete()
            return [], 0
        for key in ("npsr", "ncols"):
            if int(manifest[key]) != int(ident[key]):
                raise ValueError(
                    f"stream checkpoint {self.path} was written by a "
                    f"different stream ({key}={int(manifest[key])}, this "
                    f"stream has {int(ident[key])}); delete it or use a "
                    f"different path")
        if float(manifest["ecorr_dt"]) != float(ident["ecorr_dt"]):
            raise ValueError(
                f"stream checkpoint {self.path} uses ecorr_dt="
                f"{float(manifest['ecorr_dt'])}, this stream "
                f"{float(ident['ecorr_dt'])}; delete it or use a "
                f"different path")
        total = int(manifest["n_blocks"])
        sums = manifest["sums"]
        blocks = []
        good = total
        self._sums = {}
        for k in range(total):
            try:
                data = self._block_path(k).read_bytes()
                crc = zlib.crc32(data)
                if k < len(sums) and crc != int(sums[k]):
                    raise ValueError(f"block {k} checksum mismatch "
                                     f"(torn write)")
                with np.load(_io.BytesIO(data), allow_pickle=False) as z:
                    blocks.append({key: z[key] for key in z.files})
                self._sums[k] = crc
            except (OSError, ValueError, KeyError,
                    zipfile.BadZipFile) as exc:
                obs.flightrec.note("stream_rollback", block=k,
                                   error=repr(exc)[:200])
                good = k
                blocks = blocks[:good]
                break
        if good < total:
            # drop the bad tail and rewrite the manifest: the on-disk
            # checkpoint is the last CONSISTENT StreamState again
            for k in range(good, total):
                self._block_path(k).unlink(missing_ok=True)
                self._sums.pop(k, None)
            obs.count("faults.rollbacks", total - good)
            if good == 0:
                self.delete()
            else:
                self._write_manifest(ident, good)
        return blocks, total - good

    def delete(self):
        for p in self.path.parent.glob(self.path.name + ".b*.npz"):
            p.unlink(missing_ok=True)
        self.path.unlink(missing_ok=True)
        self._sums = {}


class StreamState:
    """Append-TOA state for one PTA: frozen grids, accumulated device
    moments, bucketed O(new-epoch) append kernels (class docstring above;
    algebra in docs/STREAMING.md).

    ``template`` pins the geometry (npsr, sky positions, stored noise PSDs)
    and the FROZEN frequency grids (``df_own``, ``tspan_common``); the
    stream itself starts empty — the template's TOAs are reference scales,
    not data. ``model`` is the :class:`~fakepta_tpu.infer.LikelihoodSpec`
    whose basis/phi the moments live on (default
    :func:`default_stream_model`); ``'sys'`` components are rejected (their
    per-band TOA masks are not well-defined for not-yet-seen data).
    ``ecorr_dt`` (seconds) enables ECORR epoch blocks with global epoch
    ids. ``watch`` names an ORF ("hd", ...) to arm the rolling
    :class:`~fakepta_tpu.detect.streaming.StreamingOS` refreshed on every
    append. ``checkpoint`` attaches a :class:`StreamCheckpoint` path and
    REPLAYS any existing consistent blocks before returning.

    Appended absolute TOAs are seconds from the stream's shared origin
    (the template's own origin: its synthetic arrays start at t=0).
    """

    def __init__(self, template, model=None, *, theta_ref=None, mesh=None,
                 ecorr_dt: Optional[float] = None, watch=None,
                 checkpoint=None, block_buckets=None, growth_ratio=None,
                 dtype=np.float64):
        self.template = template
        self.model = model if model is not None else default_stream_model()
        self._compiled = infer_model.build(self.model, template)
        if any(c["target"] == "sys" for c in self._compiled._comps):
            raise ValueError("streaming does not support 'sys' components "
                             "(per-band TOA membership is undefined for "
                             "future data); model red/dm/chrom/curn only")
        self.npsr = int(template.npsr)
        self.ncols = int(self._compiled.ncols)
        self.mesh = mesh
        if mesh is not None:
            shards = int(mesh.shape.get(PSR_AXIS, 1))
            if self.npsr % shards != 0:
                raise ValueError(f"npsr={self.npsr} must be divisible by "
                                 f"the psr mesh axis ({shards})")
        self._dtype = np.dtype(dtype)
        self._x64 = self._dtype.itemsize == 8
        self.ecorr_dt = None if ecorr_dt is None else float(ecorr_dt)
        if theta_ref is None:
            theta_ref = self._compiled.theta_from_unit(
                np.full(self._compiled.D, 0.5))
        self.theta_ref = np.asarray(theta_ref, dtype=np.float64)
        self._buckets = tuple(block_buckets if block_buckets is not None
                              else tune_defaults.STREAM_BLOCK_BUCKETS)
        self._ratio = int(growth_ratio if growth_ratio is not None
                          else tune_defaults.STREAM_GROWTH_RATIO)

        # frozen grids + per-pulsar defaults from the template (host f64)
        self._df_own = np.asarray(template.df_own, dtype=np.float64)
        self._tspan = float(np.asarray(template.tspan_common,
                                       dtype=np.float64))
        tmask = np.asarray(template.mask, dtype=np.float64)
        tsig = np.asarray(template.sigma2, dtype=np.float64)
        self._sigma2_default = (np.sum(tsig * tmask, axis=1)
                                / np.maximum(np.sum(tmask, axis=1), 1.0))
        with self._ctx():
            self._nsb = self._template_views()

        # host store of raw appended data (the restage/refresh source)
        self._cap = 0
        self._n = np.zeros(self.npsr, dtype=np.int64)
        self._store: dict = {}
        # accumulated device moment parts
        self._ecap = 0
        with self._ctx():
            self._fixed, self._res = self._zero_parts()
        self._kernels: dict = {}
        self._trace_counts: dict = {}
        self.appends = 0
        self.rebuckets = 0
        self.recompiles = 0
        self.compiles = 0
        self.rolled_back = 0
        self._moments_cache = None
        self._watch = None
        self._watch_orf = watch
        self.last_stats: Optional[dict] = None

        self._ckpt = None
        if checkpoint is not None:
            self._ckpt = (checkpoint if isinstance(checkpoint,
                                                   StreamCheckpoint)
                          else StreamCheckpoint(checkpoint))
            self._resume()

    # ------------------------------------------------------------------
    # staging helpers
    # ------------------------------------------------------------------
    def _ctx(self):
        """Dtype context for kernel trace/dispatch: the stream accumulates
        moments across appends, so it defaults to f64 (the sanctioned
        host-f64 staging layer; an f32 stream is legal where the platform
        demands it and drift is bounded by periodic :meth:`restage`)."""
        import contextlib
        return enable_x64() if self._x64 else contextlib.nullcontext()

    def _template_views(self) -> SimpleNamespace:
        """Stream-dtype views of the template fields ``basis``/``phi``
        read — the phi/finish-side namespace (times are NOT data here)."""
        b = self.template
        cast = lambda x: jnp.asarray(np.asarray(x, dtype=self._dtype))  # noqa: E731
        return SimpleNamespace(
            t_own=cast(b.t_own), t_common=cast(b.t_common),
            freqs=cast(b.freqs), df_own=cast(b.df_own),
            tspan_common=cast(b.tspan_common), red_psd=cast(b.red_psd),
            dm_psd=cast(b.dm_psd), chrom_psd=cast(b.chrom_psd),
            sys_psd=cast(b.sys_psd),
            sys_mask=jnp.asarray(np.asarray(b.sys_mask)))

    def _put(self, arr):
        """Device placement: pulsar-axis sharded when a mesh is attached
        (per-pulsar moments are embarrassingly parallel over 'psr')."""
        if self.mesh is None:
            return jnp.asarray(arr)
        spec = Psp(PSR_AXIS, *([None] * (np.ndim(arr) - 1)))
        return jax.device_put(jnp.asarray(arr),
                              NamedSharding(self.mesh, spec))

    def _zero_parts(self):
        p, c = self.npsr, self.ncols
        dt = self._dtype
        fixed = {"M": self._put(np.zeros((p, c, c), dt)),
                 "lndetN": self._put(np.zeros(p, dt)),
                 "n_valid": self._put(np.zeros(p, dt))}
        res = {"d0": self._put(np.zeros(p, dt)),
               "dT": self._put(np.zeros((p, c), dt))}
        if self._ecap:
            fixed["a"] = self._put(np.zeros((p, self._ecap), dt))
            fixed["v"] = self._put(np.zeros((p, self._ecap, c), dt))
            res["s"] = self._put(np.zeros((p, self._ecap), dt))
        return fixed, res

    def _note_trace(self, signature) -> None:
        """The engine's retrace guard: a second trace of the same kernel
        key is an unexpected recompile (the ``stream_recompiles``
        zero-expected canary)."""
        n = self._trace_counts.get(signature, 0) + 1
        self._trace_counts[signature] = n
        if n > 1:
            self.recompiles += 1
            obs.count("stream.recompiles")
        else:
            self.compiles += 1
            obs.count("stream.compiles")

    # ------------------------------------------------------------------
    # kernels (cached per (block bucket, epoch capacity))
    # ------------------------------------------------------------------
    def _kernel(self, nb: int):
        key = (int(nb), int(self._ecap))
        fn = self._kernels.get(key)
        if fn is None:
            fn = self._build_kernel(*key)
            self._kernels[key] = fn
        return fn

    def _build_kernel(self, nb: int, ecap: int):
        compiled, p = self._compiled, self.npsr
        df_own = self._nsb.df_own
        with self._ctx():       # the pinned scale must hold stream dtype
            tspan = jnp.asarray(self._tspan, self._dtype)

        def kern(fixed, res, t_abs, mask, sigma2, freqs, epoch_idx,
                 ecorr_amp, r):
            self._note_trace(("append", nb, ecap))
            # the frozen-grid normalization: absolute seconds against the
            # PINNED per-pulsar df_own / common tspan — never re-derived
            # from the accumulated data (module docstring)
            bview = SimpleNamespace(
                t_own=t_abs * df_own[:, None], t_common=t_abs / tspan,
                freqs=freqs, sys_mask=jnp.zeros((p, 1, nb), bool))
            tmat = compiled.basis(bview)

            if ecap:
                fixed2 = jax.vmap(
                    lambda f, tm, s2, mk, ei, ea: woodbury.append_parts(
                        f, tm, s2, mk, epoch_idx=ei, ecorr_amp=ea,
                        num_epochs=ecap))(fixed, tmat, sigma2, mask,
                                          epoch_idx, ecorr_amp)
                res2 = jax.vmap(
                    lambda rs, tm, s2, mk, rr, ei, ea:
                    woodbury.append_parts(
                        rs, tm, s2, mk, r=rr, epoch_idx=ei, ecorr_amp=ea,
                        num_epochs=ecap))(res, tmat, sigma2, mask, r,
                                          epoch_idx, ecorr_amp)
            else:
                fixed2 = jax.vmap(
                    lambda f, tm, s2, mk: woodbury.append_parts(
                        f, tm, s2, mk))(fixed, tmat, sigma2, mask)
                res2 = jax.vmap(
                    lambda rs, tm, s2, mk, rr: woodbury.append_parts(
                        rs, tm, s2, mk, r=rr))(res, tmat, sigma2, mask, r)
            return fixed2, res2

        return jax.jit(kern)

    def _finish_fn(self):
        key = ("finish", int(self._ecap))
        fn = self._kernels.get(key)
        if fn is None:
            def fin(fixed, res):
                self._note_trace(key)
                m, lndet, nv, corr = jax.vmap(woodbury.finish_fixed)(fixed)
                if corr is None:
                    d0, dt = jax.vmap(
                        lambda rp: woodbury.finish_res(rp))(res)
                else:
                    d0, dt = jax.vmap(woodbury.finish_res)(res, corr)
                return m, lndet, nv, d0, dt
            fn = jax.jit(fin)
            self._kernels[key] = fn
        return fn

    # ------------------------------------------------------------------
    # capacity ladders
    # ------------------------------------------------------------------
    def _grow_epochs(self, need: int) -> None:
        """Snap the ECORR epoch capacity up to the next rung and zero-pad
        the accumulated parts (exact; woodbury.pad_epoch_parts semantics on
        the batched arrays)."""
        new_cap = _snap(need, self._buckets, self._ratio)
        grow = new_cap - self._ecap
        first = self._ecap == 0
        with self._ctx():
            if self._ecap == 0:
                self._ecap = new_cap
                p, c, dt = self.npsr, self.ncols, self._dtype
                self._fixed = dict(
                    self._fixed,
                    a=self._put(np.zeros((p, new_cap), dt)),
                    v=self._put(np.zeros((p, new_cap, c), dt)))
                self._res = dict(self._res,
                                 s=self._put(np.zeros((p, new_cap), dt)))
            else:
                self._fixed = dict(
                    self._fixed,
                    a=jnp.pad(self._fixed["a"], ((0, 0), (0, grow))),
                    v=jnp.pad(self._fixed["v"],
                              ((0, 0), (0, grow), (0, 0))))
                self._res = dict(
                    self._res,
                    s=jnp.pad(self._res["s"], ((0, 0), (0, grow))))
                self._ecap = new_cap
        if not first:                 # first allocation is not a rebucket
            self.rebuckets += 1
            obs.count("stream.rebuckets")
            obs.flightrec.note("stream_rebucket", what="epochs",
                               capacity=int(new_cap))

    def _grow_store(self, need: int) -> None:
        """Snap the host raw-data capacity up to the next rung (the
        restage/refresh source arrays; a host realloc, no compile)."""
        new_cap = _snap(need, self._buckets, self._ratio)
        p = self.npsr
        grown = {}
        for key, fill in (("t", 0.0), ("r", 0.0), ("sigma2", 1.0),
                          ("freqs", 1400.0), ("ecorr", 0.0)):
            arr = np.full((p, new_cap), fill, dtype=np.float64)
            if self._cap:
                arr[:, :self._cap] = self._store[key]
            grown[key] = arr
        mask = np.zeros((p, new_cap), dtype=bool)
        eidx = np.zeros((p, new_cap), dtype=np.int64)
        if self._cap:
            mask[:, :self._cap] = self._store["mask"]
            eidx[:, :self._cap] = self._store["eidx"]
        grown["mask"], grown["eidx"] = mask, eidx
        self._store = grown
        if self._cap:
            self.rebuckets += 1
            obs.count("stream.rebuckets")
            obs.flightrec.note("stream_rebucket", what="store",
                               capacity=int(new_cap))
        self._cap = new_cap

    # ------------------------------------------------------------------
    # the append path
    # ------------------------------------------------------------------
    def _ident(self) -> dict:
        return {"npsr": self.npsr, "ncols": self.ncols,
                "ecorr_dt": 0.0 if self.ecorr_dt is None else self.ecorr_dt}

    def append(self, toas, residuals, *, sigma2=None, freqs=None,
               ecorr_amp=None, counts=None) -> dict:
        """Ingest one block of new TOAs — O(block), never O(history).

        ``toas``/``residuals`` are (P, B) absolute seconds / seconds;
        ``counts`` (P,) marks how many leading entries per pulsar are real
        (default: all B). ``sigma2`` defaults to the template's mean white
        variance per pulsar; ``freqs`` to 1400 MHz; ``ecorr_amp`` (legal
        only with ``ecorr_dt`` set) to zero. Returns the append stats dict
        (latency, bucket, totals, and — with ``watch`` armed — the rolling
        detection statistic).
        """
        act = faults.check("ingest.append", seq=int(self.appends))
        toas = np.asarray(toas, dtype=np.float64)
        residuals = np.asarray(residuals, dtype=np.float64)
        if toas.ndim != 2 or toas.shape[0] != self.npsr:
            raise ValueError(f"toas must be ({self.npsr}, B), got "
                             f"{toas.shape}")
        if residuals.shape != toas.shape:
            raise ValueError(f"residuals shape {residuals.shape} != toas "
                             f"shape {toas.shape}")
        b0 = toas.shape[1]
        if counts is None:
            counts = np.full(self.npsr, b0, dtype=np.int64)
        else:
            counts = np.asarray(counts, dtype=np.int64)
            if counts.shape != (self.npsr,) or np.any(counts < 0) \
                    or np.any(counts > b0):
                raise ValueError(f"counts must be ({self.npsr},) in "
                                 f"[0, {b0}]")
        if ecorr_amp is not None and self.ecorr_dt is None:
            raise ValueError("ecorr_amp given but the stream was built "
                             "without ecorr_dt")
        block = {
            "t": toas, "r": residuals, "counts": counts,
            "sigma2": (np.broadcast_to(self._sigma2_default[:, None],
                                       toas.shape).copy()
                       if sigma2 is None
                       else np.broadcast_to(
                           np.asarray(sigma2, dtype=np.float64),
                           toas.shape).copy()),
            "freqs": (np.full(toas.shape, 1400.0) if freqs is None
                      else np.broadcast_to(
                          np.asarray(freqs, dtype=np.float64),
                          toas.shape).copy()),
            "ecorr": (np.zeros(toas.shape) if ecorr_amp is None
                      else np.broadcast_to(
                          np.asarray(ecorr_amp, dtype=np.float64),
                          toas.shape).copy()),
        }
        info = self._ingest(block, record=True)
        if act == "torn":
            # chaos harness: the block landed and the manifest references
            # it, then failing storage tore its pages and the process died
            # — resume must roll back to the last consistent StreamState
            if self._ckpt is not None:
                self._ckpt.corrupt_block(self.appends - 1)
            raise faults.KillFault(
                f"injected torn stream append at block {self.appends - 1}")
        return info

    def _ingest(self, block: dict, record: bool) -> dict:
        t0 = obs.now()
        toas, counts = block["t"], block["counts"]
        b0 = toas.shape[1]
        nb = _snap(b0, self._buckets, self._ratio)
        valid = np.arange(b0)[None, :] < counts[:, None]

        def padded(arr, fill, dt=np.float64):
            out = np.full((self.npsr, nb), fill, dtype=dt)
            out[:, :b0] = np.where(valid, arr, fill)
            return out

        t_pad = padded(toas, 0.0)
        r_pad = padded(block["r"], 0.0)
        s_pad = padded(block["sigma2"], 1.0)
        f_pad = padded(block["freqs"], 1400.0)
        e_pad = padded(block["ecorr"], 0.0)
        rebucketed = False
        if self.ecorr_dt is not None:
            eidx = np.floor_divide(toas, self.ecorr_dt).astype(np.int64)
            eidx = np.where(valid, eidx, 0)
            if np.any(eidx < 0):
                raise ValueError("TOAs before the stream origin are not "
                                 "appendable (negative epoch id)")
            need = int(eidx.max(initial=-1)) + 1 if np.any(valid) else 0
            if need > self._ecap:
                grew = self._ecap > 0
                self._grow_epochs(need)
                rebucketed = rebucketed or grew
            ei_pad = np.zeros((self.npsr, nb), dtype=np.int32)
            ei_pad[:, :b0] = eidx
        else:
            ei_pad = np.zeros((self.npsr, nb), dtype=np.int32)
        m_pad = np.zeros((self.npsr, nb), dtype=bool)
        m_pad[:, :b0] = valid

        need_cap = int((self._n + counts).max())
        if need_cap > self._cap:
            grew = self._cap > 0      # first allocation is not a rebucket
            self._grow_store(need_cap)
            rebucketed = rebucketed or grew

        kernel = self._kernel(nb)
        with self._ctx():
            args = tuple(self._put(a) for a in
                         (t_pad, m_pad, s_pad, f_pad, ei_pad, e_pad, r_pad))
            fixed, res = kernel(self._fixed, self._res, args[0], args[1],
                                args[2], args[3], args[4], args[5], args[6])
            jax.block_until_ready(fixed["M"])
        self._fixed, self._res = fixed, res
        self._moments_cache = None

        # host raw store (restage oracle + posterior refresh source)
        for p in range(self.npsr):
            c, n = int(counts[p]), int(self._n[p])
            if c == 0:
                continue
            self._store["t"][p, n:n + c] = toas[p, :c]
            self._store["r"][p, n:n + c] = block["r"][p, :c]
            self._store["sigma2"][p, n:n + c] = block["sigma2"][p, :c]
            self._store["freqs"][p, n:n + c] = block["freqs"][p, :c]
            self._store["ecorr"][p, n:n + c] = block["ecorr"][p, :c]
            self._store["mask"][p, n:n + c] = True
            self._store["eidx"][p, n:n + c] = ei_pad[p, :c]
        self._n = self._n + counts
        k = self.appends
        self.appends += 1

        if record and self._ckpt is not None:
            self._ckpt.save_block(self._ident(), k, {
                "t": toas, "r": block["r"], "counts": counts,
                "sigma2": block["sigma2"], "freqs": block["freqs"],
                "ecorr": block["ecorr"]})

        info = {
            "schema": STREAM_SCHEMA, "append": k,
            "n_new": int(counts.sum()), "n_toas": int(self._n.sum()),
            "block_bucket": int(nb), "epoch_capacity": int(self._ecap),
            "rebucketed": bool(rebucketed),
            "rebuckets": int(self.rebuckets),
            "compiles": int(self.compiles),
            "recompiles": int(self.recompiles),
        }
        if record:
            obs.count("stream.appends")
            if self._watch_orf is not None:
                info.update(self._watcher().update(self.moments()))
        info["latency_ms"] = round((obs.now() - t0) * 1e3, 3)
        self.last_stats = info
        return info

    def _resume(self) -> None:
        blocks, rolled_back = self._ckpt.load_blocks(self._ident())
        self.rolled_back = int(rolled_back)
        for blk in blocks:
            self._ingest({k: np.asarray(v) for k, v in blk.items()},
                         record=False)
            obs.count("stream.replays")
        if blocks and self._watch_orf is not None:
            self._watcher().update(self.moments())

    # ------------------------------------------------------------------
    # consumers: moments, likelihood, detection, restage, refresh views
    # ------------------------------------------------------------------
    def moments(self):
        """``(M, lndetN, n_valid, d0, dT)`` finished from the accumulated
        parts (cached until the next append)."""
        if self._moments_cache is None:
            fin = self._finish_fn()
            with self._ctx():
                self._moments_cache = fin(self._fixed, self._res)
        return self._moments_cache

    def lnlike(self, theta) -> float:
        """GP-marginalized lnL of the accumulated data at one theta."""
        m, lndet, nv, d0, dt = self.moments()
        with self._ctx():
            phi = self._compiled.phi(jnp.asarray(theta, self._dtype),
                                     self._nsb)
            lnl = jax.vmap(woodbury.lnlike_from_moments)(
                d0, dt, m, lndet, nv, phi)
            return float(jnp.sum(lnl))

    def _watcher(self):
        if self._watch is None:
            from ..detect.streaming import StreamingOS
            self._watch = StreamingOS(
                self._compiled, self._nsb,
                np.asarray(self.template.pos, dtype=np.float64),
                orf=self._watch_orf, theta_ref=self.theta_ref)
        return self._watch

    def restage(self):
        """Recompute the moment parts from ALL stored raw data in one shot
        — the O(history) path a stream exists to avoid. Kept as the A/B
        baseline, the oracle's reference, and the drift bound for f32
        streams. Returns fresh ``(fixed, res)`` parts; the accumulated
        state is untouched."""
        if self._cap == 0:
            with self._ctx():
                return self._zero_parts()
        nb = self._cap            # already rung-snapped by _grow_store
        kernel = self._kernel(nb)
        st = self._store
        with self._ctx():
            zero_f, zero_r = self._zero_parts()
            args = tuple(self._put(a) for a in (
                st["t"], st["mask"], st["sigma2"], st["freqs"],
                st["eidx"].astype(np.int32), st["ecorr"], st["r"]))
            fixed, res = kernel(zero_f, zero_r, args[0], args[1], args[2],
                                args[3], args[4], args[5], args[6])
            jax.block_until_ready(fixed["M"])
        return fixed, res

    def restage_moments(self):
        """Finished moments from a fresh :meth:`restage` (the append-vs-
        restage oracle's reference side)."""
        fixed, res = self.restage()
        fin = self._finish_fn()
        with self._ctx():
            return fin(fixed, res)

    @property
    def tspan(self) -> float:
        """The frozen common-grid span (seconds) this stream is pinned to
        — the quantity a migration cutover widens."""
        return self._tspan

    def raw_data(self) -> dict:
        """The host raw store, trimmed to capacity, plus per-pulsar counts
        — the migration-cutover export (docs/STREAMING.md). Absolute TOAs
        by design: the block is replayable onto ANY wider frozen-grid
        template via one bulk :meth:`append`, which is what makes the
        gateway's cutover protocol a restage rather than a reinterpret."""
        cap = self._cap
        if cap == 0:
            z = np.zeros((self.npsr, 0), dtype=np.float64)
            return {"t": z, "r": z.copy(), "sigma2": z.copy(),
                    "freqs": z.copy(), "ecorr": z.copy(),
                    "counts": np.zeros(self.npsr, dtype=np.int64)}
        st = self._store
        return {"t": st["t"][:, :cap].copy(),
                "r": st["r"][:, :cap].copy(),
                "sigma2": st["sigma2"][:, :cap].copy(),
                "freqs": st["freqs"][:, :cap].copy(),
                "ecorr": st["ecorr"][:, :cap].copy(),
                "counts": self._n.copy()}

    def batch_view(self):
        """The accumulated data as a PulsarBatch on the FROZEN grids — the
        posterior-refresh input (``fakepta_tpu.sample`` consumes it).
        ECORR epoch ids are densified per pulsar (grouping is all the
        Sherman-Morrison correction needs)."""
        if self._cap == 0:
            raise ValueError("stream has no data yet")
        st = self.template
        cap = self._cap
        t_abs = self._store["t"]
        mask = self._store["mask"]
        eidx = np.zeros((self.npsr, cap), dtype=np.int32)
        if self.ecorr_dt is not None:
            for p in range(self.npsr):
                n = int(self._n[p])
                if n:
                    uniq, inv = np.unique(self._store["eidx"][p, :n],
                                          return_inverse=True)
                    eidx[p, :n] = inv.astype(np.int32)
        dt = np.asarray(st.t_own).dtype
        return dataclasses.replace(
            st,
            t_own=jnp.asarray(t_abs * self._df_own[:, None], dt),
            t_common=jnp.asarray(t_abs / self._tspan, dt),
            mask=jnp.asarray(mask),
            freqs=jnp.asarray(self._store["freqs"], dt),
            sigma2=jnp.asarray(np.where(mask, self._store["sigma2"], 1.0),
                               dt),
            epoch_idx=jnp.asarray(eidx),
            ecorr_amp=jnp.asarray(self._store["ecorr"], dt),
            sys_psd=jnp.zeros((self.npsr, 1, 1), dt),
            sys_mask=jnp.zeros((self.npsr, 1, cap), dtype=bool))

    def residuals_view(self) -> np.ndarray:
        """(P, cap) masked residuals aligned with :meth:`batch_view`."""
        return self._store["r"] * self._store["mask"]

    def stats(self) -> dict:
        """The served ``StreamRequest`` payload: totals, bucket state, and
        the last rolling-detection numbers."""
        out = {
            "schema": STREAM_SCHEMA,
            "appends": int(self.appends),
            "n_toas": int(self._n.sum()),
            "npsr": int(self.npsr),
            "capacity": int(self._cap),
            "epoch_capacity": int(self._ecap),
            "rebuckets": int(self.rebuckets),
            "compiles": int(self.compiles),
            "recompiles": int(self.recompiles),
            "rolled_back": int(self.rolled_back),
        }
        if self.last_stats is not None:
            for key in ("snr", "amp2", "significance_sigma", "latency_ms"):
                if key in self.last_stats:
                    out[key] = self.last_stats[key]
        return out
