"""Golden-run harness: one bench-schema row per registered scenario.

``golden_run(name)`` exercises a scenario through the repo's production
lanes — ensemble simulation (steady real/s/chip, ``peak_hbm_bytes``,
recovery counters), the batched-MCMC sampler (ESS/s/chip), the serving
scheduler (SLO latencies), and the telescope-cadence streaming tail
(append latencies, append≡restage oracle, zero-recompile contract) — and
emits ONE flat JSON row in the bench.py schema: the standard metric keys
every lane already declares directions for, plus the scenario headline
keys (``scenario``, ``scn_real_per_s_per_chip``, ``scn_ess_per_s_per_chip``,
``scn_peak_hbm_bytes``, ``scn_append_p99_ms`` — bench.py docstring).
``obs summarize|compare|gate`` consume the row without special-casing;
the gate bands it only against same-scenario, same-platform history
(:mod:`fakepta_tpu.obs.gate`).

``memory_lane()`` is the scaling check: sweep n_psr at fixed chunk under
``psr`` sharding and assert the memwatch watermark tracks the analytic
``chunk_bytes_model`` within :data:`MEM_BOUND_FACTOR` up to the
``ska_10k`` point (the donated-buffer depth bound is asserted in-run by
the engine's ``PackedLedger`` — a violated ring raises, it never
reports). docs/SCENARIOS.md states the full contract.

Sizes: the CPU stand-in runs each scenario's :meth:`Scenario.reduced`
rendition (rows disambiguate by ``platform``, as everywhere); an
accelerator runs the full spec. All knobs are parameters so the tier-1
smoke tests can run the whole harness in seconds.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Sequence

import numpy as np

from . import cadence as cadence_mod
from . import registry

#: Declared memory-lane bound: per-device peak-HBM watermark must stay
#: within this factor of the engine's analytic per-device
#: ``model_bytes_per_chunk`` at every sweep point. The slack covers what
#: the chunk model deliberately excludes — the resident batch arrays,
#: basis/phi staging, executable workspace — which are O(npsr * ntoa),
#: not O(chunk), so the factor SHRINKS toward 1 as the sweep grows: the
#: watermark tracking the model through the ``ska_10k`` endpoint is
#: exactly the claim under test.
MEM_BOUND_FACTOR = 3.0

#: Oracle tolerance for the cadence stream lane: the f64 append
#: accumulation vs a full restage of the same store (summation-order
#: differences only; mirrors tests/test_stream.py's 1e-8).
ORACLE_RTOL = 1e-7


def _platform() -> str:
    from ..tune import fingerprint
    return fingerprint().platform


def _percentile(vals: Sequence[float], q: float) -> float:
    # fakepta: allow[dtype-policy] host latency stats, never on device
    return float(np.percentile(np.asarray(vals, dtype=np.float64), q)) \
        if len(vals) else 0.0


def cadence_stream_lane(scn, *, mesh=None, history_frac: float = 0.85,
                        max_blocks: Optional[int] = 12,
                        nbin: int = 8, seed: int = 0) -> dict:
    """Drive a stream with the scenario's telescope-cadence append tail.

    Bulk history (everything before ``history_frac``) stages first; the
    cadence tail then replays as uneven observing-window blocks — silent
    windows, varying widths, multi-backend epochs. Contract checked here:

    - **append ≡ restage**: the accumulated device moments match a full
      recompute from the raw store (:data:`ORACLE_RTOL`);
    - **zero recompiles**: new bucket rungs compile once (``compiles``),
      but no kernel key is ever re-traced (``recompiles == 0``) — the
      ladder covers the cadence's block-size mix.

    Returns the bench-row fragment (``append_latency_ms``,
    ``scn_append_p99_ms``, ``stream_*`` shape facts, ``oracle_ok``).
    """
    import jax.numpy as jnp

    from ..stream.state import StreamState, default_stream_model
    from ..utils.compat import enable_x64

    # fakepta: allow[dtype-policy] host stage: StreamState raw-store grids
    with enable_x64():
        # fakepta: allow[dtype-policy] f64 template for the stream store
        template, _, _, _ = scn.batch_parts(dtype=jnp.float64)
    ecorr_dt = (scn.ecorr_dt_days * cadence_mod.DAY_S
                if scn.ecorr else None)
    stream = StreamState(template, default_stream_model(nbin=nbin),
                         ecorr_dt=ecorr_dt, mesh=mesh)

    rng = np.random.default_rng((seed, 0xA99))
    hist = cadence_mod.history_block(scn, history_frac)
    stream.append(hist.toas, rng.normal(0.0, scn.toaerr, hist.toas.shape),
                  freqs=hist.freqs, counts=hist.counts)

    blocks = cadence_mod.append_schedule(scn, history_frac,
                                         max_blocks=max_blocks)
    latencies = []
    for blk in blocks:
        res = rng.normal(0.0, scn.toaerr, blk.toas.shape)
        stats = stream.append(blk.toas, res, freqs=blk.freqs,
                              counts=blk.counts)
        latencies.append(stats["latency_ms"])

    got = [np.asarray(x) for x in stream.moments()]
    want = [np.asarray(x) for x in stream.restage_moments()]
    oracle_ok = True
    for g, w in zip(got, want):
        scale = np.max(np.abs(w)) or 1.0
        if not np.allclose(g, w, rtol=ORACLE_RTOL,
                           atol=ORACLE_RTOL * scale):
            oracle_ok = False
    return {
        "append_latency_ms": round(_percentile(latencies, 50), 3),
        "scn_append_p99_ms": round(_percentile(latencies, 99), 3),
        "stream_appends": int(stream.appends),
        "stream_toas": int(np.sum(stream._n)),
        "stream_rebuckets": int(stream.rebuckets),
        "stream_recompiles": int(stream.recompiles),
        "stream_compiles": int(stream.compiles),
        "oracle_ok": bool(oracle_ok),
    }


def golden_run(name: str, *, mesh=None, reduced: Optional[bool] = None,
               nreal: int = 64, chunk: int = 32,
               sample_steps: int = 96, sample_warmup: int = 48,
               sample_chains: int = 8, serve_requests: int = 32,
               max_append_blocks: Optional[int] = 12,
               skip: Sequence[str] = (), seed: int = 1,
               report_path=None) -> dict:
    """Run one scenario through every lane; return the bench-schema row.

    ``skip`` drops lanes by name (``"sample"``, ``"serve"``,
    ``"stream"``) — the ensemble lane always runs (it IS the scenario).
    ``reduced=None`` auto-reduces on the CPU stand-in. ``report_path``
    additionally saves the ensemble lane's RunReport .jsonl — the
    artifact ``obs summarize``/``compare``/``trace`` consume.
    """
    import jax

    scn_full = registry.get(name)
    platform = _platform()
    if reduced is None:
        reduced = platform == "cpu"
    scn = scn_full.reduced() if reduced else scn_full

    from ..parallel.mesh import make_mesh
    if mesh is None:
        mesh = make_mesh(jax.devices())
    n_devices = int(np.prod(list(mesh.shape.values())))

    # --- ensemble lane (always): the scenario materialized through the
    # ordinary EnsembleSimulator path — spec-hash identity and the
    # memwatch/ledger/fault machinery all engage exactly as in bench.py
    sim = scn.build(mesh=mesh)
    warm = sim.run(chunk, seed=99, chunk=chunk)
    out = sim.run(nreal, seed=seed, chunk=chunk)
    if out["curves"].shape[0] != nreal or \
            not np.all(np.isfinite(out["curves"])):
        raise RuntimeError(f"scenario {name}: wrong-shaped or non-finite "
                           f"ensemble output")
    rep = out["report"]
    rep_sum = rep.summary()
    steady = round(rep.steady_real_per_s_per_chip(), 2)
    row = {
        "metric": f"scenario golden run ({name})",
        "value": steady,
        "unit": "realizations/s/chip",
        "platform": platform,
        "scenario": name,
        "spec_hash": scn_full.spec_hash(),
        "compile_s": round(warm["report"].compile_s, 3),
        "steady_real_per_s_per_chip": steady,
        "scn_real_per_s_per_chip": steady,
        "retraces": rep.retraces,
        "pipeline_depth": rep_sum.get("pipeline_depth", 0),
        "pipeline_stall_s": rep_sum.get("pipeline_stall_s", 0.0),
        "ckpt_wait_s": rep_sum.get("ckpt_wait_s", 0.0),
    }
    if rep_sum.get("model_bytes_per_chunk"):
        row["model_bytes_per_chunk"] = rep_sum["model_bytes_per_chunk"]
    if rep_sum.get("peak_hbm_bytes"):
        row["peak_hbm_bytes"] = rep_sum["peak_hbm_bytes"]
        row["scn_peak_hbm_bytes"] = rep_sum["peak_hbm_bytes"]
    for key, counter in (("faults_retries", "faults.retries"),
                         ("faults_degradations", "faults.degradations"),
                         ("faults_rollbacks", "faults.rollbacks")):
        row[key] = int(rep.counters.get(counter, 0))
    if report_path is not None:
        rep.meta.setdefault("scenario", name)
        rep.meta.setdefault("platform", platform)
        rep.save(report_path)

    # --- sampler lane: the CURN free-spectrum posterior on the
    # scenario's array (bench.py's sampling-lane recipe, scenario batch)
    if "sample" not in skip:
        from ..infer import ComponentSpec, FreeParam, LikelihoodSpec
        from ..sample import SampleSpec, SamplingRun
        batch = sim.batch
        s_model = LikelihoodSpec(components=(
            ComponentSpec(target="red", spectrum="batch"),
            ComponentSpec(target="dm", spectrum="batch"),
            ComponentSpec(target="curn", nbin=min(6, scn.gwb_ncomp or 6),
                          spectrum="free_spectrum", free=(
                              FreeParam("log10_rho", (-9.0, -5.0),
                                        per_bin=True),)),
        ))
        s_spec = SampleSpec(model=s_model, n_chains=sample_chains,
                            n_temps=2, step_size=0.35, n_leapfrog=10,
                            thin=2, warmup=sample_warmup)
        s_out = SamplingRun(batch, s_spec, mesh=mesh, data_seed=7).run(
            sample_steps, seed=7, segment=min(sample_steps, 64))
        for key in ("ess_per_s_per_chip", "rhat_max", "accept_rate"):
            if key in s_out["summary"]:
                row[key] = s_out["summary"][key]
        row["scn_ess_per_s_per_chip"] = row.get("ess_per_s_per_chip", 0.0)

    # --- serving lane: the scenario's nearest ArraySpec family through
    # the warm pool + coalescing scheduler (SLO latencies, bit-verified)
    if "serve" not in skip:
        from ..serve import ServeConfig, run_loadgen
        serve_spec = scn.serve_spec()
        buckets = tuple(b for b in (max(1, n_devices), 16, 128)
                        if b % n_devices == 0) or (n_devices,)
        serve_row = run_loadgen(
            spec=serve_spec, mesh=mesh, n_requests=serve_requests,
            sizes=(1, 2, 4), kind="sim", baseline=False, verify=1,
            seed=5, config=ServeConfig(buckets=buckets))
        for key in ("serve_qps_per_chip", "serve_p50_ms", "serve_p99_ms",
                    "coalesce_factor", "pad_waste_frac", "serve_retraces",
                    "serve_steady_compiles"):
            if key in serve_row:
                row[key] = serve_row[key]

    # --- streaming lane: the scenario's own cadence tail as append
    # traffic (oracle + zero-recompile contract enforced here)
    if "stream" not in skip:
        stream_row = cadence_stream_lane(scn, mesh=None,
                                         max_blocks=max_append_blocks)
        if not stream_row.pop("oracle_ok"):
            raise RuntimeError(f"scenario {name}: append/restage oracle "
                               f"diverged beyond rtol={ORACLE_RTOL}")
        if stream_row["stream_recompiles"]:
            raise RuntimeError(
                f"scenario {name}: {stream_row['stream_recompiles']} "
                f"unexpected stream recompile(s) under the cadence tail "
                f"(the bucket ladder stopped covering the traffic)")
        row.update(stream_row)

    return row


def memory_lane(name: str = "ska_10k", *, chunk: int = 32,
                sweep: Optional[Sequence[int]] = None,
                psr_shards: Optional[int] = None,
                ntoa_cap: Optional[int] = None,
                bound_factor: float = MEM_BOUND_FACTOR,
                seed: int = 5) -> dict:
    """Peak-HBM watermark vs n_psr at fixed chunk under ``psr`` sharding.

    Each sweep point rebuilds the scenario at that population size (same
    cadence, same noise menu), runs one chunk through the ordinary
    engine, and compares the memwatch watermark (``peak_hbm_bytes`` —
    allocator stats on an accelerator, the static-reservation + packed-
    ledger model on the CPU stand-in) against the engine's analytic
    per-device ``model_bytes_per_chunk``. The contract
    (docs/SCENARIOS.md): ``ratio = peak / model <= bound_factor`` at
    EVERY point through the scenario's endpoint — memory scales with the
    model, not with hidden O(npsr^2) residents. The engine's
    ``PackedLedger`` separately asserts the donated-buffer depth bound
    in-run (a violated ring raises).
    """
    import jax

    from ..parallel.mesh import make_mesh

    scn_full = registry.get(name)
    platform = _platform()
    base = (scn_full.reduced(max_psr=registry.REDUCED_MAX_PSR_MEM)
            if platform == "cpu" else scn_full)
    if ntoa_cap is not None and base.cadence != "uniform":
        import math
        base = dataclasses.replace(
            base, cadence_thin=max(base.cadence_thin, math.ceil(
                base.ntoa / ntoa_cap)))
    n_dev = len(jax.devices())
    if psr_shards is None:
        psr_shards = max(d for d in (8, 4, 2, 1) if n_dev % d == 0)
    if sweep is None:
        sweep = sorted({n for n in (psr_shards, 2 * psr_shards,
                                    4 * psr_shards, base.npsr)
                        if n <= base.npsr and n % psr_shards == 0})
    mesh = make_mesh(jax.devices(), psr_shards=psr_shards)
    points = []
    for n in sweep:
        scn_n = dataclasses.replace(base, npsr=int(n))
        sim = scn_n.build(mesh=mesh)
        out = sim.run(chunk, seed=seed, chunk=chunk)
        rep_sum = out["report"].summary()
        peak = float(rep_sum.get("peak_hbm_bytes") or 0.0)
        model = float(rep_sum.get("model_bytes_per_chunk") or 0.0)
        ratio = peak / model if model else float("inf")
        points.append({
            "npsr": int(n), "chunk": int(chunk),
            "peak_hbm_bytes": peak, "model_bytes_per_chunk": model,
            "ratio": round(ratio, 3),
            "ok": bool(model and ratio <= bound_factor),
        })
    return {
        "scenario": name, "platform": platform,
        "psr_shards": int(psr_shards), "chunk": int(chunk),
        "bound_factor": float(bound_factor),
        "points": points,
        "ok": bool(points) and all(p["ok"] for p in points),
    }


def save_row(row: dict, path) -> None:
    """One bench-schema JSON line — the exact artifact ``python -m
    fakepta_tpu.obs gate`` loads."""
    with open(path, "w") as fh:
        fh.write(json.dumps(row) + "\n")
