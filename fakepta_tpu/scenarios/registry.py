"""Declarative, hashable IPTA-scale scenario specs (ROADMAP item 2).

A :class:`Scenario` is the registry's unit of meaning: one frozen,
JSON-expressible description of a PTA dataset — population size and
geometry seed, timespan, a telescope-cadence arrival process
(:mod:`.cadence`), the per-family noise menu (red/DM/chromatic GPs,
per-backend ECORR and system-noise bands), the GWB (including the
healpix anisotropic ORF machinery, ``ops/gwb.py``), per-realization
*population* draws (noise hyperpriors, white/ECORR hyperpriors, CGW
source populations, BayesEphem nuisances), and nothing about dispatch —
chunk sizes, meshes and bucket ladders stay where they live
(``fakepta_tpu.tune``).

Identity works like every other spec in the repo:
:meth:`Scenario.spec_hash` rides :func:`fakepta_tpu.obs.flightrec
.spec_hash` over :meth:`Scenario.spec_dict`, so scenario artifacts
(golden rows, checkpoints, tuned configs, served pools) group by
configuration the same way ``ArraySpec`` artifacts do, and
materialization goes through the ordinary
:class:`~fakepta_tpu.parallel.montecarlo.EnsembleSimulator` constructor —
tuning, serving, checkpointing and the flight recorder all just work.

``SCENARIOS`` holds the named entries (``flagship_100``, ``ng15``,
``ipta_dr3``, ``ska_10k``); :func:`register` adds more. Scenario
definitions are single-sourced here — the ``unregistered-scenario``
analysis rule flags flagship-scale ``ArraySpec``/``synthetic`` literals
anywhere else in library or bench code (docs/INVARIANTS.md).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

from ..obs import flightrec

# spec-dict discriminator (shared namespace with ArraySpec's "kind")
_KIND = "Scenario"

#: CPU-stand-in reduction targets (:meth:`Scenario.reduced`): the largest
#: array a virtual-device CPU mesh materializes in seconds rather than
#: hours. Reduced rows stay named (the ``scenario`` row key) and are
#: disambiguated by ``platform`` exactly like every other stand-in figure.
REDUCED_MAX_PSR = 16
REDUCED_MAX_TOA = 160
#: The memory-scaling lane's endpoint reduction keeps more pulsars (the
#: sweep needs headroom over its smaller points) at a very sparse cadence.
REDUCED_MAX_PSR_MEM = 64


def _powlaw_psd(tspan_s: float, nbin: int, log10_A: float,
                gamma: float) -> np.ndarray:
    from .. import spectrum as spectrum_lib
    f = np.arange(1, nbin + 1) / tspan_s
    return np.asarray(spectrum_lib.powerlaw(f, log10_A, gamma))


def _anis_h_map(nside: int, seed: int) -> np.ndarray:
    """Deterministic anisotropic GWB power map on a healpix grid:
    isotropic baseline plus a seeded dipole-dominated modulation —
    enough structure to light the existing ``ops/gwb.anisotropic_orf``
    machinery without pretending to a physical sky model."""
    from ..ops import healpix

    npix = 12 * nside * nside
    vecs = healpix.pixel_directions(npix)
    rng = np.random.default_rng((seed, 0xA215))
    direction = rng.normal(size=3)
    direction /= np.linalg.norm(direction)
    amp = rng.uniform(0.3, 0.7)
    h_map = 1.0 + amp * vecs @ direction
    return h_map * (npix / h_map.sum())      # mean-1 normalization


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One registered PTA scenario (module docstring). All fields are
    JSON-expressible primitives/tuples so :meth:`spec_hash` is stable and
    the CLI can ``describe`` a scenario without building anything."""

    name: str
    description: str = ""

    # -- population / geometry ------------------------------------------
    npsr: int = 100
    tspan_years: float = 15.0
    toaerr: float = 1e-7
    data_seed: int = 0
    #: cadence family (:data:`fakepta_tpu.scenarios.cadence.CADENCES`);
    #: "uniform" materializes through ``PulsarBatch.synthetic`` so the
    #: flagship scenario is bit-identical to the historical flagship batch
    cadence: str = "uniform"
    #: uniform-cadence TOA count (telescope cadences derive their own)
    ntoa: int = 780
    #: cadence thinning multiplier (the reduced/stand-in knob: same
    #: arrival process, sparser sampling)
    cadence_thin: int = 1

    # -- per-pulsar noise menu ------------------------------------------
    n_red: int = 30
    n_dm: int = 100
    n_chrom: int = 0
    red_log10_A: float = -14.0
    red_gamma: float = 13.0 / 3.0
    dm_log10_A: float = -13.8
    dm_gamma: float = 3.0
    chrom_log10_A: Optional[float] = None
    chrom_gamma: float = 3.0
    #: per-backend ECORR epochs (telescope cadences only)
    ecorr: bool = False
    log10_ecorr: float = -7.0
    ecorr_dt_days: float = 1.0
    #: per-backend system-noise bands (0 = off; telescope cadences only)
    n_sys: int = 0
    sys_log10_A: float = -14.5
    sys_gamma: float = 2.5

    # -- per-realization population draws -------------------------------
    #: red-noise hyperprior ((log10_A lo, hi), (gamma lo, hi)) or None
    red_draws: Optional[Tuple[Tuple[float, float],
                              Tuple[float, float]]] = None
    #: per-(pulsar, backend) efac/equad hyperprior draws
    white_draws: bool = False
    #: per-realization circular-SMBHB source population (CGWSampling)
    cgw_population: bool = False
    cgw_log10_h: Tuple[float, float] = (-14.5, -13.5)
    cgw_log10_fgw: Tuple[float, float] = (-8.5, -7.5)
    #: BayesEphem nuisance sampling (Jupiter-mass scale draw per
    #: realization, RoemerSampling)
    ephem_draws: bool = False
    ephem_s_mass: float = 1.5e23    # ~1e-4 M_jup [kg], BayesEphem scale

    # -- GWB -------------------------------------------------------------
    gwb_log10_A: float = float(np.log10(2e-15))
    gwb_gamma: float = 13.0 / 3.0
    gwb_ncomp: int = 30
    #: '' disables the common signal; 'anisotropic' uses the healpix map
    gwb_orf: str = "hd"
    gwb_nside: int = 0
    gwb_anis_seed: int = 0

    # -- identity --------------------------------------------------------
    def spec_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["kind"] = _KIND
        return d

    def spec_hash(self) -> str:
        """Stable identity (the flight-recorder hash over the spec dict) —
        the same grouping key serve/tune/checkpoint artifacts use."""
        return flightrec.spec_hash(self.spec_dict())

    # -- scaling ---------------------------------------------------------
    def reduced(self, max_psr: int = REDUCED_MAX_PSR,
                max_toa: int = REDUCED_MAX_TOA) -> "Scenario":
        """The CPU-stand-in rendition: same scenario name, same noise
        menu, same cadence *family*, proportionally fewer pulsars/TOAs
        (multiples of 8, for the psr/toa mesh axes). A reduced row still
        carries ``scenario=<name>``; ``platform`` disambiguates, as
        everywhere (bench.py docstring)."""
        if self.npsr <= max_psr and self.ntoa <= max_toa:
            return self
        npsr = max(8, min(self.npsr, max_psr) // 8 * 8)
        ntoa = max(32, min(self.ntoa, max_toa) // 8 * 8)
        # telescope cadences thin instead of shrinking the span: epoch
        # count scales ~ tspan/cadence, so the thinning factor is the
        # TOA ratio (rounded up) — gaps and seams survive the reduction
        thin = self.cadence_thin
        if self.cadence != "uniform":
            import math
            thin = max(thin, math.ceil(self.ntoa / ntoa))
        return dataclasses.replace(
            self, npsr=npsr, ntoa=ntoa, cadence_thin=thin,
            n_red=min(self.n_red, 16), n_dm=min(self.n_dm, 16),
            n_chrom=min(self.n_chrom, 8),
            n_sys=min(self.n_sys, 8),
            gwb_ncomp=min(self.gwb_ncomp, 16))

    # -- materialization -------------------------------------------------
    def batch_parts(self, dtype=None):
        """``(batch, toas_abs, backend_id, n_backends)`` — the cadence- or
        synthetic-path batch plus the companions the sampling lanes need
        (absolute float64 epochs, per-TOA backend ids)."""
        from . import cadence as cadence_mod

        if self.cadence != "uniform":
            return cadence_mod.build_batch(self, dtype=dtype)
        import jax.numpy as jnp

        from ..batch import PulsarBatch

        kw = {} if dtype is None else {"dtype": dtype}
        batch = PulsarBatch.synthetic(
            npsr=self.npsr, ntoa=self.ntoa, tspan_years=self.tspan_years,
            toaerr=self.toaerr, n_red=self.n_red, n_dm=self.n_dm,
            **({"n_chrom": self.n_chrom,
                "chrom_log10_A": self.chrom_log10_A,
                "chrom_gamma": self.chrom_gamma} if self.n_chrom else {}),
            red_log10_A=self.red_log10_A, red_gamma=self.red_gamma,
            dm_log10_A=self.dm_log10_A, dm_gamma=self.dm_gamma,
            seed=self.data_seed, **kw)
        span = float(batch.tspan_common)
        toas_abs = np.tile(
            cadence_mod.MJD0_S + np.linspace(0.0, span, self.ntoa),
            (self.npsr, 1))
        backend_id = np.zeros((self.npsr, batch.max_toa), dtype=np.int32)
        return batch, toas_abs, backend_id, 1

    def sim_kwargs(self, batch, toas_abs, backend_id, n_backends) -> dict:
        """The ``EnsembleSimulator`` constructor kwargs this scenario's
        menu implies (GWB config incl. anisotropic h_map, population
        draws, BayesEphem sampling). Everything rides the ordinary
        constructor — no scenario-only code path in the engine."""
        from ..parallel.montecarlo import (CGWSampling, GWBConfig,
                                           NoiseSampling, RoemerSampling,
                                           WhiteSampling)

        kw: dict = {}
        if self.gwb_orf:
            tspan = float(batch.tspan_common)
            psd = _powlaw_psd(tspan, self.gwb_ncomp, self.gwb_log10_A,
                              self.gwb_gamma)
            h_map = None
            if self.gwb_orf == "anisotropic":
                nside = self.gwb_nside or 4
                h_map = _anis_h_map(nside, self.gwb_anis_seed)
            kw["gwb"] = GWBConfig(psd=psd, orf=self.gwb_orf, h_map=h_map)
        noise_samples = []
        if self.red_draws is not None:
            noise_samples.append(NoiseSampling(
                "red", log10_A=tuple(self.red_draws[0]),
                gamma=tuple(self.red_draws[1])))
        if noise_samples:
            kw["noise_sample"] = noise_samples
        if self.white_draws:
            kw["white_sample"] = WhiteSampling(
                efac=(0.5, 2.5), log10_tnequad=(-8.0, -5.0))
            kw["toaerr2"] = np.full(
                (batch.npsr, batch.max_toa), self.toaerr ** 2)
            kw["backend_id"] = backend_id
        if self.cgw_population:
            kw["cgw_sample"] = CGWSampling(
                log10_h=tuple(self.cgw_log10_h),
                log10_fgw=tuple(self.cgw_log10_fgw))
        if self.ephem_draws:
            kw["roemer_sample"] = RoemerSampling(
                "jupiter", s_mass=self.ephem_s_mass)
        if self.cgw_population or self.ephem_draws:
            kw["toas_abs"] = toas_abs
        return kw

    def build(self, mesh=None, compile_cache_dir=None, dtype=None):
        """Construct the :class:`EnsembleSimulator` this scenario
        describes — the one engine entry point, so spec-hash identity,
        tuning, serving and checkpointing behave exactly as they do for
        any hand-built simulator."""
        from ..parallel.montecarlo import EnsembleSimulator

        batch, toas_abs, backend_id, n_backends = self.batch_parts(
            dtype=dtype)
        kw = self.sim_kwargs(batch, toas_abs, backend_id, n_backends)
        return EnsembleSimulator(batch, mesh=mesh,
                                 compile_cache_dir=compile_cache_dir, **kw)

    def serve_spec(self, reduced: bool = False):
        """The closest :class:`~fakepta_tpu.serve.spec.ArraySpec` — the
        JSON-routable serve identity for this scenario's array family
        (richer menus serve through ``ServePool.register`` with a
        prebuilt simulator; the chaos/fleet lanes only need the spec
        family)."""
        from ..serve.spec import ArraySpec

        scn = self.reduced() if reduced else self
        return ArraySpec(
            npsr=scn.npsr, ntoa=scn.ntoa, tspan_years=scn.tspan_years,
            toaerr=scn.toaerr, n_red=scn.n_red, n_dm=scn.n_dm,
            data_seed=scn.data_seed, gwb_log10_A=scn.gwb_log10_A,
            gwb_gamma=scn.gwb_gamma, gwb_ncomp=scn.gwb_ncomp,
            gwb_orf=scn.gwb_orf if scn.gwb_orf in
            ("", "hd", "curn", "monopole", "dipole") else "hd")

    def est_cost(self, chunk: int = 1024) -> dict:
        """Analytic per-chunk cost estimate (no device work): the HBM
        traffic model (``ops/megakernel.chunk_bytes_model``) at this
        scenario's array shape — the ``describe``/docs cost column."""
        from ..ops.megakernel import chunk_bytes_model

        if self.cadence == "uniform":
            ntoa = self.ntoa
        else:
            from .cadence import CADENCES
            fastest = min(t.cadence_days for t in CADENCES[self.cadence])
            # ~1.5 telescope/band tracks per pulsar on the fastest cadence
            ntoa = max(32, int(self.tspan_years * 365.25
                               / (fastest * self.cadence_thin) * 1.5))
        k_coef = 2 * (self.n_red + self.n_dm + self.n_chrom
                      + self.gwb_ncomp)
        return {
            "model_bytes_per_chunk": chunk_bytes_model(
                chunk, self.npsr, ntoa, k_coef),
            "array_values": self.npsr * ntoa,
            "est_ntoa": ntoa,
        }


def _flagship() -> Scenario:
    return Scenario(
        name="flagship_100",
        description="The historical flagship: 100 psr x 15 yr, weekly "
                    "uniform cadence, white + red + DM noise, HD GWB — "
                    "bit-identical to the bench.py north-star config.",
    )


def _ng15() -> Scenario:
    return Scenario(
        name="ng15",
        description="NANOGrav-15yr-like: 68 psr x 16 yr on the ng15 "
                    "telescope cadence (Arecibo collapse at 85% of the "
                    "span), per-backend ECORR + system bands, chromatic "
                    "noise, white hyperprior draws, HD GWB.",
        npsr=68, tspan_years=16.0, cadence="ng15", ntoa=280,
        n_red=30, n_dm=30, n_chrom=15, chrom_log10_A=-14.2,
        ecorr=True, n_sys=10, white_draws=True,
        gwb_log10_A=float(np.log10(2.4e-15)), data_seed=15)


def _ipta_dr3() -> Scenario:
    return Scenario(
        name="ipta_dr3",
        description="IPTA-DR3-like: 120 psr x 25 yr over five "
                    "observatories (staggered commissioning, maintenance "
                    "gaps, legacy retirements), anisotropic GWB on a "
                    "healpix nside=4 map, per-pulsar red hyperprior "
                    "draws, CGW source population, BayesEphem nuisances.",
        npsr=120, tspan_years=25.0, cadence="ipta", ntoa=400,
        n_red=30, n_dm=30, ecorr=True, n_sys=10,
        red_draws=((-17.0, -13.0), (1.0, 5.0)),
        cgw_population=True, ephem_draws=True,
        gwb_orf="anisotropic", gwb_nside=4, gwb_anis_seed=3,
        data_seed=33)


def _ska_10k() -> Scenario:
    return Scenario(
        name="ska_10k",
        description="SKA-era scale-out: 10,000 psr x 30 yr at monthly "
                    "SKA cadence, lean per-pulsar noise menu, CURN "
                    "common signal — the memory-scaling lane's endpoint "
                    "(peak-HBM vs n_psr under psr sharding).",
        npsr=10_000, tspan_years=30.0, cadence="ska", ntoa=360,
        toaerr=3e-8, n_red=10, n_dm=10, gwb_ncomp=10, gwb_orf="curn",
        data_seed=77)


SCENARIOS: Dict[str, Scenario] = {
    s.name: s for s in (_flagship(), _ng15(), _ipta_dr3(), _ska_10k())
}


def register(scenario: Scenario) -> Scenario:
    """Add a scenario to the registry (idempotent for identical specs;
    re-registering a name with a *different* spec raises — names are
    identities, docs/SCENARIOS.md)."""
    existing = SCENARIOS.get(scenario.name)
    if existing is not None and existing.spec_hash() != scenario.spec_hash():
        raise ValueError(
            f"scenario {scenario.name!r} is already registered with a "
            f"different spec (hash {existing.spec_hash()} != "
            f"{scenario.spec_hash()}); pick a new name")
    SCENARIOS[scenario.name] = scenario
    return scenario


def get(name: str) -> Scenario:
    if name not in SCENARIOS:
        raise KeyError(f"unknown scenario {name!r}; "
                       f"known: {sorted(SCENARIOS)}")
    return SCENARIOS[name]


def names() -> Tuple[str, ...]:
    return tuple(SCENARIOS)


def flagship_batch(dtype=None):
    """The flagship batch, registry-sourced — the single construction
    path bench.py/benchmarks use (the ``unregistered-scenario`` rule
    keeps ad-hoc flagship-scale literals out of library/bench code)."""
    return get("flagship_100").batch_parts(dtype=dtype)[0]
