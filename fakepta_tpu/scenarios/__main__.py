"""Entry point: ``python -m fakepta_tpu.scenarios list|describe|run``.

``list`` prints the registry (name, scale, cadence, spec hash, analytic
cost estimate); ``describe NAME`` the full spec dict plus the reduced
CPU-stand-in shape; ``run NAME`` executes the golden-run harness
(:mod:`.golden`) and prints the bench-schema row as one JSON line —
pipe it to a file and band it with ``python -m fakepta_tpu.obs gate``.
``run NAME --memory-lane`` runs the psr-sharded memory-scaling sweep
instead and exits 1 when any point violates the declared bound. Exit
codes mirror ``fakepta_tpu.obs``: 0 ok, 1 contract violation under
``--check``, 2 usage errors.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import registry


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m fakepta_tpu.scenarios",
        description="IPTA-scale scenario registry + golden-run suite "
                    "(docs/SCENARIOS.md)")
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("list", help="print the registered scenarios")

    desc = sub.add_parser("describe", help="print one scenario's full "
                                           "spec, hash and cost estimate")
    desc.add_argument("name")

    run = sub.add_parser("run", help="golden-run one scenario; prints the "
                                     "bench-schema JSON row")
    run.add_argument("name")
    run.add_argument("--out", default=None,
                     help="also write the row (one JSON line) here — the "
                          "artifact `obs gate` loads")
    run.add_argument("--report", default=None,
                     help="also save the ensemble lane's RunReport "
                          ".jsonl — the artifact `obs summarize|compare|"
                          "trace` load")
    run.add_argument("--full", action="store_true",
                     help="run the full-size spec even on the CPU "
                          "stand-in (default: reduced off-accelerator)")
    run.add_argument("--nreal", type=int, default=64)
    run.add_argument("--chunk", type=int, default=32)
    run.add_argument("--sample-steps", type=int, default=96)
    run.add_argument("--serve-requests", type=int, default=32)
    run.add_argument("--skip", action="append", default=[],
                     choices=("sample", "serve", "stream"),
                     help="drop a lane (repeatable); the ensemble lane "
                          "always runs")
    run.add_argument("--memory-lane", action="store_true",
                     help="run the psr-sharded memory-scaling sweep "
                          "instead of the golden lanes")
    run.add_argument("--check", action="store_true",
                     help="exit 1 when a contract (memory bound) fails "
                          "instead of just reporting")
    return parser


def _cmd_list() -> int:
    print(f"{'scenario':<14} {'npsr':>6} {'yrs':>5} {'cadence':<8} "
          f"{'hash':<12} {'model GB/chunk(1k)':>18}")
    for name in registry.names():
        s = registry.get(name)
        cost = s.est_cost(chunk=1024)
        print(f"{name:<14} {s.npsr:>6} {s.tspan_years:>5.0f} "
              f"{s.cadence:<8} {s.spec_hash():<12} "
              f"{cost['model_bytes_per_chunk'] / 1e9:>18.1f}")
    return 0


def _cmd_describe(name: str) -> int:
    s = registry.get(name)
    red = s.reduced()
    out = {
        "spec": s.spec_dict(),
        "spec_hash": s.spec_hash(),
        "est_cost": s.est_cost(chunk=1024),
        "reduced": {"npsr": red.npsr, "ntoa": red.ntoa,
                    "cadence_thin": red.cadence_thin,
                    "spec_hash": red.spec_hash()},
    }
    print(json.dumps(out, indent=2, default=str))
    return 0


def _cmd_run(args) -> int:
    from . import golden

    if args.memory_lane:
        lane = golden.memory_lane(args.name, chunk=args.chunk)
        print(json.dumps(lane, indent=2))
        if args.out:
            with open(args.out, "w") as fh:
                json.dump(lane, fh, indent=2)
        if not lane["ok"]:
            print(f"memory lane: watermark/model ratio exceeded the "
                  f"declared bound {lane['bound_factor']}x",
                  file=sys.stderr)
            return 1 if args.check else 0
        return 0

    row = golden.golden_run(
        args.name, reduced=(False if args.full else None),
        nreal=args.nreal, chunk=args.chunk,
        sample_steps=args.sample_steps,
        serve_requests=args.serve_requests, skip=tuple(args.skip),
        report_path=args.report)
    print(json.dumps(row))
    if args.out:
        golden.save_row(row, args.out)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.command == "list":
            return _cmd_list()
        if args.command == "describe":
            return _cmd_describe(args.name)
        return _cmd_run(args)
    except KeyError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2


if __name__ == "__main__":                               # pragma: no cover
    sys.exit(main())
