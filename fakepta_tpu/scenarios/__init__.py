"""IPTA-scale scenario registry + golden-run suite (docs/SCENARIOS.md).

- :mod:`.registry` — declarative, hashable :class:`Scenario` specs with
  named entries (``flagship_100``, ``ng15``, ``ipta_dr3``, ``ska_10k``),
  each materializing through the ordinary ``EnsembleSimulator`` /
  ``ArraySpec`` path.
- :mod:`.cadence` — telescope-cadence arrival processes (duty cycles,
  maintenance gaps, uneven multi-backend sampling) generating realistic
  TOA epochs and timed ``AppendRequest`` schedules.
- :mod:`.golden` — the golden-run harness: every scenario emits a full
  bench-schema row (``scenario`` + ``scn_*`` keys, bench.py docstring)
  banded by ``obs gate``, plus the psr-sharded memory-scaling lane.

CLI: ``python -m fakepta_tpu.scenarios list|describe|run``.
"""

from .registry import SCENARIOS, Scenario, get, names, register

__all__ = ["SCENARIOS", "Scenario", "get", "names", "register"]
