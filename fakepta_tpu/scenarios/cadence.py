"""Telescope-cadence arrival processes for the scenario registry.

The flagship's ``PulsarBatch.synthetic`` fabricates a uniform
``np.linspace`` grid — every pulsar observed every week forever. Real PTA
data looks nothing like that (PAPERS.md: NG15 / IPTA DR2 observing
histories): each pulsar is timed by a *subset* of telescopes, each
telescope has its own cadence, duty cycle (weather, scheduling), receiver
bands, commissioning/retirement dates, and maintenance shutdowns (the
Arecibo collapse is a step function in half the NANOGrav array). Those
gaps and backend seams are exactly what the streaming lane, the ECORR
epoch machinery and the per-backend system-noise bands claim to handle —
so the cadence model generates them deterministically, for simulation
*and* as timed append schedules the stream lane replays
(docs/STREAMING.md).

Two products, one process:

- :func:`build_batch` — a :class:`~fakepta_tpu.batch.PulsarBatch`
  constructed directly from the drawn epochs (ragged per-pulsar TOA
  counts, per-backend white levels, ECORR epoch quantization, masked
  per-backend system-noise bands), plus the float64 absolute epochs and
  backend ids the deterministic-signal / white-sampling lanes need.
- :func:`append_schedule` — the tail of the same cadence, split into
  observing-window blocks ``(t_start_s, toas, counts, freqs)`` that drive
  ``StreamState.append`` (or, wrapped by :func:`as_append_requests`, a
  served stream) with the real arrival process: uneven block sizes,
  multi-telescope epochs, and silent weeks.

Everything is a pure function of ``(cadence name, tspan, npsr, seed)`` —
two calls can never disagree about what a scenario's sky looks like.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import constants as const

DAY_S = 86400.0
#: MJD-seconds origin of every scenario's absolute epochs (the engine's
#: deterministic lanes need absolute float64 TOAs; the value matches the
#: flagship bench convention, benchmarks/suite.py ``_flagship_toas_abs``).
MJD0_S = 53000.0 * DAY_S


@dataclasses.dataclass(frozen=True)
class Telescope:
    """One telescope's observing pattern over the scenario span.

    ``cadence_days`` is the scheduled epoch spacing; ``duty_cycle`` the
    fraction of scheduled epochs actually observed (weather/scheduling
    losses, drawn per epoch); ``maintenance`` a tuple of
    ``(start_frac, end_frac)`` downtime windows in units of the scenario
    span; ``start_frac``/``end_frac`` the commissioning/retirement dates
    (Arecibo ends, MeerKAT begins); ``bands_mhz`` the receiver bands —
    each (telescope, band) pair is one backend with its own white-noise
    ``efac`` seam; ``jitter_days`` scatters epochs off the scheduled grid.
    """

    name: str
    cadence_days: float = 14.0
    duty_cycle: float = 0.9
    jitter_days: float = 1.0
    start_frac: float = 0.0
    end_frac: float = 1.0
    maintenance: Tuple[Tuple[float, float], ...] = ()
    bands_mhz: Tuple[float, ...] = (1400.0,)
    efac: float = 1.0


#: Named cadence families the registry's scenarios reference. ``uniform``
#: is the degenerate single-telescope always-on grid (the flagship's
#: historical cadence, kept bit-compatible through
#: ``PulsarBatch.synthetic``); the others are stylized real arrays.
CADENCES: Dict[str, Tuple[Telescope, ...]] = {
    "uniform": (Telescope("uniform", cadence_days=7.0, duty_cycle=1.0,
                          jitter_days=0.0),),
    # NANOGrav-15yr-like: Arecibo collapses at ~85% of the span, GBT runs
    # throughout with a maintenance summer, two bands per telescope
    "ng15": (
        Telescope("arecibo", cadence_days=21.0, duty_cycle=0.85,
                  jitter_days=2.0, end_frac=0.85,
                  bands_mhz=(430.0, 1400.0), efac=0.9),
        Telescope("gbt", cadence_days=21.0, duty_cycle=0.8, jitter_days=2.0,
                  maintenance=((0.55, 0.58),), bands_mhz=(820.0, 1400.0),
                  efac=1.1),
    ),
    # IPTA-DR3-like: five observatories joining at different dates, legacy
    # backends retiring, long maintenance gaps, three receiver generations
    "ipta": (
        Telescope("effelsberg", cadence_days=28.0, duty_cycle=0.8,
                  jitter_days=3.0, bands_mhz=(1400.0, 2600.0), efac=1.2),
        Telescope("parkes", cadence_days=21.0, duty_cycle=0.75,
                  jitter_days=3.0, maintenance=((0.42, 0.45),),
                  bands_mhz=(700.0, 1400.0, 3100.0), efac=1.0),
        Telescope("arecibo", cadence_days=28.0, duty_cycle=0.85,
                  jitter_days=2.0, end_frac=0.8, bands_mhz=(1400.0,),
                  efac=0.9),
        Telescope("gbt", cadence_days=28.0, duty_cycle=0.8, jitter_days=2.0,
                  bands_mhz=(820.0, 1400.0), efac=1.1),
        Telescope("meerkat", cadence_days=14.0, duty_cycle=0.9,
                  jitter_days=1.0, start_frac=0.75, bands_mhz=(1300.0,),
                  efac=0.7),
    ),
    # SKA-era: two dense high-duty stations, monthly per pulsar (10k
    # pulsars share the dishes), one wide band each
    "ska": (
        Telescope("ska_mid", cadence_days=30.0, duty_cycle=0.95,
                  jitter_days=2.0, bands_mhz=(1400.0,), efac=0.6),
        Telescope("ska_low", cadence_days=30.0, duty_cycle=0.95,
                  jitter_days=2.0, start_frac=0.1, bands_mhz=(350.0,),
                  efac=0.8),
    ),
}


def _telescope_epochs(tel: Telescope, tspan_s: float, thin: int,
                      rng: np.random.Generator) -> np.ndarray:
    """One telescope's observed epoch times [s] over ``tspan_s``."""
    step = tel.cadence_days * max(int(thin), 1) * DAY_S
    lo, hi = tel.start_frac * tspan_s, tel.end_frac * tspan_s
    # phase-offset grid so telescopes never alias onto a common week
    grid = np.arange(lo + rng.uniform(0.0, step), hi, step)
    if grid.size == 0:
        return grid
    keep = rng.uniform(size=grid.size) < tel.duty_cycle
    for m_lo, m_hi in tel.maintenance:
        keep &= ~((grid >= m_lo * tspan_s) & (grid < m_hi * tspan_s))
    t = grid[keep] + rng.normal(0.0, tel.jitter_days * DAY_S,
                                keep.sum())
    return np.sort(np.clip(t, 0.0, tspan_s * (1.0 - 1e-9)))


@dataclasses.dataclass(frozen=True)
class PulsarCadence:
    """One pulsar's drawn arrival process: sorted epoch times [s since
    span start], per-TOA observing frequency [MHz], per-TOA backend index
    into ``backends`` (``"<telescope>:<band>"`` labels), and the
    per-backend white-noise efac."""

    t: np.ndarray
    freqs: np.ndarray
    backend: np.ndarray
    backends: Tuple[str, ...]
    efacs: np.ndarray


def draw_cadence(cadence: str, tspan_years: float, npsr: int, seed: int,
                 thin: int = 1,
                 min_toa: int = 8) -> List[PulsarCadence]:
    """Draw every pulsar's arrival process for a named cadence family.

    Each pulsar is observed by a random non-empty subset of the family's
    telescopes (dense arrays share dishes: the subset is weighted toward
    1-2 telescopes); every (telescope, band) pair it sees becomes one of
    its backends. ``thin`` multiplies every cadence (the reduced /
    CPU-stand-in knob — same process, sparser sampling). Deterministic in
    ``(cadence, tspan_years, npsr, seed, thin)``.
    """
    if cadence not in CADENCES:
        raise KeyError(f"unknown cadence family {cadence!r}; "
                       f"known: {sorted(CADENCES)}")
    tels = CADENCES[cadence]
    tspan_s = tspan_years * const.yr
    out: List[PulsarCadence] = []
    for i in range(npsr):
        rng = np.random.default_rng((seed, 0x5CAD, i))
        n_tel = 1 + int(rng.uniform() < 0.5) if len(tels) > 1 else 1
        n_tel = min(n_tel + int(rng.uniform() < 0.2), len(tels))
        picked = sorted(rng.choice(len(tels), size=n_tel, replace=False))
        t_all: List[np.ndarray] = []
        f_all: List[np.ndarray] = []
        b_all: List[np.ndarray] = []
        backends: List[str] = []
        efacs: List[float] = []
        for k in picked:
            tel = tels[k]
            t = _telescope_epochs(tel, tspan_s, thin, rng)
            if t.size == 0:
                continue
            band = rng.integers(0, len(tel.bands_mhz), t.size)
            for bi, mhz in enumerate(tel.bands_mhz):
                sel = band == bi
                if not sel.any():
                    continue
                b_idx = len(backends)
                backends.append(f"{tel.name}:{int(mhz)}")
                efacs.append(tel.efac)
                t_all.append(t[sel])
                f_all.append(np.full(sel.sum(), mhz))
                b_all.append(np.full(sel.sum(), b_idx, dtype=np.int32))
        if not t_all or sum(t.size for t in t_all) < min_toa:
            # a pulsar nobody observed enough: fall back to the first
            # telescope's full grid so the batch never carries an
            # un-invertible empty row
            tel = tels[0]
            t = np.linspace(0.0, tspan_s * (1 - 1e-9),
                            max(min_toa, int(tspan_s / (
                                tel.cadence_days * max(thin, 1) * DAY_S))))
            t_all, f_all = [t], [np.full(t.size, tel.bands_mhz[0])]
            b_all = [np.zeros(t.size, dtype=np.int32)]
            backends, efacs = [f"{tel.name}:{int(tel.bands_mhz[0])}"], \
                [tel.efac]
        t = np.concatenate(t_all)
        order = np.argsort(t, kind="stable")
        out.append(PulsarCadence(
            t=t[order], freqs=np.concatenate(f_all)[order],
            backend=np.concatenate(b_all)[order],
            backends=tuple(backends), efacs=np.array(efacs)))
    return out


def build_batch(scenario, dtype=None):
    """Materialize a telescope-cadence scenario as a device batch.

    Returns ``(batch, toas_abs, backend_id, n_backends)``: the
    :class:`~fakepta_tpu.batch.PulsarBatch` (uneven per-pulsar TOA counts
    padded + masked, per-backend white levels, ECORR epochs, per-backend
    system-noise bands), the (P, T) float64 absolute MJD-second epochs
    (CGW / BayesEphem lanes), and the (P, T) backend-index array + count
    (``WhiteSampling``). The padded TOA count is rounded up to a multiple
    of 8 so the toa mesh axis always divides it.
    """
    import jax.numpy as jnp

    from .. import spectrum as spectrum_lib
    from ..batch import PulsarBatch
    from ..ops.white import quantise_epochs
    from ..utils.masks import stack_ragged

    if dtype is None:
        dtype = jnp.float32
    cads = draw_cadence(scenario.cadence, scenario.tspan_years,
                        scenario.npsr, scenario.data_seed,
                        thin=scenario.cadence_thin)
    toas_list = [c.t for c in cads]
    tmin = min(t.min() for t in toas_list)
    tmax = max(t.max() for t in toas_list)
    tspan_common = tmax - tmin

    toas_pad, mask = stack_ragged(toas_list)
    npsr, T = toas_pad.shape
    if T % 8:                                  # toa mesh-axis divisibility
        pad = 8 - T % 8
        toas_pad = np.pad(toas_pad, ((0, 0), (0, pad)))
        mask = np.pad(mask, ((0, 0), (0, pad)))
        T += pad

    rng = np.random.default_rng((scenario.data_seed, 0x5C10))
    costh = rng.uniform(-1, 1, npsr)
    phi = rng.uniform(0, 2 * np.pi, npsr)
    pos = np.stack([np.sqrt(1 - costh**2) * np.cos(phi),
                    np.sqrt(1 - costh**2) * np.sin(phi), costh], axis=-1)

    t_own = np.zeros((npsr, T))
    freqs = np.full((npsr, T), 1400.0)
    sigma2 = np.zeros((npsr, T))
    epoch_idx = np.zeros((npsr, T), dtype=np.int32)
    ecorr_amp = np.zeros((npsr, T))
    backend_id = np.zeros((npsr, T), dtype=np.int32)
    df_own = np.zeros(npsr)
    n_backends = max(len(c.backends) for c in cads)

    def own_grid_psd(tspan, nbin, log10_A, gamma):
        f = np.arange(1, nbin + 1) / tspan
        return np.asarray(spectrum_lib.powerlaw(f, log10_A, gamma))

    red = np.zeros((npsr, scenario.n_red))
    dm = np.zeros((npsr, scenario.n_dm))
    chrom = np.zeros((npsr, max(scenario.n_chrom, 1)))
    sys_psd = np.zeros((npsr, max(n_backends, 1), max(scenario.n_sys, 1)))
    sys_mask = np.zeros((npsr, max(n_backends, 1), T), dtype=bool)

    for i, c in enumerate(cads):
        n = c.t.size
        tspan_p = c.t.max() - c.t.min()
        df_own[i] = 1.0 / tspan_p
        t_own[i, :n] = (c.t - c.t.min()) / tspan_p
        freqs[i, :n] = c.freqs
        backend_id[i, :n] = c.backend
        efac_toa = c.efacs[c.backend]
        sigma2[i, :n] = (efac_toa * scenario.toaerr) ** 2
        red[i] = own_grid_psd(tspan_p, scenario.n_red,
                              scenario.red_log10_A, scenario.red_gamma)
        dm[i] = own_grid_psd(tspan_p, scenario.n_dm,
                             scenario.dm_log10_A, scenario.dm_gamma)
        if scenario.chrom_log10_A is not None and scenario.n_chrom:
            chrom[i, :scenario.n_chrom] = own_grid_psd(
                tspan_p, scenario.n_chrom, scenario.chrom_log10_A,
                scenario.chrom_gamma)
        if scenario.ecorr:
            flags = np.array([c.backends[b] for b in c.backend])
            idx, _, ep_counts = quantise_epochs(
                c.t - c.t.min(), flags,
                dt=scenario.ecorr_dt_days * DAY_S)
            epoch_idx[i, :n] = idx
            amp = np.full(n, 10.0 ** scenario.log10_ecorr)
            amp[ep_counts[idx] < 2] = 0.0      # single-TOA epochs: white
            ecorr_amp[i, :n] = amp
        if scenario.n_sys:
            band_psd = own_grid_psd(tspan_p, scenario.n_sys,
                                    scenario.sys_log10_A,
                                    scenario.sys_gamma)
            for b in range(len(c.backends)):
                sel = np.zeros(T, dtype=bool)
                sel[:n] = c.backend == b
                if sel.any():
                    sys_mask[i, b] = sel
                    sys_psd[i, b] = band_psd

    t_common = (toas_pad - tmin) / tspan_common * mask
    toas_abs = np.where(mask, MJD0_S + toas_pad, 0.0)

    batch = PulsarBatch(
        t_own=jnp.asarray(t_own, dtype),
        t_common=jnp.asarray(t_common, dtype),
        mask=jnp.asarray(mask),
        freqs=jnp.asarray(freqs, dtype),
        sigma2=jnp.asarray(sigma2, dtype),
        pos=jnp.asarray(pos, dtype),
        red_psd=jnp.asarray(red, dtype),
        dm_psd=jnp.asarray(dm, dtype),
        chrom_psd=jnp.asarray(chrom, dtype),
        epoch_idx=jnp.asarray(epoch_idx),
        ecorr_amp=jnp.asarray(ecorr_amp, dtype),
        sys_psd=jnp.asarray(sys_psd, dtype),
        sys_mask=jnp.asarray(sys_mask),
        df_own=jnp.asarray(df_own, dtype),
        tspan_common=jnp.asarray(tspan_common, dtype),
    )
    return batch, toas_abs, backend_id, n_backends


@dataclasses.dataclass(frozen=True)
class AppendBlock:
    """One observing window of the cadence tail, shaped for
    ``StreamState.append``: ``toas`` is (P, B) seconds from the stream's
    shared origin (the template's t=0) with the valid prefix per pulsar
    marked by ``counts`` (a pulsar nobody observed that window has count
    0), ``freqs`` the matching band frequencies, and ``t_start_s`` the
    window's wall-clock offset from the schedule start — the replay timer
    for timed append traffic."""

    t_start_s: float
    toas: np.ndarray
    counts: np.ndarray
    freqs: np.ndarray


def history_block(scenario, history_frac: float = 0.85) -> AppendBlock:
    """Everything observed BEFORE the ``history_frac`` cut, as one bulk
    append block — the stream lane's staging load (docs/STREAMING.md:
    bulk history first, then :func:`append_schedule`'s timed tail)."""
    cads = draw_cadence(scenario.cadence, scenario.tspan_years,
                        scenario.npsr, scenario.data_seed,
                        thin=scenario.cadence_thin)
    t0 = history_frac * scenario.tspan_years * const.yr
    rows = [(c.t[c.t < t0], c.freqs[c.t < t0]) for c in cads]
    width = max(max((t.size for t, _ in rows), default=1), 1)
    toas = np.zeros((scenario.npsr, width))
    freqs = np.full((scenario.npsr, width), 1400.0)
    counts = np.zeros(scenario.npsr, dtype=np.int64)
    for i, (t, f) in enumerate(rows):
        counts[i] = t.size
        toas[i, :t.size] = t
        freqs[i, :t.size] = f
    return AppendBlock(t_start_s=0.0, toas=toas, counts=counts, freqs=freqs)


def append_schedule(scenario, history_frac: float = 0.85,
                    window_days: float = 30.0,
                    max_blocks: Optional[int] = None) -> List[AppendBlock]:
    """Split the cadence tail after ``history_frac`` into observing-window
    append blocks (docs/STREAMING.md).

    The window walks the tail in fixed ``window_days`` steps; windows where
    no telescope observed produce NO block (real silent weeks — the
    zero-recompile contract has to hold across the resulting bucket
    mix), and block widths vary with how many backends happened to
    observe, exercising the bucket ladder the way uniform synthetic
    appends cannot.
    """
    cads = draw_cadence(scenario.cadence, scenario.tspan_years,
                        scenario.npsr, scenario.data_seed,
                        thin=scenario.cadence_thin)
    tspan_s = scenario.tspan_years * const.yr
    t0 = history_frac * tspan_s
    step = window_days * DAY_S
    blocks: List[AppendBlock] = []
    lo = t0
    while lo < tspan_s:
        hi = lo + step
        rows = []
        for c in cads:
            sel = (c.t >= lo) & (c.t < hi)
            rows.append((c.t[sel], c.freqs[sel]))
        width = max((t.size for t, _ in rows), default=0)
        if width:
            toas = np.zeros((scenario.npsr, width))
            freqs = np.full((scenario.npsr, width), 1400.0)
            counts = np.zeros(scenario.npsr, dtype=np.int64)
            for i, (t, f) in enumerate(rows):
                counts[i] = t.size
                # stream-origin seconds (StreamState's shared origin is the
                # template's t=0, NOT MJD) — padding slots replay the
                # window start so normalization stays in range; counts
                # masks them out
                toas[i, :t.size] = t
                toas[i, t.size:] = lo
                freqs[i, :t.size] = f
            blocks.append(AppendBlock(t_start_s=lo - t0, toas=toas,
                                      counts=counts, freqs=freqs))
        lo = hi
        if max_blocks is not None and len(blocks) >= max_blocks:
            break
    return blocks


def as_append_requests(blocks: Sequence[AppendBlock], stream: str,
                       spec=None, *, toaerr: float = 1e-7,
                       seed: int = 0, ecorr_dt: Optional[float] = None):
    """Wrap an append schedule as served ``AppendRequest`` traffic.

    The first request carries the stream-opening ``spec``/``ecorr_dt``;
    residuals are white draws at the scenario's TOA error (the served
    stream measures ingestion, not astrophysics). Returns
    ``[(t_start_s, AppendRequest), ...]`` — the caller replays them
    against a pool/fleet on the schedule's clock (or as fast as it
    wants; ``t_start_s`` preserves the arrival process either way).
    """
    from ..serve.spec import AppendRequest

    rng = np.random.default_rng((seed, 0xA99))
    out = []
    for k, blk in enumerate(blocks):
        res = rng.normal(0.0, toaerr, blk.toas.shape)
        out.append((blk.t_start_s, AppendRequest(
            stream=stream, toas=blk.toas, residuals=res,
            counts=blk.counts, freqs=blk.freqs,
            spec=spec if k == 0 else None,
            ecorr_dt=ecorr_dt if k == 0 else None)))
    return out
