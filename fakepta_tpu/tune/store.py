"""Persisted TunedConfig store: JSON beside the persistent compile cache.

One small schema-versioned JSON file holds every tuned knob set, keyed by
``<fingerprint-hash>/<family-hash>`` (platform identity x spec family —
:mod:`.fingerprint`). Warm starts then skip the search entirely: the
engine's ``run(tuned=True)``, the sampler, the serve prewarm and the
benchmarks all resolve knobs with one file read, the same way a warm
persistent compile cache turns a compile into a load.

Robustness contract (tests/test_tune.py pins each case):

- **fingerprint mismatch** — an entry written on another platform (or
  device count, or jax version) never applies; the miss is flight-recorded
  (``tune_fingerprint_mismatch``) so "why did it retune?" is answerable;
- **schema-version bump** — entries (or a whole file) written by a newer
  or older tuner version are ignored, never reinterpreted;
- **corrupt / torn file** — a loud :class:`RuntimeWarning` plus a
  flight-recorder note, then an empty store (the next search re-tunes and
  atomically rewrites the file via
  :func:`fakepta_tpu.utils.io.write_atomic`, the same torn-write-safe
  writer the checkpoints use).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
import warnings
from pathlib import Path
from typing import Dict, Optional

from ..obs import flightrec
from . import defaults
from .fingerprint import Fingerprint


@dataclasses.dataclass
class TunedConfig:
    """One platform x family's chosen dispatch knobs plus provenance."""

    fingerprint: dict              # Fingerprint.as_dict() at search time
    family: str                    # spec-family hash (fingerprint.family_hash)
    knobs: dict                    # chunk / pipeline_depth / path / precision
    #                              # / psr_shards / buckets
    metrics: dict = dataclasses.field(default_factory=dict)
    schema_version: int = defaults.STORE_VERSION
    created: str = ""              # ISO-8601 stamp (provenance only)

    @property
    def fp_hash(self) -> str:
        blob = json.dumps(self.fingerprint, sort_keys=True)
        import hashlib
        return hashlib.sha1(blob.encode()).hexdigest()[:12]

    def key(self) -> str:
        return f"{self.fp_hash}/{self.family}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, data: dict) -> "TunedConfig":
        return cls(fingerprint=dict(data["fingerprint"]),
                   family=str(data["family"]),
                   knobs=dict(data["knobs"]),
                   metrics=dict(data.get("metrics", {})),
                   schema_version=int(data.get("schema_version", -1)),
                   created=str(data.get("created", "")))


def default_store_path() -> Optional[Path]:
    """Resolve the store location: ``$FAKEPTA_TPU_TUNE_DIR`` wins, else the
    file sits beside the persistent compile cache (the knobs and the
    executables they select amortize together), else a per-user cache file
    — warm starts must survive process boundaries by default, or the
    tuner re-probes every round and "persisted" is a lie."""
    env = os.environ.get(defaults.TUNE_DIR_ENV)
    if env:
        return Path(env) / defaults.STORE_FILENAME
    # only consult jax when something already imported it: resolving a
    # store path must not drag the runtime in (gate CLI, analyzers)
    jax = sys.modules.get("jax")
    if jax is not None:
        cache_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
        if cache_dir:
            return Path(cache_dir) / defaults.STORE_FILENAME
    try:
        home = Path.home()
    except (OSError, RuntimeError):
        return None       # no resolvable home (sandboxed): un-persisted
    return home / ".cache" / "fakepta_tpu" / defaults.STORE_FILENAME


class TuneStore:
    """Load/lookup/put over the schema-versioned store file."""

    def __init__(self, path=None):
        self.path: Optional[Path] = (Path(path) if path is not None
                                     else default_store_path())

    # -- read --------------------------------------------------------------
    def load_entries(self) -> Dict[str, dict]:
        """Raw ``key -> entry`` dict; empty (with the loud warning) on any
        corruption, missing file, or schema mismatch."""
        if self.path is None or not self.path.exists():
            return {}
        try:
            data = json.loads(self.path.read_text(encoding="utf-8"))
            if not isinstance(data, dict) or "entries" not in data:
                raise ValueError("store file has no 'entries' table")
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            # corrupt/torn store: LOUD, then retune — a quietly-ignored
            # store is how a fleet silently runs hand-set knobs forever
            warnings.warn(
                f"corrupt tune store {self.path}: {exc!r}; ignoring it and "
                f"re-tuning (the next search rewrites it atomically)",
                RuntimeWarning, stacklevel=2)
            flightrec.note("tune_store_corrupt", path=str(self.path),
                           error=repr(exc)[:160])
            return {}
        if data.get("schema") != defaults.STORE_SCHEMA or \
                int(data.get("version", -1)) != defaults.STORE_VERSION:
            warnings.warn(
                f"tune store {self.path} has schema "
                f"{data.get('schema')!r} v{data.get('version')!r} != "
                f"{defaults.STORE_SCHEMA!r} v{defaults.STORE_VERSION}; "
                f"ignoring it and re-tuning", RuntimeWarning, stacklevel=2)
            flightrec.note("tune_store_schema_mismatch", path=str(self.path),
                           schema=str(data.get("schema")),
                           version=data.get("version"))
            return {}
        entries = data.get("entries")
        return dict(entries) if isinstance(entries, dict) else {}

    def lookup(self, fp: Fingerprint, family: str) -> Optional[TunedConfig]:
        """The TunedConfig for this platform x family, or None.

        A same-family entry under a *different* fingerprint is the
        diagnosable near-miss (new platform, resized slice, upgraded jax):
        it is ignored — never applied — with a flight-recorder note.
        """
        entries = self.load_entries()
        key = f"{fp.hash}/{family}"
        raw = entries.get(key)
        if raw is not None:
            cfg = TunedConfig.from_json(raw)
            if cfg.schema_version != defaults.STORE_VERSION:
                flightrec.note("tune_entry_schema_mismatch", key=key,
                               have=cfg.schema_version,
                               want=defaults.STORE_VERSION)
                return None
            return cfg
        for other_key in entries:
            if other_key.endswith(f"/{family}"):
                flightrec.note("tune_fingerprint_mismatch", family=family,
                               want=fp.hash,
                               have=other_key.split("/", 1)[0])
                break
        return None

    # -- write -------------------------------------------------------------
    def put(self, cfg: TunedConfig) -> Optional[str]:
        """Insert/replace one entry; atomic read-modify-write. Returns the
        store path, or None (recorded) when no store is configured."""
        if self.path is None:
            flightrec.note("tune_store_unconfigured", family=cfg.family)
            return None
        from ..utils.io import write_atomic

        if not cfg.created:
            cfg.created = time.strftime("%Y-%m-%dT%H:%M:%S")
        entries = self.load_entries()
        entries[cfg.key()] = cfg.to_json()
        payload = {"schema": defaults.STORE_SCHEMA,
                   "version": defaults.STORE_VERSION,
                   "entries": entries}
        self.path.parent.mkdir(parents=True, exist_ok=True)
        write_atomic(self.path,
                     (json.dumps(payload, indent=1, sort_keys=True) + "\n")
                     .encode())
        flightrec.note("tune_store_put", key=cfg.key(),
                       path=str(self.path))
        return str(self.path)

    def newest_for(self, fp: Fingerprint) -> Optional[TunedConfig]:
        """The most recently created valid entry for this fingerprint (any
        family) — the per-PLATFORM knob resolver (serve bucket ladders and
        the sampler's pipeline depth are platform-shaped, not
        family-shaped; docs/TUNING.md)."""
        best: Optional[TunedConfig] = None
        for key, raw in self.load_entries().items():
            if not key.startswith(f"{fp.hash}/"):
                continue
            try:
                cfg = TunedConfig.from_json(raw)
            except (KeyError, TypeError, ValueError):
                flightrec.note("tune_entry_unparseable", key=key)
                continue
            if cfg.schema_version != defaults.STORE_VERSION:
                continue
            if best is None or cfg.created > best.created:
                best = cfg
        return best
