"""Model-first candidate generation over the engine dispatch surface.

The search is *model-first, measure-second* (docs/TUNING.md): the analytic
models the repo already trusts — :func:`~fakepta_tpu.ops.megakernel
.chunk_bytes_model` (per-mode HBM traffic, the roofline source of truth
off-TPU), the megakernel's VMEM tile model (:func:`~fakepta_tpu.ops
.megakernel.pick_rt_mega`, which the kernel consults per shape so the
tuner never has to), and the serve pad-waste/coalesce tradeoff
(docs/SERVING.md) — prune the combinatorial knob space down to a small
frontier, and only that frontier pays measured probes.

What the models decide without a single probe:

- **path**: Pallas paths run in *interpret mode* off-TPU (a Python/XLA
  while-loop, orders of magnitude slower than the einsum path), so the
  frontier offers ``fused``/``mega`` only on TPU;
- **precision**: the bf16-storage mode exists to halve HBM reads the CPU
  backend does not have, so it is TPU-only too;
- **psr_shards**: sharding pulsars strictly *adds* traffic (the base and
  coefficient all_gathers in ``chunk_bytes_model``) — it enters the
  frontier only when the residency model says a realization-only split
  cannot fit the chunk in per-device memory;
- **chunk**: power-of-two ladder, capped where the residency model exceeds
  the per-device budget (``HBM_FRACTION`` x ``hbm_bytes`` when the backend
  exposes a limit, the conservative ``DEFAULT_BYTES_BUDGET`` otherwise);
- **bucket ladder**: chosen purely from the pad-waste/compile-count
  tradeoff — geometric ratio ``BUCKET_RATIO`` anchored at the mesh's real
  axis, capped at the largest residency-feasible bucket. No probes: serve
  probes would need live traffic shapes the tuner does not have.

Candidates are ranked by modeled HBM bytes **per realization** (the engine
is memory-bound — BASELINE round 5 measured 7.1 FLOP/B against a v5e ridge
of 240 — so modeled traffic is the principled throughput proxy), and only
the top of the ranking is probed.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from . import defaults
from .fingerprint import Fingerprint


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the dispatch-knob space (mesh split included)."""

    chunk: int
    pipeline_depth: int
    path: str                      # 'xla' | 'fused' | 'mega'
    precision: Optional[str]       # None (path default) | 'f32' | 'bf16'
    psr_shards: int = 1

    def knobs(self) -> dict:
        """The ``run(tuned=...)`` / TunedConfig knob dict."""
        return {"chunk": int(self.chunk),
                "pipeline_depth": int(self.pipeline_depth),
                "path": self.path,
                "precision": self.precision,
                "psr_shards": int(self.psr_shards)}

    def compile_key(self) -> tuple:
        """Candidates sharing this key share one compiled executable (the
        pipeline depth is a host-loop knob, not a program shape), so the
        prober pays one compile per key, not per candidate."""
        return (self.path, self.precision, self.psr_shards, self.chunk)


def traffic_bytes_per_real(cand: Candidate, npsr: int, ntoa: int,
                           k_coef: int, dtype_bytes: int = 4) -> float:
    """Modeled HBM bytes per realization for one candidate — the ranking
    proxy (lower is better on a memory-bound program)."""
    from ..ops.megakernel import chunk_bytes_model

    mode = {"xla": "xla", "fused": "fused"}.get(
        cand.path, "mega_bf16" if cand.precision == "bf16" else "mega")
    total = chunk_bytes_model(cand.chunk, npsr, ntoa, k_coef, mode=mode,
                              psr_shards=cand.psr_shards,
                              dtype_bytes=dtype_bytes)
    return total / max(cand.chunk, 1)


def resident_bytes_per_device(chunk: int, npsr: int, ntoa: int, k_coef: int,
                              n_devices: int, psr_shards: int = 1,
                              path: str = "xla",
                              dtype_bytes: int = 4) -> int:
    """Coarse per-device residency bound for one chunk in flight.

    Not the watermark — the measured probe's ``peak_hbm_bytes`` refines
    this — just a feasibility filter: the (R, P, T) residual block (plus
    its gathered copy when pulsars shard, plus the projection coefficient
    block), split over the realization shards. The mega path never
    materializes the projected residual (bases recomputed in VMEM), so
    only base + coefficients count there.
    """
    real_shards = max(n_devices // psr_shards, 1)
    r_local = max(chunk // real_shards, 1)
    p_local = max(npsr // psr_shards, 1)
    base = r_local * p_local * ntoa * dtype_bytes
    coef = r_local * p_local * k_coef * dtype_bytes
    gathered = (r_local * npsr * (ntoa + k_coef) * dtype_bytes
                if psr_shards > 1 else 0)
    if path == "mega":
        return base + coef + gathered
    # xla/fused: residual base + projected residual + coefficients
    return 2 * base + coef + gathered


def bytes_budget_per_device(fp: Fingerprint) -> int:
    """The residency budget the frontier plans into."""
    if fp.hbm_bytes > 0:
        return int(fp.hbm_bytes * defaults.HBM_FRACTION)
    return int(defaults.DEFAULT_BYTES_BUDGET)


def _pow2_ladder(lo: int, hi: int) -> List[int]:
    out, c = [], 1
    while c < lo:
        c *= 2
    while c <= hi:
        out.append(c)
        c *= 2
    return out


def _chunk_candidates(nreal_hint: int, real_shards: int,
                      lo: int, hi: int) -> List[int]:
    """Chunk ladder: powers of two PLUS the divisor chain of the workload
    size. Chunks are jitted at a static size, so a chunk that does not
    divide ``nreal_hint`` computes a truncated tail's worth of wasted
    realizations (2000 reals at chunk 1024 executes 2048) — the divisor
    chain offers zero-overshoot candidates at the scale the knobs will
    actually serve."""
    cands = set(_pow2_ladder(lo, hi))
    c = int(nreal_hint)
    while c >= lo:
        if c <= hi and c % real_shards == 0:
            cands.add(c)
        if c % 2:
            break
        c //= 2
    return sorted(cands)


def overshoot_factor(chunk: int, nreal_hint: int) -> float:
    """Computed/delivered realizations at the workload scale (>= 1): the
    final jitted chunk overshoots and is truncated, so a non-dividing
    chunk pays for realizations the caller never sees."""
    n = max(int(nreal_hint), 1)
    return (-(-n // max(chunk, 1)) * chunk) / n


def candidate_frontier(fp: Fingerprint, npsr: int, ntoa: int, k_coef: int,
                       *, nreal_hint: int, n_devices: Optional[int] = None,
                       dtype_bytes: int = 4,
                       max_candidates: int = 12) -> List[Candidate]:
    """The pruned, ranked candidate list the prober measures.

    ``nreal_hint`` is the workload scale the knobs will serve (the chunk
    ladder never exceeds it — a chunk larger than the run is just the
    run). The hand-set default candidate is always first, so a
    budget-expired search still has the baseline measured and "tuned >=
    hand-set" stays well-defined.
    """
    n_devices = int(n_devices if n_devices is not None else fp.n_devices)
    budget = bytes_budget_per_device(fp)
    on_tpu = fp.platform == "tpu"
    paths = ("mega", "fused", "xla") if on_tpu else ("xla",)

    def precisions(path: str) -> Tuple[Optional[str], ...]:
        # bf16 storage halves HBM reads — the resource only the real
        # accelerator meters; off-TPU it only adds rounding
        return (None, "bf16") if on_tpu else (None,)

    def shard_options(chunk_lo: int) -> List[int]:
        opts = [1]
        if resident_bytes_per_device(chunk_lo, npsr, ntoa, k_coef,
                                     n_devices, 1, "xla",
                                     dtype_bytes) > budget:
            # realization-only split cannot fit even the smallest chunk:
            # pulsar sharding (which *costs* gather traffic) earns its slot
            opts += [s for s in (2, 4, 8)
                     if npsr % s == 0 and n_devices % s == 0
                     and s <= n_devices]
        return opts

    chunk_cap = max(int(nreal_hint), n_devices)
    chunk_lo = n_devices
    depth_opts = [d for d in defaults.DEPTH_CANDIDATES
                  if d == 0 or nreal_hint // max(chunk_lo, 1) >= d]

    seen = set()
    cands: List[Candidate] = []
    for psr_shards in shard_options(chunk_lo):
        real_shards = max(n_devices // psr_shards, 1)
        for path in paths:
            for prec in precisions(path):
                for chunk in _chunk_candidates(
                        nreal_hint, real_shards,
                        max(chunk_lo, real_shards), chunk_cap):
                    if chunk % real_shards:
                        continue
                    if resident_bytes_per_device(
                            chunk, npsr, ntoa, k_coef, n_devices,
                            psr_shards, path, dtype_bytes) > budget:
                        break        # the ladder only grows from here
                    for depth in depth_opts:
                        c = Candidate(chunk, depth, path, prec, psr_shards)
                        if c not in seen:
                            seen.add(c)
                            cands.append(c)

    default = default_candidate(nreal_hint, n_devices)
    cands = [c for c in cands if c != default]
    # ranking: modeled HBM bytes per DELIVERED realization — the traffic
    # model times the tail-overshoot factor at the workload scale, so a
    # chunk that divides the workload outranks an equal-traffic one that
    # computes a truncated tail for nothing
    cands.sort(key=lambda c: (
        traffic_bytes_per_real(c, npsr, ntoa, k_coef, dtype_bytes)
        * overshoot_factor(c.chunk, nreal_hint), -c.chunk,
        c.pipeline_depth))
    # diversity before depth: the byte model ranks whole path families
    # above one another (mega dominates by construction), but the model
    # is a proxy — guarantee every (path, precision) family its best
    # representative before spending remaining probe slots down the
    # global ranking, so a model error can cost rank, never coverage
    picked: List[Candidate] = []
    seen_groups = set()
    for c in cands:
        g = (c.path, c.precision)
        if g not in seen_groups:
            seen_groups.add(g)
            picked.append(c)
    for c in cands:
        if len(picked) >= max_candidates - 1:
            break
        if c not in picked:
            picked.append(c)
    return [default] + picked[:max(max_candidates - 1, 0)]


def default_candidate(nreal_hint: int, n_devices: int) -> Candidate:
    """The hand-set baseline: run()'s documented defaults, normalized the
    way the engine would normalize them for this workload."""
    chunk = min(defaults.DEFAULT_CHUNK, max(int(nreal_hint), 1))
    chunk -= chunk % max(n_devices, 1)
    return Candidate(chunk=max(chunk, n_devices),
                     pipeline_depth=defaults.DEFAULT_PIPELINE_DEPTH,
                     path=defaults.DEFAULT_PATH, precision=None,
                     psr_shards=1)


def bucket_ladder(fp: Fingerprint, npsr: int, ntoa: int, k_coef: int,
                  *, n_real_shards: Optional[int] = None,
                  dtype_bytes: int = 4) -> Tuple[int, ...]:
    """Model-chosen serve bucket ladder (no probes; docs/SERVING.md).

    Geometric with ratio ``BUCKET_RATIO`` — expected pad waste
    ``(g-1)/(2g)`` (~25% at g=2) against ``O(log(max/min))`` warm
    executables — anchored at the smallest legal bucket (every bucket must
    be a multiple of the mesh's real axis) and capped at the largest
    residency-feasible dispatch.
    """
    n_real = int(n_real_shards if n_real_shards is not None
                 else fp.n_devices)
    budget = bytes_budget_per_device(fp)
    lo = 1
    while lo < n_real or lo < defaults.DEFAULT_BUCKETS[0]:
        lo *= defaults.BUCKET_RATIO
    ladder = []
    b = lo
    while len(ladder) < len(defaults.DEFAULT_BUCKETS):
        if resident_bytes_per_device(b, npsr, ntoa, k_coef, n_real,
                                     1, "xla", dtype_bytes) > budget:
            break
        ladder.append(b)
        b *= defaults.BUCKET_RATIO
    return tuple(ladder) if ladder else (lo,)
