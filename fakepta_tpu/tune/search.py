"""Search orchestration: fingerprint -> model frontier -> probes -> store.

``search()`` is the whole tuner: fingerprint the platform
(:mod:`.fingerprint`), prune the knob space with the analytic models
(:mod:`.model`), measure the surviving frontier with short probes
(:mod:`.probe`) under a wall-clock budget, persist the winner
(:mod:`.store`), and emit an obs-diffable artifact. A warm store returns
in one file read with **zero probes** — the acceptance contract
benchmarks and tests pin.

The ``resolve_*`` helpers are the consumption surface:
``EnsembleSimulator.run(tuned=True)`` resolves per spec family,
``SamplingRun`` and the serve prewarm resolve the platform-shaped knobs
(pipeline depth, bucket ladder) from the newest entry for the
fingerprint. All imports of the engine are call-time (this package must
stay importable without jax — the gate CLI reads :func:`fingerprint
<fakepta_tpu.tune.fingerprint.fingerprint>` lazily).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .. import obs
from ..obs import flightrec
from . import defaults
from .fingerprint import Fingerprint, family_hash, fingerprint
from .model import (Candidate, bucket_ladder, candidate_frontier,
                    default_candidate, overshoot_factor)
from .probe import run_probe
from .store import TunedConfig, TuneStore


def _as_store(store) -> TuneStore:
    return store if isinstance(store, TuneStore) else TuneStore(store)


def family_for_surface(surf: dict) -> str:
    """The spec-family hash of an engine dispatch surface
    (:meth:`EnsembleSimulator.dispatch_surface`)."""
    return family_hash(npsr=surf["npsr"], max_toa=surf["max_toa"],
                       nbins=surf["nbins"], k_coef=surf["k_coef"],
                       dtype=surf["dtype"])


def search(batch=None, *, gwb=None, include=None, nbins: int = 15,
           spec=None, mesh_devices=None, nreal_hint: int = 4096,
           budget_s: Optional[float] = None,
           probe_chunks: int = defaults.PROBE_CHUNKS,
           probe_timeout_s: float = defaults.PROBE_TIMEOUT_S,
           max_candidates: int = 12, store=None, force: bool = False,
           seed: int = 2024, artifact=None
           ) -> Tuple[TunedConfig, dict]:
    """Tune the dispatch knobs for one ensemble spec on this platform.

    Pass either ``batch`` (+ ``gwb``/``include``/``nbins`` — the
    :class:`EnsembleSimulator` constructor surface) or a serve
    :class:`~fakepta_tpu.serve.ArraySpec` as ``spec``. Returns
    ``(TunedConfig, info)`` where ``info`` carries ``probes`` /
    ``probe_s`` / ``warm`` / the per-candidate probe records. With a warm
    store (same fingerprint x family, not ``force``) the search performs
    zero probes and zero compiles — one store read against the family of
    the (un-probed) base simulator.
    """
    import jax

    t0 = obs.now()
    if spec is not None:
        if batch is not None:
            raise ValueError("pass batch=... or spec=..., not both")
        batch, gwb = spec.parts()
        nbins = spec.nbins
    if batch is None:
        raise ValueError("search needs a PulsarBatch (batch=...) or a "
                         "serve ArraySpec (spec=...)")
    devices = list(mesh_devices if mesh_devices is not None
                   else jax.devices())
    fp = fingerprint(devices)
    budget_s = defaults.PROBE_BUDGET_S if budget_s is None else budget_s
    tstore = _as_store(store)

    from ..parallel.mesh import make_mesh
    from ..parallel.montecarlo import EnsembleSimulator

    sims: dict = {}

    def sim_for(psr_shards: int):
        if psr_shards not in sims:
            kw = {} if include is None else {"include": include}
            sims[psr_shards] = EnsembleSimulator(
                batch, gwb=gwb, nbins=nbins,
                mesh=make_mesh(devices, psr_shards=psr_shards), **kw)
        return sims[psr_shards]

    # ONE family source: the base simulator's dispatch surface (the same
    # method run(tuned=True) resolves through, so the two can never
    # disagree about which store entry a spec belongs to)
    base_sim = sim_for(1)
    surf = base_sim.dispatch_surface()
    family = family_for_surface(surf)
    if not force:
        hit = tstore.lookup(fp, family)
        if hit is not None:
            flightrec.note("tune_warm_hit", family=family, fp=fp.hash)
            info = {"probes": 0, "probe_s": 0.0, "warm": True,
                    "records": []}
            if artifact:
                _write_artifact(artifact, fp, family, [], hit, info)
            return hit, info

    frontier = candidate_frontier(
        fp, surf["npsr"], surf["max_toa"], surf["k_coef"],
        nreal_hint=nreal_hint, n_devices=len(devices),
        dtype_bytes=surf["dtype_bytes"], max_candidates=max_candidates)

    records: List[Tuple[Candidate, dict]] = []
    attempted = 0
    last_probe_s = 0.0
    for i, cand in enumerate(frontier):
        # predictive budget stop: if the last probe's cost would push this
        # one past the budget, stop now — "bounded" means the search ends
        # near the budget, not one whole probe after it (the hand-set
        # default candidate, frontier[0], is always probed)
        if i > 0 and obs.now() - t0 + last_probe_s > budget_s:
            flightrec.note("tune_budget_exhausted", probed=attempted,
                           frontier=len(frontier))
            break
        attempted += 1
        rec = run_probe(sim_for(cand.psr_shards), cand, seed=seed,
                        probe_chunks=probe_chunks,
                        timeout_s=probe_timeout_s, nreal_cap=nreal_hint)
        if rec is not None:
            last_probe_s = rec["probe_s"]
            records.append((cand, rec))
    if not records:
        raise RuntimeError(
            f"tune search probed {attempted} candidate(s) and none "
            f"completed — refusing to persist a guess; see the flight "
            f"recorder's tune_probe_failed notes")

    default = default_candidate(nreal_hint, len(devices))
    # selection is on DELIVERED throughput at the workload scale: a probe
    # measures computed realizations/s, but a chunk that does not divide
    # nreal_hint computes a truncated tail the caller never receives
    # (model.overshoot_factor) — the same waste the frontier ranking
    # prices, so the model and the measurement agree on units
    def delivered(cand: Candidate, rec: dict) -> float:
        return (rec["real_per_s_per_chip"]
                / overshoot_factor(cand.chunk, nreal_hint))

    best_cand, best_rec = max(records, key=lambda cr: delivered(*cr))
    default_rec = next((r for c, r in records if c == default), None)
    probe_s = obs.now() - t0

    knobs = best_cand.knobs()
    knobs["buckets"] = list(bucket_ladder(
        fp, surf["npsr"], surf["max_toa"], surf["k_coef"],
        n_real_shards=len(devices), dtype_bytes=surf["dtype_bytes"]))
    metrics = {
        "real_per_s_per_chip": round(delivered(best_cand, best_rec), 3),
        "probes": attempted,
        "probe_s": round(probe_s, 3),
        "peak_hbm_bytes": best_rec["peak_hbm_bytes"],
    }
    if default_rec is not None:
        hand = delivered(default, default_rec)
        metrics["hand_set_real_per_s_per_chip"] = round(hand, 3)
        if hand > 0:
            metrics["speedup_x"] = round(
                delivered(best_cand, best_rec) / hand, 3)
    cfg = TunedConfig(fingerprint=fp.as_dict(), family=family,
                      knobs=knobs, metrics=metrics)
    store_path = tstore.put(cfg)
    info = {"probes": attempted, "probe_s": probe_s, "warm": False,
            "records": [dict(r, knobs=c.knobs()) for c, r in records],
            "store_path": store_path}
    if artifact:
        _write_artifact(artifact, fp, family, records, cfg, info)
    return cfg, info


def _write_artifact(path, fp: Fingerprint, family: str, records,
                    cfg: TunedConfig, info: dict) -> str:
    """Obs-diffable ``fakepta_tpu.tune/1`` artifact: an EventLog whose
    meta carries the chosen knobs and whose extra_metrics feed
    ``obs summarize|compare|gate`` directly."""
    from ..obs.metrics import EventLog

    summary = {
        "tuned": 1,
        "tune_probe_s": round(float(info["probe_s"]), 3),
        "tune_probes": int(info["probes"]),
    }
    if cfg.metrics.get("speedup_x") is not None:
        summary["tuned_speedup_x"] = cfg.metrics["speedup_x"]
    if cfg.metrics.get("real_per_s_per_chip") is not None:
        summary["tuned_real_per_s_per_chip"] = \
            cfg.metrics["real_per_s_per_chip"]
    log = EventLog(meta={
        "kind": "tune", "tune_schema": defaults.STORE_SCHEMA,
        "platform": fp.platform, "fingerprint": fp.as_dict(),
        "family": family, "knobs": dict(cfg.knobs),
        "extra_metrics": summary,
    })
    for cand, rec in records:
        log.append("probe", knobs=cand.knobs(),
                   real_per_s_per_chip=round(
                       rec["real_per_s_per_chip"], 3),
                   probe_s=round(rec["probe_s"], 3),
                   retraces=rec["retraces"],
                   peak_hbm_bytes=rec["peak_hbm_bytes"])
    return log.save(path, summary=summary)


# ---------------------------------------------------------------------------
# consumption surface (engine / sampler / serve / benchmarks)
# ---------------------------------------------------------------------------

def resolve_for_sim(sim, store=None) -> Optional[TunedConfig]:
    """The TunedConfig matching one simulator's platform x family, or None
    (``EnsembleSimulator.run(tuned=True)``'s store hook — one file read,
    zero probes, zero compiles)."""
    fp = fingerprint()
    family = family_for_surface(sim.dispatch_surface())
    return _as_store(store).lookup(fp, family)


def resolve_platform_knob(name: str, store=None, default=None):
    """The platform-shaped knob ``name`` from the newest store entry for
    this fingerprint (any family): pipeline depth and the serve bucket
    ladder are properties of the host/device tier, not of one spec
    (docs/TUNING.md)."""
    cfg = _as_store(store).newest_for(fingerprint())
    if cfg is None:
        return default
    value = cfg.knobs.get(name)
    return default if value is None else value


def resolve_buckets(store=None) -> Optional[Tuple[int, ...]]:
    """Tuned serve bucket ladder for this platform, or None (the
    :class:`~fakepta_tpu.serve.ServePool` prewarm hook)."""
    ladder = resolve_platform_knob("buckets", store=store)
    if not ladder:
        return None
    return tuple(int(b) for b in ladder)
