"""Platform fingerprint: the identity every tuned knob is keyed on.

The bench history is the motivating evidence (ROADMAP item 4): r02's
accelerator round ran 48,105 real/s/chip while the CPU stand-in rounds sit
near ~230 with *different* best knobs — so a tuned configuration is
meaningless without the platform it was measured on. The fingerprint
captures what changes the optimum: backend platform and device kind,
device/host counts, per-device memory, and the jax/jaxlib versions (whose
compiler changes can move the optimum as surely as hardware can).

This is also the repo's single source of platform identity: ``obs gate``'s
same-platform row matching and ``benchmarks/suite.py``'s ``platform``
column both read :func:`fingerprint` instead of probing
``jax.devices()[0].platform`` ad hoc (the regression that matters — a CPU
stand-in round must never band an accelerator round — is pinned in
tests/test_tune.py).

jax is imported lazily inside :func:`fingerprint` so importing
:mod:`fakepta_tpu.tune` (e.g. from the gate CLI) stays cheap.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Optional, Sequence

from ..obs import flightrec


@dataclasses.dataclass(frozen=True)
class Fingerprint:
    """What the tuner knows about the platform it measured on."""

    platform: str          # 'cpu' | 'tpu' | 'gpu' ...
    device_kind: str       # e.g. 'TPU v5e' / 'cpu'
    n_devices: int         # global device count (jax.devices())
    n_processes: int       # host count (jax.process_count())
    hbm_bytes: int         # per-device memory limit; 0 when not exposed
    jax_version: str
    jaxlib_version: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @property
    def hash(self) -> str:
        """Stable short identity (the store key ingredient)."""
        blob = json.dumps(self.as_dict(), sort_keys=True)
        return hashlib.sha1(blob.encode()).hexdigest()[:12]


def fingerprint(devices: Optional[Sequence] = None) -> Fingerprint:
    """Fingerprint the current jax runtime (global devices by default).

    Deliberately *global* — ``jax.devices()`` / ``jax.process_count()`` —
    rather than mesh-shaped: a simulator on a sub-mesh still runs on the
    same platform, and the mesh layout is itself a tuned knob, not an
    identity field.
    """
    import jax

    devices = list(devices if devices is not None else jax.devices())
    d0 = devices[0]
    hbm = 0
    try:
        stats = d0.memory_stats()
        hbm = int((stats or {}).get("bytes_limit", 0))
    except Exception as exc:   # noqa: BLE001 — recorded, not swallowed
        # backends without allocator stats (XLA:CPU) land here; the
        # fingerprint records hbm_bytes=0 and the residency model falls
        # back to its conservative budget (tune.defaults)
        flightrec.note("fingerprint_no_memory_stats", error=repr(exc)[:120])
    try:
        import jaxlib
        jaxlib_version = getattr(jaxlib, "__version__", "")
    except ImportError:
        jaxlib_version = ""
    return Fingerprint(
        platform=str(d0.platform),
        device_kind=str(getattr(d0, "device_kind", d0.platform)),
        n_devices=len(devices),
        n_processes=int(jax.process_count()),
        hbm_bytes=hbm,
        jax_version=str(jax.__version__),
        jaxlib_version=str(jaxlib_version),
    )


def family_hash(**fields) -> str:
    """Stable short hash of a spec *family* — the problem-shaped identity
    (pulsar/TOA/bin counts, coefficient width, dtype) a TunedConfig applies
    to, deliberately EXCLUDING the knobs themselves (chunk, depth, path,
    precision, mesh split are what the tuner chooses, not what it keys on)
    and the volatile fields (nreal, seed) the flight recorder's
    :func:`~fakepta_tpu.obs.flightrec.spec_hash` also drops."""
    blob = json.dumps(dict(sorted(fields.items())), sort_keys=True,
                      default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]
