"""fakepta_tpu.tune — platform-aware autotuner for the dispatch surface.

The engine exposes ~6 coupled dispatch knobs (chunk size, pipeline depth,
statistic path, precision mode, mesh split, serve bucket ladder), all
hand-set until now, and the bench trajectory proves the optimum is
platform-specific (ROADMAP item 4: 48,105 real/s/chip on the accelerator
vs ~230 on the CPU stand-in, with different best knobs). This package
turns that into infrastructure:

- :func:`fingerprint` — the platform identity every tuned knob is keyed
  on, and the repo's single source of the ``platform`` column
  (``obs gate`` / ``benchmarks/suite.py`` read it too);
- :func:`search` — model-first pruning over the knob space (the analytic
  HBM/VMEM/pad-waste models) followed by short measured probes through
  the obs machinery, wall-clock-budgeted, degradation-ladder-protected;
- :class:`TuneStore` / :class:`TunedConfig` — the persisted result,
  JSON beside the persistent compile cache, schema-versioned and keyed
  fingerprint x spec family, consumed by ``EnsembleSimulator.run(
  tuned=True)``, :class:`~fakepta_tpu.sample.SamplingRun`, the serve
  prewarm and the benchmarks;
- ``python -m fakepta_tpu.tune search|show|apply`` — the CLI, emitting
  obs-diffable ``fakepta_tpu.tune/1`` artifacts.

See docs/TUNING.md for the search strategy, store format and the
measured A/B protocol.
"""

from . import defaults  # noqa: F401
from .fingerprint import Fingerprint, family_hash, fingerprint  # noqa: F401
from .model import (Candidate, bucket_ladder,  # noqa: F401
                    candidate_frontier, default_candidate,
                    overshoot_factor)
from .search import (family_for_surface, resolve_buckets,  # noqa: F401
                     resolve_for_sim, resolve_platform_knob, search)
from .store import (TunedConfig, TuneStore,  # noqa: F401
                    default_store_path)

__all__ = [
    "Fingerprint", "fingerprint", "family_hash", "family_for_surface",
    "Candidate", "candidate_frontier", "default_candidate",
    "bucket_ladder", "TunedConfig", "TuneStore", "default_store_path",
    "search", "resolve_for_sim", "resolve_platform_knob",
    "resolve_buckets", "defaults",
]
